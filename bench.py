#!/usr/bin/env python3
"""Framework benchmark — prints ONE JSON line.

Two stories in one line:

1. **Control plane**: `Notebook` CR created → reconciled (admission, STS,
   Services, simulated kubelet, status mirroring) → slice Ready. This is
   the product's spawn path (BASELINE.md cold-start metric).
2. **Data plane**: the burn-in transformer's train step, scaled so it is
   MXU-bound (d_model 2048, seq 1024, bf16), measured over ≥100 steps
   with compile time reported separately. Primary metric is **MFU** =
   achieved TFLOP/s ÷ the chip's peak bf16 TFLOP/s from the topology
   library (`kubeflow_tpu/tpu/topology.py` peak_bf16_tflops). When more
   than one device is attached, the ICI all-reduce probe
   (`kubeflow_tpu/probe/ici.py`) runs too and its fraction-of-peak is
   folded in (north-star metric, BASELINE.md).

The reference publishes no comparable numbers (SURVEY.md §6); baselines
are ours: MFU target 0.40, cold-start target 60 s.
"""

import asyncio
import json
import os
import sys
import time

MFU_TARGET = 0.40
COLDSTART_TARGET_SEC = 60.0

# Persistent XLA compilation cache (utils/compilecache.py): repo-local so
# it survives across rounds/processes; the warm-start probe and any
# subsequent bench run hit it instead of recompiling (~12 s saved).
CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")

# Scaled so the steady-state step is MXU-bound, not overhead-bound.
# seq_len 1025: the loss trains on tokens[:, :-1], and the flash kernel
# wants the trained length (1024) divisible by its 128-row blocks.
# d_ff/d_model = 8 (T5-style wide FF): swept on the real chip — the wide
# FF GEMMs are the most MXU-efficient op in the model, lifting measured
# MFU 0.755 → 0.83 at the same analytic-FLOPs accounting (docs/perf.md).
BENCH_BATCH = 8
BENCH_STEPS = 100
BENCH_MODEL = dict(
    vocab=8192, d_model=2048, n_heads=16, n_layers=8, d_ff=16384,
    seq_len=1025, attention="flash",
)


SCALE_NOTEBOOKS = 200

# Fresh-probe overrides (bench.py multichip's cold-start recheck): the
# full BENCH_MODEL is sized for a real chip; on a dryrun host the probe
# flips to this CPU-feasible config (``KFTPU_BENCH_SMALL_MODEL``) and
# optionally forces the backend (``KFTPU_BENCH_PLATFORM=cpu``). The
# probe's cross-round signal there is the compile-cache HIT/MISS
# attribution — platform-independent — not the absolute seconds, so the
# printed JSON names which model ran.
SMALL_MODEL_ENV = "KFTPU_BENCH_SMALL_MODEL"
PLATFORM_ENV = "KFTPU_BENCH_PLATFORM"
SMALL_BENCH_MODEL = dict(
    vocab=512, d_model=256, n_heads=4, n_layers=2, d_ff=1024,
    seq_len=129, attention="xla",
)

# Long-context story: ring attention with trainable flash hops at 8k
# tokens on the single bench chip (multi-chip sequence parallelism is the
# dryrun gate's job; this measures the kernel path's per-chip throughput).
LONGCTX_MODEL = dict(
    vocab=8192, d_model=2048, n_layers=2, d_ff=8192, n_heads=16,
    seq_len=8192, attention="ring_flash",
)
LONGCTX_STEPS = 10


class ControlPlane:
    """In-process control plane (fake apiserver + reconcilers + kubelet
    simulator). Each measurement phase builds a FRESH one so the spawn
    notebook never sits in the scale run's object set or percentiles."""

    async def start(self):
        from kubeflow_tpu.controllers.notebook import setup_notebook_controller
        from kubeflow_tpu.runtime.manager import Manager
        from kubeflow_tpu.testing.fakekube import FakeKube
        from kubeflow_tpu.testing.podsim import PodSimulator
        from kubeflow_tpu.webhooks import register_all

        self.kube = FakeKube()
        register_all(self.kube)
        self.mgr = Manager(self.kube)
        setup_notebook_controller(self.mgr)
        self.sim = PodSimulator(self.kube)
        await self.mgr.start()
        await self.sim.start()
        return self

    async def stop(self):
        await self.sim.stop()
        await self.mgr.stop()
        self.kube.close_watches()


async def spawn_notebook(cp: ControlPlane) -> dict:
    """One CR create → slice Ready; the cold-start path's control share."""
    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.runtime.objects import deep_get

    t0 = time.perf_counter()
    await cp.kube.create(
        "Notebook", nbapi.new("bench", "bench", accelerator="v5e", topology="2x2")
    )
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        nb = await cp.kube.get("Notebook", "bench", "bench")
        if deep_get(nb, "status", "readyReplicas", default=0):
            return {"spawn_sec": time.perf_counter() - t0}
        await asyncio.sleep(0.005)
    raise RuntimeError("notebook never became Ready")


async def scale_test(cp: ControlPlane, count: int = SCALE_NOTEBOOKS) -> dict:
    """The N-notebook load test (testing/loadtest.py — the harness the
    reference ships without ever recording numbers, SURVEY.md §6). Runs
    AFTER the cold-start measurement so its wall time never pollutes
    in_process_to_first_step_sec.

    Besides throughput/latency, reports the control plane's API-write
    count (fakekube per-verb request counter — write elision should keep
    this near the object count, not the event count), the workqueue
    high-water mark, and mean reconcile latency from the manager's
    histogram."""
    from kubeflow_tpu.testing.loadtest import run_load_test

    writes_before = cp.kube.write_count()
    # The manager defaults to the process-wide registry; diff the
    # histogram around the run so each trial reports its own reconciles.
    rec_before = cp.mgr.reconcile_seconds.snapshot(controller="notebook")
    report = await run_load_test(
        cp.kube, count=count, accelerator="v5e", topology="2x2",
        timeout=120,
    )
    if report.ready != count:
        raise RuntimeError(
            f"load test: only {report.ready}/{count} ready "
            f"(failures: {report.failures[:3]})"
        )
    rec_after = cp.mgr.reconcile_seconds.snapshot(controller="notebook")
    rec = {"count": rec_after["count"] - rec_before["count"],
           "sum": rec_after["sum"] - rec_before["sum"]}
    return {
        "notebooks": report.notebooks,
        "wall_sec": round(report.wall_seconds, 3),
        "notebooks_per_sec": round(report.notebooks / report.wall_seconds, 1),
        "p50_ready_sec": round(report.p50_ready_seconds, 4),
        "p95_ready_sec": round(report.p95_ready_seconds, 4),
        "api_writes": cp.kube.write_count() - writes_before,
        "queue_depth_peak": max(
            (q.peak_depth for q in cp.mgr._queues.values()), default=0),
        "reconciles": rec["count"],
        "reconcile_mean_sec": (
            round(rec["sum"] / rec["count"], 5) if rec["count"] else None),
    }


def train_step_flops(cfg, batch: int) -> float:
    """Analytic matmul FLOPs for one train step (fwd + bwd ≈ 3× fwd).

    Counts the MXU work only (dense matmuls + attention einsums); the
    elementwise chains XLA fuses into them are noise at this scale.
    """
    s = cfg.seq_len - 1  # loss_fn trains on tokens[:, :-1]
    d, ff, v = cfg.d_model, cfg.d_ff, cfg.vocab
    per_token_layer = (
        2 * d * 3 * d       # qkv projection
        + 2 * d * d         # attention output projection
        + 2 * d * ff        # ff1
        + 2 * ff * d        # ff2
    )
    # Causal convention: the model needs s²/2 of the score/context
    # matmuls, so credit 2·b·s²·d per layer (the flash kernel computes
    # exactly this; the dense XLA path computes 2× and gets no credit).
    per_layer_attn = 2 * batch * s * s * d
    fwd = (
        batch * s * (cfg.n_layers * per_token_layer + 2 * d * v)  # + lm head
        + cfg.n_layers * per_layer_attn
    )
    return 3.0 * fwd


def detect_accelerator(device) -> str | None:
    """Map a jax device's kind string onto the topology library's names."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    if "v5 lite" in kind or "v5lite" in kind or "v5e" in kind:
        return "v5e"
    if "v6" in kind:
        return "v6e"
    if "v5" in kind:  # v5p once lite is excluded
        return "v5p"
    if "v4" in kind:
        return "v4"
    return None


MEASURE_TRIALS = 3


def _median_sorted(xs: list) -> float:
    """True median of an already-sorted list (mean of the two middle
    elements for even counts — ``xs[n//2]`` alone is the upper-middle,
    which biased even-count spreads slightly high)."""
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def _measure_trials(run_window, *, trials: int = MEASURE_TRIALS) -> dict:
    """Run a timing window ``trials`` times; report the median plus the
    raw trials and relative spread, so a shared-relay blip (r02→r03's
    unexplained 4.7% longctx drift) is classifiable from the JSON alone:
    large spread → variance, tight spread + moved median → regression."""
    secs = sorted(run_window() for _ in range(trials))
    median = _median_sorted(secs)
    return {
        "median_sec": median,
        "trials_sec": [round(s, 4) for s in secs],
        "spread_pct": round(100.0 * (secs[-1] - secs[0]) / median, 2),
    }


CANARY_DIM = 4096
CANARY_ITERS = 600

# The chip is reached through a shared remote relay whose device→host
# value fetch — the only reliable sync primitive — costs a variable
# ~60–110 ms (measured r5 with a trivial-op probe). Every timed window
# includes exactly one such fetch, so a window must be LONG enough that
# the fetch is noise: at 2.5 s it is <5%. r1–r4 timed the fast families
# over ~0.5 s windows, silently deflating vision by ~15% (0.60 reported
# vs 0.70 over a 2.5 s window) and longctx by ~10% — and the fetch's
# variance, not the chip, was vision's run-to-run wobble.
WINDOW_TARGET_SEC = 2.5


def _canary_probe() -> float:
    """Fixed-shape bf16 matmul chain (4096³ × 600 iters ≈ 0.4 s),
    identical every round: its achieved TFLOP/s is a pure environment
    signal (relay contention, thermal/clock state) with no dependence on
    this repo's model code. Timed before AND after the burn-in window so
    BENCH JSON classifies a headline-MFU drift by itself (VERDICT r4
    weak #3: the −1.3% r3→r4 drift was attributed to 'environment' on
    faith): canary moved too → environment; canary flat, MFU moved →
    regression. The value includes one relay sync (~100 ms, ~20% here) —
    compare it across rounds, not against peak."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(a, b):
        def body(c, _):
            return (c @ b) * (1.0 / 64.0), None  # rescale keeps bf16 finite
        c, _ = jax.lax.scan(body, a, None, length=CANARY_ITERS)
        return c

    k = jax.random.key(42)
    a = jax.random.normal(k, (CANARY_DIM, CANARY_DIM), jnp.bfloat16)
    b = jax.random.normal(k, (CANARY_DIM, CANARY_DIM), jnp.bfloat16)
    out = chain(a, b)
    float(jnp.sum(out.astype(jnp.float32)))  # warm-up + reliable sync
    t0 = time.perf_counter()
    out = chain(a, b)
    float(jnp.sum(out.astype(jnp.float32)))
    sec = time.perf_counter() - t0
    flops = 2.0 * CANARY_DIM ** 3 * CANARY_ITERS
    return round(flops / sec / 1e12, 2)


def _longctx_bench() -> dict:
    """Trainable flash ring attention at 8k tokens (one chip)."""
    import numpy as np
    from jax.sharding import Mesh

    import jax

    from kubeflow_tpu.models import longctx

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("data", "seq"))
    cfg = longctx.LongContextConfig(**LONGCTX_MODEL)
    params = longctx.init_params(jax.random.key(2), cfg)
    tokens = np.zeros((1, cfg.seq_len), np.int32)
    toks, params = longctx.shard_inputs(tokens, params, mesh)
    step = jax.jit(longctx.make_train_step(cfg, mesh), donate_argnums=(0,))
    params, loss = step(params, toks)
    float(loss)  # value fetch = reliable sync through the remote relay

    # Window sized to WINDOW_TARGET_SEC (same rationale as the family
    # bench: 10 steps ≈ 0.7 s left the per-window relay sync at ~10%).
    t0 = time.perf_counter()
    for _ in range(3):
        params, loss = step(params, toks)
    float(loss)
    est = (time.perf_counter() - t0) / 3
    n_steps = max(LONGCTX_STEPS, int(WINDOW_TARGET_SEC / est) + 1)

    def window():
        nonlocal params
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, loss = step(params, toks)
        float(loss)
        return (time.perf_counter() - t0) / n_steps

    m = _measure_trials(window)
    m["window_steps"] = n_steps
    sec = m["median_sec"]
    return {
        "attention": cfg.attention,
        "seq_len": cfg.seq_len,
        "step_sec": round(sec, 4),
        "tokens_per_sec": round(cfg.seq_len / sec, 0),
        **_spread_fields(m),
    }


# Per-phase classification rules for the cold-start waterfall: every
# phase names the signal that tells environment drift from a repo
# regression, so r06+ artifacts classify a cold-start move from JSON
# alone (the ROADMAP cold-start item's groundwork).
COLDSTART_PHASE_RULES = {
    "interpreter_spawn_sec": (
        "environment: fork + CPython start + site init; compare "
        "coldstart_canary.interpreter_spawn_sec — canary moved too -> "
        "environment drift, canary flat -> probe-harness regression"),
    "imports_sec": (
        "import graph: compare coldstart_canary.import_jax_sec — canary "
        "flat while this grew -> repo import regression (heavier "
        "kubeflow_tpu import path)"),
    "jax_init_sec": (
        "backend attach: device client init / relay contention; grows "
        "when another process holds the chip or the TPU runtime "
        "restarts, never with cache state"),
    "compile_sec": (
        "XLA compile (param-init jit + train-step lower+compile): the "
        "warm-cache run should collapse this toward ~0 — a warm run "
        "paying cold-level compile is a cache miss (key churn: "
        "jax/model version bump, shape change)"),
    "first_step_sec": (
        "first execution: weight allocation + host->device transfer; "
        "scales with model size, independent of cache state"),
    "unattributed_sec": (
        "residual outside the instrumented phases; growth means a phase "
        "boundary is missing from the probe"),
}


def _fresh_probe(t0_epoch: float) -> None:
    """Fresh-process start-to-first-step: everything a user's notebook
    start pays — interpreter + imports + device-client attach + init +
    compile + first step. The compilation cache dir comes from the
    ``KFTPU_BENCH_CACHE_DIR`` env: pointed at the populated repo cache
    this measures the WARM start; pointed at an empty temp dir it
    measures the TRUE COLD start (nothing reusable on disk). Prints one
    JSON line; the parent folds it into the main output.

    Besides the headline, emits the PHASE-ATTRIBUTED waterfall
    (``phases``): interpreter spawn / imports / jax init / compile /
    first step, each classifiable via :data:`COLDSTART_PHASE_RULES` —
    "where do the 43s go" answered from the artifact alone. The
    standalone ``compile_sec`` keeps its historical meaning (train-step
    lower+compile only) for cross-round comparability; the waterfall's
    ``compile_sec`` phase also covers the param-init jit."""
    proc_start = time.time()
    phases: dict = {
        "interpreter_spawn_sec": round(max(0.0, proc_start - t0_epoch), 3)}

    t = time.perf_counter()
    from kubeflow_tpu.utils.compilecache import (
        cache_entries,
        enable_persistent_cache,
        note_compile,
        seed_cache,
    )

    probe_cache_dir = enable_persistent_cache(
        os.environ.get("KFTPU_BENCH_CACHE_DIR", CACHE_DIR))
    # Warm-pool seeding path (no-op without KFTPU_COMPILE_CACHE_SEED_DIR):
    # the same seed_cache the warm-idle loop runs, so the probe measures
    # exactly what a seeded warm pod's first compile pays.
    seeded = seed_cache(cache_dir=probe_cache_dir)
    from functools import partial

    import jax

    from kubeflow_tpu.models import BurninConfig, init_params, make_train_step
    phases["imports_sec"] = round(time.perf_counter() - t, 3)

    # Platform override (multichip's cold-start recheck on a dryrun
    # host): must land before the first backend query.
    platform = os.environ.get(PLATFORM_ENV)
    if platform:
        jax.config.update("jax_platforms", platform)

    t = time.perf_counter()
    jax.devices()  # force the backend/device-client attach eagerly
    phases["jax_init_sec"] = round(time.perf_counter() - t, 3)

    t_phase = time.perf_counter()
    entries_before = cache_entries(probe_cache_dir)
    small = bool(os.environ.get(SMALL_MODEL_ENV))
    cfg = BurninConfig(**(SMALL_BENCH_MODEL if small else BENCH_MODEL))
    params = jax.jit(partial(init_params, cfg=cfg))(jax.random.key(0))
    tokens = jax.random.randint(
        jax.random.key(1), (BENCH_BATCH, cfg.seq_len), 0, cfg.vocab
    )
    step = jax.jit(make_train_step(cfg), donate_argnums=(0,))
    t0 = time.perf_counter()
    compiled = step.lower(params, tokens).compile()
    compile_sec = time.perf_counter() - t0
    phases["compile_sec"] = round(time.perf_counter() - t_phase, 3)
    entries_after = cache_entries(probe_cache_dir)
    # Per-phase cache attribution (ISSUE 14): an unchanged entry count
    # across the compile phase = served from the persistent cache.
    compile_cache = {
        "entries_before": entries_before,
        "entries_after": entries_after,
        "result": note_compile(entries_before, entries_after),
        "seeded": seeded["seeded"],
        "cache_dir_ready": seeded["ready"],
    }

    t = time.perf_counter()
    params, loss = compiled(params, tokens)
    float(loss)
    phases["first_step_sec"] = round(time.perf_counter() - t, 3)

    total = round(time.time() - t0_epoch, 3)
    phases["unattributed_sec"] = round(
        max(0.0, total - sum(phases.values())), 3)
    print(json.dumps({
        "coldstart_sec": total,
        "compile_sec": round(compile_sec, 3),
        "model": "small" if small else "bench",
        "phases": phases,
        "compile_cache": compile_cache,
    }))


def _run_fresh_probe(cache_dir: str) -> dict | None:
    """Run a fresh-process start probe in a subprocess (the axon relay
    multiplexes the chip, so the child can attach while this process
    holds it) against the given compilation-cache dir."""
    import subprocess

    env = dict(os.environ, KFTPU_BENCH_CACHE_DIR=cache_dir)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--fresh-probe", repr(time.time())],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            return None
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception:
        return None


def _coldstart_canary() -> dict:
    """Environment canary for the COLD-START numbers, mirroring the MFU
    canary (`_canary_probe`): fixed probes with zero dependence on this
    repo's model/control-plane code, timed the same way every round, so
    a warm-cache cold-start drift (r03 11.6 s → r05 13.9 s) is
    classifiable from the BENCH JSON alone. Components:

    - ``interpreter_spawn_sec``: fork + CPython start + site init for a
      no-op child — the floor every fresh-process probe pays;
    - ``import_jax_sec``: a fresh child importing jax (+ backend
      registration) — the import share of every notebook start.

    Rule (stamped in the block): compare across rounds. Canary moved
    with the warm cold-start → environment drift (slower disk/CPU,
    fatter site-packages); canary flat while warm cold-start moved →
    a regression this repo owns (cache miss, heavier import graph)."""
    import subprocess

    def timed(code: str) -> float | None:
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                timeout=180, cwd=os.path.dirname(os.path.abspath(__file__)))
        except Exception:
            return None
        if proc.returncode != 0:
            return None
        return round(time.perf_counter() - t0, 3)

    interpreter = timed("pass")
    import_jax = timed("import jax")
    return {
        "interpreter_spawn_sec": interpreter,
        "import_jax_sec": import_jax,
        "fixed_overhead_sec": (
            round(interpreter + import_jax, 3)
            if interpreter is not None and import_jax is not None
            else None),
        "rule": "compare across rounds: canary moved with "
                "coldstart_warm_cache_sec -> environment; canary flat "
                "while warm coldstart moved -> repo regression",
    }


def _coldstart_probes() -> dict:
    """Both fresh-process start numbers, measured apples-to-apples:

    - ``cold_cache``: empty cache dir — the first-ever notebook start.
    - ``warm_cache``: re-run over the cache the cold probe just wrote —
      guaranteed-warm for the CURRENT model, and independent of whatever
      state the repo cache is in.

    Must run BEFORE the bench process attaches its own jax client: a
    probe compiling while the parent holds the chip through the shared
    relay measures contention, not start-up (measured: warm compile
    16 s under a live parent vs 2.6 s without).

    (The in-process ``in_process_to_first_step_sec`` is a third, smaller
    number: it starts its clock after imports and device attach, so it
    is NOT comparable to these — that asymmetry, not cache state, was
    the r03 "warm slower than cold" inversion.)"""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="kftpu-coldcache-")
    try:
        cold = _run_fresh_probe(tmp)
        warm = _run_fresh_probe(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "coldstart_cold_cache_sec": cold.get("coldstart_sec") if cold else None,
        "cold_compile_sec": cold.get("compile_sec") if cold else None,
        "coldstart_warm_cache_sec": warm.get("coldstart_sec") if warm else None,
        "warm_compile_sec": warm.get("compile_sec") if warm else None,
        # Phase-attributed waterfall (ISSUE 13): WHERE the cold/warm
        # seconds go, with a per-phase classification rule — the
        # ROADMAP cold-start war's attribution groundwork.
        "coldstart_waterfall": {
            "cold": cold.get("phases") if cold else None,
            "warm": warm.get("phases") if warm else None,
            # Hit/miss attribution per probe (ISSUE 14): the warm run's
            # compile phase must be a HIT — a warm run paying a miss is
            # the cache-key-churn regression the rules name.
            "cold_compile_cache": cold.get("compile_cache") if cold else None,
            "warm_compile_cache": warm.get("compile_cache") if warm else None,
            "classification": COLDSTART_PHASE_RULES,
        },
        # Environment canary alongside the numbers it classifies (the
        # r03→r05 warm-cache drift was unattributable from artifacts
        # alone; this block fixes that going forward).
        "coldstart_canary": _coldstart_canary(),
    }


def moe_train_step_flops(cfg, batch: int) -> float:
    """Analytic matmul FLOPs for one MoE train step — same discipline as
    ``train_step_flops``: credit only *useful* routed work (k experts per
    token), NOT the capacity-padded compute the hardware actually does
    (capacity_factor overcounting would inflate MFU)."""
    s = cfg.seq_len - 1
    d, ff, v, k = cfg.d_model, cfg.d_ff, cfg.vocab, cfg.router_top_k
    per_token_layer = (
        2 * d * 3 * d                 # qkv
        + 2 * d * d                   # attention out projection
        + 2 * d * cfg.n_experts      # router logits
        + k * (2 * d * ff + 2 * ff * d)   # routed experts (credited k, not capacity)
    )
    per_layer_attn = 2 * batch * s * s * d   # causal ½ credit (see above)
    fwd = (
        batch * s * (cfg.n_layers * per_token_layer + 2 * d * v)
        + cfg.n_layers * per_layer_attn
    )
    return 3.0 * fwd


FAMILY_STEPS = 20
# Family spread past this → one re-measure (shared-relay contention; the
# per-family spreads in r01–r04 sat under 3.2% on an idle chip).
RETRY_SPREAD_PCT = 5.0


def _spread_fields(m: dict) -> dict:
    """The variance fields every family row carries, including the
    retry evidence when the contention re-measure fired."""
    row = {"trials_sec": m["trials_sec"], "spread_pct": m["spread_pct"]}
    if "window_steps" in m:
        row["window_steps"] = m["window_steps"]
    if m.get("retried"):
        row["retried"] = True
        row["first_attempt"] = m["first_attempt"]
    return row

# Per-family perf configs (VERDICT r2 weak #6: regressions in MoE /
# pipelined / vision were invisible with only the burnin number tracked).
# capacity_factor 1.0 (Switch-style tight capacity): the experts compute
# exactly the credited k-per-token work instead of 1.25× padded seats —
# measured on the chip, cf 1.25→1.0 at batch 8 is 92.3→84.5 ms/step
# (MFU 0.510→0.557, tokens/s 88.7k→96.9k). The trade is real token
# dropping under router imbalance — fine for a kernel-efficiency bench,
# documented in docs/perf.md; training configs pick their own cf.
# n_layers 4 (r5, was 2): the r5 on-chip decomposition (docs/perf.md)
# showed the 2-layer config spent ~9% of its step in the fixed lm-head +
# final-softmax — a depth artifact no real MoE model (dozens of layers)
# carries. 4 layers halves that dilution while every layer still pays
# the full router/dispatch machinery; per-layer costs are unchanged, so
# dispatch regressions move this row exactly as before.
MOE_MODEL = dict(
    vocab=8192, d_model=2048, n_heads=16, n_layers=4, d_ff=8192,
    seq_len=1025, n_experts=8, router_top_k=2, attention="flash",
    capacity_factor=1.0,
)
MOE_BATCH = 8
# attention="flash": the pallas fused kernel instead of materialized
# scores — measured on the chip (r5): fused 0.475→0.578 MFU, schedule
# 0.42→0.52 on top of the full-unroll schedule rewrite. Equivalence vs
# the xla-attention oracle is tested (tests/test_pipeline.py).
PP_MODEL = dict(
    vocab=8192, d_model=2048, n_heads=16, n_layers=4, d_ff=8192,
    seq_len=1025, n_micro=4, attention="flash",
)
# Swept on the chip (docs/perf.md): with the space-to-depth stem,
# batch 128→256 lifts conv MFU 0.597→0.639 AND img/s 10.1k→10.8k — the
# bigger batch now wins throughput too, so the 2× step latency is the
# right trade for this family's purpose (tracking conv-path efficiency).
VISION_BATCH = 256


def _family_bench(peak_tflops: float | None) -> dict:
    """MoE / pipelined / vision step time + MFU on the bench chip. Single
    chip: parallel axes are size 1 (the 8-device dryrun gate owns the
    sharded paths); what this tracks is each family's kernel/schedule
    efficiency so a regression moves a number (BENCH_r0N history)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    out: dict = {}
    dev = jax.devices()[:1]

    def timed(step, params, *rest):
        """Median of MEASURE_TRIALS windows + spread (see _measure_trials).

        Contention retry (VERDICT r4 next #3): a spread past
        ``RETRY_SPREAD_PCT`` means the shared relay interfered with at
        least one window — re-measure ONCE and keep whichever run has
        the tighter spread, recording that a retry happened (and the
        first run's numbers) so the artifact shows its work."""
        params, loss = step(params, *rest)   # warm-up (and donate-in)
        float(loss)

        # Size the window to WINDOW_TARGET_SEC of chip time so the one
        # relay sync per window stays <5% (see the constant's rationale —
        # fixed 20-step windows deflated the fast families by up to 15%).
        t0 = time.perf_counter()
        for _ in range(3):
            params, loss = step(params, *rest)
        float(loss)
        est = (time.perf_counter() - t0) / 3
        n_steps = max(FAMILY_STEPS, int(WINDOW_TARGET_SEC / est) + 1)

        def window():
            nonlocal params
            t0 = time.perf_counter()
            for _ in range(n_steps):
                params, loss = step(params, *rest)
            float(loss)
            return (time.perf_counter() - t0) / n_steps

        m = _measure_trials(window)
        if m["spread_pct"] > RETRY_SPREAD_PCT:
            retry = _measure_trials(window)
            first = {"trials_sec": m["trials_sec"],
                     "spread_pct": m["spread_pct"]}
            if retry["spread_pct"] < m["spread_pct"]:
                m = retry
            m["retried"] = True
            m["first_attempt"] = first
        m["window_steps"] = n_steps
        return m

    # --- Vision FIRST (residual convnet; FLOPs from XLA's cost model —
    # conv shapes are stage-dependent, and the compiler's count can't be
    # gamed). Ordered first + explicit buffer frees between families as
    # allocator hygiene: the fastest family must not absorb whatever HBM
    # state ~0.5B-param donated buffers leave behind.
    from kubeflow_tpu.models import vision

    import jax.numpy as jnp

    v_cfg = vision.VisionConfig()
    v_params = vision.init_params(jax.random.key(9), v_cfg)
    images = jax.random.normal(
        jax.random.key(10),
        (VISION_BATCH, v_cfg.image_size, v_cfg.image_size, v_cfg.channels),
        jnp.dtype(v_cfg.dtype))
    labels = jax.random.randint(
        jax.random.key(11), (VISION_BATCH,), 0, v_cfg.num_classes)
    v_step_fn = vision.make_train_step(v_cfg)
    v_compiled = jax.jit(v_step_fn, donate_argnums=(0,)).lower(
        v_params, (images, labels)).compile()
    m = timed(v_compiled, v_params, (images, labels))
    sec = m["median_sec"]
    try:
        cost = v_compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops = float(cost.get("flops", 0.0))
    except Exception:
        flops = 0.0
    tf = flops / sec / 1e12 if flops else None
    out["vision"] = {
        "step_sec": round(sec, 4),
        **_spread_fields(m),
        "images_per_sec": round(VISION_BATCH / sec, 1),
        "achieved_tflops": round(tf, 2) if tf else None,
        "mfu": round(tf / peak_tflops, 4) if (tf and peak_tflops) else None,
        "flops_source": "xla_cost_analysis",
    }
    del v_params, images, labels, v_compiled  # free HBM for the next family

    # --- MoE (top-2 routed FF; expert axis size 1 on one chip) ---------------
    from kubeflow_tpu.models import moe as moe_model

    mesh = Mesh(np.asarray(dev).reshape(1, 1), ("data", "expert"))
    cfg = moe_model.MoEConfig(**MOE_MODEL)
    params = moe_model.shard_params(
        moe_model.init_params(jax.random.key(5), cfg), mesh, cfg)
    tokens = jax.random.randint(
        jax.random.key(6), (MOE_BATCH, cfg.seq_len), 0, cfg.vocab)
    step = jax.jit(moe_model.make_train_step(cfg, mesh), donate_argnums=(0,))
    m = timed(step, params, tokens)
    sec = m["median_sec"]
    flops = moe_train_step_flops(cfg, MOE_BATCH)
    tf = flops / sec / 1e12
    out["moe"] = {
        "step_sec": round(sec, 4),
        **_spread_fields(m),
        "achieved_tflops": round(tf, 2),
        "mfu": round(tf / peak_tflops, 4) if peak_tflops else None,
        "router_top_k": cfg.router_top_k,
        "n_experts": cfg.n_experts,
    }
    del params, tokens, step

    # --- Pipelined (GPipe schedule, 1 stage on one chip) ---------------------
    from kubeflow_tpu.models import pipelined

    pp_mesh = pipelined.make_pp_mesh(dev, n_stages=1, n_model=1)
    pp_cfg = pipelined.PipelinedConfig(**PP_MODEL)
    pp_params = pipelined.shard_params(
        pipelined.init_params(jax.random.key(7), pp_cfg), pp_mesh, pp_cfg)
    pp_tokens = jax.random.randint(
        jax.random.key(8), (8, pp_cfg.seq_len), 0, pp_cfg.vocab)
    pp_step = jax.jit(pipelined.make_train_step(pp_cfg, pp_mesh),
                      donate_argnums=(0,))
    m = timed(pp_step, pp_params, pp_tokens)
    sec = m["median_sec"]
    flops = train_step_flops(pp_cfg, 8)
    tf = flops / sec / 1e12
    out["pipelined"] = {
        "step_sec": round(sec, 4),
        **_spread_fields(m),
        "achieved_tflops": round(tf, 2),
        "mfu": round(tf / peak_tflops, 4) if peak_tflops else None,
        "n_micro": pp_cfg.n_micro,
        "path": "fused_bypass",  # n_stages=1 routes around the schedule
    }

    # Same model through the REAL GPipe tick/scan (force_schedule): the
    # row that moves when models/pipelined.py's schedule machinery — the
    # scan, masking, ppermute self-hop — regresses. The fused row above
    # tracks the production single-stage path; this one tracks the
    # machinery multi-stage jobs actually run (r03 weak #3: the schedule
    # had no tracked number on hardware).
    sched_params = pipelined.shard_params(
        pipelined.init_params(jax.random.key(7), pp_cfg), pp_mesh, pp_cfg)
    sched_step = jax.jit(
        pipelined.make_train_step(pp_cfg, pp_mesh, force_schedule=True),
        donate_argnums=(0,))
    m = timed(sched_step, sched_params, pp_tokens)
    sec = m["median_sec"]
    tf = flops / sec / 1e12
    out["pipelined_schedule"] = {
        "step_sec": round(sec, 4),
        **_spread_fields(m),
        "achieved_tflops": round(tf, 2),
        "mfu": round(tf / peak_tflops, 4) if peak_tflops else None,
        "n_micro": pp_cfg.n_micro,
        "path": "gpipe_schedule",
    }
    return out


# --------------------------------------------------------------------------
# `bench.py multichip [--smoke]` — the MULTICHIP gate made real (ISSUE 18):
# moe / pipelined / ring+ulysses long-context / vision on an 8-device mesh
# THROUGH the step profiler, with per-family MFU and the paired
# serialize-mode collective-overlap attribution — numbers, not `ok=true`.
# Self-provisioning like __graft_entry__.dryrun_multichip: the parent
# re-execs a child with a virtual 8-device CPU host platform (a fresh
# interpreter is the only way to force the device count), so the gate runs
# identically on a 1-chip bench host and in chip-free CI.
# --------------------------------------------------------------------------

MULTICHIP_DEVICES = 8
MC_STEPS = 4        # measured steps per arm; +1 compile-inclusive first step
MC_SMOKE_STEPS = 3

# Family configs sized for the virtual CPU mesh (every virtual device
# shares the host cores, so per-step work must stay small): the point is
# exercising the REAL sharded paths — 8-way expert all_to_alls, the 4-stage
# x 2-way-tp GPipe schedule, the 2-D ring x ulysses sequence mesh — and the
# telemetry plumbing around them, not absolute throughput. f32: CPU bf16 is
# emulated and would only add noise.
MC_MOE_MODEL = dict(
    vocab=512, d_model=128, n_heads=4, n_layers=2, d_ff=512, seq_len=129,
    n_experts=8, router_top_k=2, capacity_factor=1.25, attention="xla",
    dtype="float32",
)
MC_MOE_BATCH = 8
MC_PP_MODEL = dict(
    vocab=512, d_model=128, n_heads=4, n_layers=4, d_ff=512, seq_len=129,
    n_micro=4, attention="xla", dtype="float32",
)
MC_PP_BATCH = 8
MC_PP_STAGES = 4
MC_PP_TP = 2
# Long-context past either strategy alone: sequence sharded over a 2-D
# (ring 4 x ulysses 2) mesh — ulysses all-to-alls gather contiguous ring
# blocks inside each group, ring hops K/V between groups (see
# parallel/ulysses.ring_ulysses_attention). 32k full / 4k smoke; flash
# block impl streams the gathered blocks so no [S/Pr]^2 logits buffer is
# materialized (xla impl at 32k thrashes a CPU host's caches).
MC_LONGCTX_MODEL = dict(
    vocab=256, d_model=32, n_heads=2, n_layers=1, d_ff=128,
    attention="ring_ulysses_flash", dtype="float32",
)
MC_LONGCTX_SEQ = 32768
MC_LONGCTX_SMOKE_SEQ = 4096
MC_LONGCTX_RING = 4
MC_LONGCTX_ULY = 2
MC_VISION_MODEL = dict(
    image_size=32, widths=(32, 64, 128), blocks_per_stage=1,
    num_classes=100, dtype="float32",
)
MC_VISION_BATCH = 32

MC_PROBE_DIM = 1024
MC_PROBE_ITERS = 12


def longctx_train_step_flops(cfg, batch: int) -> float:
    """Analytic matmul FLOPs for one long-context train step. Same
    discipline as ``train_step_flops`` (dense matmuls + causal-credited
    attention), but the roll-shift loss trains on all S tokens."""
    s = cfg.seq_len
    d, ff, v = cfg.d_model, cfg.d_ff, cfg.vocab
    per_token_layer = 2 * d * 3 * d + 2 * d * d + 2 * d * ff + 2 * ff * d
    per_layer_attn = 2 * batch * s * s * d  # causal half credit
    fwd = (
        batch * s * (cfg.n_layers * per_token_layer + 2 * d * v)
        + cfg.n_layers * per_layer_attn
    )
    return 3.0 * fwd


def _host_peak_probe() -> float:
    """f32 matmul-chain TFLOP/s on one virtual device — the MFU
    denominator on the dryrun mesh (``mfu_basis="host_matmul_probe"``).
    Every virtual device time-slices the same host cores, so the
    single-device probe IS the whole mesh's peak; the resulting MFU is
    comparable across rounds on the same host class, never against
    accelerator-basis numbers (`classify_mfu_drift` refuses cross-basis
    comparisons). Best of two runs: the probe fights the same CPU the
    families run on, and the max is the less contended sample."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(a, b):
        def body(c, _):
            return (c @ b) * (1.0 / MC_PROBE_DIM), None
        c, _ = jax.lax.scan(body, a, None, length=MC_PROBE_ITERS)
        return c

    k = jax.random.key(7)
    a = jax.random.normal(k, (MC_PROBE_DIM, MC_PROBE_DIM), jnp.float32)
    b = jax.random.normal(k, (MC_PROBE_DIM, MC_PROBE_DIM), jnp.float32)
    chain(a, b).block_until_ready()  # compile + warm
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        chain(a, b).block_until_ready()
        sec = time.perf_counter() - t0
        best = max(best, 2.0 * MC_PROBE_DIM ** 3 * MC_PROBE_ITERS / sec / 1e12)
    return round(best, 4)


_MC_ROUND = {
    "step_p50_sec": 5, "step_mean_sec": 5, "achieved_tflops": 4, "mfu": 4,
    "tokens_per_sec": 1, "first_step_sec": 3, "compile_sec": 3,
    "overlap_fraction": 4, "serialized_step_sec": 5,
}


def _mc_family(name: str, build, *, flops_per_step: float,
               tokens_per_step: int, peak_tflops: float, steps: int,
               has_sections: bool = True) -> dict:
    """Run one family through the step profiler: an overlapped arm (the
    shipped schedule) and — when the family issues registered collective
    sections — a serialized arm traced under
    ``sections.set_serialize_collectives(True)`` (fresh build = fresh
    trace+compile; the flag is trace-time). The pair yields the
    collective-overlap attribution the profiler summary carries.

    ``build()`` returns a zero-arg ``run()`` that executes one training
    step (mutating its own state closure) and returns a sync value."""
    import jax

    from kubeflow_tpu.telemetry import StepProfiler, sections
    from kubeflow_tpu.telemetry.profiler import overlap_fraction

    prof = StepProfiler(
        name, flops_per_step=flops_per_step, tokens_per_step=tokens_per_step,
        peak_flops=peak_tflops * 1e12, mfu_basis="host_matmul_probe",
        window=max(2, steps), sync_every=1,
    )
    run = build()
    for i in range(steps + 1):  # +1: first step is the compile-inclusive one
        prof.start()
        sync = run()
        prof.stop(step=i + 1, sync_value=sync)
    prof.note_hbm()

    if has_sections:
        serial: list[float] = []
        sections.set_serialize_collectives(True)
        try:
            run_s = build()
            for _ in range(steps + 1):
                t0 = time.perf_counter()
                sync = run_s()
                jax.block_until_ready(sync)
                serial.append(time.perf_counter() - t0)
        finally:
            sections.set_serialize_collectives(False)
        serialized_p50 = _median_sorted(sorted(serial[1:]))
        prof.note_overlap(
            overlap_fraction(prof.step_p50_sec() or 0.0, serialized_p50),
            serialized_p50)

    row = prof.summary()
    for key, digits in _MC_ROUND.items():
        if isinstance(row.get(key), float):
            row[key] = round(row[key], digits)
    if not has_sections:
        row["overlap_note"] = (
            "no registered collective sections: pure data-parallel jit "
            "(grad all-reduce is GSPMD-inserted, not attributable)")
    return row


def _multichip_child(smoke: bool) -> dict:
    """Runs inside the forced 8-device child; prints nothing itself."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < MULTICHIP_DEVICES:
        raise RuntimeError(
            f"multichip child has {len(devs)} devices; host-platform "
            f"forcing failed (XLA_FLAGS={os.environ.get('XLA_FLAGS')})")
    devs = devs[:MULTICHIP_DEVICES]
    steps = MC_SMOKE_STEPS if smoke else MC_STEPS
    peak = _host_peak_probe()
    families: dict = {}

    # --- MoE: 8-way expert parallelism — dispatch/combine all_to_alls ----
    from kubeflow_tpu.models import moe as moe_model

    moe_cfg = moe_model.MoEConfig(**MC_MOE_MODEL)
    moe_mesh = Mesh(np.asarray(devs).reshape(1, MULTICHIP_DEVICES),
                    ("data", "expert"))

    def build_moe():
        params = moe_model.shard_params(
            moe_model.init_params(jax.random.key(5), moe_cfg), moe_mesh,
            moe_cfg)
        tokens = jax.random.randint(
            jax.random.key(6), (MC_MOE_BATCH, moe_cfg.seq_len), 0,
            moe_cfg.vocab)
        step = jax.jit(moe_model.make_train_step(moe_cfg, moe_mesh),
                       donate_argnums=(0,))
        state = {"params": params}

        def run():
            state["params"], loss = step(state["params"], tokens)
            return loss
        return run

    families["moe"] = {
        **_mc_family("moe", build_moe,
                     flops_per_step=moe_train_step_flops(moe_cfg,
                                                         MC_MOE_BATCH),
                     tokens_per_step=MC_MOE_BATCH * (moe_cfg.seq_len - 1),
                     peak_tflops=peak, steps=steps),
        "mesh": {"data": 1, "expert": MULTICHIP_DEVICES},
        "n_experts": moe_cfg.n_experts,
        "router_top_k": moe_cfg.router_top_k,
    }

    # --- Pipelined: 4-stage GPipe schedule x 2-way tensor parallel -------
    from kubeflow_tpu.models import pipelined

    pp_cfg = pipelined.PipelinedConfig(**MC_PP_MODEL)
    pp_mesh = pipelined.make_pp_mesh(devs, n_stages=MC_PP_STAGES,
                                     n_model=MC_PP_TP)

    def build_pp():
        params = pipelined.shard_params(
            pipelined.init_params(jax.random.key(7), pp_cfg), pp_mesh,
            pp_cfg)
        tokens = jax.random.randint(
            jax.random.key(8), (MC_PP_BATCH, pp_cfg.seq_len), 0,
            pp_cfg.vocab)
        step = jax.jit(pipelined.make_train_step(pp_cfg, pp_mesh),
                       donate_argnums=(0,))
        state = {"params": params}

        def run():
            state["params"], loss = step(state["params"], tokens)
            return loss
        return run

    families["pipelined"] = {
        **_mc_family("pipelined", build_pp,
                     flops_per_step=train_step_flops(pp_cfg, MC_PP_BATCH),
                     tokens_per_step=MC_PP_BATCH * (pp_cfg.seq_len - 1),
                     peak_tflops=peak, steps=steps),
        "mesh": {"data": 1, "stage": MC_PP_STAGES, "model": MC_PP_TP},
        "n_micro": pp_cfg.n_micro,
        "path": "gpipe_schedule",
    }

    # --- Long-context: ring x ulysses composed sequence parallelism ------
    from kubeflow_tpu.models import longctx

    lc_seq = MC_LONGCTX_SMOKE_SEQ if smoke else MC_LONGCTX_SEQ
    lc_cfg = longctx.LongContextConfig(seq_len=lc_seq, **MC_LONGCTX_MODEL)
    lc_mesh = Mesh(
        np.asarray(devs).reshape(1, MC_LONGCTX_RING, MC_LONGCTX_ULY),
        ("data", "seq_ring", "seq_uly"))
    lc_axes = ("seq_ring", "seq_uly")

    def build_longctx():
        params = longctx.init_params(jax.random.key(2), lc_cfg)
        tokens = np.zeros((1, lc_cfg.seq_len), np.int32)
        toks, params = longctx.shard_inputs(tokens, params, lc_mesh,
                                            seq_axis=lc_axes)
        step = jax.jit(
            longctx.make_train_step(lc_cfg, lc_mesh, seq_axis=lc_axes),
            donate_argnums=(0,))
        state = {"params": params}

        def run():
            state["params"], loss = step(state["params"], toks)
            return loss
        return run

    families["longctx"] = {
        **_mc_family("longctx", build_longctx,
                     flops_per_step=longctx_train_step_flops(lc_cfg, 1),
                     tokens_per_step=lc_cfg.seq_len,
                     peak_tflops=peak, steps=steps),
        "mesh": {"data": 1, "seq_ring": MC_LONGCTX_RING,
                 "seq_uly": MC_LONGCTX_ULY},
        "seq_len": lc_cfg.seq_len,
        "attention": lc_cfg.attention,
    }

    # --- Vision: 8-way data parallelism (FLOPs from XLA's cost model) ----
    from kubeflow_tpu.models import vision

    v_cfg = vision.VisionConfig(**MC_VISION_MODEL)
    v_mesh = Mesh(np.asarray(devs), ("data",))
    v_flops = [0.0]

    def build_vision():
        params = vision.init_params(jax.random.key(9), v_cfg)
        images = jax.random.normal(
            jax.random.key(10),
            (MC_VISION_BATCH, v_cfg.image_size, v_cfg.image_size,
             v_cfg.channels), jnp.dtype(v_cfg.dtype))
        labels = jax.random.randint(
            jax.random.key(11), (MC_VISION_BATCH,), 0, v_cfg.num_classes)
        images = jax.device_put(
            images, NamedSharding(v_mesh, P("data", None, None, None)))
        labels = jax.device_put(labels, NamedSharding(v_mesh, P("data")))
        params = jax.device_put(params, NamedSharding(v_mesh, P()))
        step = jax.jit(vision.make_train_step(v_cfg), donate_argnums=(0,))
        compiled = step.lower(params, (images, labels)).compile()
        try:
            cost = compiled.cost_analysis()
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            v_flops[0] = float(cost.get("flops", 0.0))
        except Exception:  # kftpu: ignore[exception-swallow] cost model is optional — a backend without cost_analysis reports mfu=None rather than fail the gate
            pass
        state = {"params": params}

        def run():
            state["params"], loss = compiled(state["params"],
                                             (images, labels))
            return loss
        return run

    # Probe the FLOPs count first so the profiler row can carry MFU (the
    # builder fills v_flops on compile).
    build_vision()
    families["vision"] = {
        **_mc_family("vision", build_vision, flops_per_step=v_flops[0],
                     tokens_per_step=0, peak_tflops=peak, steps=steps,
                     has_sections=False),
        "mesh": {"data": MULTICHIP_DEVICES},
        "images_per_sec": None,
        "flops_source": "xla_cost_analysis",
    }
    p50 = families["vision"].get("step_p50_sec")
    if p50:
        families["vision"]["images_per_sec"] = round(MC_VISION_BATCH / p50, 1)

    return {
        "n_devices": len(devs),
        "backend": jax.default_backend(),
        "host_peak_tflops": peak,
        "mfu_basis": "host_matmul_probe",
        "steps_per_arm": steps,
        "families": families,
    }


def _run_multichip_child(smoke: bool) -> dict:
    """Re-exec this file with a forced 8-device CPU host platform (the
    dryrun_multichip pattern: jax is uninitialized in the parent, but only
    a fresh interpreter honors the XLA_FLAGS device count; the child also
    flips jax.config before any backend query because the image's
    sitecustomize registers the TPU plugin regardless of JAX_PLATFORMS)."""
    import subprocess

    env = dict(os.environ)
    extra = f"--xla_force_host_platform_device_count={MULTICHIP_DEVICES}"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + extra).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["KFTPU_MULTICHIP_CHILD"] = "1"
    cmd = [sys.executable, os.path.abspath(__file__), "--multichip-child"]
    if smoke:
        cmd.append("--smoke")
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=3600, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except Exception as e:
        return {"ok": False, "error": str(e)}
    if proc.returncode != 0:
        return {"ok": False, "rc": proc.returncode,
                "tail": proc.stderr[-2000:]}
    try:
        child = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"ok": False, "rc": 0, "tail": proc.stdout[-2000:]}
    return {"ok": True, **child}


def _multichip_coldstart_recheck() -> dict:
    """The r05 warm-cache drift chase (ISSUE 18 bugfix satellite): re-run
    the fresh-probe cold-start waterfall alongside the multichip round so
    MULTICHIP_r06 carries a post-PR-14 compile-cache attribution. Runs
    the CPU-feasible small model with the backend forced to cpu — the
    absolute seconds are NOT comparable to the BENCH rounds' on-chip
    numbers (both fields say so), but the proving signal is platform-
    independent: the warm run's compile phase must classify as a cache
    HIT and its compile_sec must collapse vs the cold run's. A warm run
    still paying a miss is the cache-key-churn regression the r05 note
    suspected."""
    saved = {k: os.environ.get(k) for k in (SMALL_MODEL_ENV, PLATFORM_ENV)}
    os.environ[SMALL_MODEL_ENV] = "1"
    os.environ[PLATFORM_ENV] = "cpu"
    try:
        probes = _coldstart_probes()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    waterfall = probes.get("coldstart_waterfall") or {}
    warm_cache = (waterfall.get("warm_compile_cache") or {})
    return {
        **probes,
        "model": "small",
        "platform": "cpu",
        "comparable_to_bench_rounds": False,
        "warm_compile_is_hit": warm_cache.get("result") == "hit",
    }


def _load_multichip_artifact(path: str) -> dict | None:
    """A MULTICHIP_r0x.json is either the raw `multichip` JSON or a
    driver wrapper (``tail`` holding the JSON line / ``parsed`` copy) —
    same tolerance as `_load_bench_artifact`. Returns a dict with a
    ``families`` key, or None (pre-r06 rounds carried only ok=true)."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    if isinstance(data.get("families"), dict):
        return data
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("families"), dict):
        return parsed
    tail = data.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and isinstance(obj.get("families"),
                                                    dict):
                return obj
    return None


def classify_mfu_drift(current: dict, baseline: dict, *,
                       threshold_pct: float = 10.0) -> dict:
    """Warn-only MFU-regression canary between MULTICHIP rounds (the
    `classify_coldstart_drift` discipline applied to the data plane):
    compare per-family MFU and flag any same-basis drop past the
    threshold. Always ``warn_only`` — dryrun-mesh MFU moves with host
    load, so the canary annotates rather than gates; a flagged family is
    the cue to re-measure on a quiet host (or the real chip) before
    shipping. Cross-basis comparisons (host probe vs accelerator) are
    refused per family, never silently mixed."""
    cur_f = (current or {}).get("families") or {}
    base_f = (baseline or {}).get("families") or {}
    drops: dict = {}
    compared = 0
    for fam, row in sorted(cur_f.items()):
        base_row = base_f.get(fam) or {}
        cur_mfu, base_mfu = row.get("mfu"), base_row.get("mfu")
        if not isinstance(cur_mfu, (int, float)) \
                or not isinstance(base_mfu, (int, float)) or base_mfu <= 0:
            continue
        if row.get("mfu_basis") != base_row.get("mfu_basis"):
            continue
        compared += 1
        drop_pct = round(100.0 * (base_mfu - cur_mfu) / base_mfu, 2)
        if drop_pct > threshold_pct:
            drops[fam] = {"mfu": [base_mfu, cur_mfu], "drop_pct": drop_pct}
    if not compared:
        return {"classification": "insufficient-data",
                "detail": "no same-basis family MFU pair between rounds",
                "warn_only": True}
    verdict = {"families_compared": compared,
               "threshold_pct": threshold_pct, "warn_only": True}
    if drops:
        return {**verdict, "classification": "mfu-regression",
                "families": drops}
    return {**verdict, "classification": "ok"}


def multichip_mfu_canary(current: dict | None = None) -> dict:
    """Classify this round's family MFU against the newest MULTICHIP
    artifact that carries families (r01–r05 were ok=true smokes)."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    artifacts = sorted(glob.glob(os.path.join(here, "MULTICHIP_r*.json")))
    baseline = None
    baseline_name = None
    for path in reversed(artifacts):
        loaded = _load_multichip_artifact(path)
        if loaded is not None and loaded is not current:
            baseline = loaded
            baseline_name = os.path.basename(path)
            break
    verdict = classify_mfu_drift(current or {}, baseline or {})
    verdict["baseline_round"] = baseline_name
    return verdict


def multichip(smoke: bool = False) -> dict:
    """`bench.py multichip [--smoke]` — the acceptance gate (ISSUE 18):
    per-family MFU + collective-overlap attribution from the 8-device
    mesh through the step profiler, the ring+ulysses long-context
    composition at ≥32k (4k smoke), the fresh-probe cold-start recheck,
    and the warn-only cross-round MFU canary. Exit 1 (via __main__) when
    a family row is missing its numbers or the long-context floor is
    unmet; the canary never gates."""
    # Cold-start recheck FIRST — fresh-process probes must not compile
    # against a parent that holds a device client (this parent never
    # attaches jax at all; families run in the re-exec'd child).
    recheck = _multichip_coldstart_recheck()
    child = _run_multichip_child(smoke)
    canary = multichip_mfu_canary(child if child.get("ok") else None)

    fams = child.get("families") or {}
    need = ("moe", "pipelined", "longctx", "vision")
    rows_ok = all(
        isinstance((fams.get(f) or {}).get("mfu"), (int, float))
        and (fams.get(f) or {}).get("step_p50_sec")
        for f in need)
    overlap_ok = all(
        isinstance((fams.get(f) or {}).get("overlap_fraction"), (int, float))
        for f in ("moe", "pipelined", "longctx"))
    seq_floor = MC_LONGCTX_SMOKE_SEQ if smoke else MC_LONGCTX_SEQ
    seq_ok = (fams.get("longctx") or {}).get("seq_len", 0) >= seq_floor
    return {
        "metric": "multichip",
        "smoke": smoke,
        **child,
        "coldstart_recheck": recheck,
        "mfu_canary": canary,
        "longctx_seq_floor": seq_floor,
        "pass": bool(child.get("ok") and rows_ok and overlap_ok and seq_ok),
    }


SIM_RTT_SEC = 0.005
SIM_RTT_SLICES = 4


def simulated_rtt() -> dict:
    """`bench.py simulated_rtt` — the latency-hiding acceptance gate
    (ISSUE 4). FakeKube's RTT cost is ~0 so the regular scale numbers
    can't see round-trip serialization at all; this variant injects a
    5 ms per-request latency and reconciles ONE multislice notebook
    (4 slices, istio + network policies on — a wide child set) twice:

    - **serial**: `KFTPU_SERIAL_APPLY=1` — the pre-ISSUE-4 shape, every
      child apply a sequential round trip. Its request count IS the
      sequential-RTT-depth (each request = one paid RTT).
    - **parallel**: the shipped DAG-parallel path (apply_set stages +
      overlapped reconcile tail).

    Chip-free. `pass` gates the ≥2× per-notebook convergence speedup;
    `in_flight_peak` proves the overlap is real (serial never exceeds 1).
    """
    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.controllers.notebook import (
        NotebookOptions,
        NotebookReconciler,
    )
    from kubeflow_tpu.testing.fakekube import FakeKube

    async def one() -> dict:
        kube = FakeKube()
        rec = NotebookReconciler(kube, NotebookOptions(
            use_istio=True, create_network_policies=True))
        await kube.create("Notebook", nbapi.new(
            "rtt", "bench", accelerator="v5e", topology="4x4",
            num_slices=SIM_RTT_SLICES))
        kube.set_latency(SIM_RTT_SEC)
        t0 = time.perf_counter()
        await rec.reconcile(("bench", "rtt"))
        wall = time.perf_counter() - t0
        return {
            "wall_sec": round(wall, 4),
            "requests": sum(kube.requests.values()),
            "in_flight_peak": kube.in_flight_peak,
        }

    def run(serial: bool) -> dict:
        prev = os.environ.get("KFTPU_SERIAL_APPLY")
        os.environ["KFTPU_SERIAL_APPLY"] = "1" if serial else "0"
        try:
            return asyncio.run(one())
        finally:
            if prev is None:
                os.environ.pop("KFTPU_SERIAL_APPLY", None)
            else:
                os.environ["KFTPU_SERIAL_APPLY"] = prev

    serial = run(True)
    parallel = run(False)
    speedup = serial["wall_sec"] / max(parallel["wall_sec"], 1e-9)
    return {
        "metric": "simulated_rtt",
        "rtt_sec": SIM_RTT_SEC,
        "num_slices": SIM_RTT_SLICES,
        "serial": serial,
        "parallel": parallel,
        # Each serial request is one paid round trip — the depth the DAG
        # collapses to its critical path.
        "serial_rtt_depth": serial["requests"],
        "speedup": round(speedup, 2),
        "pass": speedup >= 2.0,
    }


SCHED_RUN_SECONDS = 0.15        # simulated "work" before a gang completes
SCHED_GANG_SLICES = 2           # every bench gang: 2 × v5e 4x4 = 32 chips


def _percentile(sorted_xs: list, q: float) -> float:
    if not sorted_xs:
        return 0.0
    idx = min(len(sorted_xs) - 1, int(round(q * (len(sorted_xs) - 1))))
    return sorted_xs[idx]


def scheduler_scale(smoke: bool = False) -> dict:
    """`bench.py scheduler_scale [--smoke]` — the fleet-scheduler
    acceptance gate (ISSUE 5). N namespaces × M queued multislice
    notebooks land on a fixed fleet sized well below demand, so gangs
    queue and admit in waves as earlier gangs complete (the driver
    stop-annotates each admitted gang after a short simulated run).
    Chip-free: FakeKube + podsim + the real manager/controller stack
    with the scheduler wired exactly as production wires it.

    Reported: time-to-admission p50/p95, fairness as the max/min ratio
    of per-namespace *chip-seconds* (time-integrated admitted chips —
    equal-weight namespaces must stay ≤ 1.5 at saturation), zero
    ledger-invariant violations, and the idle-preemption scenario (an
    idle gang must be preempted and a queued higher-priority gang
    admitted within one reconcile round)."""
    import time as _time

    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.controllers.notebook import setup_notebook_controller
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.objects import fmt_iso
    from kubeflow_tpu.scheduler import (
        Fleet,
        SchedulerOptions,
        TpuFleetScheduler,
    )
    from kubeflow_tpu.testing.fakekube import FakeKube
    from kubeflow_tpu.testing.podsim import PodSimulator
    from kubeflow_tpu.webhooks import register_all

    namespaces = 2 if smoke else 4
    per_ns = 2 if smoke else 6
    fleet_spec = ("pool-a=v5e:4x4:2" if smoke
                  else "pool-a=v5e:4x4:4,pool-b=v5e:4x4:4")
    deadline_sec = 30.0 if smoke else 90.0

    async def drive() -> dict:
        kube = FakeKube()
        register_all(kube)
        mgr = Manager(kube)
        fleet = Fleet.parse(fleet_spec)
        sched = TpuFleetScheduler(
            kube,
            SchedulerOptions(queued_requeue_seconds=0.05),
            fleet=fleet, registry=mgr.registry,
        )
        setup_notebook_controller(mgr, scheduler=sched)
        sim = PodSimulator(kube)
        await mgr.start()
        await sim.start()
        try:
            created_at: dict[tuple, float] = {}
            # Round-robin across namespaces — the natural arrival shape
            # for independent tenants, and the one the fairness gate is
            # defined over.
            for i in range(per_ns):
                for n in range(namespaces):
                    ns = f"team-{n}"
                    name = f"nb-{i}"
                    await kube.create("Notebook", nbapi.new(
                        name, ns, accelerator="v5e", topology="4x4",
                        num_slices=SCHED_GANG_SLICES))
                    created_at[(ns, name)] = time.perf_counter()
            total = namespaces * per_ns
            ledger = sched.policy.ledger
            admitted_at: dict[tuple, float] = {}
            completed: set = set()
            chip_seconds: dict[str, float] = {}
            last_sample = time.perf_counter()
            deadline = last_sample + deadline_sec
            while len(completed) < total:
                now = time.perf_counter()
                if now > deadline:
                    raise RuntimeError(
                        f"scheduler_scale: only {len(completed)}/{total} "
                        "gangs completed before the deadline")
                dt = now - last_sample
                last_sample = now
                for ns_name, chips in ledger.ns_chips.items():
                    chip_seconds[ns_name] = \
                        chip_seconds.get(ns_name, 0.0) + chips * dt
                for key in list(ledger.allocations):
                    if key not in admitted_at:
                        admitted_at[key] = now
                    elif (key not in completed
                          and now - admitted_at[key] >= SCHED_RUN_SECONDS):
                        completed.add(key)
                        await kube.patch(
                            "Notebook", key[1],
                            {"metadata": {"annotations": {
                                nbapi.STOP_ANNOTATION: fmt_iso(
                                    _time.time())}}}, key[0])
                await asyncio.sleep(0.005)
            await mgr.wait_idle(timeout=20)
            ledger.assert_consistent()
            waits = sorted(admitted_at[k] - created_at[k]
                           for k in admitted_at)
            integrals = sorted(chip_seconds.values())
            ratio = (integrals[-1] / integrals[0]
                     if integrals and integrals[0] > 0 else float("inf"))
            return {
                "namespaces": namespaces,
                "notebooks_per_namespace": per_ns,
                "gang_slices": SCHED_GANG_SLICES,
                "fleet_chips": fleet.total_chips,
                "demand_chips": total * SCHED_GANG_SLICES * 16,
                "admitted": len(admitted_at),
                "time_to_admission_p50_sec": round(
                    _percentile(waits, 0.50), 4),
                "time_to_admission_p95_sec": round(
                    _percentile(waits, 0.95), 4),
                "fairness_chip_seconds": {
                    ns: round(v, 3)
                    for ns, v in sorted(chip_seconds.items())},
                "fairness_max_min_ratio": round(ratio, 3),
                "ledger_violations": ledger.violations,
                "queue_depth_final": len(sched.policy.pending),
            }
        finally:
            await sim.stop()
            await mgr.stop()
            kube.close_watches()

    async def preemption_scenario() -> dict:
        kube = FakeKube()
        register_all(kube)
        mgr = Manager(kube)
        sched = TpuFleetScheduler(
            kube,
            SchedulerOptions(idle_preempt_after_seconds=0.2,
                             queued_requeue_seconds=0.05),
            fleet=Fleet.parse("pool-a=v5e:4x4:1"), registry=mgr.registry,
        )
        setup_notebook_controller(mgr, scheduler=sched)
        sim = PodSimulator(kube)
        await mgr.start()
        await sim.start()
        try:
            await kube.create("Notebook", nbapi.new(
                "idler", "team-low", accelerator="v5e", topology="4x4"))
            await mgr.wait_idle(timeout=20)
            assert ("team-low", "idler") in sched.policy.ledger.allocations
            # Culling's probe says the server has been idle for an hour
            # (without this signal a holder is NEVER idle-preemptible);
            # the admitted-at stamp floors it, so the idle window still
            # clocks from admission. Let it elapse, then refresh the
            # holder's signal via its periodic reconcile.
            await kube.patch(
                "Notebook", "idler",
                {"metadata": {"annotations": {
                    nbapi.LAST_ACTIVITY_ANNOTATION: fmt_iso(
                        _time.time() - 3600)}}}, "team-low")
            await asyncio.sleep(0.25)
            mgr.enqueue("notebook", ("team-low", "idler"))
            await mgr.wait_idle(timeout=20)
            t0 = time.perf_counter()
            await kube.create("Notebook", {
                **nbapi.new("urgent", "team-hi", accelerator="v5e",
                            topology="4x4"),
                "metadata": {"name": "urgent", "namespace": "team-hi",
                             "annotations": {
                                 nbapi.PRIORITY_ANNOTATION: "high"}},
            })
            await mgr.wait_idle(timeout=20)
            wall = time.perf_counter() - t0
            victim = await kube.get("Notebook", "idler", "team-low")
            annotations = victim.get("metadata", {}).get("annotations", {})
            preempted = nbapi.STOP_ANNOTATION in annotations and \
                annotations.get(nbapi.PREEMPTED_ANNOTATION) == "idle"
            admitted = ("team-hi", "urgent") in \
                sched.policy.ledger.allocations
            return {
                "victim_preempted": preempted,
                "high_priority_admitted": admitted,
                "wall_sec": round(wall, 4),
                "pass": preempted and admitted,
            }
        finally:
            await sim.stop()
            await mgr.stop()
            kube.close_watches()

    out = asyncio.run(drive())
    preemption = asyncio.run(preemption_scenario())
    ratio_ok = out["fairness_max_min_ratio"] <= 1.5
    return {
        "metric": "scheduler_scale",
        "smoke": smoke,
        **out,
        "preemption": preemption,
        "pass": (ratio_ok and out["ledger_violations"] == 0
                 and out["admitted"] == out["namespaces"]
                 * out["notebooks_per_namespace"]
                 and preemption["pass"]),
    }


def migration_roundtrip(smoke: bool = False) -> dict:
    """`bench.py migration_roundtrip [--smoke]` — the preempt-to-
    checkpoint acceptance gate (ISSUE 7). For each gang size: an idle
    victim holds the whole fleet, a high-priority gang arrives, and the
    driver measures the full migration loop — drain requested →
    checkpoint ack (simulated SDK) → victim parked → waiter admitted →
    waiter done → victim re-admitted with its restore hint in the pod
    env. Chip-free: FakeKube + podsim + the real manager/controller/
    scheduler stack with migration enabled exactly as KFTPU_MIGRATION=on
    wires it.

    Reported per gang size: roundtrip p50/p95 (high-pri create → victim
    restored), drain→ack→admit latency, and the gates: every loop
    completes, zero ledger violations, zero grace-deadline fallbacks
    (the simulated SDK always acks — a fallback means the protocol lost
    an ack)."""
    import time as _time

    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.controllers.notebook import setup_notebook_controller
    from kubeflow_tpu.migration import protocol as migration
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.objects import deep_get, fmt_iso
    from kubeflow_tpu.scheduler import (
        Fleet,
        SchedulerOptions,
        TpuFleetScheduler,
    )
    from kubeflow_tpu.testing.fakekube import FakeKube
    from kubeflow_tpu.testing.podsim import PodSimulator
    from kubeflow_tpu.webhooks import register_all

    gang_sizes = [1, 2] if smoke else [1, 2, 4]
    reps = 2 if smoke else 5
    phase_timeout = 30.0

    async def wait_for(predicate, what: str):
        deadline = time.perf_counter() + phase_timeout
        while True:
            value = await predicate()
            if value:
                return value
            if time.perf_counter() > deadline:
                raise RuntimeError(f"migration_roundtrip: timed out "
                                   f"waiting for {what}")
            await asyncio.sleep(0.01)

    async def sdk_ack_loop(kube, stop_flag, acked):
        """The simulated in-pod SDK: polls every notebook's annotations
        (the real SDK polls its own CR) and acks any un-acked drain with
        a committed-checkpoint patch, exactly the shape
        sdk.CheckpointGuard stamps."""
        while not stop_flag[0]:
            try:
                nbs = await kube.list("Notebook")
            except Exception:
                nbs = []
            for nb in nbs:
                ann = (nb.get("metadata") or {}).get("annotations") or {}
                name = nb["metadata"]["name"]
                ns = nb["metadata"].get("namespace")
                if (migration.drain_requested_at(ann) is not None
                        and not migration.drain_acked(ann)
                        and nbapi.STOP_ANNOTATION not in ann):
                    step = acked.get((ns, name), 0) + 100
                    acked[(ns, name)] = step
                    try:
                        await kube.patch(
                            "Notebook", name,
                            {"metadata": {"annotations": migration.ack_patch(
                                f"/home/jovyan/ckpt/{name}", step,
                                _time.time(),
                                for_request=ann.get(
                                    nbapi.DRAIN_REQUESTED_ANNOTATION))}},
                            ns)
                    except Exception:
                        pass
            await asyncio.sleep(0.005)

    async def one_size(num_slices: int) -> dict:
        kube = FakeKube()
        register_all(kube)
        mgr = Manager(kube)
        sched = TpuFleetScheduler(
            kube,
            SchedulerOptions(
                queued_requeue_seconds=0.05,
                idle_preempt_after_seconds=0.2,
                enable_migration=True,
                drain_grace_seconds=15.0,
            ),
            fleet=Fleet.parse(f"pool-a=v5e:4x4:{num_slices}"),
            registry=mgr.registry,
        )
        setup_notebook_controller(mgr, scheduler=sched)
        sim = PodSimulator(kube)
        await mgr.start()
        await sim.start()
        stop_flag = [False]
        acked: dict = {}
        ack_task = asyncio.create_task(sdk_ack_loop(kube, stop_flag, acked))
        roundtrips: list[float] = []
        drain_to_admit: list[float] = []
        try:
            for r in range(reps):
                victim, urgent = f"victim-{r}", f"urgent-{r}"

                async def get(name):
                    return await kube.get_or_none("Notebook", name, "bench")

                await kube.create("Notebook", nbapi.new(
                    victim, "bench", accelerator="v5e", topology="4x4",
                    num_slices=num_slices))

                async def victim_admitted():
                    return _admitted(sched, ("bench", victim))
                await wait_for(victim_admitted, f"{victim} admitted")
                await mgr.wait_idle(timeout=20)
                # Idle signal: culling says the victim has been idle for
                # an hour; the admitted-at floor keeps the window honest.
                await kube.patch(
                    "Notebook", victim,
                    {"metadata": {"annotations": {
                        nbapi.LAST_ACTIVITY_ANNOTATION: fmt_iso(
                            _time.time() - 3600)}}}, "bench")
                await asyncio.sleep(0.25)
                mgr.enqueue("notebook", ("bench", victim))
                await mgr.wait_idle(timeout=20)

                t0 = time.perf_counter()
                await kube.create("Notebook", {
                    **nbapi.new(urgent, "bench", accelerator="v5e",
                                topology="4x4", num_slices=num_slices),
                    "metadata": {"name": urgent, "namespace": "bench",
                                 "annotations": {
                                     nbapi.PRIORITY_ANNOTATION: "high"}},
                })

                async def drained():
                    nb = await get(victim)
                    ann = (nb or {}).get("metadata", {}).get(
                        "annotations") or {}
                    return migration.drain_requested_at(ann) is not None \
                        or nbapi.STOP_ANNOTATION in ann
                await wait_for(drained, f"{victim} drain request")
                t_drain = time.perf_counter()

                async def urgent_admitted():
                    return _admitted(sched, ("bench", urgent))
                await wait_for(urgent_admitted, f"{urgent} admitted")
                drain_to_admit.append(time.perf_counter() - t_drain)

                async def victim_parked():
                    nb = await get(victim)
                    ann = (nb or {}).get("metadata", {}).get(
                        "annotations") or {}
                    return nbapi.STOP_ANNOTATION in ann \
                        and nbapi.CHECKPOINT_PATH_ANNOTATION in ann
                await wait_for(victim_parked, f"{victim} parked")

                # The waiter finishes; the victim comes back and restores.
                await kube.patch(
                    "Notebook", urgent,
                    {"metadata": {"annotations": {
                        nbapi.STOP_ANNOTATION: fmt_iso(_time.time())}}},
                    "bench")
                await mgr.wait_idle(timeout=20)
                await kube.patch(
                    "Notebook", victim,
                    {"metadata": {"annotations": {
                        nbapi.STOP_ANNOTATION: None}}}, "bench")

                async def victim_restored():
                    if not _admitted(sched, ("bench", victim)):
                        return False
                    sts = await kube.get_or_none(
                        "StatefulSet",
                        victim if num_slices == 1 else f"{victim}-s0",
                        "bench")
                    env = deep_get(
                        sts or {}, "spec", "template", "spec",
                        "containers", default=[{}])[0].get("env", [])
                    return any(e.get("name") == migration.RESTORE_PATH_ENV
                               for e in env)
                await wait_for(victim_restored, f"{victim} restored")
                roundtrips.append(time.perf_counter() - t0)

                # Park before deleting: a delete racing an in-flight
                # reconcile's child update is normal (workqueue retries),
                # but the released-first order keeps bench logs clean.
                await kube.patch(
                    "Notebook", victim,
                    {"metadata": {"annotations": {
                        nbapi.STOP_ANNOTATION: fmt_iso(_time.time())}}},
                    "bench")

                async def fleet_empty():
                    return not sched.policy.ledger.allocations
                await wait_for(fleet_empty, "fleet drained between reps")
                await mgr.wait_idle(timeout=20)
                for name in (victim, urgent):
                    await kube.delete("Notebook", name, "bench")
                await mgr.wait_idle(timeout=20)
            sched.policy.ledger.assert_consistent()
            fallbacks = sched.m_drain_fallback.labels().value
            return {
                "gang_slices": num_slices,
                "reps": reps,
                "roundtrip_p50_sec": round(
                    _percentile(sorted(roundtrips), 0.50), 4),
                "roundtrip_p95_sec": round(
                    _percentile(sorted(roundtrips), 0.95), 4),
                "drain_to_admit_p50_sec": round(
                    _percentile(sorted(drain_to_admit), 0.50), 4),
                "ledger_violations": sched.policy.ledger.violations,
                "grace_fallbacks": fallbacks,
            }
        finally:
            stop_flag[0] = True
            ack_task.cancel()
            try:
                await ack_task
            except (asyncio.CancelledError, Exception):
                pass
            await sim.stop()
            await mgr.stop()
            kube.close_watches()

    def _admitted(sched, key) -> bool:
        alloc = sched.policy.ledger.allocations.get(key)
        return alloc is not None and not alloc.draining

    sizes = [asyncio.run(one_size(n)) for n in gang_sizes]
    ok = bool(sizes) and all(
        s["ledger_violations"] == 0 and s["grace_fallbacks"] == 0
        for s in sizes)
    return {
        "metric": "migration_roundtrip",
        "smoke": smoke,
        "sizes": sizes,
        "pass": ok,
    }


def chaos_soak(smoke: bool = False) -> dict:
    """`bench.py chaos_soak [--smoke]` — the chaos/self-healing
    acceptance gate (ISSUE 9). Per seed: notebooks churn through the
    scheduler + migration paths under a seeded API fault storm (5xx/429/
    409 injection, mid-stream watch resets, stale LISTs) while the
    Manager is killed and restarted mid-reconcile ≥3 times; after every
    convergence the global invariants must hold — zero ledger
    violations, no orphan/duplicate slice StatefulSets, no gang both
    Admitted and Queued, every drain terminal, every workqueue drained,
    zero permanently-wedged keys. Separately, a deliberately poisoned CR
    must quarantine within the retry budget, surface the Degraded
    condition + Warning Event + /debug/queue row, and resume on the next
    spec edit. The sharded control plane rides the same gate (ISSUE 17):
    one shard of N is crash-killed mid-flight and survivors must absorb
    its keyspace — zero dropped queued keys, timeline continuity and
    ledger invariants intact. Chip-free: FakeKube + podsim + the real
    manager/controller/scheduler stack; the same seeds replay in tier-1
    (tests/test_chaos.py)."""
    from kubeflow_tpu.testing.chaos import (
        SoakConfig,
        poison_scenario,
        run_soak,
        shard_kill_scenario,
    )

    seeds = list(range(2)) if smoke else list(range(5))
    reports = []
    for seed in seeds:
        report = asyncio.run(run_soak(SoakConfig(
            seed=seed,
            rounds=3,
            storm_seconds=0.5 if smoke else 0.8,
        )))
        reports.append(report.to_dict())
    poison = asyncio.run(poison_scenario(seed=0))
    shard_kill = asyncio.run(shard_kill_scenario(
        seed=0, replicas=3 if smoke else 4))
    ok = all(r["ok"] for r in reports) and poison.get("pass", False) \
        and shard_kill.get("pass", False) \
        and all(r["manager_restarts"] >= 3 for r in reports)
    return {
        "metric": "chaos_soak",
        "smoke": smoke,
        "seeds": seeds,
        "soaks": reports,
        "poison": poison,
        "shard_kill": shard_kill,
        "total_injected": {
            k: sum(r["injected"].get(k, 0) for r in reports)
            for k in sorted({k for r in reports for k in r["injected"]})},
        "pass": ok,
    }


CPS_SHARDS = 4
# Per-REPLICA client budget (client-go rest.Config QPS analog). The
# active-active win is aggregate budget: one event loop gains no CPU
# from more in-process replicas, but each replica carries its own
# request budget — exactly how N real pods each carry their own rate
# limiter against the apiserver.
CPS_QPS_PER_REPLICA = 250.0


def control_plane_scale(smoke: bool = False) -> dict:
    """`bench.py control_plane_scale [--smoke]` — the sharded active-
    active control plane at 10k-CR scale (ISSUE 17).

    Phase A races the SAME multi-namespace notebook load through one
    budgeted manager replica (N=1, unsharded) and through N=4 replicas
    (namespace-hash shard leases, filtered informers, per-shard
    workqueues); an unbudgeted N=1 run is included as the CPU-bound
    reference. CI gate: N=4 must STRICTLY beat N=1 on notebooks/s.

    Phase B drives 10k+ CRs with churn (annotation patches plus
    delete/recreate) through the N=4 ring, crash-kills one replica
    mid-flight, and reports per-shard fairness (ready-latency p50
    spread), failover time, and aggregate notebooks/s. Every surviving
    CR — including keys queued on the dead replica — must converge:
    zero dropped keys."""
    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.controllers.notebook import (
        NotebookOptions,
        setup_notebook_controller,
    )
    from kubeflow_tpu.runtime.flowcontrol import BudgetedClient, FlowControl
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.metrics import Registry
    from kubeflow_tpu.runtime.objects import deep_get
    from kubeflow_tpu.runtime.sharding import ShardRing, shard_of
    from kubeflow_tpu.testing.fakekube import FakeKube
    from kubeflow_tpu.testing.loadtest import run_load_test
    from kubeflow_tpu.testing.podsim import PodSimulator
    from kubeflow_tpu.webhooks import register_all

    shards = CPS_SHARDS
    qps = CPS_QPS_PER_REPLICA
    phase_a_count = 240 if smoke else 1000
    phase_b_count = 1200 if smoke else 10240
    nss = [f"team-{i:02d}" for i in range(16)]
    # Smoke gets soak-speed clocks (sub-second failover, fast CI). The
    # full 10k run saturates the event loop for minutes at a time, and a
    # 0.6s lease flaps under that lag — the victim would own nothing by
    # kill time and the failover measurement would be vacuous. 3s/0.6s
    # keeps the production lease:renew ratio while tolerating multi-
    # second loop stalls.
    lease_seconds, renew_seconds = (0.6, 0.15) if smoke else (3.0, 0.6)

    class Stack:
        """N in-process replicas over ONE FakeKube: each with its own
        registry, shard ring, and client budget — the unit under test is
        the sharding protocol + budget scaling, not process isolation."""

        def __init__(self, replicas: int, *, budget: bool = True):
            self.replicas = replicas
            self.kube = FakeKube()
            register_all(self.kube)
            self.sim = PodSimulator(self.kube)
            self.mgrs, self.rings = [], []
            self._dead: set[int] = set()
            for r in range(replicas):
                reg = Registry()
                # The create burst starves the event loop and early lease
                # expiries scramble the spread; the claim protocol hands a
                # scrambled shard back to its live preferred owner within a
                # couple of ticks, so the victim holds its slice by kill
                # time — while the DEAD victim's shard, absorbed after the
                # kill, is never churned back into an unowned window.
                ring = (ShardRing(
                    self.kube, shards=shards, replica=r, replicas=replicas,
                    lease_seconds=lease_seconds,
                    renew_seconds=renew_seconds,
                    registry=reg)
                    if replicas > 1 else None)
                client = (BudgetedClient(self.kube, FlowControl(max_qps=qps))
                          if budget else self.kube)
                mgr = Manager(client, registry=reg, shard_ring=ring)
                setup_notebook_controller(mgr, NotebookOptions(),
                                          scheduler=None)
                for q in mgr._queues.values():
                    q.base_delay = 0.002
                    q.max_delay = 0.05
                for inf in mgr.informers.values():
                    inf.resync_backoff = 0.02
                    inf.resync_backoff_max = 0.2
                self.mgrs.append(mgr)
                self.rings.append(ring)

        async def start(self):
            for r in range(self.replicas):
                if self.rings[r] is not None:
                    await self.rings[r].start()
                await self.mgrs[r].start()
            await self.sim.start()

        async def kill(self, r: int):
            """Crash semantics: leases left to expire, queues die."""
            if self.rings[r] is not None:
                await self.rings[r].kill()
            await self.mgrs[r].stop()
            self._dead.add(r)

        async def stop(self):
            await self.sim.stop()
            for r in range(self.replicas):
                if r in self._dead:
                    continue
                await self.mgrs[r].stop()
                if self.rings[r] is not None:
                    await self.rings[r].stop()
            self.kube.close_watches()

    async def equal_load(replicas: int, *, budget: bool = True) -> dict:
        stack = Stack(replicas, budget=budget)
        await stack.start()
        try:
            report = await run_load_test(
                stack.kube, count=phase_a_count, namespaces=nss,
                accelerator="v5e", topology="2x2",
                timeout=300.0, poll_interval=0.05)
            d = report.to_dict()
            d["replicas"] = replicas
            d["budgeted"] = budget
            d["rate_nb_per_sec"] = (
                round(report.ready / report.wall_seconds, 2)
                if report.wall_seconds else 0.0)
            return d
        finally:
            await stack.stop()

    async def scale_10k() -> dict:
        stack = Stack(shards)
        await stack.start()
        out: dict = {"replicas": shards}
        try:
            t0 = time.perf_counter()
            keyed = [(nss[i % len(nss)], f"cr-{i}")
                     for i in range(phase_b_count)]
            for ns, name in keyed:
                await stack.kube.create("Notebook", nbapi.new(
                    name, ns, accelerator="v5e", topology="2x2"))
            out["create_wall_seconds"] = round(time.perf_counter() - t0, 2)

            # Churn while reconciles are in flight: spec edits re-enqueue
            # live keys, deletes + recreates exercise tombstone handling
            # under load.
            churn_patch = keyed[::20]
            for ns, name in churn_patch:
                await stack.kube.patch(
                    "Notebook", name,
                    {"metadata": {"annotations": {"bench/churn": "1"}}}, ns)
            churn_delete = keyed[7::50]
            for ns, name in churn_delete:
                try:
                    await stack.kube.delete("Notebook", name, ns)
                except Exception:
                    pass
            deleted = set(churn_delete)
            recreated = []
            for i, (ns, _name) in enumerate(churn_delete):
                await stack.kube.create("Notebook", nbapi.new(
                    f"rc-{i}", ns, accelerator="v5e", topology="2x2"))
                recreated.append((ns, f"rc-{i}"))
            want = [k for k in keyed if k not in deleted] + recreated

            victim = shards - 1  # never the arbiter (shard 0 → replica 0)
            ready_at: dict[tuple, float] = {}
            pending = set(want)
            killed = False
            kill_t = None
            absorb_seconds = None
            victim_shards: set[int] = set()
            deadline = time.perf_counter() + (240.0 if smoke else 900.0)
            while pending and time.perf_counter() < deadline:
                for ns in nss:
                    for nb in await stack.kube.list(
                            "Notebook", ns, copy=False):
                        k = (ns, nb["metadata"]["name"])
                        if k not in pending:
                            continue
                        hosts = deep_get(
                            nb, "status", "tpu", "hosts", default=1) or 1
                        got = deep_get(
                            nb, "status", "readyReplicas", default=0) or 0
                        if got >= hosts:
                            ready_at[k] = time.perf_counter() - t0
                pending -= set(ready_at)
                if not killed and len(ready_at) >= 0.4 * len(want) \
                        and stack.rings[victim].owned:
                    # Only a victim that actually holds shards makes the
                    # failover measurement mean anything.
                    victim_shards = set(stack.rings[victim].owned)
                    kill_t = time.perf_counter()
                    await stack.kill(victim)
                    killed = True
                if killed and absorb_seconds is None:
                    held: set[int] = set()
                    for r in range(shards):
                        if r != victim:
                            held |= stack.rings[r].owned
                    if victim_shards <= held:
                        absorb_seconds = time.perf_counter() - kill_t
                await asyncio.sleep(0.05)
            wall = time.perf_counter() - t0

            per_shard: dict[int, list] = {s: [] for s in range(shards)}
            for (ns, _name), t_ready in ready_at.items():
                per_shard[shard_of(ns, shards)].append(t_ready)
            shard_stats = {}
            p50s = []
            for s, lats in sorted(per_shard.items()):
                lats.sort()
                p50 = lats[len(lats) // 2] if lats else None
                shard_stats[str(s)] = {
                    "ready": len(lats),
                    "p50_ready_sec": round(p50, 3) if p50 else None,
                }
                if p50:
                    p50s.append(p50)
            out.update({
                "created": len(keyed),
                "churn_patched": len(churn_patch),
                "churn_deleted": len(churn_delete),
                "recreated": len(recreated),
                "expected": len(want),
                "converged": len(ready_at),
                "dropped_keys": len(want) - len(ready_at),
                "wall_seconds": round(wall, 2),
                "rate_nb_per_sec": (round(len(ready_at) / wall, 2)
                                    if wall else 0.0),
                "victim_replica": victim,
                "victim_shards": sorted(victim_shards),
                "killed": killed,
                "failover_seconds": (round(absorb_seconds, 3)
                                     if absorb_seconds is not None else None),
                "per_shard": shard_stats,
                # max/min of per-shard p50 ready latency: 1.0 = perfectly
                # fair; the victim's shards legitimately read worse (they
                # lived through the failover).
                "fairness_p50_spread": (
                    round(max(p50s) / min(p50s), 3)
                    if p50s and min(p50s) > 0 else None),
            })
            return out
        finally:
            await stack.stop()

    n1 = asyncio.run(equal_load(1))
    n4 = asyncio.run(equal_load(shards))
    reference = asyncio.run(equal_load(1, budget=False))
    b = asyncio.run(scale_10k())
    sharded_beats = (
        n1["ready"] == phase_a_count
        and n4["ready"] == phase_a_count
        and n4["rate_nb_per_sec"] > n1["rate_nb_per_sec"])
    ok = bool(
        sharded_beats
        and b["killed"]
        and b["victim_shards"]
        and b["failover_seconds"] is not None
        and b["dropped_keys"] == 0
        and (smoke or b["created"] >= 10000))
    return {
        "metric": "control_plane_scale",
        "smoke": smoke,
        "shards": shards,
        "qps_budget_per_replica": qps,
        "equal_load": {
            "n1": n1,
            "n4": n4,
            "n1_unbudgeted_reference": reference,
            "speedup": (round(
                n4["rate_nb_per_sec"] / n1["rate_nb_per_sec"], 2)
                if n1["rate_nb_per_sec"] else None),
        },
        "scale_10k": b,
        "pass": ok,
    }


def _ckpt_bench_tree(step: int, leaf_elems: int):
    """Deterministic per-step training state: the fault-storm verifier
    regenerates this to check a restore bit-exactly."""
    import numpy as np

    base = np.arange(leaf_elems, dtype=np.float32)
    return {
        "step": np.int64(step),
        "params": {"w": base + step, "b": np.full(64, step, np.float32)},
        "opt": {"m": base * 0.5 + step, "v": base * 0.25},
    }


def checkpoint_fabric(smoke: bool = False) -> dict:
    """`bench.py checkpoint_fabric [--smoke]` — the checkpoint-fabric
    acceptance gate (ISSUE 16). Four gates, all chip-free (tmp dirs +
    a simulated object-store RTT):

    1. snapshot-then-ack: `save_async` must return (the drain-ack
       point) ≥3× faster than a synchronous save-and-wait drain;
    2. delta < full: an incremental save of mostly-unchanged state
       must upload fewer bytes than its full predecessor;
    3. tiered restore: a staging-tier restore must beat the same
       restore served from the (RTT-taxed) remote tier;
    4. fault storm: under seeded crash-mid-upload / torn-manifest /
       stale-staging / read-corruption injection, every restore must
       return the last *committed* step bit-exactly — zero partial or
       wrong-step restores (detected-and-refused manifests are the
       fabric working, not a violation).
    """
    import random
    import shutil
    import tempfile

    import numpy as np

    from kubeflow_tpu.checkpoint import (
        CheckpointFabric,
        CheckpointIntegrityError,
    )
    from kubeflow_tpu.runtime.metrics import Registry

    leaf_elems = 1 << 12 if smoke else 1 << 14
    reps = 3 if smoke else 5
    op_delay = 0.002          # simulated per-op object-store round trip
    chunk_bytes = 8 << 10     # ~8 chunks per leaf → RTT cost is visible

    class _StormFaults:
        """Seeded probabilistic storage faults (same knobs the chaos
        soak's FaultPlan probes)."""

        def __init__(self, seed: int):
            self.rng = random.Random(seed)
            self.injected: dict[str, int] = {}

        def _roll(self, name: str, p: float) -> bool:
            if self.rng.random() < p:
                self.injected[name] = self.injected.get(name, 0) + 1
                return True
            return False

        def should_crash_upload(self):
            return self._roll("crash_upload", 0.01)

        def should_fail_upload(self):
            return self._roll("fail_upload", 0.02)

        def should_tear_manifest(self, tier):
            return self._roll("torn_manifest", 0.15)

        def should_corrupt_read(self, tier):
            return self._roll("corrupt_read", 0.05)

        def should_skip_staging_commit(self):
            return self._roll("stale_staging", 0.3)

    root = tempfile.mkdtemp(prefix="ckpt-fabric-bench-")
    try:
        # -- gate 1: snapshot-ack vs synchronous drain --------------------
        ack_times, sync_times = [], []
        with CheckpointFabric(
                os.path.join(root, "latency", "remote"),
                staging_dir=os.path.join(root, "latency", "staging"),
                chunk_bytes=chunk_bytes, full_interval=1,
                remote_op_delay=op_delay, registry=Registry()) as fab:
            step = 0
            for _ in range(reps):
                step += 1
                t0 = time.perf_counter()
                handle = fab.save_async(step, _ckpt_bench_tree(
                    step, leaf_elems))
                ack_times.append(time.perf_counter() - t0)
                handle.result(60)     # drain the queue between trials
                step += 1
                t0 = time.perf_counter()
                fab.save_async(step, _ckpt_bench_tree(
                    step, leaf_elems)).result(60)
                sync_times.append(time.perf_counter() - t0)
        ack_ms = _median_sorted(sorted(ack_times)) * 1e3
        sync_ms = _median_sorted(sorted(sync_times)) * 1e3
        ack_speedup = sync_ms / max(ack_ms, 1e-9)

        # -- gate 2: delta saves upload fewer bytes than full -------------
        with CheckpointFabric(
                os.path.join(root, "delta", "remote"),
                chunk_bytes=chunk_bytes, full_interval=100,
                registry=Registry()) as fab:
            tree = _ckpt_bench_tree(1, leaf_elems)
            h_full = fab.save_async(1, tree)
            # Step advances; the big leaves stay put — the common shape
            # of a between-steps checkpoint cadence.
            tree2 = dict(tree, step=np.int64(2))
            h_delta = fab.save_async(2, tree2)
            h_full.result(60), h_delta.result(60)
        full_bytes, delta_bytes = h_full.bytes_written, h_delta.bytes_written

        # -- gate 3: staging restore beats remote restore -----------------
        staging_times, remote_times = [], []
        with CheckpointFabric(
                os.path.join(root, "tiers", "remote"),
                staging_dir=os.path.join(root, "tiers", "staging"),
                chunk_bytes=chunk_bytes, remote_op_delay=op_delay,
                registry=Registry()) as fab:
            fab.save_async(1, _ckpt_bench_tree(1, leaf_elems)).result(60)
            for _ in range(reps):
                t0 = time.perf_counter()
                fab.restore()
                staging_times.append(time.perf_counter() - t0)
            assert fab.last_restore["tier"] == "staging"
            shutil.rmtree(fab.staging._chunk_dir)
            os.makedirs(fab.staging._chunk_dir)
            fab.staging._lru.clear()
            for _ in range(reps):
                t0 = time.perf_counter()
                fab.restore()
                remote_times.append(time.perf_counter() - t0)
            assert fab.last_restore["tier"] == "remote"
        staging_ms = _median_sorted(sorted(staging_times)) * 1e3
        remote_ms = _median_sorted(sorted(remote_times)) * 1e3

        # -- gate 4: fault storm — committed-step invariant ---------------
        storm_steps = 20 if smoke else 60
        faults = _StormFaults(seed=16)
        violations: list[str] = []
        commits = 0
        restores = 0
        reg = Registry()
        with CheckpointFabric(
                os.path.join(root, "storm", "remote"),
                staging_dir=os.path.join(root, "storm", "staging"),
                chunk_bytes=chunk_bytes, full_interval=4,
                upload_retries=2, backoff_seconds=0.001,
                faults=faults, registry=reg) as fab:
            for step in range(1, storm_steps + 1):
                fab.save_async(step, _ckpt_bench_tree(step, leaf_elems))
                if step % 5 != 0:
                    continue
                fab.wait()           # settle so "committed" is stable
                committed = fab.latest_step()
                try:
                    tree = fab.restore()
                except FileNotFoundError:
                    if committed is not None:
                        violations.append(
                            f"step {step}: committed={committed} but "
                            f"restore found nothing")
                    continue
                except CheckpointIntegrityError:
                    # Legal only when every candidate was torn/corrupt;
                    # fallback exhaustion is detected refusal, not a
                    # partial restore.
                    continue
                restores += 1
                got = int(tree["step"])
                if got != committed and not fab.last_restore["fallback"]:
                    violations.append(
                        f"step {step}: restored {got}, committed "
                        f"{committed}, no fallback flagged")
                want = _ckpt_bench_tree(got, leaf_elems)
                for key in ("params", "opt"):
                    for leaf, arr in want[key].items():
                        if not np.array_equal(tree[key][leaf], arr):
                            violations.append(
                                f"step {step}: leaf {key}/{leaf} of "
                                f"restored step {got} is a partial")
            fab.wait()
            final_committed = fab.latest_step()
            commits = sum(1 for _ in fab.all_steps())
            orphans = (fab.remote.orphaned_tmp_files()
                       + fab.staging.orphaned_tmp_files())
        if final_committed is None:
            violations.append("fault storm ended with nothing committed")
        if orphans:
            violations.append(f"orphaned tmp files after close: {orphans}")

        gates = {
            "ack_speedup_ge_3x": ack_speedup >= 3.0,
            "delta_lt_full_bytes": 0 < delta_bytes < full_bytes,
            "staging_beats_remote": staging_ms < remote_ms,
            "storm_zero_integrity_violations": not violations,
        }
        return {
            "metric": "checkpoint_fabric",
            "smoke": smoke,
            "ack_ms": round(ack_ms, 3),
            "sync_drain_ms": round(sync_ms, 3),
            "ack_speedup": round(ack_speedup, 2),
            "full_bytes": full_bytes,
            "delta_bytes": delta_bytes,
            "staging_restore_ms": round(staging_ms, 3),
            "remote_restore_ms": round(remote_ms, 3),
            "storm": {
                "steps": storm_steps,
                "restores_verified": restores,
                "final_committed": final_committed,
                "manifests_retained": commits,
                "injected": dict(sorted(faults.injected.items())),
                "violations": violations,
            },
            "gates": gates,
            "pass": all(gates.values()),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _load_bench_artifact(path: str) -> dict | None:
    """A BENCH_r0x.json is either the raw bench JSON or a driver wrapper
    whose ``tail`` holds the JSON line (and sometimes a ``parsed``
    copy). Returns the bench dict, or None."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    if "coldstart_warm_cache_sec" in data or "metric" in data:
        return data
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and parsed:
        return parsed
    tail = data.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                return obj
        # Clipped tail (the driver keeps only the END of the output):
        # fish the cold-start fields out by key — enough for the canary
        # classification even when the JSON line was truncated.
        import re

        out: dict = {}
        m = re.search(r'"coldstart_warm_cache_sec":\s*([0-9.]+)', tail)
        if m:
            out["coldstart_warm_cache_sec"] = float(m.group(1))
        m = re.search(r'"fixed_overhead_sec":\s*([0-9.]+)', tail)
        if m:
            out["coldstart_canary"] = {
                "fixed_overhead_sec": float(m.group(1))}
        if out:
            return out
    return None


def classify_coldstart_drift(current: dict, baseline: dict, *,
                             threshold_pct: float = 10.0) -> dict:
    """The PR 13 ``coldstart_canary`` classification rule as an
    ACTIONABLE verdict (ISSUE 14 satellite): compare two rounds'
    warm-cache cold starts and attribute any drift with the canary —
    canary moved too → "environment" (warn only: slower disk/CPU,
    fatter site-packages); canary flat while the warm number moved →
    "repo regression" (the gate's exit-1 case: cache-key churn or a
    heavier import graph this repo owns). Pure: callers feed bench
    JSON dicts."""
    cur = (current or {}).get("coldstart_warm_cache_sec")
    base = (baseline or {}).get("coldstart_warm_cache_sec")
    if not isinstance(cur, (int, float)) \
            or not isinstance(base, (int, float)) or base <= 0:
        return {"classification": "insufficient-data",
                "detail": "both rounds need coldstart_warm_cache_sec",
                "warn_only": True}
    drift_pct = round(100.0 * (cur - base) / base, 2)
    verdict = {"warm_cache_sec": [base, cur], "drift_pct": drift_pct,
               "threshold_pct": threshold_pct}
    if drift_pct <= threshold_pct:
        return {**verdict, "classification": "ok", "warn_only": False}
    cur_can = ((current or {}).get("coldstart_canary")
               or {}).get("fixed_overhead_sec")
    base_can = ((baseline or {}).get("coldstart_canary")
                or {}).get("fixed_overhead_sec")
    if not isinstance(cur_can, (int, float)) \
            or not isinstance(base_can, (int, float)) or base_can <= 0:
        return {**verdict, "classification": "insufficient-canary",
                "detail": "drift unattributable: a round predates the "
                          "coldstart_canary block",
                "warn_only": True}
    canary_drift_pct = round(
        100.0 * (cur_can - base_can) / base_can, 2)
    verdict["canary_fixed_overhead_sec"] = [base_can, cur_can]
    verdict["canary_drift_pct"] = canary_drift_pct
    if canary_drift_pct >= threshold_pct / 2.0:
        # The fixed-overhead probes (interpreter spawn + import jax)
        # moved with the warm number: the HOST drifted, not this repo.
        return {**verdict, "classification": "environment",
                "warn_only": True}
    return {**verdict, "classification": "repo regression",
            "warn_only": False}


def coldstart_canary_gate() -> dict:
    """Classify the two newest BENCH_r*.json artifacts in the repo.
    Environment-classified (and unattributable) drift stays warn-only;
    only a canary-confirmed repo regression fails the gate."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    artifacts = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if len(artifacts) < 2:
        return {"classification": "insufficient-data",
                "detail": "need two BENCH_r*.json rounds", "pass": True}
    baseline = _load_bench_artifact(artifacts[-2])
    current = _load_bench_artifact(artifacts[-1])
    verdict = classify_coldstart_drift(current or {}, baseline or {})
    verdict["rounds"] = [os.path.basename(artifacts[-2]),
                         os.path.basename(artifacts[-1])]
    verdict["pass"] = verdict["classification"] != "repo regression"
    return verdict


async def _coldstart_warmpool_bench(smoke: bool) -> dict:
    """Warm-pool claim path vs cold path, measured on the podsim-modeled
    control plane: podsim charges image-pull latency once per
    (node, image) and runtime-start latency per fresh pod — the two
    costs a claim skips entirely. Also proves the reserve contract: a
    real gang arriving against a fully-reserved fleet takes warm-pool
    chips (instantly, no drain) before any real gang is touched."""
    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.controllers.notebook import (
        NotebookOptions,
        setup_notebook_controller,
    )
    from kubeflow_tpu.controllers.warmpool import (
        WarmPoolManager,
        WarmPoolOptions,
    )
    from kubeflow_tpu.runtime import timeline as timeline_mod
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.metrics import Registry
    from kubeflow_tpu.runtime.objects import annotations_of, deep_get
    from kubeflow_tpu.scheduler import SchedulerOptions, TpuFleetScheduler
    from kubeflow_tpu.testing.fakekube import FakeKube
    from kubeflow_tpu.testing.podsim import PodSimulator
    from kubeflow_tpu.webhooks import register_all

    n = 3 if smoke else 6
    pull, start = (0.25, 0.12) if smoke else (0.6, 0.3)
    warm_image = "kubeflow-tpu/jupyter-jax:bench"

    kube = FakeKube()
    register_all(kube)
    mgr = Manager(kube, registry=Registry())
    # 3n+1 slices: n cold + n warm-claimed gangs + n replenished warm
    # slots fit with ONE slice spare, so the pressure phase's three real
    # gangs must take at least two from the warm reserve.
    sched = TpuFleetScheduler(
        kube, SchedulerOptions(fleet_spec=f"pool-a=v5e:2x2:{3 * n + 1}"),
        registry=mgr.registry)
    warmpool = WarmPoolManager(
        kube,
        WarmPoolOptions(spec=f"bench/{warm_image}@v5e:2x2:{n}",
                        replenish_seconds=0.05),
        registry=mgr.registry)
    setup_notebook_controller(mgr, NotebookOptions(), scheduler=sched,
                              warmpool=warmpool)
    sim = PodSimulator(kube, image_pull_latency=pull,
                       runtime_start_latency=start)
    await mgr.start()
    await sim.start()

    async def time_to_ready(name: str, image: str) -> float:
        t0 = time.perf_counter()
        await kube.create("Notebook", nbapi.new(
            name, "bench", image=image, accelerator="v5e",
            topology="2x2"))
        deadline = t0 + 60
        while time.perf_counter() < deadline:
            nb = await kube.get("Notebook", name, "bench")
            if deep_get(nb, "status", "readyReplicas", default=0):
                return time.perf_counter() - t0
            await asyncio.sleep(0.002)
        raise RuntimeError(f"notebook {name} never became Ready")

    async def pool_ready(count: int, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = await warmpool.debug_info()
            if info["pools"] and info["pools"][0]["ready"] >= count:
                return True
            await asyncio.sleep(0.02)
        return False

    try:
        # Cold path first: unique images so EVERY cold start pays the
        # image pull (distinct user images — the realistic worst case
        # warm pools exist to beat).
        cold = sorted([
            await time_to_ready(f"cold-{i}", f"user-img:{i}")
            for i in range(n)])
        pool_filled = await pool_ready(n)
        warm = []
        claims_attributed = 0
        for i in range(n):
            warm.append(await time_to_ready(f"warm-{i}", warm_image))
            nb = await kube.get("Notebook", f"warm-{i}", "bench")
            ann = annotations_of(nb)
            states = [e["state"]
                      for e in timeline_mod.decode(ann)]
            if ann.get(nbapi.WARM_CLAIMED_ANNOTATION) \
                    and timeline_mod.CLAIMED in states:
                claims_attributed += 1
        warm.sort()
        replenished = await pool_ready(n)

        # Reserve contract: the fleet is now tight (n cold + n warm
        # gangs + n fresh warm slots on 2n+2 slices → 2 free). Three
        # real gangs arrive: at least one's chips must come from the
        # warm reserve — instantly, with every pre-existing REAL gang
        # still admitted afterwards (warm slots die first, real gangs
        # never).
        pool_slugs = tuple(p.slug for p in warmpool.pools)
        real_before = {k for k in sched.policy.ledger.allocations
                       if not str(k[1]).startswith(pool_slugs)}
        real_gangs = {f"pressure-{i}" for i in range(3)}
        for name in sorted(real_gangs):
            await kube.create("Notebook", nbapi.new(
                name, "bench", image="pressure:1", accelerator="v5e",
                topology="2x2"))
        deadline = time.monotonic() + 30
        pressure_admitted = False
        while time.monotonic() < deadline:
            allocs = sched.policy.ledger.allocations
            if all(("bench", g) in allocs for g in real_gangs):
                pressure_admitted = True
                break
            await asyncio.sleep(0.02)
        no_real_gang_preempted = all(
            k in sched.policy.ledger.allocations for k in real_before)
        warm_reclaims = int(warmpool.m_reclaimed.labels().value)
    finally:
        warmpool.stop()
        await sim.stop()
        await mgr.stop()
        kube.close_watches()

    cold_p50 = _median_sorted(cold)
    warm_p50 = _median_sorted(warm)
    speedup = cold_p50 / max(warm_p50, 1e-9)
    return {
        "notebooks": n,
        "image_pull_latency_sec": pull,
        "runtime_start_latency_sec": start,
        "cold_ready_secs": [round(s, 4) for s in cold],
        "warm_ready_secs": [round(s, 4) for s in warm],
        "cold_p50_sec": round(cold_p50, 4),
        "warm_p50_sec": round(warm_p50, 4),
        "speedup": round(speedup, 2),
        "pool_filled": pool_filled,
        "claims_attributed": claims_attributed,
        "pool_replenished_after_claims": replenished,
        "pressure_admitted": pressure_admitted,
        "no_real_gang_preempted": no_real_gang_preempted,
        "warm_reserve_reclaims": warm_reclaims,
        "ledger_violations": sched.policy.ledger.violations,
        "sim_pass": bool(
            pool_filled and replenished and claims_attributed == n
            and speedup >= 3.0 and pressure_admitted
            and no_real_gang_preempted and warm_reclaims >= 1
            and sched.policy.ledger.violations == 0),
    }


def coldstart(smoke: bool = False) -> dict:
    """`bench.py coldstart [--smoke]` — the cold-start war's acceptance
    gate (ISSUE 14). Two parts, both enforced (exit 1 via __main__):

    - **warm-pool sim**: podsim models image-pull + runtime-start
      latency; the warm-pool claim path must be ≥3× faster to Ready
      than the cold path, every claim must attribute through the
      timeline's Claimed transition, the pool must replenish after
      claims, and a real gang under pressure must take warm-reserve
      chips (instantly) with no real gang preempted — 0 ledger
      violations throughout.
    - **canary gate**: the PR 13 coldstart_canary classification over
      the two newest BENCH_r*.json rounds — a canary-confirmed repo
      regression of the warm-cache number fails; environment-classified
      (or unattributable) drift stays warn-only."""
    sim = asyncio.run(_coldstart_warmpool_bench(smoke))
    canary = coldstart_canary_gate()
    return {
        "metric": "coldstart",
        "smoke": smoke,
        **sim,
        "canary_gate": canary,
        "pass": bool(sim["sim_pass"] and canary["pass"]),
    }


def elastic_fleet(smoke: bool = False) -> dict:
    """`bench.py elastic_fleet [--smoke]` — the elastic-fleet acceptance
    gate (ISSUE 10). Three scenarios on FakeKube + podsim + the real
    manager/controller/scheduler stack with KFTPU_ELASTIC semantics on:

    - **wedge/defrag**: four 4-chip (v5e:2x2) notebooks flex-borrow
      hosts on the big-slice pool, breaking both of its 4x4 slices; a
      16-chip (v5e:4x4) gang then starves even after the small pool
      frees up — until the defragmenter migrates the idle borrowers to
      their pack pool. Measured: the large gang's time-to-admission
      with defrag on; with defrag OFF it must still be starved at the
      end of the window (the before/after the ROADMAP asks for).
    - **scale-up round trip**: a gang that fits no pool even fully
      drained raises a ProvisioningRequest-shaped intent; the driver
      grants it by growing the fleet ConfigMap (the dynamic source) and
      measures intent→admission latency; the intent must withdraw as
      granted.
    - **reclaim storm**: spot pools revoked on a seeded FaultPlan
      schedule while a simulated SDK acks every drain; gates on zero
      ledger violations, zero lost gangs, and zero grace fallbacks —
      every reclaim with a live SDK routed through checkpoint-drain.
      A final ack-less victim must hard-stop via the grace fallback
      (the counter increments exactly once) so chips are never held
      hostage.
    """
    import time as _time

    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.controllers.notebook import (
        NotebookOptions,
        setup_notebook_controller,
    )
    from kubeflow_tpu.migration import protocol as migration
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.metrics import Registry
    from kubeflow_tpu.runtime.objects import annotations_of, fmt_iso
    from kubeflow_tpu.scheduler import (
        Fleet,
        SchedulerOptions,
        TpuFleetScheduler,
    )
    from kubeflow_tpu.testing.fakekube import FakeKube, FaultPlan
    from kubeflow_tpu.testing.podsim import PodSimulator
    from kubeflow_tpu.webhooks import register_all

    async def sdk_ack_loop(kube, stop_flag, skip=()):
        """Simulated in-pod SDK: ack any un-acked drain (except gangs in
        ``skip`` — the deliberately ack-less victims)."""
        while not stop_flag[0]:
            try:
                nbs = await kube.list("Notebook")
            except Exception:
                nbs = []
            for nb in nbs:
                ann = annotations_of(nb)
                key = (nb["metadata"].get("namespace"),
                       nb["metadata"]["name"])
                if key in skip:
                    continue
                if (migration.drain_requested_at(ann) is not None
                        and not migration.drain_acked(ann)
                        and nbapi.STOP_ANNOTATION not in ann):
                    try:
                        await kube.patch(
                            "Notebook", key[1],
                            {"metadata": {"annotations":
                                          migration.ack_patch(
                                              f"/ckpt/{key[1]}", 1000,
                                              _time.time(),
                                              for_request=ann.get(
                                                  nbapi.DRAIN_REQUESTED_ANNOTATION))}},
                            key[0])
                    except Exception:
                        pass
            await asyncio.sleep(0.005)

    def build(fleet_spec=None, *, configmap=False, defrag=True,
              grace=10.0):
        kube = FakeKube()
        register_all(kube)
        mgr = Manager(kube, registry=Registry())
        opts = SchedulerOptions(
            queued_requeue_seconds=0.05,
            enable_migration=True, drain_grace_seconds=grace,
            enable_elastic=True, enable_defrag=defrag,
            defrag_interval_seconds=0.05, defrag_idle_seconds=0.2,
            scale_up_ttl_seconds=30.0,
            fleet_refresh_seconds=0.05,
            **({"fleet_configmap": "kftpu-fleet",
                "controller_namespace": "kubeflow-tpu"}
               if configmap else {}),
        )
        sched = TpuFleetScheduler(
            kube, opts,
            fleet=Fleet.parse(fleet_spec) if fleet_spec else None,
            registry=mgr.registry)
        setup_notebook_controller(mgr, NotebookOptions(), scheduler=sched)
        return kube, mgr, sched

    async def wait_until(predicate, timeout, what):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if predicate():
                return True
            await asyncio.sleep(0.01)
        raise RuntimeError(f"elastic_fleet: timed out waiting for {what}")

    async def wedge_scenario(defrag: bool) -> dict:
        kube, mgr, sched = build("pack=v5e:4x4:2,small=v5e:2x2:2",
                                 defrag=defrag)
        sim = PodSimulator(kube)
        await mgr.start()
        await sim.start()
        stop_flag = [False]
        ack = asyncio.create_task(sdk_ack_loop(kube, stop_flag))
        try:
            # Two native small gangs fill the small pool, then the four
            # 4-chip gangs of the wedge flex-borrow every pack host.
            for i in range(2):
                await kube.create("Notebook", nbapi.new(
                    f"native-{i}", "bench", accelerator="v5e",
                    topology="2x2"))
            await mgr.wait_idle(timeout=20)
            for i in range(4):
                await kube.create("Notebook", nbapi.new(
                    f"wedge-{i}", "bench", accelerator="v5e",
                    topology="2x2"))
            await mgr.wait_idle(timeout=20)
            borrowed = dict(sched.policy.ledger.borrowed)
            # The 16-chip gang starves: both pack slices are broken.
            t0 = time.perf_counter()
            await kube.create("Notebook", nbapi.new(
                "big16", "bench", accelerator="v5e", topology="4x4"))
            await mgr.wait_idle(timeout=20)
            # The native small gangs complete — pack homes open up; the
            # wedge gangs go idle (culling's probe signal).
            for i in range(2):
                await kube.patch(
                    "Notebook", f"native-{i}",
                    {"metadata": {"annotations": {
                        nbapi.STOP_ANNOTATION: fmt_iso(_time.time())}}},
                    "bench")
            for i in range(4):
                await kube.patch(
                    "Notebook", f"wedge-{i}",
                    {"metadata": {"annotations": {
                        nbapi.LAST_ACTIVITY_ANNOTATION: fmt_iso(
                            _time.time() - 3600)}}}, "bench")
            admitted = False
            try:
                await wait_until(
                    lambda: ("bench", "big16") in
                    sched.policy.ledger.allocations
                    and not sched.policy.ledger.allocations[
                        ("bench", "big16")].draining,
                    10.0 if defrag else 3.0, "big16 admission")
                admitted = True
            except RuntimeError:
                pass
            wall = time.perf_counter() - t0
            await mgr.wait_idle(timeout=20)
            sched.policy.ledger.assert_consistent()
            return {
                "defrag": defrag,
                "borrowed_hosts_at_wedge": borrowed,
                "large_gang_admitted": admitted,
                "time_to_admission_sec": round(wall, 4) if admitted
                else None,
                "defrag_moves": sched._defrag_moves,
                "ledger_violations": sched.policy.ledger.violations,
            }
        finally:
            stop_flag[0] = True
            ack.cancel()
            try:
                await ack
            except (asyncio.CancelledError, Exception):
                pass
            await sim.stop()
            await mgr.stop()
            kube.close_watches()

    async def scale_up_scenario() -> dict:
        kube, mgr, sched = build(configmap=True)
        await kube.create("ConfigMap", {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "kftpu-fleet",
                         "namespace": "kubeflow-tpu"},
            "data": {"fleet": "pool-a=v5e:4x4:1"},
        })
        sim = PodSimulator(kube)
        await mgr.start()
        await sim.start()
        try:
            t0 = time.perf_counter()
            await kube.create("Notebook", nbapi.new(
                "needs-three", "bench", accelerator="v5e",
                topology="4x4", num_slices=3))
            await wait_until(
                lambda: sched._intent_book is not None
                and sched._intent_book.intents,
                10.0, "scale-up intent")
            t_intent = time.perf_counter()
            intent = next(iter(sched._intent_book.intents.values()))
            pr = await kube.get_or_none("ProvisioningRequest",
                                        intent.name, "kubeflow-tpu")
            # Grant: the operator/autoscaler grows the pool; the dynamic
            # fleet source reflects it and the gang admits.
            await kube.patch(
                "ConfigMap", "kftpu-fleet",
                {"data": {"fleet": "pool-a=v5e:4x4:3"}}, "kubeflow-tpu")
            await wait_until(
                lambda: ("bench", "needs-three") in
                sched.policy.ledger.allocations,
                15.0, "admission against granted capacity")
            t_admit = time.perf_counter()
            await mgr.wait_idle(timeout=20)
            granted = sched.m_scale_up_events.labels(
                event="granted").value
            pr_after = await kube.get_or_none(
                "ProvisioningRequest", intent.name, "kubeflow-tpu")
            sched.policy.ledger.assert_consistent()
            return {
                "intent_latency_sec": round(t_intent - t0, 4),
                "grant_roundtrip_sec": round(t_admit - t_intent, 4),
                "intent_pr_created": pr is not None,
                "intent_withdrawn_granted": granted >= 1
                and not sched._intent_book.intents,
                "intent_pr_deleted": pr_after is None,
                "ledger_violations": sched.policy.ledger.violations,
            }
        finally:
            await sim.stop()
            await mgr.stop()
            kube.close_watches()

    async def reclaim_storm(rounds: int) -> dict:
        kube, mgr, sched = build(
            "res=v5e:4x4:2,spot-a=v5e:4x4:2:spot,spot-b=v5e:4x4:2:spot",
            grace=8.0)
        plan = FaultPlan(seed=7)
        plan.reclaim_spot(rate=1.0)   # the schedule below paces itself
        sim = PodSimulator(kube)
        await mgr.start()
        await sim.start()
        stop_flag = [False]
        ack = asyncio.create_task(sdk_ack_loop(kube, stop_flag))
        nodes = {}
        try:
            for pool in ("spot-a", "spot-b"):
                for i in range(2):
                    node = f"{pool}-node-{i}"
                    nodes.setdefault(pool, []).append(node)
                    await kube.create("Node", {
                        "apiVersion": "v1", "kind": "Node",
                        "metadata": {"name": node, "labels": {
                            "cloud.google.com/gke-nodepool": pool,
                            "cloud.google.com/gke-spot": "true"}},
                    })
            for i in range(6):
                await kube.create("Notebook", nbapi.new(
                    f"gang-{i}", "bench", accelerator="v5e",
                    topology="4x4"))
            await mgr.wait_idle(timeout=20)
            revocations = 0
            for _ in range(rounds):
                for pool, pool_nodes in nodes.items():
                    if plan.should_reclaim_spot(pool):
                        revocations += 1
                        for node in pool_nodes:
                            await kube.patch(
                                "Node", node, {"spec": {"taints": [{
                                    "key": "cloud.google.com/"
                                    "gke-spot-termination",
                                    "effect": "NoSchedule"}]}})
                await asyncio.sleep(0.3)
                # Revocation completes; replacement capacity arrives.
                for pool_nodes in nodes.values():
                    for node in pool_nodes:
                        await kube.patch("Node", node,
                                         {"spec": {"taints": None}})
                await asyncio.sleep(0.2)
            await wait_until(
                lambda: not sched._draining and not sched._spot_reclaims,
                30.0, "storm drains to finish")
            await mgr.wait_idle(timeout=30)
            sched.policy.ledger.assert_consistent()
            lost = []
            for nb in await kube.list("Notebook"):
                key = (nb["metadata"].get("namespace"),
                       nb["metadata"]["name"])
                if nbapi.STOP_ANNOTATION in annotations_of(nb):
                    continue
                if key not in sched.policy.ledger.allocations \
                        and key not in sched.policy.pending:
                    lost.append(key)
            storm = {
                "rounds": rounds,
                "revocations": revocations,
                "reclaim_drains": sched.m_spot_reclaims.labels().value,
                "grace_fallbacks_during_storm":
                    sched.m_drain_fallback.labels().value,
                "lost_gangs": [f"{k[0]}/{k[1]}" for k in lost],
                "ledger_violations": sched.policy.ledger.violations,
            }
            # Ack-less arm: a victim whose SDK never answers must
            # hard-stop via the grace fallback — chips never hostage.
            stop_flag[0] = True
            before = sched.m_drain_fallback.labels().value
            victim = next(
                (k for k, a in sched.policy.ledger.allocations.items()
                 if any(p.startswith("spot") for p in a.placements)),
                None)
            residents = 0
            if victim is not None:
                pool = next(p for p in sched.policy.ledger.allocations[
                    victim].placements if p.startswith("spot"))
                residents = sum(
                    1 for a in sched.policy.ledger.allocations.values()
                    if a.placements.get(pool))
                for node in nodes[pool]:
                    await kube.patch(
                        "Node", node, {"spec": {"taints": [{
                            "key": "cloud.google.com/gke-spot-termination",
                            "effect": "NoSchedule"}]}})
                await wait_until(
                    lambda: sched.m_drain_fallback.labels().value
                    >= before + residents, 30.0,
                    "grace fallback for ack-less victims")
            await mgr.wait_idle(timeout=20)
            storm["ackless_fallbacks"] = (
                sched.m_drain_fallback.labels().value - before)
            storm["ackless_residents"] = residents
            storm["ackless_victim_tested"] = victim is not None
            return storm
        finally:
            stop_flag[0] = True
            ack.cancel()
            try:
                await ack
            except (asyncio.CancelledError, Exception):
                pass
            await sim.stop()
            await mgr.stop()
            kube.close_watches()

    wedge_off = asyncio.run(wedge_scenario(defrag=False))
    wedge_on = asyncio.run(wedge_scenario(defrag=True))
    scale_up = asyncio.run(scale_up_scenario())
    storm = asyncio.run(reclaim_storm(rounds=2 if smoke else 5))
    ok = (
        wedge_on["large_gang_admitted"]
        and not wedge_off["large_gang_admitted"]
        and wedge_on["ledger_violations"] == 0
        and wedge_off["ledger_violations"] == 0
        and scale_up["intent_pr_created"]
        and scale_up["intent_withdrawn_granted"]
        and scale_up["ledger_violations"] == 0
        and storm["ledger_violations"] == 0
        and not storm["lost_gangs"]
        and storm["grace_fallbacks_during_storm"] == 0
        and (not storm["ackless_victim_tested"]
             or storm["ackless_fallbacks"] == storm["ackless_residents"])
    )
    return {
        "metric": "elastic_fleet",
        "smoke": smoke,
        "wedge_defrag_off": wedge_off,
        "wedge_defrag_on": wedge_on,
        "scale_up": scale_up,
        "reclaim_storm": storm,
        "pass": ok,
    }


def inference_serving(smoke: bool = False) -> dict:
    """`bench.py inference_serving [--smoke]` — the serving workload
    class acceptance gate (ISSUE 11, grown to the v2 engine in ISSUE
    19). Two halves:

    - **data plane** (in-process JAX): the serving engine v2 (paged
      KV-cache + chunked prefill + multi-model warm standbys) under a
      seeded, trace-driven OPEN-LOOP load generator at **10× the PR 11
      trace rate** — arrivals never wait for completions, so overload
      shows up as p99 queueing, like production. Gates on: every
      request completing at the 10× rate; zero KV-block accounting
      violations under a seeded fault storm AND a tiny-pool pressure
      serve (backpressure = queue wait, never OOM, never oversell);
      chunked prefill keeping decode p99 no worse than the
      head-of-line run-to-completion baseline (paired trials on the
      same long-prompt trace); a warm model swap ≥3× faster than cold
      init+compile; and the PR 11 warm-vs-cold park gate.
    - **control plane** (FakeKube + podsim + the real manager/scheduler/
      serving-controller stack): an InferenceService scales 0 → N → 0 →
      1 against the SAME chip ledger as contending notebook gangs.
      Gates on: the serving burst draining an *idle* notebook through
      the checkpoint protocol (serving priority over idle notebooks),
      zero ledger violations throughout the collision, a real park
      (replica-0 StatefulSet kept at 0 replicas, chips released), and a
      warm scale-from-zero that re-admits off the parked standby.
    """
    import time as _time

    from kubeflow_tpu.api import inferenceservice as isvcapi
    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.controllers.notebook import (
        NotebookOptions,
        setup_notebook_controller,
    )
    from kubeflow_tpu.migration import protocol as migration
    from kubeflow_tpu.models.burnin import BurninConfig
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.metrics import Registry
    from kubeflow_tpu.runtime.objects import annotations_of, deep_get, fmt_iso
    from kubeflow_tpu.scheduler import (
        Fleet,
        SchedulerOptions,
        TpuFleetScheduler,
    )
    from kubeflow_tpu.serving.controller import (
        ServingOptions,
        setup_serving_controller,
    )
    from kubeflow_tpu.serving.engine import (
        EngineOptions,
        Request,
        ServingEngine,
    )
    from kubeflow_tpu.serving.kvcache import KVBlockPool
    from kubeflow_tpu.serving.loadgen import Phase, burst_trace, generate_trace
    from kubeflow_tpu.testing.fakekube import FakeKube
    from kubeflow_tpu.testing.podsim import PodSimulator
    from kubeflow_tpu.webhooks import register_all

    # ---- data plane -----------------------------------------------------------

    # PR 11's burst rate was 40 req/s; the v2 acceptance bar is ≥10×.
    PR11_BURST_RATE = 40.0
    V2_BURST_RATE = 400.0

    small_cfg = BurninConfig(vocab=128, d_model=64, n_heads=2, n_layers=1,
                             d_ff=128, seq_len=64)

    def kv_fault_storm() -> dict:
        """Seeded adversarial op stream straight at the block pool:
        admits, releases, double-releases, unknown-rid releases and
        oversized admits, interleaved in random order. The pool must
        reject (never oversell), stay internally consistent, and end
        with zero accounting violations."""
        import random as _random

        pool = KVBlockPool(32, block_size=8)
        rng = _random.Random(31)
        live: list = []
        ops = 600 if smoke else 3000
        for i in range(ops):
            roll = rng.random()
            if roll < 0.50:
                table = pool.admit(i, rng.randint(0, 64),
                                   rng.randint(1, 16))
                if table is not None:
                    live.append(i)
            elif roll < 0.75 and live:
                pool.release(live.pop(rng.randrange(len(live))))
            elif roll < 0.90:
                # Hostile: double-release / release of a rid the pool
                # never admitted. Must be an idempotent no-op.
                pool.release(rng.randint(-ops, ops))
            else:
                # Hostile: worst-case need larger than the whole pool.
                pool.admit(-i - 1, 10_000, 10_000)
            if i % 50 == 0:
                pool.assert_consistent()
        for rid in live:
            pool.release(rid)
        pool.assert_consistent()
        return {
            "ops": ops,
            "rejections": pool.rejections,
            "violations": pool.violations,
            "leaked_blocks": pool.used_blocks,
        }

    def kv_pressure_serve() -> dict:
        """A pool far too small for the offered burst: admission must
        backpressure into queue wait — every request still completes,
        rejections are counted, and the accounting never oversells."""
        engine = ServingEngine(
            small_cfg, max_batch=4, use_mesh=False,
            options=EngineOptions(kv_blocks=6, kv_block_size=8))
        engine.cold_start(seed=0)
        trace = generate_trace(
            [Phase(0.3, 200.0)], seed=21, tokens_out=10, tokens_jitter=4)
        report = engine.serve(trace)
        engine.kv.assert_consistent()
        return {
            "requests": len(trace),
            "completed": len(report.completions),
            "kv_blocks": engine.kv.total_blocks,
            "rejections": engine.kv.rejections,
            "violations": engine.kv.violations,
            "peak_pressure": round(report.kv_peak_pressure, 3),
            "p99_queue_wait_sec": round(sorted(
                c.queue_wait for c in report.completions)[
                    max(0, int(0.99 * len(report.completions)) - 1)], 4),
        }

    def chunked_vs_hol() -> dict:
        """Paired trials on the SAME long-prompt collision: a batch of
        decode requests is mid-flight when a very long prompt lands on
        the prefill lane. Head-of-line runs the prefill to completion
        — every admitted decode freezes for the full chunk count —
        while chunked prefill interleaves one chunk per decode
        iteration. Decode service p99 (started → finished; queue wait
        is shared fate under either policy) must stay bounded."""
        import random as _random

        opts = dict(kv_blocks=1024, kv_block_size=16, prefill_chunk=32)
        eng_chunked = ServingEngine(
            small_cfg, max_batch=4, use_mesh=False,
            options=EngineOptions(chunked_prefill=True, **opts))
        eng_hol = ServingEngine(
            small_cfg, max_batch=4, use_mesh=False,
            options=EngineOptions(chunked_prefill=False, **opts))
        eng_chunked.cold_start(seed=0)
        eng_hol.cold_start(seed=0)
        pairs = []
        for k in range(2 if smoke else 3):
            rng = _random.Random(41 + k)
            # Three decodes admitted at t=0, the long prompt right
            # behind them (FIFO admits the decodes first), stragglers
            # arriving while the prefill is in flight.
            trace = sorted(
                [Request(rid=i, arrival=0.0,
                         tokens_out=rng.randint(48, 80))
                 for i in range(3)]
                + [Request(rid=3, arrival=0.0, tokens_out=4,
                           prompt_tokens=32 * rng.randint(80, 120))]
                + [Request(rid=4 + j, arrival=0.005 * (1 + j),
                           tokens_out=rng.randint(24, 48))
                   for j in range(2)],
                key=lambda r: (r.arrival, r.rid))
            # Alternate order across pairs so machine drift cancels.
            first, second = ((eng_chunked, eng_hol) if k % 2 == 0
                             else (eng_hol, eng_chunked))
            r1 = first.serve(trace)
            r2 = second.serve(trace)
            rc, rh = (r1, r2) if first is eng_chunked else (r2, r1)
            pairs.append({
                "chunked_decode_p99": round(
                    rc.decode_service_percentile(0.99), 4),
                "hol_decode_p99": round(
                    rh.decode_service_percentile(0.99), 4),
                "prefill_chunks": rc.prefill_chunks,
            })
        wins = sum(1 for p in pairs
                   if p["chunked_decode_p99"]
                   <= p["hol_decode_p99"] * 1.05)
        return {"pairs": pairs, "wins": wins, "trials": len(pairs)}

    def data_plane() -> dict:
        engine = ServingEngine(
            BurninConfig(vocab=512, d_model=128, n_heads=4, n_layers=2,
                         d_ff=512, seq_len=128),
            max_batch=8)
        cold_sec = engine.cold_start(seed=0)

        # Multi-model multiplexing: two more models behind the same
        # replica. Cold-load both once (init + compile, measured), then
        # swap back to the default — a warm swap off the host-resident
        # standby through the retained compiled fns. The ≥3× gate is
        # the reason warm standbys exist.
        engine.register_model("alt-a")
        engine.register_model("alt-b")
        engine.use_model("alt-a")
        engine.use_model("alt-b")       # LRU-demotes "default" to host
        engine.use_model("default")     # warm swap back
        cold_model_sec = max(engine.models.entry("alt-a").cold_init_sec,
                             engine.models.entry("alt-b").cold_init_sec)
        warm_swap_sec = engine.models.entry("default").warm_swap_sec

        # The headline trace: 10× PR 11's rates, with a prompt mix and
        # a weighted model mix riding the same seeded open loop.
        trace = burst_trace(
            seed=11, warm_rate=40.0, burst_rate=V2_BURST_RATE,
            warm_sec=0.25 if smoke else 1.0,
            burst_sec=0.25 if smoke else 1.0,
            cool_sec=0.1 if smoke else 0.5,
            tokens_out=8, tokens_jitter=4,
            long_prompt_frac=0.05, long_prompt_tokens=96,
            models={"default": 18, "alt-a": 1, "alt-b": 1})
        report = engine.serve(trace)
        engine.kv.assert_consistent()
        ckpt = engine.park()
        warm_sec = engine.warm_restore()
        # Serve again off the restored standby: the restore must yield a
        # WORKING engine, not just a fast timer.
        replay = engine.serve(burst_trace(seed=12, warm_sec=0.25,
                                          burst_sec=0.25, cool_sec=0.1))
        return {
            "requests": len(trace),
            "completed": len(report.completions),
            "tokens_out": report.tokens,
            "tokens_per_sec": round(report.tokens_per_sec, 1),
            "p50_latency_sec": round(report.latency_percentile(0.50), 4),
            "p99_latency_sec": round(report.latency_percentile(0.99), 4),
            "batch_occupancy": round(report.batch_occupancy, 2),
            "decode_steps": report.steps,
            "prefill_chunks": report.prefill_chunks,
            "model_swaps": report.model_swaps,
            "kv_peak_pressure": round(report.kv_peak_pressure, 3),
            "kv_violations": engine.kv.violations,
            "trace_burst_rate": V2_BURST_RATE,
            "rate_multiplier_vs_pr11": round(
                V2_BURST_RATE / PR11_BURST_RATE, 1),
            "cold_start_sec": round(cold_sec, 4),
            "warm_restore_sec": round(warm_sec, 4),
            "warm_speedup": round(cold_sec / max(warm_sec, 1e-9), 1),
            "parked_checkpoint": ckpt,
            "replay_completed": len(replay.completions),
            "model_swap": {
                "cold_init_sec": round(cold_model_sec, 4),
                "warm_swap_sec": round(warm_swap_sec, 4),
                "warm_vs_cold": round(
                    cold_model_sec / max(warm_swap_sec, 1e-9), 1),
            },
            "kv_fault_storm": kv_fault_storm(),
            "kv_pressure": kv_pressure_serve(),
            "chunked_prefill": chunked_vs_hol(),
        }

    # ---- control plane --------------------------------------------------------

    async def serving_engine_sim(kube, stop_flag):
        """Simulated in-pod serving engine: ack park drains (stamp the
        parked-checkpoint annotations when park-requested appears) and
        ack notebook drains (the idle victims the serving burst
        preempts must checkpoint, or every drain waits out the grace)."""
        step = [1000]
        while not stop_flag[0]:
            try:
                isvcs = await kube.list("InferenceService")
            except Exception:
                isvcs = []
            for isvc in isvcs:
                ann = annotations_of(isvc)
                requested = ann.get(isvcapi.PARK_REQUESTED_ANNOTATION)
                if requested and ann.get(
                        isvcapi.PARK_CHECKPOINT_FOR_ANNOTATION) \
                        != requested:
                    step[0] += 1
                    try:
                        await kube.patch(
                            "InferenceService",
                            isvc["metadata"]["name"],
                            {"metadata": {"annotations": {
                                isvcapi.PARK_CHECKPOINT_PATH_ANNOTATION:
                                    f"/ckpt/{isvc['metadata']['name']}",
                                isvcapi.PARK_CHECKPOINT_STEP_ANNOTATION:
                                    str(step[0]),
                                # Echo the request being answered —
                                # park_acked() correlates on it, so a
                                # previous cycle's checkpoint can never
                                # instant-ack a new park.
                                isvcapi.PARK_CHECKPOINT_FOR_ANNOTATION:
                                    requested,
                            }}}, isvc["metadata"].get("namespace"))
                    except Exception:
                        pass
            try:
                nbs = await kube.list("Notebook")
            except Exception:
                nbs = []
            for nb in nbs:
                ann = annotations_of(nb)
                if (migration.drain_requested_at(ann) is not None
                        and not migration.drain_acked(ann)
                        and nbapi.STOP_ANNOTATION not in ann):
                    try:
                        await kube.patch(
                            "Notebook", nb["metadata"]["name"],
                            {"metadata": {"annotations":
                                          migration.ack_patch(
                                              f"/ckpt/{nb['metadata']['name']}",
                                              500, _time.time(),
                                              for_request=ann.get(
                                                  nbapi.DRAIN_REQUESTED_ANNOTATION))}},
                            nb["metadata"].get("namespace"))
                    except Exception:
                        pass
            await asyncio.sleep(0.005)

    async def wait_until(predicate, timeout, what):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if predicate():
                return True
            await asyncio.sleep(0.01)
        raise RuntimeError(f"inference_serving: timed out waiting for {what}")

    async def stamp_load(kube, rate: float, *, fresh: bool = True):
        await kube.patch(
            "InferenceService", "svc",
            {"metadata": {"annotations": {
                isvcapi.OBSERVED_RATE_ANNOTATION: str(rate),
                isvcapi.LAST_REQUEST_AT_ANNOTATION:
                    fmt_iso(_time.time() if fresh
                            else _time.time() - 3600),
            }}}, "bench")

    async def control_plane() -> dict:
        kube = FakeKube()
        register_all(kube)
        mgr = Manager(kube, registry=Registry())
        sched = TpuFleetScheduler(
            kube,
            SchedulerOptions(
                queued_requeue_seconds=0.05, enable_migration=True,
                drain_grace_seconds=5.0, enable_elastic=True,
                idle_preempt_after_seconds=0.5),
            fleet=Fleet.parse("pool-a=v5e:2x2:2"), registry=mgr.registry)
        setup_notebook_controller(mgr, NotebookOptions(), scheduler=sched)
        serving = setup_serving_controller(
            mgr,
            ServingOptions(enabled=True, autoscale_period_seconds=0.05,
                           park_grace_seconds=2.0,
                           default_stabilization=0.1),
            scheduler=sched)
        sim = PodSimulator(kube)
        await mgr.start()
        await sim.start()
        stop_flag = [False]
        ack = asyncio.create_task(serving_engine_sim(kube, stop_flag))

        def svc_ready(n: int):
            def check():
                alive = sum(
                    1 for i in range(4)
                    if (("bench", f"svc#r{i}")
                        in sched.policy.ledger.allocations))
                return alive >= n
            return check

        try:
            # An idle notebook holds one of the two slices; a second,
            # busy notebook queues behind the serving burst later.
            await kube.create("Notebook", nbapi.new(
                "idle-nb", "bench", accelerator="v5e", topology="2x2"))
            await mgr.wait_idle(timeout=20)
            await kube.patch(
                "Notebook", "idle-nb",
                {"metadata": {"annotations": {
                    nbapi.LAST_ACTIVITY_ANNOTATION:
                        fmt_iso(_time.time() - 3600)}}}, "bench")

            # Cold create: 0 → 1 replica on the free slice.
            await kube.create("InferenceService", isvcapi.new(
                "svc", "bench", accelerator="v5e", topology="2x2",
                min_replicas=0, max_replicas=2, target_rate=5.0,
                scale_to_zero_after=0.4))
            t0 = time.perf_counter()
            await stamp_load(kube, 4.0)
            await wait_until(svc_ready(1), 15.0, "cold replica admission")
            await mgr.wait_idle(timeout=20)
            cold_create_sec = time.perf_counter() - t0

            # Burst + collision: the service wants 2 replicas — the
            # second must DRAIN the idle notebook (serving priority over
            # idle holders) — while a fresh notebook gang contends for
            # the same pool and must queue behind the serving class.
            # The holder first ages past idle_preempt_after (0.5 s):
            # the victim search floors the idle clock at admission.
            await asyncio.sleep(0.7)
            drains_before = sched.m_preemptions.labels(
                reason="idle").value
            t1 = time.perf_counter()
            await stamp_load(kube, 30.0)
            await kube.create("Notebook", nbapi.new(
                "contender-nb", "bench", accelerator="v5e",
                topology="2x2"))
            await wait_until(svc_ready(2), 20.0, "burst scale-out")
            burst_sec = time.perf_counter() - t1
            await mgr.wait_idle(timeout=20)
            sched.policy.ledger.assert_consistent()
            idle_drains = sched.m_preemptions.labels(
                reason="idle").value - drains_before
            contender_queued = ("bench", "contender-nb") in \
                sched.policy.pending

            # Cool down: rate 0, idle window passes → park with a
            # checkpoint ack from the simulated engine.
            await stamp_load(kube, 0.0, fresh=False)
            await wait_until(
                lambda: not any(
                    ("bench", f"svc#r{i}")
                    in sched.policy.ledger.allocations for i in range(4)),
                20.0, "scale-to-zero park")
            await mgr.wait_idle(timeout=20)
            isvc = await kube.get("InferenceService", "svc", "bench")
            parked_ann = annotations_of(isvc)
            parked = isvcapi.PARKED_AT_ANNOTATION in parked_ann
            parked_ckpt = isvcapi.parked_checkpoint(parked_ann)
            standby = await kube.get_or_none("StatefulSet", "svc-r0",
                                             "bench")
            standby_kept = standby is not None and \
                deep_get(standby, "spec", "replicas", default=None) == 0

            # The contender takes the freed chips once serving parks.
            await wait_until(
                lambda: ("bench", "contender-nb")
                in sched.policy.ledger.allocations,
                15.0, "contender admission after park")

            # Scale-from-zero: the parked standby restores (restore env
            # from the parked checkpoint; replicas patched back up).
            t2 = time.perf_counter()
            await stamp_load(kube, 4.0)
            await wait_until(svc_ready(1), 15.0, "warm re-admission")
            await mgr.wait_idle(timeout=20)
            warm_restore_cp_sec = time.perf_counter() - t2
            sched.policy.ledger.assert_consistent()
            warm_restores = serving.m_warm_restores.labels().value
            sts = await kube.get_or_none("StatefulSet", "svc-r0", "bench")
            restore_env = [
                e for e in deep_get(
                    sts or {}, "spec", "template", "spec", "containers",
                    default=[{}])[0].get("env", [])
                if e.get("name") == migration.RESTORE_PATH_ENV]
            return {
                "cold_replica_create_sec": round(cold_create_sec, 4),
                "burst_scale_out_sec": round(burst_sec, 4),
                "idle_notebook_drains": idle_drains,
                "contender_queued_during_burst": contender_queued,
                "parked": parked,
                "parked_checkpoint": (
                    {"path": parked_ckpt[0], "step": parked_ckpt[1]}
                    if parked_ckpt else None),
                "warm_standby_sts_kept": standby_kept,
                "warm_restore_sec": round(warm_restore_cp_sec, 4),
                "warm_restores": warm_restores,
                "restore_env_stamped": bool(restore_env),
                "ledger_violations": sched.policy.ledger.violations,
            }
        finally:
            stop_flag[0] = True
            ack.cancel()
            try:
                await ack
            except (asyncio.CancelledError, Exception):
                pass
            await sim.stop()
            await mgr.stop()
            kube.close_watches()

    dp = data_plane()
    cp = asyncio.run(control_plane())
    ok = (
        dp["completed"] == dp["requests"]
        and dp["replay_completed"] > 0
        and dp["warm_restore_sec"] < dp["cold_start_sec"]
        # ---- serving engine v2 gates (ISSUE 19) ----
        and dp["rate_multiplier_vs_pr11"] >= 10.0
        and dp["kv_violations"] == 0
        and dp["kv_fault_storm"]["violations"] == 0
        and dp["kv_fault_storm"]["leaked_blocks"] == 0
        and dp["kv_fault_storm"]["rejections"] > 0
        and dp["kv_pressure"]["completed"] == dp["kv_pressure"]["requests"]
        and dp["kv_pressure"]["violations"] == 0
        and dp["kv_pressure"]["rejections"] > 0
        and dp["chunked_prefill"]["wins"] * 2
        > dp["chunked_prefill"]["trials"]
        and dp["model_swap"]["cold_init_sec"]
        >= 3.0 * dp["model_swap"]["warm_swap_sec"]
        and dp["model_swaps"] >= 1
        and cp["idle_notebook_drains"] >= 1
        and cp["contender_queued_during_burst"]
        and cp["parked"]
        and cp["warm_standby_sts_kept"]
        and cp["warm_restores"] >= 1
        and cp["restore_env_stamped"]
        and cp["ledger_violations"] == 0
    )
    return {
        "metric": "inference_serving",
        "smoke": smoke,
        "data_plane": dp,
        "control_plane": cp,
        "pass": ok,
    }


def tracing_overhead() -> dict:
    """`bench.py tracing_overhead` — prove the always-on tracing path
    (span trees + flight recorder + API-call tagging, PR 3) costs <5% of
    control-plane reconcile throughput vs the PR 2 baseline.

    Runs the same `control_plane_scale` load test in PAIRS — each pair
    is one traced (the shipped default) and one untraced (kill switch)
    trial back-to-back, alternating order across pairs — and reports the
    **median of per-pair overhead deltas**. Pairing is the point: host
    load on a shared machine drifts between trials by more than the
    effect size, but barely within a pair, and a load spike poisons one
    pair instead of one whole arm (the median discards it). Two signals:

    - `overhead_pct` — median per-pair throughput delta, the headline
      and the <5% acceptance gate (`pass`);
    - `reconcile_overhead_pct` — same pairing on the manager histogram's
      mean reconcile latency (thousands of reconciles per trial), the
      tighter per-reconcile signal.

    Chip-free: the control plane runs on the in-process fake apiserver.
    """
    from kubeflow_tpu.runtime import tracing

    pairs = 5

    async def _run_phase(fn):
        cp = await ControlPlane().start()
        try:
            return await fn(cp)
        finally:
            await cp.stop()

    def one_trial(enabled: bool) -> dict:
        tracing.set_enabled(enabled)
        try:
            return asyncio.run(_run_phase(scale_test))
        finally:
            tracing.set_enabled(True)

    traced: list[dict] = []
    untraced: list[dict] = []
    deltas: list[float] = []
    rec_deltas: list[float] = []
    for i in range(pairs):
        # Alternate order within the pair so warm-up/ordering effects
        # cancel across pairs.
        if i % 2 == 0:
            on, off = one_trial(True), one_trial(False)
        else:
            off, on = one_trial(False), one_trial(True)
        traced.append(on)
        untraced.append(off)
        deltas.append(
            100.0 * (off["notebooks_per_sec"] - on["notebooks_per_sec"])
            / off["notebooks_per_sec"])
        if on.get("reconcile_mean_sec") and off.get("reconcile_mean_sec"):
            rec_deltas.append(
                100.0 * (on["reconcile_mean_sec"] - off["reconcile_mean_sec"])
                / off["reconcile_mean_sec"])

    overhead_pct = round(_median_sorted(sorted(deltas)), 2)
    reconcile_overhead_pct = (
        round(_median_sorted(sorted(rec_deltas)), 2) if rec_deltas else None)
    return {
        "metric": "tracing_overhead",
        "value": overhead_pct,
        "unit": "pct_throughput_regression",
        "notebooks": SCALE_NOTEBOOKS,
        "pairs": pairs,
        "pair_deltas_pct": [round(d, 2) for d in deltas],
        "traced_notebooks_per_sec": sorted(
            t["notebooks_per_sec"] for t in traced),
        "untraced_notebooks_per_sec": sorted(
            t["notebooks_per_sec"] for t in untraced),
        "traced_reconcile_mean_sec": _median_sorted(sorted(
            t["reconcile_mean_sec"] for t in traced
            if t.get("reconcile_mean_sec"))),
        "untraced_reconcile_mean_sec": _median_sorted(sorted(
            t["reconcile_mean_sec"] for t in untraced
            if t.get("reconcile_mean_sec"))),
        "overhead_pct": overhead_pct,
        "reconcile_overhead_pct": reconcile_overhead_pct,
        "pass": overhead_pct < 5.0,
    }


def slo_overhead(smoke: bool = False) -> dict:
    """`bench.py slo_overhead [--smoke]` — prove the SLO engine +
    durable lifecycle timelines (ISSUE 13: per-reconcile SLI scoring,
    per-transition journal annotation patches) cost <5% of control-plane
    reconcile throughput. Same paired-trial protocol as
    `tracing_overhead` (PR 3): each pair runs one enabled and one
    disabled `control_plane_scale` trial back-to-back with alternating
    order, the headline is the MEDIAN per-pair throughput delta, and the
    <5% gate fails the CI step. Chip-free."""
    from kubeflow_tpu.runtime import slo as slo_mod
    from kubeflow_tpu.runtime import timeline as timeline_mod

    pairs = 3 if smoke else 5
    count = 120 if smoke else SCALE_NOTEBOOKS

    async def _run_phase():
        cp = await ControlPlane().start()
        try:
            return await scale_test(cp, count=count)
        finally:
            await cp.stop()

    def one_trial(enabled: bool) -> dict:
        slo_mod.set_enabled(enabled)
        timeline_mod.set_enabled(enabled)
        try:
            return asyncio.run(_run_phase())
        finally:
            slo_mod.set_enabled(True)
            timeline_mod.set_enabled(True)

    enabled_trials: list[dict] = []
    disabled_trials: list[dict] = []
    deltas: list[float] = []
    rec_deltas: list[float] = []
    for i in range(pairs):
        if i % 2 == 0:
            on, off = one_trial(True), one_trial(False)
        else:
            off, on = one_trial(False), one_trial(True)
        enabled_trials.append(on)
        disabled_trials.append(off)
        deltas.append(
            100.0 * (off["notebooks_per_sec"] - on["notebooks_per_sec"])
            / off["notebooks_per_sec"])
        if on.get("reconcile_mean_sec") and off.get("reconcile_mean_sec"):
            rec_deltas.append(
                100.0 * (on["reconcile_mean_sec"] - off["reconcile_mean_sec"])
                / off["reconcile_mean_sec"])

    overhead_pct = round(_median_sorted(sorted(deltas)), 2)
    return {
        "metric": "slo_overhead",
        "value": overhead_pct,
        "unit": "pct_throughput_regression",
        "notebooks": count,
        "pairs": pairs,
        "pair_deltas_pct": [round(d, 2) for d in deltas],
        "enabled_notebooks_per_sec": sorted(
            t["notebooks_per_sec"] for t in enabled_trials),
        "disabled_notebooks_per_sec": sorted(
            t["notebooks_per_sec"] for t in disabled_trials),
        # Timeline writes are real API patches: surface the write-count
        # delta so a regression is attributable (journal churn vs CPU).
        "enabled_api_writes": sorted(
            t["api_writes"] for t in enabled_trials),
        "disabled_api_writes": sorted(
            t["api_writes"] for t in disabled_trials),
        "overhead_pct": overhead_pct,
        "reconcile_overhead_pct": (
            round(_median_sorted(sorted(rec_deltas)), 2)
            if rec_deltas else None),
        "pass": overhead_pct < 5.0,
    }


TELEMETRY_OH_STEPS = 40
TELEMETRY_OH_SMOKE_STEPS = 25


def telemetry_overhead(smoke: bool = False) -> dict:
    """`bench.py telemetry_overhead [--smoke]` — prove the always-on
    step profiler + publisher (ISSUE 18) cost <5% of training-loop
    throughput. Same paired-trial discipline as `tracing_overhead` /
    `slo_overhead`: each pair runs the SHIPPED hot path —
    ``trainer.fit`` with a StepProfiler and a TelemetryPublisher wired
    exactly as the SDK wires them (per-step observe + rate-limited
    publish; the no-op patcher stands in for the API call, which the
    rate limiter fires at most once per trial anyway) — against a bare
    ``fit`` back-to-back with alternating order, and the headline is
    the median per-pair per-step delta. Both arms drain the final loss
    so async dispatch can't hide either arm's tail. Chip-free (the
    small burn-in model; per-step cost is what's gated, not FLOPs)."""
    from functools import partial

    import jax

    from kubeflow_tpu import telemetry
    from kubeflow_tpu.models import BurninConfig, burnin
    from kubeflow_tpu.models import trainer
    from kubeflow_tpu.runtime.metrics import Registry
    from kubeflow_tpu.telemetry import StepProfiler, TelemetryPublisher

    pairs = 3 if smoke else 5
    steps = TELEMETRY_OH_SMOKE_STEPS if smoke else TELEMETRY_OH_STEPS

    cfg = BurninConfig(**SMALL_BENCH_MODEL)
    params0 = jax.jit(partial(burnin.init_params, cfg=cfg))(jax.random.key(0))
    tokens = jax.random.randint(
        jax.random.key(1), (BENCH_BATCH, cfg.seq_len), 0, cfg.vocab)
    raw_step = burnin.make_train_step(cfg)

    def step_fn(state, batch):
        params, loss = raw_step(state["params"], batch)
        return {"params": params, "step": state["step"] + 1}, loss

    # No donation: every trial restarts from the same warm params, so the
    # buffers must outlive each fit() run (identical in both arms — the
    # paired delta only cares that the arms match).
    step_fn = jax.jit(step_fn)
    # Compile + warm once outside the trials so neither arm pays it.
    warm, _ = step_fn({"params": params0, "step": 0}, tokens)
    jax.block_until_ready(warm)

    def batches():
        while True:
            yield tokens

    telemetry.set_enabled(True)

    def one_trial(enabled: bool) -> float:
        """Per-step wall seconds for one fit() run of ``steps`` steps."""
        state = {"params": params0, "step": 0}
        kwargs = {}
        if enabled:
            prof = StepProfiler(
                "burnin",
                flops_per_step=train_step_flops(cfg, BENCH_BATCH),
                tokens_per_step=BENCH_BATCH * (cfg.seq_len - 1))
            kwargs = {
                "profiler": prof,
                "publisher": TelemetryPublisher(lambda body: None,
                                                registry=Registry()),
            }
        t0 = time.perf_counter()
        state = trainer.fit(state, batches(), steps=steps, step_fn=step_fn,
                            **kwargs)
        jax.block_until_ready(state["params"])
        return (time.perf_counter() - t0) / steps

    enabled_secs: list[float] = []
    disabled_secs: list[float] = []
    deltas: list[float] = []
    for i in range(pairs):
        if i % 2 == 0:
            on, off = one_trial(True), one_trial(False)
        else:
            off, on = one_trial(False), one_trial(True)
        enabled_secs.append(on)
        disabled_secs.append(off)
        deltas.append(100.0 * (on - off) / off)

    overhead_pct = round(_median_sorted(sorted(deltas)), 2)
    return {
        "metric": "telemetry_overhead",
        "value": overhead_pct,
        "unit": "pct_step_time_regression",
        "steps": steps,
        "pairs": pairs,
        "pair_deltas_pct": [round(d, 2) for d in deltas],
        "enabled_step_sec": [round(s, 6) for s in sorted(enabled_secs)],
        "disabled_step_sec": [round(s, 6) for s in sorted(disabled_secs)],
        "overhead_pct": overhead_pct,
        "pass": overhead_pct < 5.0,
    }


def bench() -> dict:
    from kubeflow_tpu.utils.compilecache import cache_entries, enable_persistent_cache

    entries_before = cache_entries(CACHE_DIR)
    enable_persistent_cache(CACHE_DIR)

    import jax

    from kubeflow_tpu.models import BurninConfig, init_params, make_train_step

    async def _run_phase(fn):
        cp = await ControlPlane().start()
        try:
            return await fn(cp)
        finally:
            await cp.stop()

    # Fresh-process start probes FIRST — before this process attaches its
    # own jax client (see _coldstart_probes: a probe compiling while the
    # parent holds the chip measures relay contention, not start-up).
    starts = _coldstart_probes()

    t_start = time.perf_counter()
    spawn = asyncio.run(_run_phase(spawn_notebook))

    from functools import partial as _partial

    cfg = BurninConfig(**BENCH_MODEL)
    # One jitted program for the whole init: eager per-leaf RNG costs ~12 s
    # extra through the remote relay (measured; docs/perf.md).
    params = jax.jit(_partial(init_params, cfg=cfg))(jax.random.key(0))
    tokens = jax.random.randint(
        jax.random.key(1), (BENCH_BATCH, cfg.seq_len), 0, cfg.vocab
    )
    step = jax.jit(make_train_step(cfg), donate_argnums=(0,))

    # Compile separately from execution (AOT lower+compile).
    t0 = time.perf_counter()
    compiled = step.lower(params, tokens).compile()
    compile_sec = time.perf_counter() - t0

    # Warm-up: first execution pays allocation/transfer costs. Sync via a
    # scalar device->host fetch rather than block_until_ready — the final
    # loss transitively depends on every chained step, and the value fetch
    # is the only sync primitive that is reliable on every backend
    # (block_until_ready returned early through the remote-relay backend).
    params, loss = compiled(params, tokens)
    float(loss)
    coldstart_sec = time.perf_counter() - t_start

    canary_before = _canary_probe()

    # The 100 measured steps, timed as 4 chunks: the headline step_sec /
    # MFU stay the full-window mean (comparable to prior rounds), and the
    # chunk median + spread classify relay noise vs real drift (r03 weak
    # #6) without extra chip time.
    chunk = BENCH_STEPS // 4
    chunk_secs = []
    t1 = time.perf_counter()
    for _ in range(4):
        tc = time.perf_counter()
        for _ in range(chunk):
            params, loss = compiled(params, tokens)
        float(loss)
        chunk_secs.append((time.perf_counter() - tc) / chunk)
    step_sec = (time.perf_counter() - t1) / (4 * chunk)
    chunk_secs.sort()
    step_spread_pct = round(
        100.0 * (chunk_secs[-1] - chunk_secs[0]) / _median_sorted(chunk_secs),
        2)

    canary_after = _canary_probe()

    flops = train_step_flops(cfg, BENCH_BATCH)
    achieved_tflops = flops / step_sec / 1e12

    devices = jax.devices()
    acc_name = detect_accelerator(devices[0])
    mfu = peak_tflops = None
    if acc_name is not None:
        from kubeflow_tpu.tpu.topology import ACCELERATORS

        peak_tflops = ACCELERATORS[acc_name].peak_bf16_tflops_per_chip
        mfu = achieved_tflops / peak_tflops

    ici = None
    if len(devices) > 1:
        from kubeflow_tpu.probe.ici import run_ici_probe

        ici = run_ici_probe(accelerator=acc_name, topology=None).to_dict()

    longctx_out = _longctx_bench()
    families = _family_bench(peak_tflops)

    # Control-plane scale AFTER the cold-start window (its wall time must
    # not pollute in_process_to_first_step_sec). Three trials, each on a
    # FRESH control plane; the median-throughput trial is the tracked
    # number and the per-trial list bounds host-load variance (r03 weak
    # #1: a doc quoted an untracked low-load run the artifact refuted).
    scale_trials = [asyncio.run(_run_phase(scale_test))
                    for _ in range(MEASURE_TRIALS)]
    scale_trials.sort(key=lambda s: s["notebooks_per_sec"])
    scale = dict(scale_trials[len(scale_trials) // 2])
    rates = [s["notebooks_per_sec"] for s in scale_trials]
    scale["trials_notebooks_per_sec"] = rates
    scale["spread_pct"] = round(
        100.0 * (rates[-1] - rates[0]) / rates[len(rates) // 2], 2)
    # Latency-hiding variant: 5 ms injected RTT, DAG-parallel vs forced
    # serial (ISSUE 4 acceptance: ≥2× per-notebook convergence).
    scale["simulated_rtt"] = simulated_rtt()

    out = {
        "metric": "train_step_mfu",
        "value": round(mfu, 4) if mfu is not None else round(achieved_tflops, 3),
        "unit": "fraction_of_peak_bf16" if mfu is not None else "tflops",
        "vs_baseline": (
            round(mfu / MFU_TARGET, 3) if mfu is not None
            else round(COLDSTART_TARGET_SEC / max(coldstart_sec, 1e-9), 2)
        ),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "achieved_tflops": round(achieved_tflops, 3),
        "peak_bf16_tflops": peak_tflops,
        "step_sec": round(step_sec, 6),
        "step_chunk_secs": [round(s, 6) for s in chunk_secs],
        "step_spread_pct": step_spread_pct,
        # Environment canary (see _canary_probe): same 4096-cubed bf16
        # matmul chain every round, timed before and after the burn-in
        # window. Compare across rounds: canary moved with the headline →
        # environment drift; canary flat while the headline moved →
        # code regression. The before/after pair also bounds IN-run drift.
        "canary": {
            "shape": [CANARY_DIM, CANARY_DIM],
            "iters": CANARY_ITERS,
            "before_tflops": canary_before,
            "after_tflops": canary_after,
            "drift_pct": round(
                100.0 * (canary_after - canary_before) / canary_before, 2),
        },
        "compile_sec": round(compile_sec, 3),
        "steps_measured": BENCH_STEPS,
        "step_flops": flops,
        # In-process number: clock starts AFTER imports + device attach,
        # so it is smaller than (and not comparable to) the fresh-process
        # coldstart_* fields below.
        "in_process_to_first_step_sec": round(coldstart_sec, 3),
        "compile_cache": {
            "dir": CACHE_DIR,
            "entries_before": entries_before,
            "entries_after": cache_entries(CACHE_DIR),
            "warm_start": entries_before > 0,
        },
        **starts,
        "control_plane_spawn_sec": round(spawn["spawn_sec"], 4),
        "control_plane_scale": scale,
        "longctx": longctx_out,
        "families": families,
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "n_devices": len(devices),
        "backend": jax.default_backend(),
    }
    if ici is not None:
        out["ici_probe"] = ici
    return out


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--fresh-probe":
        _fresh_probe(float(sys.argv[2]) if len(sys.argv) > 2 else time.time())
    elif len(sys.argv) >= 2 and sys.argv[1] == "--multichip-child":
        # Runs inside the re-exec'd 8-virtual-device interpreter
        # (_run_multichip_child). Force the cpu backend BEFORE any jax
        # backend query: the image's sitecustomize registers the TPU
        # plugin regardless of JAX_PLATFORMS, and a TPU attach here
        # would both miss the forced host device count and fight the
        # parent for the chip.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_multichip_child(smoke="--smoke" in sys.argv[2:])))
    elif len(sys.argv) >= 2 and sys.argv[1] == "multichip":
        result = multichip(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate (ISSUE 18): every family row must carry real numbers
        # (MFU + step p50; overlap attribution for the collective
        # families) and the long-context composition must hit its
        # sequence floor — ok=true with no numbers is exactly the blind
        # spot this gate closes. The MFU canary stays warn-only.
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "telemetry_overhead":
        result = telemetry_overhead(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate (ISSUE 18): the always-on step profiler + publisher
        # must cost <5% of training-loop step time in the paired A/B.
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "tracing_overhead":
        print(json.dumps(tracing_overhead()))
    elif len(sys.argv) >= 2 and sys.argv[1] == "slo_overhead":
        result = slo_overhead(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate like tracing_overhead: the SLO engine + timeline
        # journal must stay under 5% of control-plane throughput.
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "simulated_rtt":
        print(json.dumps(simulated_rtt()))
    elif len(sys.argv) >= 2 and sys.argv[1] == "scheduler_scale":
        result = scheduler_scale(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # This subcommand is a CI gate (unit-tests workflow): the
        # fairness/ledger/preemption criteria must fail the step, not
        # just flip a field in the printed JSON.
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "migration_roundtrip":
        result = migration_roundtrip(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate like scheduler_scale: a lost ack (grace fallback) or a
        # ledger violation must fail the step.
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "chaos_soak":
        result = chaos_soak(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate: any invariant violation, wedged key, or a poison pill
        # that fails to quarantine/resume must fail the step.
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "control_plane_scale":
        result = control_plane_scale(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate (ISSUE 17): N=4 sharded replicas must strictly beat
        # N=1 on notebooks/s under equal per-replica budgets, and the
        # 10k-CR churn run must converge every key through a mid-flight
        # shard kill (zero dropped keys, failover measured).
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "checkpoint_fabric":
        result = checkpoint_fabric(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate: snapshot-ack must beat a synchronous drain ≥3×, a
        # delta must upload fewer bytes than its full, staging restore
        # must beat remote, and the fault storm must end with zero
        # partial/wrong-step restores.
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "coldstart":
        result = coldstart(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate: the warm-pool claim path must beat the cold path ≥3×
        # in the podsim-modeled bench (claims attributed via the
        # timeline, pool replenished, real gangs never preempted for the
        # reserve, 0 ledger violations), and a canary-confirmed repo
        # regression of the warm-cache cold start fails here too
        # (environment-classified drift stays warn-only).
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "elastic_fleet":
        result = elastic_fleet(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate: the wedge must resolve via defrag (and starve without
        # it), scale-up must round-trip, and the reclaim storm must end
        # with zero ledger violations / lost gangs / live-SDK fallbacks.
        if not result["pass"]:
            sys.exit(1)
    elif len(sys.argv) >= 2 and sys.argv[1] == "inference_serving":
        result = inference_serving(smoke="--smoke" in sys.argv[2:])
        print(json.dumps(result))
        # CI gate: open-loop serve must complete, the parked warm
        # standby must restore faster than a cold create, the serving
        # burst must drain an idle notebook (never the reverse), and the
        # collision must end with zero chip-ledger violations.
        if not result["pass"]:
            sys.exit(1)
    else:
        print(json.dumps(bench()))
