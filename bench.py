#!/usr/bin/env python3
"""Framework benchmark — prints ONE JSON line.

Metric: end-to-end notebook cold-start on the in-process control plane —
time from `Notebook` CR creation to the slice-validation workload's first
completed training step (the "first psum" moment of BASELINE.md), using the
fake cluster (kubelet simulated) and REAL accelerator compute for the
workload. The reference publishes no comparable number (SURVEY.md §6:
`published: {}`); `vs_baseline` is measured against our own BASELINE target
of 60 s (the reference CI's notebook-Ready gate is 100 s, BASELINE.md).

Until the controller slice lands, this measures the workload path only
(compile + first step); the control-plane spawn is added in front as the
controller matures.
"""

import json
import time

BASELINE_TARGET_SEC = 60.0


def bench() -> dict:
    import jax

    from __graft_entry__ import entry

    t0 = time.perf_counter()
    fn, (params, tokens) = entry()
    step = jax.jit(fn)
    jax.block_until_ready(step(params, tokens))  # compile + first step
    first = time.perf_counter() - t0

    # Steady-state step time (10 iters) as a sanity check of chip health.
    t1 = time.perf_counter()
    for _ in range(10):
        out = step(params, tokens)
    jax.block_until_ready(out)
    steady = (time.perf_counter() - t1) / 10

    return {
        "metric": "coldstart_to_first_step_sec",
        "value": round(first, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_TARGET_SEC / max(first, 1e-9), 2),
        "steady_step_sec": round(steady, 6),
        "backend": jax.default_backend(),
    }


if __name__ == "__main__":
    print(json.dumps(bench()))
