#!/usr/bin/env python3
"""Framework benchmark — prints ONE JSON line.

End-to-end notebook cold-start: `Notebook` CR created → control plane
reconciles (admission webhooks, StatefulSet, Services, kubelet-simulated
pod start, status mirroring) → slice Ready → the burn-in workload's first
completed training step on the REAL accelerator (the "first psum" moment of
BASELINE.md).

The reference publishes no comparable number (SURVEY.md §6: published {});
`vs_baseline` is measured against our BASELINE target of 60 s (the
reference CI's notebook-Ready gate is 100 s, BASELINE.md).
"""

import asyncio
import json
import time

BASELINE_TARGET_SEC = 60.0


async def spawn_notebook() -> dict:
    """CR create → Ready on the in-process control plane; returns timings."""
    from kubeflow_tpu.api import notebook as nbapi
    from kubeflow_tpu.controllers.notebook import setup_notebook_controller
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.objects import deep_get
    from kubeflow_tpu.testing.fakekube import FakeKube
    from kubeflow_tpu.testing.podsim import PodSimulator
    from kubeflow_tpu.webhooks import register_all

    kube = FakeKube()
    register_all(kube)
    mgr = Manager(kube)
    setup_notebook_controller(mgr)
    sim = PodSimulator(kube)
    await mgr.start()
    await sim.start()
    t0 = time.perf_counter()
    await kube.create(
        "Notebook", nbapi.new("bench", "bench", accelerator="v5e", topology="2x2")
    )
    ready = None
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        nb = await kube.get("Notebook", "bench", "bench")
        if deep_get(nb, "status", "readyReplicas", default=0):
            ready = time.perf_counter() - t0
            break
        await asyncio.sleep(0.005)
    await sim.stop()
    await mgr.stop()
    kube.close_watches()
    if ready is None:
        raise RuntimeError("notebook never became Ready")
    return {"spawn_sec": ready}


def bench() -> dict:
    import jax

    from __graft_entry__ import entry

    t_start = time.perf_counter()
    spawn = asyncio.run(spawn_notebook())

    fn, (params, tokens) = entry()
    step = jax.jit(fn)
    jax.block_until_ready(step(params, tokens))  # compile + first step
    total = time.perf_counter() - t_start

    # Steady-state step time as a chip-health sanity check.
    t1 = time.perf_counter()
    for _ in range(10):
        out = step(params, tokens)
    jax.block_until_ready(out)
    steady = (time.perf_counter() - t1) / 10

    return {
        "metric": "coldstart_to_first_step_sec",
        "value": round(total, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_TARGET_SEC / max(total, 1e-9), 2),
        "control_plane_spawn_sec": round(spawn["spawn_sec"], 4),
        "steady_step_sec": round(steady, 6),
        "backend": jax.default_backend(),
    }


if __name__ == "__main__":
    print(json.dumps(bench()))
