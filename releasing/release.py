#!/usr/bin/env python3
"""Release tooling: version stamping, manifest image pinning, changelog.

The reference ships this as `releasing/` (README + `update-manifests-images`
rewriting Deployment image tags + a `version` marker — reference
releasing/README.md steps 1-4). Rebuilt here as one idempotent tool over
this repo's actual surfaces:

    python releasing/release.py set-version v1.2.0
        Writes VERSION, syncs pyproject.toml's `version`, rewrites every
        `kubeflow-tpu/*:<tag>` image reference in manifests/ to the new
        tag, and prepends a changelog section generated from git history
        (subjects since the previous release tag).

    python releasing/release.py check [EXPECTED_TAG]
        Exit 1 if VERSION, pyproject.toml and the manifest image tags
        disagree — the drift gate the release workflow runs. With an
        argument (the workflow passes "$GITHUB_REF_NAME"), also fail when
        the pushed tag differs from VERSION — tagging a commit that was
        never stamped (VERSION=dev expects "latest") must not release.

Release-branch flow mirrors the reference: cut a branch, run set-version,
commit, tag. `VERSION` of `dev` means manifests float on `:latest`.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VERSION_FILE = os.path.join(REPO, "VERSION")
PYPROJECT = os.path.join(REPO, "pyproject.toml")
CHANGELOG = os.path.join(REPO, "CHANGELOG.md")
MANIFEST_DIRS = [os.path.join(REPO, "manifests")]

# Every first-party image reference looks like kubeflow-tpu/<name>:<tag>.
IMAGE_RE = re.compile(r"(kubeflow-tpu/[\w.-]+):([\w.-]+)")


def read_version() -> str:
    if not os.path.exists(VERSION_FILE):
        return "dev"
    return open(VERSION_FILE).read().strip() or "dev"


def _manifest_files():
    for root_dir in MANIFEST_DIRS:
        for dirpath, _dirs, files in os.walk(root_dir):
            for name in sorted(files):
                if name.endswith((".yaml", ".yml")):
                    yield os.path.join(dirpath, name)


def manifest_tags() -> dict[str, set[str]]:
    """image name → set of tags referenced across manifests/."""
    out: dict[str, set[str]] = {}
    for path in _manifest_files():
        for image, tag in IMAGE_RE.findall(open(path).read()):
            out.setdefault(image, set()).add(tag)
    return out


def rewrite_manifest_tags(tag: str) -> list[str]:
    changed = []
    for path in _manifest_files():
        src = open(path).read()
        out = IMAGE_RE.sub(lambda m: f"{m.group(1)}:{tag}", src)
        if out != src:
            open(path, "w").write(out)
            changed.append(os.path.relpath(path, REPO))
    return changed


def pyproject_version() -> str:
    m = re.search(r'^version = "([^"]+)"', open(PYPROJECT).read(),
                  re.MULTILINE)
    if not m:
        raise SystemExit("pyproject.toml has no version field")
    return m.group(1)


def set_pyproject_version(version: str) -> None:
    src = open(PYPROJECT).read()
    out = re.sub(r'^version = "[^"]+"', f'version = "{version}"', src,
                 count=1, flags=re.MULTILINE)
    open(PYPROJECT, "w").write(out)


def previous_tag() -> str | None:
    try:
        return subprocess.run(
            ["git", "describe", "--tags", "--abbrev=0"],
            cwd=REPO, capture_output=True, text=True, check=True,
        ).stdout.strip() or None
    except subprocess.CalledProcessError:
        return None


def changelog_section(version: str) -> str:
    prev = previous_tag()
    rev_range = f"{prev}..HEAD" if prev else "HEAD"
    subjects = subprocess.run(
        ["git", "log", "--no-merges", "--pretty=format:%s", rev_range],
        cwd=REPO, capture_output=True, text=True, check=True,
    ).stdout.strip().splitlines()
    since = f" (since {prev})" if prev else ""
    lines = [f"## {version}{since}", ""]
    lines += [f"- {s}" for s in subjects] or ["- (no changes)"]
    return "\n".join(lines) + "\n"


def _upsert_changelog_section(version: str, section: str) -> tuple[str, str]:
    """Insert (or, when a ``## <version>`` heading already exists, replace
    in place) the version's changelog section — re-running set-version on
    a release branch must not stack duplicate sections."""
    existing = open(CHANGELOG).read() if os.path.exists(CHANGELOG) else (
        "# Changelog\n\n")
    # (?=[ \n]) not \b: "## v1.2.3" must not match a "## v1.2.3-rc.0"
    # heading (\b matches before the hyphen).
    heading_re = re.compile(
        rf"^## {re.escape(version)}(?=[ \n]).*?(?=^## |\Z)",
        re.MULTILINE | re.DOTALL)
    if heading_re.search(existing):
        return heading_re.sub(lambda _m: section, existing, count=1), "replaced"
    head, _, rest = existing.partition("\n## ")
    body = head + "\n" + section + ("\n## " + rest if rest else "")
    return body, "added"


def cmd_set_version(version: str) -> int:
    if not re.fullmatch(r"v\d+\.\d+\.\d+(-[\w.]+)?", version):
        raise SystemExit(
            f"version {version!r} must look like v1.2.3 or v1.2.3-rc.0")
    open(VERSION_FILE, "w").write(version + "\n")
    set_pyproject_version(version.lstrip("v"))
    changed = rewrite_manifest_tags(version)
    section = changelog_section(version)
    body, action = _upsert_changelog_section(version, section)
    open(CHANGELOG, "w").write(body)
    print(f"VERSION={version}; pyproject={version.lstrip('v')}; "
          f"manifests updated: {changed or 'none'}; "
          f"changelog section {action}")
    return 0


def cmd_check(expected: str | None = None) -> int:
    version = read_version()
    errors = []
    if version == "dev":
        expected_tag = "latest"
    else:
        expected_tag = version
        if pyproject_version() != version.lstrip("v"):
            errors.append(
                f"pyproject version {pyproject_version()} != VERSION "
                f"{version}")
    if expected is not None and expected != expected_tag:
        # The release workflow passes the pushed tag ($GITHUB_REF_NAME):
        # a tag that doesn't match the stamped VERSION means the commit
        # was never run through set-version (VERSION=dev expects the
        # floating "latest") — refuse to release it.
        errors.append(
            f"expected tag {expected!r} != {expected_tag!r} derived from "
            f"VERSION={version} (run set-version before tagging)")
    for image, tags in sorted(manifest_tags().items()):
        if tags != {expected_tag}:
            errors.append(
                f"{image} pinned to {sorted(tags)}, expected "
                f"[{expected_tag!r}] for VERSION={version}")
    for err in errors:
        print(f"release check: {err}", file=sys.stderr)
    print("release check: OK" if not errors else
          f"release check: {len(errors)} problem(s)")
    return 1 if errors else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_set = sub.add_parser("set-version",
                           help="stamp VERSION/pyproject/manifests")
    p_set.add_argument("version")
    p_check = sub.add_parser("check", help="verify version/tag consistency")
    p_check.add_argument(
        "expected", nargs="?", default=None,
        help="tag being released (e.g. $GITHUB_REF_NAME); must match VERSION")
    args = parser.parse_args(argv)
    if args.cmd == "set-version":
        return cmd_set_version(args.version)
    return cmd_check(args.expected)


if __name__ == "__main__":
    raise SystemExit(main())
