/* Inotify-based directory watcher for config hot-reload.
 *
 * Native parity note: the reference's profile-controller hot-reloads its
 * mounted namespace-labels file through fsnotify (profile_controller.go:
 * 368-399), a native inotify binding. This is the same primitive for the
 * TPU rebuild's runtime: watch the *directory* containing a mounted config
 * file — Kubernetes ConfigMap updates are atomic symlink swaps of the
 * ..data directory, which surface as IN_CREATE/IN_MOVED_TO/IN_DELETE on
 * the mount dir, not IN_MODIFY on the file — and wake the caller, who then
 * re-stats the file of interest.
 *
 * Built as libkfswatch.so (native/Makefile) and loaded via ctypes from
 * kubeflow_tpu/utils/fswatch.py, which falls back to mtime polling when
 * the library is unavailable (non-Linux, no compiler).
 *
 * API (all errors return -1, errno left set):
 *   kfs_watch_open(dir)          -> inotify fd watching dir
 *   kfs_watch_wait(fd, timeout)  -> 1 events drained, 0 timeout, -1 error
 *   kfs_watch_close(fd)
 */

#include <errno.h>
#include <poll.h>
#include <sys/inotify.h>
#include <unistd.h>

#define KFS_EVENTS                                                         \
    (IN_CLOSE_WRITE | IN_MOVED_TO | IN_MOVED_FROM | IN_CREATE | IN_DELETE | \
     IN_ATTRIB | IN_MODIFY | IN_DELETE_SELF | IN_MOVE_SELF)

int kfs_watch_open(const char *dir) {
    int fd = inotify_init1(IN_NONBLOCK | IN_CLOEXEC);
    if (fd < 0) return -1;
    if (inotify_add_watch(fd, dir, KFS_EVENTS) < 0) {
        int saved = errno;
        close(fd);
        errno = saved;
        return -1;
    }
    return fd;
}

int kfs_watch_wait(int fd, int timeout_ms) {
    struct pollfd pfd = {.fd = fd, .events = POLLIN};
    int rc;
    do {
        rc = poll(&pfd, 1, timeout_ms);
    } while (rc < 0 && errno == EINTR);
    if (rc <= 0) return rc; /* 0 timeout, -1 error */

    /* Drain everything queued so the next wait blocks afresh. */
    char buf[4096];
    ssize_t n;
    do {
        n = read(fd, buf, sizeof buf);
    } while (n > 0);
    if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) return -1;
    return 1;
}

void kfs_watch_close(int fd) { close(fd); }
