// DCN ring-bandwidth probe.
//
// Validates the pod-network path between TPU slice workers — the path
// jax.distributed.initialize() bootstraps over (headless-Service DNS) and
// the path DCN collectives ride for multi-slice training. The reference
// stack has no native code (SURVEY.md §2: zero .cc/.cu in the repo); this
// probe is the one justified native artifact of the TPU rebuild
// (SURVEY.md §7): a dependency-free C++ tool baked into jupyter-jax so a
// notebook can measure worker-to-worker bandwidth before committing a
// long run to a slice.
//
// Protocol: W ranks form a ring. Rank i listens on base_port+i, connects
// to rank (i+1)%W, then pushes `bytes` around the ring `iters` times
// (send to next while receiving from prev — both directions active, like
// a ring all-gather step). Prints one JSON line per rank.
//
// Usage:
//   dcn_probe --rank 0 --world 2 --peers host0,host1 --base-port 19000 \
//             --mbytes 64 --iters 8
//
// Build: g++ -O2 -std=c++17 -pthread -o dcn_probe dcn_probe.cpp

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <iostream>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Options {
  int rank = 0;
  int world = 1;
  std::vector<std::string> peers;
  int base_port = 19000;
  double mbytes = 64.0;
  int iters = 8;
  int connect_timeout_sec = 30;
};

[[noreturn]] void die(const std::string& msg) {
  std::cerr << "dcn_probe: " << msg << " (" << std::strerror(errno) << ")\n";
  std::exit(1);
}

std::vector<std::string> split(const std::string& s, char sep) {
  std::vector<std::string> out;
  std::stringstream ss(s);
  std::string part;
  while (std::getline(ss, part, sep)) {
    if (!part.empty()) out.push_back(part);
  }
  return out;
}

Options parse_args(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) die("missing value for " + arg);
      return argv[++i];
    };
    if (arg == "--rank") opt.rank = std::stoi(next());
    else if (arg == "--world") opt.world = std::stoi(next());
    else if (arg == "--peers") opt.peers = split(next(), ',');
    else if (arg == "--base-port") opt.base_port = std::stoi(next());
    else if (arg == "--mbytes") opt.mbytes = std::stod(next());
    else if (arg == "--iters") opt.iters = std::stoi(next());
    else if (arg == "--connect-timeout") opt.connect_timeout_sec = std::stoi(next());
    else die("unknown flag " + arg);
  }
  if (opt.peers.empty()) {
    for (int r = 0; r < opt.world; ++r) opt.peers.push_back("127.0.0.1");
  }
  if ((int)opt.peers.size() != opt.world) die("need one peer per rank");
  return opt;
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

int listen_on(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) die("socket");
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) < 0) die("bind");
  if (listen(fd, 1) < 0) die("listen");
  return fd;
}

int connect_to(const std::string& host, int port, int timeout_sec) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  std::string port_s = std::to_string(port);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::seconds(timeout_sec);
  // Workers of a slice start in parallel; retry until the peer is up
  // (the same tolerance jax.distributed has for the coordinator).
  while (true) {
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) == 0) {
      int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 && connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
        freeaddrinfo(res);
        set_nodelay(fd);
        return fd;
      }
      if (fd >= 0) close(fd);
      freeaddrinfo(res);
      res = nullptr;
    }
    if (std::chrono::steady_clock::now() > deadline)
      die("connect to " + host + ":" + port_s + " timed out");
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
  }
}

void send_all(int fd, const char* buf, size_t n) {
  while (n > 0) {
    ssize_t sent = send(fd, buf, n, 0);
    if (sent <= 0) die("send");
    buf += sent;
    n -= (size_t)sent;
  }
}

void recv_all(int fd, char* buf, size_t n) {
  while (n > 0) {
    ssize_t got = recv(fd, buf, n, 0);
    if (got <= 0) die("recv");
    buf += got;
    n -= (size_t)got;
  }
}

}  // namespace

int main(int argc, char** argv) {
  Options opt = parse_args(argc, argv);
  size_t bytes = (size_t)(opt.mbytes * 1e6);

  if (opt.world == 1) {
    std::cout << "{\"rank\":0,\"world\":1,\"gbps\":null,"
              << "\"note\":\"single rank, nothing to measure\"}\n";
    return 0;
  }

  int next_rank = (opt.rank + 1) % opt.world;
  int listen_fd = listen_on(opt.base_port + opt.rank);
  int send_fd = connect_to(opt.peers[next_rank], opt.base_port + next_rank,
                           opt.connect_timeout_sec);
  sockaddr_in peer{};
  socklen_t len = sizeof(peer);
  int recv_fd = accept(listen_fd, (sockaddr*)&peer, &len);
  if (recv_fd < 0) die("accept");
  set_nodelay(recv_fd);

  std::vector<char> out_buf(bytes, 0x5a), in_buf(bytes);

  // Warmup pass wires both directions before timing.
  std::thread w([&] { send_all(send_fd, out_buf.data(), bytes); });
  recv_all(recv_fd, in_buf.data(), bytes);
  w.join();

  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < opt.iters; ++it) {
    std::thread sender([&] { send_all(send_fd, out_buf.data(), bytes); });
    recv_all(recv_fd, in_buf.data(), bytes);
    sender.join();
  }
  double secs = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0).count();

  // Each iteration moves `bytes` out and `bytes` in concurrently; ring
  // bandwidth is the per-direction rate.
  double gbps = (double)bytes * opt.iters / secs / 1e9;
  std::cout << "{\"rank\":" << opt.rank << ",\"world\":" << opt.world
            << ",\"mbytes\":" << opt.mbytes << ",\"iters\":" << opt.iters
            << ",\"seconds\":" << secs << ",\"gbps\":" << gbps << "}\n";

  close(send_fd);
  close(recv_fd);
  close(listen_fd);
  return 0;
}
