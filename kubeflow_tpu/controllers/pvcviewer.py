"""PVCViewer reconciler: CR → filebrowser Deployment + Service (+ VS).

Reference: ``pvcviewer-controller/controllers/pvcviewer_controller.go``
(:96-146) with the file-based defaulting webhook folded into
``api.pvcviewer.default`` (pvcviewer_webhook.go:33-60) and RWO
co-scheduling like the tensorboard controller.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from kubeflow_tpu.api import pvcviewer as pvcapi
from kubeflow_tpu.controllers.common import (
    POD_PVC_INDEX,
    index_pod_by_pvc,
    rwo_affinity,
)
from kubeflow_tpu.runtime.apply import (
    ApplyCache,
    Stage,
    apply_set,
    informer_reader,
)
from kubeflow_tpu.runtime.manager import Controller, Manager, Result
from kubeflow_tpu.runtime.objects import (
    deep_get,
    deepcopy,
    get_meta,
    name_of,
    namespace_of,
)
from kubeflow_tpu.runtime.tracing import span

log = logging.getLogger(__name__)


@dataclass
class PVCViewerOptions:
    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"


class PVCViewerReconciler:
    def __init__(self, kube, options: PVCViewerOptions | None = None):
        self.kube = kube
        self.opts = options or PVCViewerOptions()
        # Wired by setup_pvcviewer_controller; bare-reconciler tests run
        # with the apiserver fallbacks.
        self._pod_informer = None
        self._child_informers: dict[str, object] = {}
        self._reader = informer_reader(self._child_informers)
        self._apply_cache = ApplyCache()

    async def reconcile(self, key) -> Result | None:
        ns, name = key
        with span("cache_read"):
            viewer = await self.kube.get_or_none("PVCViewer", name, ns)
        if viewer is None or get_meta(viewer).get("deletionTimestamp"):
            return None
        pvcapi.default(viewer)  # idempotent; covers CRs that bypassed admission

        with span("build_children"):
            deployment = await self.generate_deployment(viewer)
            children = [deployment, self.generate_service(viewer)]
            if self.opts.use_istio:
                children.append(self.generate_virtual_service(viewer))
        with span("apply"):
            # Independent children — one stage, applied concurrently
            # (latency hiding, ISSUE 4).
            outcomes = await apply_set(
                self.kube, [Stage("children", children)],
                cache=self._apply_cache, reader=self._reader, owner=viewer,
            )
        live_deployment = next(
            (row.result for row in outcomes[0]
             if isinstance(row.child, dict)
             and row.child.get("kind") == "Deployment"), None)
        with span("status"):
            await self._update_status(viewer, live_deployment)
        return None

    async def generate_deployment(self, viewer: dict) -> dict:
        name, ns = name_of(viewer), namespace_of(viewer)
        pod_spec = deepcopy(deep_get(viewer, "spec", "podSpec", default={}))
        if deep_get(viewer, "spec", "rwoScheduling"):
            affinity = await rwo_affinity(
                self.kube, ns, deep_get(viewer, "spec", "pvc"),
                pod_informer=self._pod_informer,
            )
            if affinity:
                pod_spec["affinity"] = affinity
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": f"{name}-pvcviewer", "namespace": ns},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"pvcviewer": name}},
                "template": {
                    "metadata": {"labels": {"pvcviewer": name}},
                    "spec": pod_spec,
                },
            },
        }

    def generate_service(self, viewer: dict) -> dict:
        name, ns = name_of(viewer), namespace_of(viewer)
        target = deep_get(
            viewer, "spec", "networking", "targetPort",
            default=pvcapi.DEFAULT_TARGET_PORT,
        )
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": f"{name}-pvcviewer", "namespace": ns},
            "spec": {
                "type": "ClusterIP",
                "selector": {"pvcviewer": name},
                "ports": [
                    {"name": "http", "port": 80, "targetPort": target,
                     "protocol": "TCP"}
                ],
            },
        }

    def url_of(self, viewer: dict) -> str:
        name, ns = name_of(viewer), namespace_of(viewer)
        base = deep_get(
            viewer, "spec", "networking", "basePrefix",
            default=pvcapi.DEFAULT_BASE_PREFIX,
        )
        return f"{base}/{ns}/{name}/"

    def generate_virtual_service(self, viewer: dict) -> dict:
        name, ns = name_of(viewer), namespace_of(viewer)
        prefix = self.url_of(viewer)
        rewrite = deep_get(viewer, "spec", "networking", "rewrite", default=prefix)
        http = {
            "match": [{"uri": {"prefix": prefix}}],
            "rewrite": {"uri": rewrite},
            "route": [
                {
                    "destination": {
                        "host": f"{name}-pvcviewer.{ns}.svc."
                        f"{self.opts.cluster_domain}",
                        "port": {"number": 80},
                    }
                }
            ],
        }
        timeout = deep_get(viewer, "spec", "networking", "timeout")
        if timeout:
            http["timeout"] = timeout
        return {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"pvcviewer-{ns}-{name}", "namespace": ns},
            "spec": {
                "hosts": [self.opts.istio_host],
                "gateways": [self.opts.istio_gateway],
                "http": [http],
            },
        }

    async def _update_status(self, viewer: dict, deployment: dict | None) -> None:
        name, ns = name_of(viewer), namespace_of(viewer)
        ready = deep_get(deployment or {}, "status", "readyReplicas", default=0) or 0
        replicas = deep_get(deployment or {}, "spec", "replicas", default=1)
        status = {
            "ready": bool(ready) and ready == replicas,
            "conditions": deep_get(deployment or {}, "status", "conditions",
                                   default=[]),
        }
        if self.opts.use_istio:
            status["url"] = self.url_of(viewer)
        if deep_get(viewer, "status") != status:
            await self.kube.patch(
                "PVCViewer", name, {"status": status}, ns, subresource="status"
            )


def setup_pvcviewer_controller(
    mgr: Manager, options: PVCViewerOptions | None = None
) -> PVCViewerReconciler:
    rec = PVCViewerReconciler(mgr.kube, options)
    owned = ["Deployment", "Service"] + (
        ["VirtualService"] if rec.opts.use_istio else [])
    mgr.add_controller(
        Controller(
            name="pvcviewer",
            kind="PVCViewer",
            reconcile=rec.reconcile,
            owns=owned,
        )
    )
    # update(), not rebind: rec._reader closed over this dict in __init__.
    rec._child_informers.update({k: mgr.informer_for(k) for k in owned})
    rec._pod_informer = mgr.informer_for("Pod")
    rec._pod_informer.add_indexer(POD_PVC_INDEX, index_pod_by_pvc)
    return rec
