"""Warm pod pools: pre-started notebook pods claimed into incoming CRs.

Cold start is the worst-scaling user-facing number in the stack
(cold-cache 43–47 s, warm-cache ~14 s; BENCH_r05) and almost none of it
is reconcile time (~6 ms) — the spend is pod scheduling, image pull,
interpreter + import, device-client attach, and XLA compile. This module
attacks all of it at once with the pool-of-prewarmed-sandboxes idiom
(KServe/ModelMesh in the reference's ODH ecosystem): per image×shape
pools of **fully started** pods — interpreter up, ``jax`` imported,
devices initialized, compile cache seeded — held by the SDK's warm-idle
loop (:func:`kubeflow_tpu.sdk.warm_idle`), so an incoming Notebook can
**claim** one and be Ready in the time it takes to re-label a pod.

Shape of the thing:

- **Spec**: ``KFTPU_WARM_POOLS=[ns/]image@acc:topo:n,...`` (env, static)
  or the same grammar under ``data["warm-pools"]`` of a ConfigMap
  (``KFTPU_WARM_POOLS_CONFIGMAP``, dynamic — re-read on a throttle),
  mirroring the fleet-spec grammar. Pools are namespace-local (pods
  cannot cross namespaces, so a pool serves notebooks in its own
  namespace; the default is the controller namespace). Only single-host,
  single-slice shapes pre-warm — a warm pod IS the slice.
- **Slots**: each warm pod rides its own one-replica StatefulSet
  (``<pool-slug>-p<i>``) labeled :data:`keys.TPU_WARM_POOL_LABEL`, so
  the kubelet path (admission webhooks, pod identity labels) is exactly
  the cold path's. A claim CONSUMES the slot (the StatefulSet is
  deleted; the pod, re-owned to the Notebook, survives); the
  **replenisher** tops the pool back up off the reconcile hot path.
- **Claim protocol** (the ONLY way a pool pod changes hands — enforced
  by the ``warm-pool-contract`` analysis pass): CAS-claim → adopt.
  The claimer stamps :data:`keys.TPU_WARM_CLAIM` with a nonce'd value
  and reads it back; a claimer that sees a value it did not write LOST
  the race and tries another pod — two reconcilers can never adopt the
  same pod. Adoption re-labels the pod into the Notebook's identity
  (``notebook-name``/``statefulset``/pod-name labels — the Service
  selects it), re-owns it (GC cascades with the CR), and injects the
  user's env (NB_PREFIX, restore hints; the in-pod warm-idle shim
  applies them by exec'ing the real server). An empty pool falls back
  to the cold path transparently.
- **Chip accounting**: every slot holds a ledger reservation
  (``TpuFleetScheduler.warm_reserve``) at warm-pool priority — the
  fleet's capacity view stays honest, and the reservation is the FIRST
  preemption victim (before any real gang, released instantly — nothing
  to checkpoint), so the scheduler cannibalizes the pool under pressure
  and the replenisher rebuilds it when pressure clears.
"""

from __future__ import annotations

import asyncio
import logging
import time
import zlib
from dataclasses import dataclass, field

from kubeflow_tpu.api import keys
from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.controllers.common import bounded_name
from kubeflow_tpu.migration import protocol as migration
from kubeflow_tpu.runtime.errors import AlreadyExists, ApiError, NotFound
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import (
    annotations_of,
    deep_get,
    fmt_iso,
    get_meta,
    name_of,
    namespace_of,
)
from kubeflow_tpu.runtime.tracing import span
from kubeflow_tpu.tpu.topology import TopologyError, TpuSlice

log = logging.getLogger(__name__)

# Knobs (docs/operations.md "Warm pools & cold-start"):
WARM_POOLS_ENV = "KFTPU_WARM_POOLS"
WARM_POOLS_CONFIGMAP_ENV = "KFTPU_WARM_POOLS_CONFIGMAP"
WARM_REPLENISH_ENV = "KFTPU_WARM_REPLENISH_SECONDS"
WARM_IDLE_ENV = "KFTPU_WARM_IDLE"

WARM_POOLS_CONFIGMAP_KEY = "warm-pools"
DEFAULT_REPLENISH_SECONDS = 5.0

# The pod-identity labels the claim re-stamps so the Notebook's Service
# (and every notebook-name-indexed lookup) selects the adopted pod —
# the same labels the cold path's StatefulSet template carries
# (controllers/notebook.py STS_LABEL / POD_NAME_LABEL; duplicated here
# because notebook.py imports this module, not the reverse).
_STS_LABEL = "statefulset"
_POD_NAME_LABEL = "statefulset.kubernetes.io/pod-name"


class WarmPoolConfigError(ValueError):
    """Malformed warm-pool specification."""


@dataclass(frozen=True)
class WarmPoolSpec:
    """One pool: ``size`` fully-started pods of one image×shape in one
    namespace."""

    namespace: str
    image: str
    accelerator: str
    topology: str
    size: int

    def __post_init__(self):
        if not self.image:
            raise WarmPoolConfigError("warm pool: image must be non-empty")
        if self.size < 0:
            raise WarmPoolConfigError(
                f"warm pool {self.image}: size must be >= 0, "
                f"got {self.size}")
        shape = TpuSlice.parse(self.accelerator, self.topology)
        if shape.num_hosts != 1:
            raise WarmPoolConfigError(
                f"warm pool {self.image}@{self.accelerator}:"
                f"{self.topology}: only single-host shapes can pre-warm "
                f"(this one needs {shape.num_hosts} hosts — a warm pod "
                "IS the slice; multi-host gangs take the cold path)")

    @property
    def shape_key(self) -> tuple[str, str]:
        return (self.accelerator.lower(), self.topology.lower())

    @property
    def slice(self) -> TpuSlice:
        return TpuSlice.parse(self.accelerator, self.topology)

    @property
    def slug(self) -> str:
        """Deterministic DNS-safe pool id: image basename + shape + a
        short hash of the full (ns, image, shape) — slot StatefulSets
        keep their names across controller restarts, so a rebuilt
        manager adopts the running pool instead of rebuilding it."""
        base = self.image.rsplit("/", 1)[-1].split(":", 1)[0].lower()
        base = "".join(c if c.isalnum() or c == "-" else "-" for c in base)
        h = zlib.crc32(
            f"{self.namespace}/{self.image}@{self.accelerator}:"
            f"{self.topology}".encode()) & 0xFFFFFF
        return bounded_name(
            f"warm-{base}-{self.accelerator}-{self.topology.replace('x', '')}"
            f"-{h:06x}")


def parse_warm_pools(spec: str, *,
                     default_namespace: str) -> tuple[WarmPoolSpec, ...]:
    """``[ns/]image@acc:topo:n,...`` → pool specs (the fleet-spec grammar
    with ``@`` separating the image). Empty spec → no pools (the whole
    subsystem is a no-op — the kill-switch story). Duplicate
    (namespace, image, shape) entries are a hard error, like duplicate
    fleet pool names: two entries would race one slot namespace."""
    pools: list[WarmPoolSpec] = []
    seen: dict[tuple, int] = {}
    position = 0
    for raw in (spec or "").replace("\n", ",").split(","):
        entry = raw.strip()
        if not entry:
            continue
        position += 1
        image, sep, shape = entry.rpartition("@")
        parts = shape.split(":")
        if not sep or not image or len(parts) != 3:
            raise WarmPoolConfigError(
                f"bad warm-pool entry {entry!r}: want "
                "[namespace/]image@accelerator:topology:size")
        ns, slash, image_only = image.partition("/")
        # An image reference itself contains "/" (registry/repo) — only a
        # FIRST segment with no dot/colon (not a registry host) and a
        # remaining path reads as a namespace prefix.
        if slash and "." not in ns and ":" not in ns and "/" in image:
            namespace, image_ref = ns, image_only
            if not image_ref:
                raise WarmPoolConfigError(
                    f"bad warm-pool entry {entry!r}: empty image after "
                    f"namespace {ns!r}")
        else:
            namespace, image_ref = default_namespace, image
        acc, topo, n = (p.strip() for p in parts)
        try:
            size = int(n)
        except ValueError:
            raise WarmPoolConfigError(
                f"bad warm-pool entry {entry!r}: size {n!r} is not an "
                "integer") from None
        key = (namespace, image_ref, acc.lower(), topo.lower())
        if key in seen:
            raise WarmPoolConfigError(
                f"duplicate warm pool {image_ref}@{acc}:{topo} in "
                f"namespace {namespace} (entries {seen[key]} and "
                f"{position}): merge the sizes into one entry")
        seen[key] = position
        try:
            pools.append(WarmPoolSpec(namespace, image_ref, acc.lower(),
                                      topo.lower(), size))
        except TopologyError as e:
            raise WarmPoolConfigError(
                f"bad warm-pool entry {entry!r}: {e}") from None
    return tuple(pools)


async def load_warm_pools_from_configmap(
        kube, name: str, namespace: str, *,
        default_namespace: str) -> tuple[WarmPoolSpec, ...] | None:
    """ConfigMap source (``data["warm-pools"]``), same tolerance contract
    as the fleet loader: absent/malformed → None (a broken spec must not
    wedge the replenisher — the last good spec keeps serving)."""
    cm = await kube.get_or_none("ConfigMap", name, namespace)
    spec = ((cm or {}).get("data") or {}).get(WARM_POOLS_CONFIGMAP_KEY) or ""
    if not spec.strip():
        return None
    try:
        return parse_warm_pools(spec, default_namespace=default_namespace)
    except Exception:
        log.exception("bad warm-pool spec in ConfigMap %s/%s",
                      namespace, name)
        return None


@dataclass
class WarmPoolOptions:
    """Env contract (cmd/envconfig.py warm_pool_options)."""

    spec: str = ""                      # KFTPU_WARM_POOLS
    configmap: str | None = None        # KFTPU_WARM_POOLS_CONFIGMAP
    controller_namespace: str = "kubeflow-tpu"
    replenish_seconds: float = DEFAULT_REPLENISH_SECONDS
    # Dynamic (ConfigMap) spec re-read throttle; rides the replenish
    # cadence by default.
    refresh_seconds: float = 30.0

    @property
    def enabled(self) -> bool:
        return bool(self.spec.strip()) or bool(self.configmap)


class WarmPoolManager:
    """Maintains the pools and owns the claim protocol. One instance per
    manager process, shared by the notebook reconciler (claims) and the
    replenisher background task; the in-process claim lock plus the CAS
    annotation make claims safe against both local concurrency and a
    second manager process."""

    def __init__(self, kube, options: WarmPoolOptions | None = None, *,
                 scheduler=None, registry: Registry | None = None):
        self.kube = kube
        self.options = options or WarmPoolOptions()
        self.scheduler = scheduler
        self._pools: tuple[WarmPoolSpec, ...] = ()
        if self.options.spec.strip():
            self._pools = parse_warm_pools(
                self.options.spec,
                default_namespace=self.options.controller_namespace)
        self._spec_next_try = 0.0
        self._now = time.time
        self._lock = asyncio.Lock()
        self._claimed_local: set[tuple] = set()
        self._nonce_seq = 0
        # Slots whose ledger reservation the scheduler cannibalized
        # (note_reclaimed): torn down by the next replenish pass UNLESS a
        # claim consumed them first — an admission that reclaims warm
        # chips and a claim racing it in the same reconcile should hand
        # the pod over, not kill it.
        self._reclaimed_slots: set[tuple] = set()
        self._wake = asyncio.Event()
        self._running = False
        registry = registry or global_registry
        self.m_target = registry.gauge(
            "warm_pool_target", "Configured warm-pool size", ["pool"])
        self.m_ready = registry.gauge(
            "warm_pool_ready", "Warm pods up and claimable", ["pool"])
        self.m_unfilled = registry.gauge(
            "warm_pool_unfilled",
            "Slots the replenisher could not back with chips", ["pool"])
        self.m_claims = registry.counter(
            "warm_pool_claims_total", "Warm pods claimed into Notebooks",
            ["pool"])
        self.m_exhausted = registry.counter(
            "warm_pool_exhausted_total",
            "Claim attempts that found the pool empty (cold fallback)",
            ["pool"])
        self.m_reclaimed = registry.counter(
            "warm_pool_reclaimed_total",
            "Warm slots cannibalized by the fleet scheduler")
        self.m_claim_seconds = registry.histogram(
            "warm_pool_claim_seconds",
            "Claim protocol duration (CAS + adopt)")

    # ---- spec --------------------------------------------------------------------

    @property
    def active(self) -> bool:
        return bool(self._pools)

    @property
    def pools(self) -> tuple[WarmPoolSpec, ...]:
        return self._pools

    async def _ensure_pools(self) -> None:
        """Dynamic spec refresh (ConfigMap source only — env is immutable
        for the process's lifetime), throttled like the fleet source."""
        opts = self.options
        if opts.spec.strip() or not opts.configmap:
            return
        now = self._now()
        if now < self._spec_next_try:
            return
        self._spec_next_try = now + max(opts.refresh_seconds, 0.01)
        pools = await load_warm_pools_from_configmap(
            self.kube, opts.configmap, opts.controller_namespace,
            default_namespace=opts.controller_namespace)
        if pools is not None and pools != self._pools:
            log.info("warm pools updated: %d pool(s)", len(pools))
            self._pools = pools

    # ---- eligibility + claim -----------------------------------------------------

    def pool_for(self, nb: dict, ms) -> WarmPoolSpec | None:
        """The pool that could serve this notebook, or None: same
        namespace (pods cannot cross namespaces), same image, same
        single-host single-slice shape."""
        if not self.active or ms is None or ms.num_slices != 1 \
                or ms.slice.num_hosts != 1:
            return None
        ns = namespace_of(nb)
        containers = deep_get(nb, "spec", "template", "spec", "containers",
                              default=[]) or []
        image = (containers[0].get("image") if containers else None) or ""
        shape = (ms.slice.accelerator.name.lower(),
                 ms.slice.topology_str.lower())
        for pool in self._pools:
            if pool.size > 0 and pool.namespace == ns \
                    and pool.image == image and pool.shape_key == shape:
                return pool
        return None

    async def claim(self, nb: dict, ms, *,
                    since: float | None = None) -> dict | None:
        """Claim one warm pod for this notebook: CAS the claim annotation,
        adopt the winner, consume its slot, stamp the verdict on the CR.
        Returns the adopted pod, or None (pool empty / every CAS lost /
        no matching pool) — the caller falls back to the cold path."""
        pool = self.pool_for(nb, ms)
        if pool is None:
            return None
        key = (namespace_of(nb), name_of(nb))
        t0 = time.perf_counter()
        with span("warm_claim", key=f"{key[0]}/{key[1]}", pool=pool.slug):
            async with self._lock:
                for pod in await self._claimable_pods(pool):
                    pod_key = (pool.namespace, name_of(pod))
                    if pod_key in self._claimed_local:
                        continue
                    nonce = self._next_nonce(key)
                    if not await self._cas_claim(pool, name_of(pod), nonce):
                        continue
                    self._claimed_local.add(pod_key)
                    try:
                        adopted = await self._adopt(nb, pod, ms, pool,
                                                    since=since)
                    except ApiError:
                        # Adoption half-done: release the claim so the
                        # pod stays poolable; the caller goes cold.
                        self._claimed_local.discard(pod_key)
                        try:
                            await self.kube.patch(
                                "Pod", name_of(pod),
                                {"metadata": {"annotations": {
                                    keys.TPU_WARM_CLAIM: None}}},
                                pool.namespace)
                        except ApiError as exc:
                            log.debug("CAS rollback for pod %s failed "
                                      "(stale-claim healer finishes "
                                      "it): %s", name_of(pod), exc)
                        continue
                    # The durable claim annotation (never cleared after a
                    # successful hand-off) guards from here; keeping the
                    # local mark would leak it forever and block a future
                    # pod that legitimately reuses this slot pod name.
                    self._claimed_local.discard(pod_key)
                    self.m_claims.labels(pool=pool.slug).inc()
                    self.m_claim_seconds.observe(time.perf_counter() - t0)
                    self._wake.set()  # replenish the consumed slot now
                    return adopted
            self.m_exhausted.labels(pool=pool.slug).inc()
            return None

    def _next_nonce(self, key: tuple) -> str:
        self._nonce_seq += 1
        return f"{key[0]}/{key[1]}/{self._nonce_seq}"

    async def _cas_claim(self, pool: WarmPoolSpec, pod_name: str,
                         nonce: str) -> bool:
        """The CAS: claim only an unclaimed pod (fresh read), then verify
        OUR value survived. Merge-patch is last-wins, so exactly one
        claimer's value is final — a claimer that reads back a foreign
        value lost and moves on; the unclaimed-precheck keeps the race
        window to one in-flight patch."""
        fresh = await self.kube.get_or_none("Pod", pod_name, pool.namespace)
        if fresh is None or annotations_of(fresh).get(keys.TPU_WARM_CLAIM):
            return False
        try:
            await self.kube.patch(
                "Pod", pod_name,
                {"metadata": {"annotations": {keys.TPU_WARM_CLAIM: nonce}}},
                pool.namespace)
        except ApiError:
            return False
        check = await self.kube.get_or_none("Pod", pod_name, pool.namespace)
        return check is not None \
            and annotations_of(check).get(keys.TPU_WARM_CLAIM) == nonce

    async def _adopt(self, nb: dict, pod: dict, ms, pool: WarmPoolSpec,
                     *, since: float | None) -> dict:
        """Re-own a CAS-won pod into the Notebook: identity labels (the
        Service and every notebook-name index select it), ownerReference
        (GC cascades with the CR), user env (NB_PREFIX + the notebook's
        own env + restore hints — the in-pod warm-idle shim execs the
        real server with them). Then consume the slot: delete its
        StatefulSet (the re-owned pod survives the cascade) and release
        its chip reservation — the notebook's own admission carries the
        booking from here.

        Fault ordering matters (the chaos soak's claim-uniqueness
        invariant found the original hole): (a) the CR's claim INTENT is
        stamped first — a failure there aborts with nothing mutated but
        the CAS mark; (b) the pod hand-off — a failure rolls the intent
        back (best-effort; the claim gate validates ownership and heals
        a surviving stale intent); (c) slot consumption is best-effort —
        the replenisher's stale-claim healer finishes whatever a fault
        interrupts. The CAS mark is NEVER cleared after a successful
        hand-off: an adopted pod that looked unclaimed could be adopted
        twice.

        Real-cluster note: Kubernetes pod SPECS are immutable
        (metadata is not), so the env written below is the simulation
        of the delivery a real cluster does through the shim — the
        warm-idle loop reads the claim from the downward-API file,
        fetches its new identity's env off its claimer's CR, and execs
        the server; the metadata half of this patch is the actual
        on-the-wire protocol."""
        name, ns = name_of(nb), namespace_of(nb)
        pod_name = name_of(pod)
        sts0 = ms.slice_sts_name(name, 0)
        slot_ref = next(
            (r for r in get_meta(pod).get("ownerReferences", [])
             if r.get("controller") and r.get("kind") == "StatefulSet"),
            None)
        labels = {
            nbapi.NOTEBOOK_NAME_LABEL: name,
            "app": name,
            _STS_LABEL: sts0,
            _POD_NAME_LABEL: f"{sts0}-0",
        }
        owner_patch: dict = {"metadata": {}}
        from kubeflow_tpu.runtime.objects import set_controller_owner

        set_controller_owner(owner_patch, nb)
        live_ctr = (deep_get(pod, "spec", "containers", default=[{}])
                    or [{}])[0]
        merged = self._merge_env(nb, live_ctr, ns, name)
        now = self._now()
        claimed_in = (round(max(0.0, now - since), 3)
                      if since is not None else None)
        # (a) intent on the CR first.
        await self.kube.patch(
            "Notebook", name,
            {"metadata": {"annotations": {
                nbapi.WARM_CLAIMED_ANNOTATION: pod_name,
                nbapi.WARM_CLAIMED_AT_ANNOTATION: fmt_iso(now),
                **({nbapi.WARM_CLAIMED_IN_ANNOTATION: str(claimed_in)}
                   if claimed_in is not None else {}),
            }}}, ns)
        # (b) the pod hand-off.
        try:
            await self.kube.patch(
                "Pod", pod_name,
                {
                    "metadata": {
                        "labels": labels,
                        "ownerReferences":
                            owner_patch["metadata"]["ownerReferences"],
                    },
                    "spec": {"containers": [merged]},
                },
                pool.namespace)
        except ApiError:
            try:
                await self.kube.patch(
                    "Notebook", name,
                    {"metadata": {"annotations": {
                        nbapi.WARM_CLAIMED_ANNOTATION: None,
                        nbapi.WARM_CLAIMED_AT_ANNOTATION: None,
                        nbapi.WARM_CLAIMED_IN_ANNOTATION: None,
                    }}}, ns)
            except ApiError as exc:
                # the gate's ownership validation self-heals this
                log.debug("claim-intent rollback for %s/%s failed: %s",
                          ns, name, exc)
            raise
        # (c) consume the slot — every step best-effort.
        if slot_ref is not None:
            slot_key = (pool.namespace, slot_ref["name"])
            self._reclaimed_slots.discard(slot_key)
            try:
                await self.kube.delete("StatefulSet", slot_ref["name"],
                                       pool.namespace)
            except (NotFound, ApiError) as exc:
                log.debug("slot consume delete %s failed (replenisher "
                          "heals interrupted claims): %s",
                          slot_ref["name"], exc)
            await self._release_reservation(slot_key)
        try:
            fresh = await self.kube.get_or_none("Pod", pod_name,
                                                pool.namespace)
        except ApiError:
            fresh = None
        return fresh if fresh is not None else pod

    def _merge_env(self, nb: dict, live_ctr: dict, ns: str,
                   name: str) -> dict:
        """The adopted container: the live warm container (image, ports,
        resources — immutable in spirit) with the USER's env layered on
        top, plus NB_PREFIX and the restore hint. The warm-idle shim in
        the pod applies these by exec'ing the real notebook server."""
        user_ctrs = deep_get(nb, "spec", "template", "spec", "containers",
                             default=[]) or []
        user_env = list((user_ctrs[0].get("env") if user_ctrs else None)
                        or [])
        env: dict[str, dict] = {}
        for e in (live_ctr.get("env") or []):
            if e.get("name") and e.get("name") != WARM_IDLE_ENV:
                env[e["name"]] = dict(e)
        for e in user_env:
            if e.get("name"):
                env[e["name"]] = dict(e)
        env[nbapi.PREFIX_ENV_VAR] = {
            "name": nbapi.PREFIX_ENV_VAR,
            "value": f"/notebook/{ns}/{name}"}
        hint = migration.restore_hint(annotations_of(nb))
        if hint is not None:
            env.setdefault(migration.RESTORE_PATH_ENV, {
                "name": migration.RESTORE_PATH_ENV, "value": hint[0]})
            if hint[1] is not None:
                env.setdefault(migration.RESTORE_STEP_ENV, {
                    "name": migration.RESTORE_STEP_ENV,
                    "value": str(hint[1])})
        merged = dict(live_ctr)
        merged["env"] = list(env.values())
        return merged

    async def _pool_pods(self, pool: WarmPoolSpec) -> list[dict]:
        """EVERY pod carrying the pool label — adopted (claimed) pods
        keep it, which is exactly why the slot indexer needs them."""
        try:
            return await self.kube.list(
                "Pod", pool.namespace,
                label_selector={"matchLabels": {
                    keys.TPU_WARM_POOL_LABEL: pool.slug}})
        except ApiError:
            return []

    async def _claimable_pods(self, pool: WarmPoolSpec) -> list[dict]:
        """Running+Ready, unclaimed pool pods, oldest-name-first (the
        longest-warmed pod has the most seeded cache)."""
        pods = await self._pool_pods(pool)
        out = []
        for pod in pods:
            if annotations_of(pod).get(keys.TPU_WARM_CLAIM):
                continue
            if deep_get(pod, "status", "phase") != "Running":
                continue
            if not any(c.get("type") == "Ready" and c.get("status") == "True"
                       for c in deep_get(pod, "status", "conditions",
                                         default=[])):
                continue
            out.append(pod)
        return sorted(out, key=name_of)

    def pool_status(self, pool: WarmPoolSpec,
                    ready: int | None = None) -> dict:
        return {"pool": pool.slug, "size": pool.size,
                **({"ready": ready} if ready is not None else {})}

    async def replenishing_status(self, nb: dict, ms) -> dict | None:
        """The JWA "Warming pool replenishing (k/n ready)" payload for a
        notebook whose pool was empty — None when no pool matches."""
        pool = self.pool_for(nb, ms)
        if pool is None:
            return None
        ready = len(await self._claimable_pods(pool))
        return {"ready": ready, "size": pool.size}

    # ---- ledger reservations + scheduler callback --------------------------------

    async def _reserve(self, pool: WarmPoolSpec, slot_name: str) -> bool:
        if self.scheduler is None:
            return True
        return await self.scheduler.warm_reserve(
            (pool.namespace, slot_name),
            namespace=pool.namespace,
            accelerator=pool.accelerator, topology=pool.topology)

    async def _release_reservation(self, slot_key: tuple) -> None:
        if self.scheduler is not None:
            await self.scheduler.warm_release(slot_key)

    async def note_reclaimed(self, key: tuple) -> None:
        """Scheduler callback: this slot's chip reservation was
        cannibalized for a real gang. The teardown is DEFERRED to the
        next replenish tick so a claim racing the same arbitration pass
        (the admitted notebook may be about to claim this very pod) wins
        the pod instead of finding it deleted."""
        self.m_reclaimed.inc()
        self._reclaimed_slots.add(tuple(key))
        self._wake.set()

    # ---- replenisher --------------------------------------------------------------

    async def run_replenisher(self) -> None:
        """Background loop (Manager.add_background): tops pools up to
        target, tears down reclaimed/excess/orphaned slots, and keeps
        every live slot's ledger reservation current — all off the
        reconcile hot path."""
        self._running = True
        while self._running:
            # Clear BEFORE the pass, not after: a claim or reclaim that
            # sets the wake DURING replenish() (its awaits interleave
            # with the reconcile tasks) must survive into the wait below
            # — clearing afterwards would erase the signal and delay the
            # top-up by a full replenish interval (the lost-wakeup shape
            # the await-race pass flags; regression test
            # test_wake_during_replenish_pass_is_not_lost).
            # kftpu: ignore[await-race] clear-before-work ordering: a set() landing during replenish() survives into the wait below by construction
            self._wake.clear()
            try:
                await self.replenish()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("warm-pool replenish pass failed; retrying")
            try:
                await asyncio.wait_for(
                    self._wake.wait(),
                    timeout=max(self.options.replenish_seconds, 0.01))
            except asyncio.TimeoutError:
                pass

    def stop(self) -> None:
        self._running = False
        self._wake.set()

    async def replenish(self) -> None:
        """One replenish pass. Idempotent and restart-safe: slots are
        discovered from their pool label (a rebuilt manager adopts the
        running pool), reservations re-assert per pass (a fleet that
        activated late back-fills), and a slot whose reservation cannot
        be backed is torn down (the chips belong to real gangs now)."""
        await self._ensure_pools()
        with span("warm_replenish"):
            # Only the HEALING steps take the claim lock (a healer that
            # observed a mid-flight claim would tear down the very pod
            # being adopted); the top-up below runs lock-free so claims
            # — the reconcile hot path — never wait out a full
            # multi-round-trip replenish pass. A top-up racing a claim
            # is graceful either way: a slot deleted under a CAS-winning
            # claimer fails its adopt patch and the claim moves on.
            async with self._lock:
                await self._teardown_reclaimed()
                for pool in self._pools:
                    await self._heal_pool(pool)
            seen_ns_slugs: dict[str, set] = {}
            for pool in self._pools:
                seen_ns_slugs.setdefault(pool.namespace, set()).add(
                    pool.slug)
                await self._replenish_pool(pool)
            await self._teardown_removed_pools(seen_ns_slugs)

    async def _heal_pool(self, pool: WarmPoolSpec) -> None:
        """Under the claim lock: tear down slots whose pod carries a
        claim annotation — such a slot should not exist (adoption
        deletes it), so a crash interrupted the claim protocol mid-way.
        An adopted pod (re-owned to its Notebook) survives the cascade;
        a stale-claimed pool pod dies with it and the top-up replaces
        the slot."""
        for sts in await self._slots(pool):
            if await self._slot_claim_interrupted(pool, sts):
                await self._delete_slot(pool, name_of(sts))

    async def _replenish_pool(self, pool: WarmPoolSpec) -> None:
        slots = await self._slots(pool)
        kept: list[dict] = []
        for sts in sorted(slots, key=name_of):
            if len(kept) >= pool.size \
                    or not await self._reserve(pool, name_of(sts)):
                # Excess (spec shrink) or unbackable (capacity gone to
                # real gangs): tear the slot down — its pod must not
                # squat on chips the ledger no longer reserves.
                await self._delete_slot(pool, name_of(sts))
                continue
            # The slot list is a pre-reserve snapshot: a claim can
            # consume this slot (delete the STS, release its
            # reservation) while _reserve's round trips are in flight,
            # and re-reserving AFTER the claim's release would book a
            # ghost allocation no later pass ever frees — the pool
            # permanently under-fills by one slot (chips held for a
            # slot that no longer exists). Re-validate and release.
            # (regression test test_claim_racing_replenish_leaves_no_
            # ghost_reservation)
            try:
                fresh = await self.kube.get_or_none(
                    "StatefulSet", name_of(sts), pool.namespace)
            except ApiError as exc:
                log.debug("slot liveness re-check for %s failed; "
                          "keeping it this pass: %s", name_of(sts), exc)
                fresh = sts
            if fresh is None:
                await self._release_reservation(
                    (pool.namespace, name_of(sts)))
                continue
            kept.append(sts)
        index = self._next_index(slots, await self._pool_pods(pool))
        while len(kept) < pool.size:
            slot_name = bounded_name(f"{pool.slug}-p{index}")
            index += 1
            if not await self._reserve(pool, slot_name):
                break  # no chips free — pressure wins; retry next pass
            try:
                created = await self.kube.create(
                    "StatefulSet", self._slot_statefulset(pool, slot_name),
                    pool.namespace)
            except AlreadyExists:
                created = None
            except ApiError:
                await self._release_reservation((pool.namespace, slot_name))
                break
            if created is not None:
                kept.append(created)
        ready = len(await self._claimable_pods(pool))
        self.m_target.labels(pool=pool.slug).set(pool.size)
        self.m_ready.labels(pool=pool.slug).set(ready)
        self.m_unfilled.labels(pool=pool.slug).set(
            max(0, pool.size - len(kept)))

    async def _teardown_reclaimed(self) -> None:
        for slot_key in list(self._reclaimed_slots):
            self._reclaimed_slots.discard(slot_key)
            ns, slot_name = slot_key
            pool = next((p for p in self._pools
                         if p.namespace == ns
                         and slot_name.startswith(p.slug)), None)
            sts = await self.kube.get_or_none("StatefulSet", slot_name, ns)
            if sts is None:
                continue  # already consumed by a claim — the race we defer for
            await self._delete_slot(pool, slot_name, namespace=ns)

    async def _teardown_removed_pools(self, seen: dict[str, set]) -> None:
        """Durable orphan sweep: every slot carries the pool label, so
        slots of a pool dropped from the spec are discovered from the
        cluster itself — including slots left behind while the manager
        was down, which no in-memory diff of previous passes can know
        about. Guarded on a loaded spec: a ConfigMap-sourced manager
        whose first read has not succeeded yet must not mistake every
        healthy pool for an orphan."""
        if not self._pools:
            return
        try:
            labeled = await self.kube.list(
                "StatefulSet", None,
                label_selector={"matchExpressions": [
                    {"key": keys.TPU_WARM_POOL_LABEL,
                     "operator": "Exists"}]})
        except ApiError as exc:
            log.debug("orphan-slot sweep LIST failed (retried next "
                      "pass): %s", exc)
            return
        for sts in labeled:
            ns = namespace_of(sts)
            slug = (get_meta(sts).get("labels") or {}).get(
                keys.TPU_WARM_POOL_LABEL)
            if slug in seen.get(ns, set()):
                continue
            await self._delete_slot(None, name_of(sts), namespace=ns)

    async def _delete_slot(self, pool: WarmPoolSpec | None, slot_name: str,
                           *, namespace: str | None = None) -> None:
        ns = namespace or (pool.namespace if pool else None)
        try:
            await self.kube.delete("StatefulSet", slot_name, ns)
        except (NotFound, ApiError) as exc:
            log.debug("slot teardown delete %s failed (reservation "
                      "still released; orphan sweep retries): %s",
                      slot_name, exc)
        await self._release_reservation((ns, slot_name))

    async def _slot_claim_interrupted(self, pool: WarmPoolSpec,
                                      sts: dict) -> bool:
        pod = await self.kube.get_or_none(
            "Pod", f"{name_of(sts)}-0", pool.namespace)
        if pod is None:
            return False
        return bool(annotations_of(pod).get(keys.TPU_WARM_CLAIM))

    async def _slots(self, pool: WarmPoolSpec) -> list[dict]:
        try:
            return await self.kube.list(
                "StatefulSet", pool.namespace,
                label_selector={"matchLabels": {
                    keys.TPU_WARM_POOL_LABEL: pool.slug}})
        except ApiError:
            return []

    @staticmethod
    def _next_index(slots: list[dict], pods: list[dict] = ()) -> int:
        """Monotone slot index. Claims CONSUME slot StatefulSets while
        the ADOPTED pod keeps living under the old slot's pod name
        (``<slug>-p<i>-0``) — so the index must clear every live slot
        AND every pool-labeled pod: counting only slots would reuse an
        index whose pod name is still taken the moment every slot is
        claimed within one replenish interval (or across a restart),
        and the recreated slot could never start its pod."""
        top = 0
        for sts in slots:
            _, _, tail = name_of(sts).rpartition("-p")
            if tail.isdigit():
                top = max(top, int(tail) + 1)
        for pod in pods:
            base, _, _ordinal = name_of(pod).rpartition("-")
            _, _, tail = base.rpartition("-p")
            if tail.isdigit():
                top = max(top, int(tail) + 1)
        return top

    def _slot_statefulset(self, pool: WarmPoolSpec, slot_name: str) -> dict:
        """One warm slot: a one-replica StatefulSet whose pod runs the
        SDK warm-idle loop under the pool's image with the full TPU
        wiring (selectors, chip requests, webhook annotations) — the
        kubelet path is exactly what a cold notebook pod would take, so
        the warmth is real, not simulated."""
        shape = pool.slice
        env = [
            {"name": WARM_IDLE_ENV, "value": "1"},
        ]
        template_labels = {
            keys.TPU_WARM_POOL_LABEL: pool.slug,
            nbapi.TPU_SLICE_LABEL: "true",
        }
        template_annotations = {
            nbapi.TPU_ACCELERATOR_ANNOTATION: shape.accelerator.name,
            nbapi.TPU_TOPOLOGY_ANNOTATION: shape.topology_str,
        }
        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": slot_name,
                "namespace": pool.namespace,
                "labels": {keys.TPU_WARM_POOL_LABEL: pool.slug},
            },
            "spec": {
                "replicas": 1,
                "serviceName": slot_name,
                "selector": {"matchLabels": {
                    keys.TPU_WARM_POOL_LABEL: pool.slug,
                    _STS_LABEL: slot_name}},
                "template": {
                    "metadata": {
                        "labels": {**template_labels,
                                   _STS_LABEL: slot_name},
                        "annotations": template_annotations,
                    },
                    "spec": {
                        "nodeSelector": shape.node_selectors(),
                        "containers": [{
                            "name": "warm",
                            "image": pool.image,
                            "command": ["python", "-m", "kubeflow_tpu.sdk",
                                        "--warm-idle"],
                            "env": env,
                            "resources": {
                                "requests": shape.resource_requests(),
                                "limits": shape.resource_requests(),
                            },
                            # The claim annotation reaches the warm-idle
                            # shim through the downward API — live
                            # annotation updates, no apiserver credential.
                            "volumeMounts": [{
                                "name": "podinfo",
                                "mountPath": "/etc/podinfo",
                                "readOnly": True,
                            }],
                        }],
                        "volumes": [{
                            "name": "podinfo",
                            "downwardAPI": {"items": [{
                                "path": "annotations",
                                "fieldRef": {
                                    "fieldPath": "metadata.annotations"},
                            }]},
                        }],
                    },
                },
            },
        }

    # ---- introspection -------------------------------------------------------------

    async def debug_info(self) -> dict:
        pools = []
        for pool in self._pools:
            ready = len(await self._claimable_pods(pool))
            slots = await self._slots(pool)
            pools.append({
                "pool": pool.slug,
                "namespace": pool.namespace,
                "image": pool.image,
                "shape": f"{pool.accelerator}:{pool.topology}",
                "target": pool.size,
                "slots": len(slots),
                "ready": ready,
            })
        return {
            "active": self.active,
            "pools": pools,
            "reclaimed_pending_teardown": sorted(
                f"{k[0]}/{k[1]}" for k in self._reclaimed_slots),
        }
