"""Reconcilers for the TPU-native notebook stack.

One module per controller, mirroring the reference's component split
(SURVEY.md §2.1) but collapsed to a single manager process — the reference's
two-controller lock dance (notebook-controller + odh-notebook-controller) is
deliberately absent (SURVEY.md §7 hard-part (c): one controller + one webhook
deletes that entire class of races).
"""

from kubeflow_tpu.controllers.notebook import NotebookReconciler, setup_notebook_controller

__all__ = ["NotebookReconciler", "setup_notebook_controller"]
