"""Shared reconciler helpers."""

from __future__ import annotations

import hashlib

from kubeflow_tpu.runtime.objects import deep_get


def bounded_name(name: str, limit: int = 253) -> str:
    """Clamp a generated child-object name to the apiserver's limit.

    Kubernetes object names are DNS subdomains (≤253 chars); generated
    names composed from user-controlled parts (role + notebook names) can
    exceed that and fail the create. Over-long names are truncated and
    suffixed with a short content hash so distinct inputs stay distinct
    and the result is stable across reconciles.
    """
    if len(name) <= limit:
        return name
    digest = hashlib.sha256(name.encode()).hexdigest()[:10]
    return f"{name[: limit - 11].rstrip('-.')}-{digest}"


async def rwo_affinity(kube, ns: str, claim: str) -> dict | None:
    """Node affinity pinning to the node of the pod already mounting an RWO
    claim, so a second mount succeeds (reference
    ``tensorboard_controller.go:428-471``; same logic in the pvcviewer
    controller). Returns None when the claim is not RWO or not mounted."""
    pvc = await kube.get_or_none("PersistentVolumeClaim", claim, ns)
    modes = deep_get(pvc or {}, "spec", "accessModes", default=[])
    if "ReadWriteOnce" not in modes:
        return None
    for pod in await kube.list("Pod", ns):
        node = deep_get(pod, "spec", "nodeName")
        if not node or deep_get(pod, "status", "phase") not in ("Running", "Pending"):
            continue
        for vol in deep_get(pod, "spec", "volumes", default=[]):
            if deep_get(vol, "persistentVolumeClaim", "claimName") == claim:
                return {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchFields": [
                                        {
                                            "key": "metadata.name",
                                            "operator": "In",
                                            "values": [node],
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                }
    return None
