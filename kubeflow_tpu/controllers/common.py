"""Shared reconciler helpers."""

from __future__ import annotations

import hashlib

from kubeflow_tpu.runtime.objects import deep_get, namespace_of

# Pod-informer secondary index: pods by the PVC claims they mount,
# namespace-qualified (shared by the tensorboard and pvcviewer RWO
# co-scheduling probes).
POD_PVC_INDEX = "pvc"


def index_pod_by_pvc(pod: dict) -> list:
    ns = namespace_of(pod)
    return [
        (ns, claim)
        for vol in deep_get(pod, "spec", "volumes", default=[])
        if (claim := deep_get(vol, "persistentVolumeClaim", "claimName"))
    ]


def bounded_name(name: str, limit: int = 253) -> str:
    """Clamp a generated child-object name to the apiserver's limit.

    Kubernetes object names are DNS subdomains (≤253 chars); generated
    names composed from user-controlled parts (role + notebook names) can
    exceed that and fail the create. Over-long names are truncated and
    suffixed with a short content hash so distinct inputs stay distinct
    and the result is stable across reconciles.
    """
    if len(name) <= limit:
        return name
    digest = hashlib.sha256(name.encode()).hexdigest()[:10]
    return f"{name[: limit - 11].rstrip('-.')}-{digest}"


async def rwo_affinity(kube, ns: str, claim: str, pod_informer=None) -> dict | None:
    """Node affinity pinning to the node of the pod already mounting an RWO
    claim, so a second mount succeeds (reference
    ``tensorboard_controller.go:428-471``; same logic in the pvcviewer
    controller). Returns None when the claim is not RWO or not mounted.

    With a ``pod_informer`` carrying the POD_PVC_INDEX (wired by the
    controller setups), the mounting pod comes from an O(1) index lookup;
    the namespace-wide apiserver LIST remains only as the bare-reconciler
    fallback."""
    pvc = await kube.get_or_none("PersistentVolumeClaim", claim, ns)
    modes = deep_get(pvc or {}, "spec", "accessModes", default=[])
    if "ReadWriteOnce" not in modes:
        return None
    if pod_informer is not None and pod_informer.has_indexer(POD_PVC_INDEX):
        candidates = pod_informer.by_index(POD_PVC_INDEX, (ns, claim))
    else:
        candidates = await kube.list("Pod", ns)
    for pod in candidates:
        node = deep_get(pod, "spec", "nodeName")
        if not node or deep_get(pod, "status", "phase") not in ("Running", "Pending"):
            continue
        for vol in deep_get(pod, "spec", "volumes", default=[]):
            if deep_get(vol, "persistentVolumeClaim", "claimName") == claim:
                return {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchFields": [
                                        {
                                            "key": "metadata.name",
                                            "operator": "In",
                                            "values": [node],
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                }
    return None
