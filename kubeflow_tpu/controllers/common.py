"""Shared reconciler helpers."""

from __future__ import annotations

from kubeflow_tpu.runtime.objects import deep_get


async def rwo_affinity(kube, ns: str, claim: str) -> dict | None:
    """Node affinity pinning to the node of the pod already mounting an RWO
    claim, so a second mount succeeds (reference
    ``tensorboard_controller.go:428-471``; same logic in the pvcviewer
    controller). Returns None when the claim is not RWO or not mounted."""
    pvc = await kube.get_or_none("PersistentVolumeClaim", claim, ns)
    modes = deep_get(pvc or {}, "spec", "accessModes", default=[])
    if "ReadWriteOnce" not in modes:
        return None
    for pod in await kube.list("Pod", ns):
        node = deep_get(pod, "spec", "nodeName")
        if not node or deep_get(pod, "status", "phase") not in ("Running", "Pending"):
            continue
        for vol in deep_get(pod, "spec", "volumes", default=[]):
            if deep_get(vol, "persistentVolumeClaim", "claimName") == claim:
                return {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchFields": [
                                        {
                                            "key": "metadata.name",
                                            "operator": "In",
                                            "values": [node],
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                }
    return None
