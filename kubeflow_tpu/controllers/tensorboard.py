"""Tensorboard reconciler: CR → Deployment + Service (+ VirtualService).

Reference: ``tensorboard-controller/controllers/tensorboard_controller.go``:

- ``Reconcile`` (:67-157), ``generateDeployment`` (:167-299) with gs://
  creds mount (:232-247), scheme parsing (:380-410), RWO-PVC co-scheduling
  via node affinity with the pod currently mounting the claim (:428-471,
  gated by ``RWO_PVC_SCHEDULING``), image from env (:172), Service 80→6006,
  VirtualService ``/tensorboard/<ns>/<name>/`` with 300 s timeout (:370).

TPU-native: ``spec.profilerPlugin`` starts TensorBoard with the profile
plugin so XLA/TPU traces written by ``jax.profiler.trace`` to the logdir
(typically ``gs://``) are browsable — the TPU profiling story of BASELINE.md.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from kubeflow_tpu.api import tensorboard as tbapi
from kubeflow_tpu.controllers.common import (
    POD_PVC_INDEX,
    index_pod_by_pvc,
    rwo_affinity,
)
from kubeflow_tpu.runtime.apply import (
    ApplyCache,
    Stage,
    apply_set,
    informer_reader,
)
from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.runtime.manager import Controller, Manager, Result, Watch
from kubeflow_tpu.runtime.objects import (
    deep_get,
    get_meta,
    name_of,
    namespace_of,
)
from kubeflow_tpu.runtime.tracing import span

log = logging.getLogger(__name__)

TB_PORT = 6006


@dataclass
class TensorboardOptions:
    image: str = "tensorflow/tensorflow:latest"      # TENSORBOARD_IMAGE
    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    rwo_pvc_scheduling: bool = True                   # RWO_PVC_SCHEDULING
    gcp_creds_secret: str | None = None               # mounted for gs:// when set


class TensorboardReconciler:
    def __init__(self, kube, options: TensorboardOptions | None = None):
        self.kube = kube
        self.opts = options or TensorboardOptions()
        # Wired by setup_tensorboard_controller; bare-reconciler tests run
        # with the apiserver fallbacks.
        self._pod_informer = None
        self._child_informers: dict[str, object] = {}
        self._reader = informer_reader(self._child_informers)
        self._apply_cache = ApplyCache()

    async def reconcile(self, key) -> Result | None:
        ns, name = key
        with span("cache_read"):
            tb = await self.kube.get_or_none("Tensorboard", name, ns)
        if tb is None or get_meta(tb).get("deletionTimestamp"):
            return None
        with span("build_children"):
            try:
                deployment = await self.generate_deployment(tb)
            except Invalid as e:
                log.warning("tensorboard %s/%s: %s", ns, name, e)
                return None
            children = [deployment, self.generate_service(tb)] + (
                [self.generate_virtual_service(tb)]
                if self.opts.use_istio else []
            )
        with span("apply"):
            # Deployment / Service / VirtualService are independent —
            # one stage, all children overlap (latency hiding, ISSUE 4).
            outcomes = await apply_set(
                self.kube, [Stage("children", children)],
                cache=self._apply_cache, reader=self._reader, owner=tb,
            )
        live_deployment = next(
            (row.result for row in outcomes[0]
             if isinstance(row.child, dict)
             and row.child.get("kind") == "Deployment"), None)
        with span("status"):
            await self._update_status(tb, live_deployment)
        return None

    async def generate_deployment(self, tb: dict) -> dict:
        name, ns = name_of(tb), namespace_of(tb)
        logspath = str(deep_get(tb, "spec", "logspath", default=""))
        scheme, claim, logdir = tbapi.parse_logspath(logspath)

        command = [
            "/usr/local/bin/tensorboard",
            f"--logdir={logdir}",
            "--bind_all",
            f"--port={TB_PORT}",
        ]
        if deep_get(tb, "spec", "profilerPlugin"):
            # XLA profiler traces refresh as training runs; poll the logdir.
            command.append("--reload_multifile=true")

        container: dict = {
            "name": "tensorboard",
            "image": self.opts.image,
            "command": command,
            "ports": [{"containerPort": TB_PORT, "name": "http", "protocol": "TCP"}],
        }
        volumes: list[dict] = []
        pod_spec: dict = {"containers": [container], "volumes": volumes}

        if scheme == tbapi.SCHEME_PVC:
            volumes.append(
                {"name": "logs", "persistentVolumeClaim": {"claimName": claim}}
            )
            container["volumeMounts"] = [
                {"name": "logs", "mountPath": "/tensorboard_logs", "readOnly": True}
            ]
            if self.opts.rwo_pvc_scheduling:
                affinity = await rwo_affinity(
                    self.kube, ns, claim, pod_informer=self._pod_informer)
                if affinity:
                    pod_spec["affinity"] = affinity
        elif scheme == tbapi.SCHEME_GCS and self.opts.gcp_creds_secret:
            # Reference mounts user-gcp-sa creds (:232-247); on GKE prefer
            # Workload Identity (profile plugin) — secret is the fallback.
            volumes.append(
                {
                    "name": "gcp-creds",
                    "secret": {"secretName": self.opts.gcp_creds_secret},
                }
            )
            container["volumeMounts"] = [
                {"name": "gcp-creds", "mountPath": "/secret/gcp", "readOnly": True}
            ]
            container["env"] = [
                {
                    "name": "GOOGLE_APPLICATION_CREDENTIALS",
                    "value": "/secret/gcp/user-gcp-sa.json",
                }
            ]

        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": pod_spec,
                },
            },
        }

    def generate_service(self, tb: dict) -> dict:
        name, ns = name_of(tb), namespace_of(tb)
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "type": "ClusterIP",
                "selector": {"app": name},
                "ports": [
                    {"name": "http", "port": 80, "targetPort": TB_PORT,
                     "protocol": "TCP"}
                ],
            },
        }

    def generate_virtual_service(self, tb: dict) -> dict:
        name, ns = name_of(tb), namespace_of(tb)
        prefix = f"/tensorboard/{ns}/{name}/"
        return {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"tensorboard-{ns}-{name}", "namespace": ns},
            "spec": {
                "hosts": [self.opts.istio_host],
                "gateways": [self.opts.istio_gateway],
                "http": [
                    {
                        "match": [{"uri": {"prefix": prefix}}],
                        "rewrite": {"uri": "/"},
                        "route": [
                            {
                                "destination": {
                                    "host": f"{name}.{ns}.svc."
                                    f"{self.opts.cluster_domain}",
                                    "port": {"number": 80},
                                }
                            }
                        ],
                        "timeout": "300s",
                    }
                ],
            },
        }

    async def _update_status(self, tb: dict, deployment: dict | None) -> None:
        name, ns = name_of(tb), namespace_of(tb)
        ready = deep_get(deployment or {}, "status", "readyReplicas", default=0) or 0
        conditions = deep_get(deployment or {}, "status", "conditions", default=[])
        status = {
            "readyReplicas": ready,
            "conditions": [
                {"deploymentState": c.get("type", "")} for c in conditions
            ] or ([{"deploymentState": "Available"}] if ready else []),
        }
        if deep_get(tb, "status") != status:
            await self.kube.patch(
                "Tensorboard", name, {"status": status}, ns, subresource="status"
            )


def setup_tensorboard_controller(
    mgr: Manager, options: TensorboardOptions | None = None
) -> TensorboardReconciler:
    rec = TensorboardReconciler(mgr.kube, options)
    owned = ["Deployment", "Service"] + (
        ["VirtualService"] if rec.opts.use_istio else [])
    mgr.add_controller(
        Controller(
            name="tensorboard",
            kind="Tensorboard",
            reconcile=rec.reconcile,
            owns=owned,
        )
    )
    # update(), not rebind: rec._reader closed over this dict in __init__.
    rec._child_informers.update({k: mgr.informer_for(k) for k in owned})
    if rec.opts.rwo_pvc_scheduling:
        rec._pod_informer = mgr.informer_for("Pod")
        rec._pod_informer.add_indexer(POD_PVC_INDEX, index_pod_by_pvc)
    return rec
