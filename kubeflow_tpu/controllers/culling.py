"""Culling reconciler: probe Jupyter activity, stop idle notebooks.

Reference: ``notebook-controller/controllers/culling_controller.go``:

- periodic requeue every IDLENESS_CHECK_PERIOD (default 1 min, :31)
- probes ``http://<nb>.<ns>.svc.<domain>/notebook/<ns>/<nb>/api/kernels``
  and ``/api/terminals`` (:209-279) with a 10 s timeout (:210-212)
- a notebook is busy if any kernel's ``execution_state`` != idle; last
  activity folds the max of kernel/terminal ``last_activity`` (:281-315)
- tracks ``notebooks.kubeflow.org/last-activity`` + check-timestamp
  annotations (:156-167); idle > CULL_IDLE_TIME (default 1440 min, :30)
  → sets the ``kubeflow-resource-stopped`` annotation, which the notebook
  reconciler turns into replicas=0 (notebook_controller.go:410-412)

TPU-native slice semantics (SURVEY.md §2.4 last row): the Jupyter server —
and therefore kernel activity — lives on worker 0; culling one worker of a
slice is meaningless, so the stop annotation always parks the *whole* slice
(the notebook reconciler scales every worker to zero together). Chips are
the scarce resource: default idle window is kept but the controller exposes
``tpu_chips_idle_culled_total`` so operators can see reclaimed capacity.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Awaitable, Callable

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.migration import protocol as migration
from kubeflow_tpu.runtime.errors import ApiError
from kubeflow_tpu.runtime.events import EventRecorder
from kubeflow_tpu.runtime.manager import Controller, Manager, Result
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import deep_get, get_meta
from kubeflow_tpu.runtime.objects import fmt_iso as _fmt_time
from kubeflow_tpu.runtime.objects import parse_iso as _parse_time
from kubeflow_tpu.runtime.tracing import span

log = logging.getLogger(__name__)

# Prober contract: GET url → parsed JSON (list) or None on any error.
Prober = Callable[[str], Awaitable[list | None]]


async def http_prober(url: str) -> list | None:
    """Production prober over aiohttp (10 s budget like the reference)."""
    import aiohttp

    try:
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=10)
        ) as sess:
            async with sess.get(url) as resp:
                if resp.status != 200:
                    return None
                data = await resp.json()
                return data if isinstance(data, list) else None
    except Exception:
        return None


@dataclass
class CullingOptions:
    """Reference env contract (culling_controller.go:511-544) as one block."""

    enable_culling: bool = True
    cull_idle_seconds: float = 1440 * 60.0     # CULL_IDLE_TIME (minutes) default
    check_period_seconds: float = 60.0         # IDLENESS_CHECK_PERIOD
    cluster_domain: str = "cluster.local"
    dev_url: str | None = None                 # DEV mode: probe localhost instead
    notebook_port: int = nbapi.DEFAULT_CONTAINER_PORT  # direct pod probes
    # Preempt-to-checkpoint reuse (kubeflow_tpu/migration): an idle cull
    # of a TPU notebook requests checkpoint-then-stop instead of a bare
    # stop, so culled servers resume where they left off. The DATACLASS
    # default is off (bare construction = pre-migration behavior); the
    # env wiring (KFTPU_CULL_DRAIN under KFTPU_MIGRATION, both default
    # on) turns it on in production.
    drain_on_cull: bool = False
    drain_grace_seconds: float = migration.DEFAULT_DRAIN_GRACE_SECONDS


class CullingReconciler:
    def __init__(
        self,
        kube,
        prober: Prober | None = None,
        options: CullingOptions | None = None,
        *,
        clock: Callable[[], float] = time.time,
        registry: Registry | None = None,
    ):
        self.kube = kube
        self.prober = prober or http_prober
        self.opts = options or CullingOptions()
        self.clock = clock
        self.recorder = EventRecorder(kube, "culling-controller")
        # Pod informer (wired by setup_culling_controller): the auth-proxy
        # probe path resolves worker-0's pod IP from the watch cache
        # instead of a per-check apiserver GET.
        self._pod_informer = None
        registry = registry or global_registry
        self.m_culled = registry.counter(
            "notebook_culling_total", "Total times of culling notebooks"
        )
        self.m_last_cull = registry.gauge(
            "last_notebook_culling_timestamp_seconds",
            "Timestamp of the last notebook culling",
            ["namespace", "notebook"],
        )
        self.m_chips_culled = registry.counter(
            "tpu_chips_idle_culled_total",
            "TPU chips reclaimed by culling idle notebooks",
        )

    def probe_url(self, name: str, ns: str, api: str) -> str:
        if self.opts.dev_url:
            return f"{self.opts.dev_url}/notebook/{ns}/{name}/api/{api}"
        return (
            f"http://{name}.{ns}.svc.{self.opts.cluster_domain}"
            f"/notebook/{ns}/{name}/api/{api}"
        )

    async def _probe_urls(self, nb: dict, name: str, ns: str) -> dict | None:
        """Resolve the probe endpoints for this notebook.

        When the auth-proxy sidecar is injected, the Service targetPort is
        the proxy (controllers/notebook.py _serving_target_port) and an
        unauthenticated probe through it gets a non-200 — the notebook
        would never be culled and idle TPU chips never reclaimed. Probe
        worker-0's pod IP on the notebook port directly instead, bypassing
        the proxied Service. Returns None if the pod IP isn't known yet
        (probe later rather than mis-deciding)."""
        from kubeflow_tpu.controllers.notebook import AUTH_PROXY_ANNOTATION

        annotations = get_meta(nb).get("annotations") or {}
        if self.opts.dev_url or annotations.get(AUTH_PROXY_ANNOTATION) != "true":
            return {
                api: self.probe_url(name, ns, api)
                for api in ("kernels", "terminals")
            }
        if self._pod_informer is not None:
            pod = self._pod_informer.get(f"{name}-0", ns)
        else:
            pod = await self.kube.get_or_none("Pod", f"{name}-0", ns)
        pod_ip = deep_get(pod or {}, "status", "podIP")
        if not pod_ip:
            return None
        base = (
            f"http://{pod_ip}:{self.opts.notebook_port}"
            f"/notebook/{ns}/{name}/api"
        )
        return {api: f"{base}/{api}" for api in ("kernels", "terminals")}

    async def reconcile(self, key) -> Result | None:
        ns, name = key
        requeue = Result(requeue_after=self.opts.check_period_seconds)
        if not self.opts.enable_culling:
            return None
        with span("cache_read"):
            nb = await self.kube.get_or_none("Notebook", name, ns)
        if nb is None or get_meta(nb).get("deletionTimestamp"):
            return None
        if _is_serving_workload(nb):
            # Workload-class guard (kubeflow_tpu/serving): a serving
            # workload exposes no Jupyter kernels, so every probe below
            # would read "idle forever" and the culler would stop the
            # service precisely when it is busiest. Serving capacity is
            # the InferenceService autoscaler's to reclaim (scale-to-
            # zero after ITS idle window), never the culler's.
            return None
        if nbapi.is_stopped(nb):
            return None  # already parked; notebook reconciler owns restart

        now = self.clock()
        drain_annotations = get_meta(nb).get("annotations") or {}
        if migration.drain_requested_at(drain_annotations) is not None:
            # A drain is in flight. Ours ("cull") is driven to its stop
            # here; anyone else's (preemption, suspend) owns the park —
            # probing/culling under it would race the finalizer.
            if migration.drain_reason(drain_annotations) == "cull":
                return await self._drive_cull_drain(nb, name, ns, now)
            return requeue
        with span("probe"):
            urls = await self._probe_urls(nb, name, ns)
            if urls is None:
                return requeue  # auth-proxied pod IP not known yet
            kernels = await self.prober(urls["kernels"])
            if kernels is None:
                # Kernels probe unreachable/invalid (server starting,
                # crashed, or mid-restart): without it a busy kernel is
                # indistinguishable from idle — never make a cull decision
                # on a failed probe (reference skips and retries, :226-239).
                return requeue
            # Terminals are tolerated missing (servers run with terminals
            # disabled → 404 forever; hard-requiring it would block culling
            # permanently). Kernels above are the authoritative busy signal.
            terminals = await self.prober(urls["terminals"])

        annotations = dict(get_meta(nb).get("annotations") or {})
        last_activity = _parse_time(
            annotations.get(nbapi.LAST_ACTIVITY_ANNOTATION, "")
        )
        # Idleness clocks from when the notebook last RAN, not from its
        # history: a gang that sat hours in the fleet scheduler's queue
        # still carries its pre-queue last-activity annotation, and
        # culling it seconds after admission would bounce it between
        # queue and cull forever. The scheduler's admitted-at stamp
        # (which it also reads back for idle-preemption ranking) floors
        # the clock at the moment the notebook actually started running.
        # It only RAISES an existing stale signal — a notebook with no
        # activity record at all must fall through to the fresh-server
        # branch below, not inherit the admission time as "activity"
        # (admission precedes the GKE provisioning wait, so that would
        # cull a slow-booting gang on its very first probe).
        admitted_at = _parse_time(
            annotations.get(nbapi.SCHEDULER_ADMITTED_AT_ANNOTATION, "")
        )
        if admitted_at is not None and last_activity is not None:
            last_activity = max(last_activity, admitted_at)

        busy, probe_activity = _fold_activity(kernels or [], terminals or [])
        if busy:
            last_activity = now
        elif probe_activity is not None:
            last_activity = max(last_activity or 0, probe_activity)
        elif last_activity is None:
            # Fresh server, no kernels yet: start the idle clock now.
            last_activity = now

        patch_annotations = {
            nbapi.LAST_ACTIVITY_ANNOTATION: _fmt_time(last_activity),
            nbapi.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION: _fmt_time(now),
        }

        with span("status"):
            if not busy and now - last_activity > self.opts.cull_idle_seconds:
                if (self.opts.drain_on_cull
                        and nbapi.tpu_spec_of(nb) is not None):
                    # Checkpoint-then-stop (kubeflow_tpu/migration): ask
                    # the in-pod SDK to snapshot first, so the culled
                    # server resumes where it left off. The stop lands in
                    # _drive_cull_drain on the ack — or on the grace
                    # deadline for servers that never ack (no SDK loop
                    # running), which restores plain culling, just
                    # delayed by the grace. KFTPU_CULL_DRAIN=off skips
                    # this branch entirely.
                    patch_annotations.update(
                        migration.request_drain_patch("cull", now))
                    try:
                        await self.kube.patch(
                            "Notebook", name,
                            {"metadata": {"annotations": patch_annotations}},
                            ns)
                    except ApiError:
                        return requeue
                    await self.recorder.event(
                        nb, "Normal", "CullDrainRequested",
                        f"Notebook idle for "
                        f"{(now - last_activity) / 60:.0f} min; "
                        "checkpointing before scale-to-zero (grace "
                        f"{self.opts.drain_grace_seconds:.0f}s)")
                    return Result(requeue_after=min(
                        self.opts.check_period_seconds,
                        self.opts.drain_grace_seconds + 0.1))
                if not await self._cull_stop(nb, name, ns, now,
                                             patch_annotations):
                    return requeue
                return None  # parked; nothing to poll until restarted
            if any(annotations.get(k) != v for k, v in patch_annotations.items()):
                try:
                    await self.kube.patch(
                        "Notebook", name,
                        {"metadata": {"annotations": patch_annotations}}, ns,
                    )
                except ApiError as exc:
                    log.debug("activity-stamp patch for %s/%s failed "
                              "(next probe re-stamps): %s", ns, name, exc)
        return requeue

    async def _cull_stop(self, nb: dict, name: str, ns: str, now: float,
                         extra_annotations: dict | None = None,
                         *, checkpoint_step: int | None = None) -> bool:
        """The one place an idle cull actually parks a notebook — shared
        by the bare-stop path and the drain finalizer so the bookkeeping
        (event, counters, reclaimed-chip metric) can't drift."""
        annotations = dict(extra_annotations or {})
        annotations[nbapi.STOP_ANNOTATION] = _fmt_time(now)
        try:
            await self.kube.patch(
                "Notebook", name,
                {"metadata": {"annotations": annotations}}, ns)
        except ApiError:
            return False
        last = _parse_time(
            (get_meta(nb).get("annotations") or {}).get(
                nbapi.LAST_ACTIVITY_ANNOTATION, "")) or now
        idle_min = max(0.0, now - last) / 60
        note = (f"; resumes from checkpoint @ step {checkpoint_step}"
                if checkpoint_step is not None else "")
        await self.recorder.event(
            nb, "Normal", "NotebookCulled",
            f"Notebook idle for {idle_min:.0f} min; scaled to zero{note}")
        self.m_culled.inc()
        self.m_last_cull.labels(namespace=ns or "", notebook=name).set(now)
        chips = deep_get(nb, "status", "tpu", "chips", default=0) or 0
        if chips:
            self.m_chips_culled.inc(chips)
        return True

    async def _drive_cull_drain(self, nb: dict, name: str, ns: str,
                                now: float) -> Result | None:
        """Finalize a cull-owned drain: stop on the checkpoint ack, or on
        the grace deadline for a server that never acks — UNLESS the user
        came back: the grace window is exactly the span the pre-migration
        code never had, so busyness is re-probed every pass and a busy
        kernel cancels the drain instead of parking an actively-used
        server. The drain marks clear with the stop; the checkpoint
        path/step annotations stay — they are the restore hint a later
        restart rides."""
        urls = await self._probe_urls(nb, name, ns)
        if urls is not None:
            kernels = await self.prober(urls["kernels"])
            if kernels is not None:
                busy, _ = _fold_activity(kernels, [])
                if busy:
                    try:
                        await self.kube.patch(
                            "Notebook", name,
                            {"metadata": {"annotations": {
                                **migration.clear_drain_patch(),
                                nbapi.LAST_ACTIVITY_ANNOTATION:
                                    _fmt_time(now),
                            }}}, ns)
                    except ApiError as exc:
                        log.debug("cull-drain cancel patch for %s/%s "
                                  "failed (re-probed next pass): %s",
                                  ns, name, exc)
                    else:
                        await self.recorder.event(
                            nb, "Normal", "CullDrainCancelled",
                            "Activity detected during the checkpoint "
                            "grace window; cull cancelled")
                    return Result(
                        requeue_after=self.opts.check_period_seconds)
        annotations = get_meta(nb).get("annotations") or {}
        acked = migration.drain_acked(annotations)
        expired = migration.drain_expired(
            annotations, now, self.opts.drain_grace_seconds)
        if not (acked or expired):
            deadline = migration.drain_deadline(
                annotations, self.opts.drain_grace_seconds) or now
            return Result(requeue_after=min(
                self.opts.check_period_seconds,
                max(0.1, deadline - now + 0.05)))
        step = migration.checkpoint_step(annotations) if acked else None
        if not acked:
            await self.recorder.event(
                nb, "Warning", "CullDrainDeadlineExceeded",
                f"No checkpoint ack within "
                f"{self.opts.drain_grace_seconds:.0f}s; culling without "
                "a fresh checkpoint")
        if not await self._cull_stop(
                nb, name, ns, now,
                migration.clear_drain_patch(keep_reason=True),
                checkpoint_step=step):
            return Result(requeue_after=self.opts.check_period_seconds)
        return None


def _is_serving_workload(nb: dict) -> bool:
    """The culler's workload-class guard (see reconcile)."""
    from kubeflow_tpu.api import inferenceservice as isvcapi

    return isvcapi.is_serving_class(nb)


def _fold_activity(kernels: list, terminals: list) -> tuple[bool, float | None]:
    """→ (busy, latest_activity_ts). A kernel not idle ⇒ busy
    (culling_controller.go:281-315)."""
    busy = any(
        isinstance(k, dict) and k.get("execution_state") not in (None, "idle")
        for k in kernels
    )
    times = []
    for item in [*kernels, *terminals]:
        if isinstance(item, dict) and item.get("last_activity"):
            ts = _parse_time(str(item["last_activity"]))
            if ts is not None:
                times.append(ts)
    return busy, (max(times) if times else None)


def setup_culling_controller(
    mgr: Manager,
    prober: Prober | None = None,
    options: CullingOptions | None = None,
    *,
    clock: Callable[[], float] = time.time,
) -> CullingReconciler:
    rec = CullingReconciler(
        mgr.kube, prober, options, clock=clock, registry=mgr.registry
    )
    mgr.add_controller(
        Controller(name="culling", kind="Notebook", reconcile=rec.reconcile)
    )
    rec._pod_informer = mgr.informer_for("Pod")
    return rec
