"""Profile reconciler: one tenant = one namespace + RBAC + quota.

Reference: ``profile-controller/controllers/profile_controller.go``:

- ``Reconcile`` (:105-334): Namespace (istio-injection label, owner
  annotation, :126-198), Istio AuthorizationPolicy (:200-206, 419-556),
  ServiceAccounts ``default-editor``/``default-viewer`` + RoleBindings
  (:208-251, 592-671), owner ``namespaceAdmin`` RoleBinding, ResourceQuota
  ``kf-resource-quota`` from ``spec.resourceQuotaSpec`` (:253-280), plugin
  apply with finalizer-driven revoke (:281-331).
- Default namespace labels hot-reloaded from file (:368-399) → here a plain
  dict option (config-file layer wires it in cmd/).

TPU-native deltas: ``spec.tpuQuota`` (chip-count ceiling) merges into the
quota as ``requests.google.com/tpu`` (SURVEY.md §2.4 row 5); the GKE
WorkloadIdentity plugin is first-class (TPU pods reach GCS via WI, no key
files).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Protocol

from kubeflow_tpu.api import profile as profileapi
from kubeflow_tpu.runtime.apply import Stage, apply_set, reconcile_child
from kubeflow_tpu.runtime.errors import AlreadyExists, ApiError, NotFound
from kubeflow_tpu.runtime.events import EventRecorder
from kubeflow_tpu.runtime.manager import Controller, Manager, Result
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import (
    deep_get,
    get_meta,
    name_of,
    now_iso,
    set_controller_owner,
)
from kubeflow_tpu.runtime.tracing import span

log = logging.getLogger(__name__)

PROFILE_FINALIZER = "profile-finalizer.kubeflow.org"
DEFAULT_EDITOR = "default-editor"
DEFAULT_VIEWER = "default-viewer"
ADMIN_BINDING = "namespaceAdmin"

# GKE Workload Identity SA annotation (plugin_workload_identity.go:44-166).
WI_ANNOTATION = "iam.gke.io/gcp-service-account"
# AWS IRSA SA annotation (plugin_iam.go:36-120).
IRSA_ANNOTATION = "eks.amazonaws.com/role-arn"


class Plugin(Protocol):
    """Reference plugin interface (profile_controller.go:77-83)."""

    kind: str

    async def apply(self, kube, profile: dict, spec: dict) -> None: ...
    async def revoke(self, kube, profile: dict, spec: dict) -> None: ...


class WorkloadIdentityPlugin:
    """Bind the tenant's default-editor SA to a GCP service account so TPU
    pods reach GCS/Artifact Registry without key files."""

    kind = "WorkloadIdentity"

    async def apply(self, kube, profile: dict, spec: dict) -> None:
        gsa = spec.get("gcpServiceAccount", "")
        if not gsa:
            return
        await _annotate_sa(kube, name_of(profile), DEFAULT_EDITOR, WI_ANNOTATION, gsa)

    async def revoke(self, kube, profile: dict, spec: dict) -> None:
        await _annotate_sa(kube, name_of(profile), DEFAULT_EDITOR, WI_ANNOTATION, None)


class AwsIamForServiceAccountPlugin:
    kind = "AwsIamForServiceAccount"

    async def apply(self, kube, profile: dict, spec: dict) -> None:
        arn = spec.get("awsIamRole", "")
        if not arn:
            return
        await _annotate_sa(kube, name_of(profile), DEFAULT_EDITOR, IRSA_ANNOTATION, arn)

    async def revoke(self, kube, profile: dict, spec: dict) -> None:
        await _annotate_sa(
            kube, name_of(profile), DEFAULT_EDITOR, IRSA_ANNOTATION, None
        )


async def _annotate_sa(kube, ns: str, sa: str, key: str, value: str | None) -> None:
    try:
        await kube.patch(
            "ServiceAccount", sa, {"metadata": {"annotations": {key: value}}}, ns
        )
    except NotFound:
        pass


@dataclass
class ProfileOptions:
    """Reference flags/env (main.go + hot-reloaded label file) as one block."""

    namespace_labels: dict = field(
        default_factory=lambda: {
            "istio-injection": "enabled",
            "app.kubernetes.io/part-of": "kubeflow-profile",
        }
    )
    # Mounted-file override, hot-reloaded (reference: fsnotify on the
    # ConfigMap-mounted labels file, profile_controller.go:368-399 +
    # readDefaultLabelsFromFile :775-790). A flat YAML map; when set it
    # REPLACES namespace_labels, and edits re-reconcile every Profile.
    namespace_labels_file: str | None = None
    use_istio: bool = False
    userid_header: str = "kubeflow-userid"
    userid_prefix: str = ""
    notebook_controller_principal: str = (
        "cluster.local/ns/kubeflow/sa/notebook-controller-service-account"
    )
    edit_cluster_role: str = "kubeflow-edit"
    view_cluster_role: str = "kubeflow-view"
    admin_cluster_role: str = "kubeflow-admin"


class ProfileReconciler:
    def __init__(
        self,
        kube,
        options: ProfileOptions | None = None,
        *,
        plugins: dict[str, Plugin] | None = None,
        registry: Registry | None = None,
    ):
        self.kube = kube
        self.opts = options or ProfileOptions()
        self.plugins: dict[str, Plugin] = plugins or {
            p.kind: p
            for p in (WorkloadIdentityPlugin(), AwsIamForServiceAccountPlugin())
        }
        self.recorder = EventRecorder(kube, "profile-controller")
        registry = registry or global_registry
        # Same metric family as the reference (monitoring.go:24-77).
        self.m_update = registry.counter(
            "profile_update_total", "Profile reconciles applying changes",
            ["profile"],
        )
        self.m_failure = registry.counter(
            "profile_failure_total", "Profile reconcile failures", ["profile"]
        )

    async def reconcile(self, key) -> Result | None:
        _, name = key
        with span("cache_read"):
            profile = await self.kube.get_or_none("Profile", name)
        if profile is None:
            return None
        if get_meta(profile).get("deletionTimestamp"):
            await self._finalize(profile)
            return None

        try:
            with span("apply"):
                await self._ensure_finalizer(profile)
                # Dependency DAG (latency hiding, ISSUE 4): the Namespace
                # must exist before anything namespaced lands in it; the
                # RBAC/quota children are then independent of each other;
                # plugins patch the ServiceAccounts the rbac stage made.
                await apply_set(self.kube, [
                    Stage("namespace", [self._namespace_obj(profile)]),
                    Stage("rbac", [
                        self._create_service_account(profile, DEFAULT_EDITOR),
                        self._create_service_account(profile, DEFAULT_VIEWER),
                        *self._role_bindings(profile),
                        # Deliberate change from the pre-DAG code: the
                        # policy now carries the Profile ownerReference
                        # like every sibling, so it GC-cascades with the
                        # tenant (one-time drift update on upgrade).
                        (self._authorization_policy(profile)
                         if self.opts.use_istio else None),
                        self._reconcile_quota(profile),
                    ]),
                    Stage("plugins", [self._apply_plugins(profile)]),
                ], owner=profile)
        except ApiError as e:
            self.m_failure.labels(profile=name).inc()
            with span("status"):
                await self._set_condition(profile, profileapi.FAILED, str(e))
            raise
        self.m_update.labels(profile=name).inc()
        with span("status"):
            await self._set_condition(profile, profileapi.SUCCEED, "")
        return None

    # ---- pieces -----------------------------------------------------------------

    async def _ensure_finalizer(self, profile: dict) -> None:
        meta = get_meta(profile)
        finalizers = meta.get("finalizers") or []
        if PROFILE_FINALIZER not in finalizers and deep_get(profile, "spec", "plugins"):
            await self.kube.patch(
                "Profile",
                name_of(profile),
                {"metadata": {"finalizers": finalizers + [PROFILE_FINALIZER]}},
            )

    def current_namespace_labels(self) -> dict:
        """Static option, or the hot-reloaded mounted file when configured
        (mtime-cached read; the setup-time watcher re-enqueues Profiles on
        change, so edits converge without a restart)."""
        path = self.opts.namespace_labels_file
        if not path:
            return dict(self.opts.namespace_labels)
        import os

        import yaml

        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            return dict(self.opts.namespace_labels)
        cached = getattr(self, "_labels_cache", None)
        if cached and cached[0] == mtime:
            return dict(cached[1])
        with open(path) as fh:
            labels = yaml.safe_load(fh) or {}
        if not isinstance(labels, dict):
            raise ValueError(f"{path}: namespace labels must be a flat map")
        labels = {str(k): str(v) for k, v in labels.items()}
        self._labels_cache = (mtime, labels)
        return dict(labels)

    def _namespace_obj(self, profile: dict) -> dict:
        name = name_of(profile)
        owner = profileapi.owner_of(profile).get("name", "")
        return {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {
                "name": name,
                "labels": self.current_namespace_labels(),
                "annotations": {
                    profileapi.OWNER_ANNOTATION: owner,
                    "profile-name": name,
                },
            },
        }

    async def _create_service_account(self, profile: dict, sa_name: str) -> None:
        sa = {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": sa_name, "namespace": name_of(profile)},
        }
        set_controller_owner(sa, profile)
        try:
            await self.kube.create("ServiceAccount", sa)
        except AlreadyExists:
            pass  # plugin annotations are patched separately

    def _role_bindings(self, profile: dict) -> list[dict]:
        ns = name_of(profile)
        owner = profileapi.owner_of(profile)
        return [
            _role_binding(
                ns, DEFAULT_EDITOR, self.opts.edit_cluster_role,
                {"kind": "ServiceAccount", "name": DEFAULT_EDITOR, "namespace": ns},
            ),
            _role_binding(
                ns, DEFAULT_VIEWER, self.opts.view_cluster_role,
                {"kind": "ServiceAccount", "name": DEFAULT_VIEWER, "namespace": ns},
            ),
            _role_binding(
                ns, ADMIN_BINDING, self.opts.admin_cluster_role,
                {
                    "kind": owner.get("kind", "User"),
                    "name": owner.get("name", ""),
                    "apiGroup": "rbac.authorization.k8s.io",
                },
            ),
        ]

    def _authorization_policy(self, profile: dict) -> dict:
        """Reference getAuthorizationPolicy (:419-504): owner + notebook
        controller may reach the namespace; anyone may reach
        ``*/api/kernels`` (the culler's probe path)."""
        ns = name_of(profile)
        owner = profileapi.owner_of(profile).get("name", "")
        return {
            "apiVersion": "security.istio.io/v1beta1",
            "kind": "AuthorizationPolicy",
            "metadata": {"name": "ns-owner-access-istio", "namespace": ns},
            "spec": {
                "rules": [
                    {
                        "when": [
                            {
                                "key": f"request.headers[{self.opts.userid_header}]",
                                "values": [self.opts.userid_prefix + owner],
                            }
                        ]
                    },
                    {
                        "from": [
                            {
                                "source": {
                                    "principals": [
                                        self.opts.notebook_controller_principal
                                    ]
                                }
                            }
                        ]
                    },
                    {"to": [{"operation": {"paths": ["*/api/kernels"]}}]},
                ]
            },
        }

    async def _reconcile_quota(self, profile: dict) -> None:
        ns = name_of(profile)
        quota_spec = profileapi.quota_spec_of(profile)
        existing = await self.kube.get_or_none(
            "ResourceQuota", profileapi.QUOTA_NAME, ns
        )
        if not quota_spec or not quota_spec.get("hard"):
            if existing is not None:
                await self.kube.delete("ResourceQuota", profileapi.QUOTA_NAME, ns)
            return
        quota = {
            "apiVersion": "v1",
            "kind": "ResourceQuota",
            "metadata": {"name": profileapi.QUOTA_NAME, "namespace": ns},
            "spec": quota_spec,
        }
        set_controller_owner(quota, profile)
        await reconcile_child(self.kube, quota)

    async def _apply_plugins(self, profile: dict) -> None:
        for entry in deep_get(profile, "spec", "plugins", default=[]) or []:
            kind = entry.get("kind", "")
            plugin = self.plugins.get(kind)
            if plugin is None:
                await self.recorder.event(
                    profile, "Warning", "UnknownPlugin", f"no plugin {kind!r}"
                )
                continue
            await plugin.apply(self.kube, profile, entry.get("spec", {}) or {})

    async def _finalize(self, profile: dict) -> None:
        """Deletion path: revoke plugins, then drop our finalizer (:281-331)."""
        for entry in deep_get(profile, "spec", "plugins", default=[]) or []:
            plugin = self.plugins.get(entry.get("kind", ""))
            if plugin is not None:
                try:
                    await plugin.revoke(
                        self.kube, profile, entry.get("spec", {}) or {}
                    )
                except ApiError:
                    log.exception("plugin revoke failed for %s", name_of(profile))
        finalizers = [
            f for f in get_meta(profile).get("finalizers", [])
            if f != PROFILE_FINALIZER
        ]
        try:
            await self.kube.patch(
                "Profile", name_of(profile), {"metadata": {"finalizers": finalizers}}
            )
        except NotFound:
            pass

    async def _set_condition(self, profile: dict, ctype: str, message: str) -> None:
        now = now_iso()
        conditions = [{"type": ctype, "status": "True", "message": message,
                       "lastTransitionTime": now}]
        current = deep_get(profile, "status", "conditions", default=[])
        if current and current[0].get("type") == ctype and \
                current[0].get("message") == message:
            return
        try:
            await self.kube.patch(
                "Profile", name_of(profile),
                {"status": {"conditions": conditions}}, subresource="status",
            )
        except ApiError as exc:
            log.debug("Profile condition write for %s failed (re-set "
                      "next reconcile): %s", name_of(profile), exc)


def _role_binding(ns: str, name: str, cluster_role: str, subject: dict) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": {
            "name": name,
            "namespace": ns,
            "annotations": {"role": cluster_role, "user": subject.get("name", "")},
        },
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": cluster_role,
        },
        "subjects": [subject],
    }


def setup_profile_controller(
    mgr: Manager, options: ProfileOptions | None = None, **kw
) -> ProfileReconciler:
    rec = ProfileReconciler(mgr.kube, options, registry=mgr.registry, **kw)
    mgr.add_controller(
        Controller(name="profile", kind="Profile", reconcile=rec.reconcile)
    )
    if rec.opts.namespace_labels_file:
        # Reference parity: fsnotify on the mounted labels file triggers a
        # reconcile of ALL profiles (profile_controller.go:368-399). The
        # watcher is the native inotify library when available (event-driven
        # wakeups for ConfigMap symlink swaps) and degrades to 2 s mtime
        # polling with the same interface (utils/fswatch.py).
        async def watch_labels_file():
            from kubeflow_tpu.utils.fswatch import FileWatcher

            watcher = FileWatcher(rec.opts.namespace_labels_file)
            try:
                while True:
                    if await watcher.wait(timeout=2.0):
                        for profile in await mgr.kube.list("Profile"):
                            mgr.enqueue("profile", (None, name_of(profile)))
            finally:
                watcher.close()

        mgr.add_background(watch_labels_file)
    return rec
