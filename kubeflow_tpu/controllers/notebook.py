"""Notebook reconciler: Notebook CR → StatefulSet + Services (+ VirtualService).

Reference behavior being matched (``notebook-controller/controllers/
notebook_controller.go``):

- ``Reconcile`` (:90-272): create/patch StatefulSet, Service, VirtualService;
  mirror pod status into the CR; re-emit pod events onto the CR.
- ``generateStatefulSet`` (:408-484): stop-annotation → replicas 0 (:410-412),
  ``NB_PREFIX`` env (:392-406), fsGroup 100 (:471-482), ``notebook-name``
  label (:430).
- ``generateService`` (:486-513): ClusterIP, port 80 → named port
  ``http-<name>``.
- ``generateVirtualService`` (:519-619): `/notebook/<ns>/<name>/` prefix with
  optional rewrite/headers from annotations.

TPU-native redesign (not in the reference, SURVEY.md §2.4):

- ``spec.tpu`` resolves through :class:`kubeflow_tpu.tpu.topology.TpuSlice`;
  the StatefulSet gets ``replicas = num_hosts`` (one worker pod per TPU
  host), ``podManagementPolicy: Parallel`` (slice workers must start
  together), GKE node selectors, and ``google.com/tpu`` chip requests.
- A **headless Service** (``<name>-workers``) gives every worker a stable DNS
  name for ``TPU_WORKER_HOSTNAMES`` / ``jax.distributed.initialize`` (DCN
  bootstrap; ICI is wired by libtpu from topology).
  ``publishNotReadyAddresses: true`` so bootstrap DNS resolves before
  readiness.
- Slice-wide static TPU env goes into the pod template; the *per-worker*
  ``TPU_WORKER_ID`` / ``JAX_PROCESS_ID`` is injected at pod admission from
  the pod ordinal (see ``kubeflow_tpu.webhooks.tpu``) because a StatefulSet
  template cannot vary env per ordinal.
- **Slice-atomic restart**: a multi-host slice is an all-or-nothing unit —
  one failed worker leaves the other hosts wedged in a broken ICI ring, so
  the reconciler deletes *all* worker pods when any of them enters a
  terminal failure state and lets the StatefulSet rebuild the slice.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

from kubeflow_tpu.api import keys
from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.controllers.common import bounded_name
from kubeflow_tpu.runtime.apply import (
    ApplyCache,
    Stage,
    apply_set,
    informer_reader,
    overlap,
    reconcile_child,
    state_hash,
)
from kubeflow_tpu.runtime.errors import ApiError, Conflict, Invalid, NotFound
from kubeflow_tpu.runtime.events import EventRecorder
from kubeflow_tpu.runtime.informer import (
    NAMESPACE_INDEX,
    OWNER_INDEX,
    index_by_label,
    index_by_namespace,
)
from kubeflow_tpu.runtime.manager import (
    Controller,
    Manager,
    Result,
    Watch,
    soonest,
)
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import (
    annotations_of,
    deep_get,
    fmt_iso,
    get_meta,
    name_of,
    namespace_of,
    now_iso,
    parse_iso,
    set_controller_owner,
    uid_of,
)
from kubeflow_tpu.runtime import slo
from kubeflow_tpu.runtime import timeline as timeline_mod
from kubeflow_tpu.runtime.tracing import current_trace_id, span
from kubeflow_tpu.migration import protocol as migration
from kubeflow_tpu.telemetry import publisher as telemetry_pub
from kubeflow_tpu.tpu.topology import JAX_COORDINATOR_PORT, TpuSlice

log = logging.getLogger(__name__)

TPU_ACCELERATOR_ANNOTATION = nbapi.TPU_ACCELERATOR_ANNOTATION
TPU_TOPOLOGY_ANNOTATION = nbapi.TPU_TOPOLOGY_ANNOTATION

STS_LABEL = "statefulset"  # reference labels pods with statefulset=<name> (:429)
POD_NAME_LABEL = "statefulset.kubernetes.io/pod-name"  # set by the STS controller

# Secondary-index names on the shared informers (runtime/informer.py
# AddIndexers semantics). Every per-reconcile child lookup goes through one
# of these instead of a kube.list() or a cache scan — the control plane
# stays O(changes) as the cluster grows.
NB_POD_INDEX = "notebook-name"      # Pod informer, by notebook-name label
POD_NODE_INDEX = "node"             # Pod informer, by spec.nodeName
EVENT_POD_INDEX = "involved-pod"    # Event informer, by involved Pod


def index_pod_by_node(pod: dict) -> list:
    node = deep_get(pod, "spec", "nodeName")
    return [node] if node else []


def index_event_by_involved_pod(event: dict) -> list:
    involved = event.get("involvedObject") or {}
    if involved.get("kind") != "Pod" or not involved.get("name"):
        return []
    return [(namespace_of(event), involved["name"])]


# Impending-maintenance surfacing: nodes hosting TPU workers get this taint
# from GKE graceful node termination ahead of a maintenance event; the
# controller mirrors it onto the CR (api/notebook.py MAINTENANCE_ANNOTATION,
# a comma-joined sorted node list) so the UI and in-notebook tooling can
# checkpoint before the slice goes down.
MAINTENANCE_ANNOTATION = nbapi.MAINTENANCE_ANNOTATION
DEFAULT_MAINTENANCE_TAINTS = ("cloud.google.com/impending-node-termination",)

# Queued provisioning (spec.tpu.queuedProvisioning): the slice's capacity
# is reserved through a GKE ProvisioningRequest before any worker pod
# exists; once Provisioned, the pods consume the reservation via the
# cluster-autoscaler annotation. Names: <notebook>-capacity for both the
# request and its PodTemplate.
PROVISIONING_CLASS = "queued-provisioning.gke.io"
CONSUME_PR_ANNOTATION = (
    "cluster-autoscaler.kubernetes.io/consume-provisioning-request")
PR_CLASS_ANNOTATION = (
    "cluster-autoscaler.kubernetes.io/provisioning-class-name")


def capacity_name(notebook_name: str) -> str:
    """The one place the PR/PodTemplate/consume-annotation name contract
    lives — three consumers must agree or the pods reference a request
    that doesn't exist."""
    return bounded_name(f"{notebook_name}-capacity")


@dataclass
class NotebookOptions:
    """The reference's env-var sprawl (USE_ISTIO, ISTIO_GATEWAY, CLUSTER_DOMAIN,
    ADD_FSGROUP — notebook_controller.go:213,475,537-560) as one typed block.
    The odh-controller features fold in here too (SURVEY.md §2.1):
    NetworkPolicies (notebook_network.go), trusted-CA aggregation
    (notebook_controller.go:253-353), auth-proxy sidecar (notebook_oauth.go)."""

    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    add_fsgroup: bool = True
    fsgroup: int = 100
    workers_service_suffix: str = "-workers"
    default_serving_port: int = nbapi.DEFAULT_CONTAINER_PORT
    # NetworkPolicy per notebook: HTTP only from gateway namespaces; slice
    # workers may talk to each other (DCN bootstrap).
    create_network_policies: bool = False
    gateway_namespaces: tuple = ("istio-system", "kubeflow-tpu")
    # Trusted-CA bundle: ConfigMap <trusted_ca_configmap> in
    # <controller_namespace> is mirrored into the notebook namespace and
    # mounted into every container.
    trusted_ca_configmap: str | None = None
    controller_namespace: str = "kubeflow-tpu"
    ca_bundle_mount_path: str = "/etc/pki/tls/certs/custom-ca-bundle.crt"
    # Auth-proxy sidecar (odh oauth-proxy equivalent) for meshless clusters:
    # injected when the notebook has the inject-auth-proxy annotation.
    auth_proxy_image: str | None = None
    auth_proxy_port: int = 3000
    # Pipeline-access RBAC (odh's ReconcileRoleBindings, notebook_rbac.go:
    # 36-154): when a Role with this name exists in the notebook namespace
    # (created by a pipelines deployment), bind the notebook's
    # ServiceAccount to it so in-notebook pipeline clients (elyra-style)
    # can submit runs. None disables the probe entirely.
    pipeline_access_role: str | None = "pipeline-user-access"

    # Taint keys that mean "this node is about to go down for maintenance"
    # (GKE graceful node termination for TPU/GPU maintenance events).
    # Empty disables the maintenance-pending mirror.
    maintenance_taints: tuple[str, ...] = DEFAULT_MAINTENANCE_TAINTS

    # Queued provisioning support (spec.tpu.queuedProvisioning). Disable
    # on clusters without the autoscaling.x-k8s.io ProvisioningRequest
    # CRD — the watch would otherwise relist-404 forever. When disabled,
    # a queued spec runs as if unqueued.
    enable_queued_provisioning: bool = True

    # Workqueue event-coalescing window (seconds): a multi-host slice
    # coming up emits one status event per worker pod within milliseconds;
    # the window folds the burst into one reconcile. Small enough to be
    # invisible in ready-latency percentiles. 0 disables.
    coalesce_window: float = 0.005

    # Preempt-to-checkpoint (kubeflow_tpu/migration): drives the
    # annotation-driven suspend/resume flow, the restore-hint pod env,
    # and the status.migration block. Safe on by default — all three are
    # no-ops until a drain/checkpoint annotation exists. The scheduler's
    # own drain path has its own switch (SchedulerOptions/KFTPU_MIGRATION).
    enable_migration: bool = True
    drain_grace_seconds: float = migration.DEFAULT_DRAIN_GRACE_SECONDS


AUTH_PROXY_ANNOTATION = keys.NOTEBOOK_INJECT_AUTH_PROXY
CA_BUNDLE_CONFIGMAP = "kubeflow-tpu-ca-bundle"
CA_BUNDLE_KEY = "ca-bundle.crt"

# Slice-restart backoff state (annotations so damping survives controller
# restarts) + schedule: attempt N waits base·2^(N-1) seconds, capped.
SLICE_RESTART_ATTEMPTS_ANNOTATION = keys.NOTEBOOK_SLICE_RESTART_ATTEMPTS
SLICE_RESTART_AT_ANNOTATION = keys.NOTEBOOK_SLICE_RESTART_AT
SLICE_RESTART_BASE_SECONDS = 10.0
SLICE_RESTART_MAX_SECONDS = 300.0


class NotebookReconciler:
    def __init__(
        self,
        kube,
        options: NotebookOptions | None = None,
        *,
        registry: Registry | None = None,
    ):
        self.kube = kube
        self.opts = options or NotebookOptions()
        self.recorder = EventRecorder(kube, "notebook-controller",
                                      registry=registry)
        # Fleet scheduler (kubeflow_tpu/scheduler): the cluster-level gang
        # arbiter the capacity stage consults before any slice StatefulSet
        # exists. None (bare-reconciler tests, KFTPU_SCHEDULER=off) or an
        # inactive scheduler (no fleet configured) means admission passes
        # through — the pre-scheduler behavior. Set by
        # setup_notebook_controller.
        self._scheduler = None
        # Warm pod pools (controllers/warmpool.py, ISSUE 14): the claim
        # gate adopts a pre-warmed pod for eligible notebooks instead of
        # creating slice StatefulSets. None (no KFTPU_WARM_POOLS) keeps
        # the cold path byte-for-byte.
        self._warmpool = None
        # (ns, name) → {pod-event-name: count} — events already mirrored, so
        # each reconcile re-emits only NEW occurrences (a plain list-driven
        # re-emit would bump the mirrored count once per reconcile, turning
        # it into a reconcile-frequency counter).
        self._mirrored: dict[tuple, dict[str, int]] = {}
        # ns → (role exists, checked-at); see _namespace_has_role. The
        # generation counter closes the TOCTOU between an in-flight probe
        # and the Role watch busting the cache: a probe only writes its
        # result back if no Role event landed while it was awaiting.
        self._role_probe_cache: dict[str, tuple[bool, float]] = {}
        self._role_probe_gen: dict[str, int] = {}
        self._role_probe_ttl = 60.0
        # Wall clock for the slice-restart backoff; tests inject a fake.
        self._now = time.time
        # Informer handles (set by setup_notebook_controller): mirror and
        # status reads come from the watch-driven caches, not LISTs/GETs
        # per reconcile. None (bare-reconciler unit tests) falls back to
        # direct apiserver reads.
        self._event_informer = None
        self._sts_informer = None
        self._node_informer = None
        self._nb_informer = None
        self._pr_informer = None
        self._pod_informer = None
        # Durable lifecycle timeline recorder (runtime/timeline.py) —
        # the manager's, shared across controllers; None in bare
        # reconciler tests. This reconciler is the SINGLE timeline
        # writer per notebook key (the workqueue serializes reconciles
        # per key), so every layer's transition lands through
        # _update_status exactly once.
        self._timeline = None
        # kind → informer for owned children: reconcile_child reads the
        # live object from the watch cache instead of a per-child GET.
        # (Populated by setup_notebook_controller; the reader reads the
        # dict live.)
        self._child_informers: dict[str, object] = {}
        self._reader = informer_reader(self._child_informers)
        # Write elision: last-applied hashes for children (apply.py) and a
        # per-key last-written status hash, so a reconcile whose desired
        # state is unchanged issues ZERO PATCH/PUT calls.
        self._apply_cache = ApplyCache()
        # (ns, name) → (computed-status hash, stored-status hash); see
        # _update_status for why the pair (not either hash alone) is the
        # elision key.
        self._last_status: dict[tuple, tuple[str, str]] = {}
        # Per-notebook gauge contributions, aggregated incrementally:
        # recomputing the namespace rollup from the informer cache cost
        # O(notebooks-in-ns) per reconcile — O(N²) across a namespace
        # coming up (the scale bench's biggest remaining scan).
        self._gauge_contrib: dict[tuple, tuple[int, int]] = {}
        self._ns_totals: dict[str | None, list[int]] = {}
        # Training telemetry fold (ISSUE 18): latest decoded annotation
        # entry per key (the /debug/telemetry data source) and the last
        # publish seq fed downstream — the SLO engine, the Prometheus
        # mirror, and the scheduler's efficiency ledger each consume one
        # observation per publish, not one per reconcile.
        self._telemetry: dict[tuple, dict] = {}
        self._telemetry_seq: dict[tuple, int] = {}
        registry = registry or global_registry
        # Metric names match the reference (pkg/metrics/metrics.go:14-62) so
        # dashboards/alerts carry over.
        self.m_create = registry.counter(
            "notebook_create_total", "Total times of creating notebooks"
        )
        self.m_running = registry.gauge(
            "notebook_running", "Running notebooks in the cluster", ["namespace"]
        )
        self.m_chips = registry.gauge(
            "notebook_tpu_chips_requested",
            "TPU chips requested by non-stopped notebooks", ["namespace"],
        )
        self.m_status_elided = registry.counter(
            "notebook_status_writes_elided_total",
            "Status reconciles skipped because nothing changed",
        )

    # ---- reconcile --------------------------------------------------------------

    async def reconcile(self, key) -> Result | None:
        namespace, name = key
        # Phase spans: every section below lands in the reconcile's trace
        # tree (manager opens the root + queue_wait), so /debug/traces
        # shows which phase ate the time when a Notebook sticks.
        with span("cache_read"):
            nb = await self.kube.get_or_none("Notebook", name, namespace)
        if nb is None or get_meta(nb).get("deletionTimestamp"):
            self._mirrored.pop((namespace, name), None)
            self._last_status.pop((namespace, name), None)
            self._telemetry.pop((namespace, name), None)
            self._telemetry_seq.pop((namespace, name), None)
            if self._timeline is not None:
                self._timeline.forget((namespace, name))
            # The namespace's running/chip gauges must drop the deleted
            # notebook's contribution now, not at the next unrelated
            # reconcile in this namespace.
            self._set_gauge_contribution(namespace, name, 0, 0)
            if self._scheduler is not None:
                # Admission handle dies with the CR: its chips go back to
                # the fleet and the scheduler re-arbitrates immediately.
                await self._scheduler.release((namespace, name))
            return None  # children die by ownerReference cascade

        try:
            ms = nbapi.multi_slice_of(nb)
        except Invalid as e:
            await self.recorder.event(nb, "Warning", "InvalidSpec", str(e))
            return None
        tpu = ms.slice if ms else None

        # User-facing suspend/resume rides the same drain protocol as
        # scheduler preemption (kubeflow_tpu/migration). Runs before the
        # children phase: a suspend that just acked must park THIS
        # reconcile's StatefulSets, and a resume must un-park before the
        # scheduler gate re-arbitrates. Patches annotations only; the
        # resulting watch event drives the follow-up reconcile.
        suspend_requeue = await self._check_suspend(nb, ms)

        with span("apply"):
            capacity_pending, capacity_requeue, admission, warm = \
                await self._apply_children(nb, ms, tpu)

        with span("status"):
            pods = await self._worker_pods(nb)  # one lookup, shared by the tail
            # The tail's three sections are independent reads over the
            # same pod set (slice health, node taints, event mirror) —
            # against a real apiserver each is its own RTT chain, so
            # overlap them; the status write waits for all three (the
            # restart path's annotation patches must land first).
            requeue, _, _ = await overlap(
                self._restart_broken_slice(nb, ms, pods),
                self._check_maintenance(nb, pods),
                self._mirror_events(nb, pods),
            )
            await self._update_status(nb, ms, capacity_pending=capacity_pending,
                                      admission=admission, pods=pods,
                                      warm=warm)
        if capacity_pending:
            return capacity_requeue
        if admission is not None and admission.state == "Draining" \
                and admission.requeue_after:
            # A draining victim must reconcile again by the grace
            # deadline even if the SDK never acks — the scheduler's
            # hard-stop fallback fires on that pass.
            return _soonest(Result(requeue_after=admission.requeue_after),
                            requeue)
        # Soonest wins: a pending suspend drain's grace deadline must not
        # be deferred behind a longer periodic requeue from the status
        # tail (or vice versa).
        return _soonest(requeue, suspend_requeue)

    async def _apply_children(
        self, nb: dict, ms, tpu
    ) -> tuple[bool, Result | None, object | None, dict | None]:
        """The child-object phase of reconcile as a dependency DAG
        (latency hiding, ISSUE 4): capacity gate → [all slice
        StatefulSets] → [Service, headless Service, VirtualService,
        NetworkPolicy, RBAC, slice GC]. Stage-mates overlap; each stage
        waits for the previous one, so against a real apiserver the wall
        time is the critical-path RTT depth, not the child count.
        Returns (capacity_pending, capacity_requeue, admission, warm)."""
        # Stage "capacity", part 1: cluster-level gang arbitration
        # (kubeflow_tpu/scheduler). The fleet scheduler is the single
        # admission point between the CR and its slice StatefulSets —
        # while the gang is Queued, nothing downstream runs: no slice
        # may exist and GKE capacity must not be reserved for a gang
        # that lost the arbitration. In-process (no RTT), so it runs
        # before — not overlapped with — the provisioning gate.
        admission = await self._scheduler_gate(nb, ms)
        if admission is not None and admission.state == "Queued":
            # Queued ⇒ no StatefulSet AND no GKE reservation. A PR left
            # behind on any path that still lands here would double-book
            # the physical slice while the chips belong to another gang —
            # drop it (informer-checked: a no-op for the common
            # never-admitted queued gang).
            if self.opts.enable_queued_provisioning and ms \
                    and nbapi.queued_provisioning(nb):
                await self._release_capacity(nb)
            # Same contract for slices: a gang that slipped through the
            # scheduler's pre-activation pass-through window (fresh
            # restart, dynamic fleet source still loading, possibly a
            # partial DAG apply under API faults) can own live
            # StatefulSets by the time arbitration lands Queued — scale
            # them to 0; their chips belong to whoever wins.
            await self._park_queued_slices(nb)
            requeue = Result(requeue_after=(
                self._scheduler.options.queued_requeue_seconds))
            return True, requeue, admission, None
        # Warm-pool claim gate (ISSUE 14): an admitted (or pass-through)
        # eligible notebook adopts a pre-warmed pod INSTEAD of creating
        # slice StatefulSets — the whole pod+runtime start collapses to
        # a re-label. An empty pool (state "warming") falls through to
        # the cold path transparently.
        warm = await self._warm_claim_gate(nb, ms)
        claimed = warm is not None and warm.get("state") == "claimed"
        if claimed:
            # The adopted pod IS the slice: no ProvisioningRequest (its
            # capacity already exists under the running pod) and no
            # slice StatefulSets.
            capacity_pending, capacity_provisioned, capacity_requeue = \
                False, True, None
        else:
            # Stage "capacity", part 2: the queued-provisioning gate and
            # the CA-bundle mirror are independent round-trip chains —
            # overlap them. The gate's verdict shapes the slices stage,
            # so it stays control flow rather than an apply_set child.
            with span("apply_stage", stage="capacity"):
                (capacity_pending, capacity_provisioned,
                 capacity_requeue), _ = \
                    await overlap(
                        self._capacity_gate(nb, ms),
                        self._mirror_ca_bundle(nb)
                        if self.opts.trusted_ca_configmap else None,
                    )

        # One StatefulSet per slice (ICI placement is per-slice; DCN joins
        # them — tpu/topology.py MultiSlice). Single-slice keeps the bare
        # name, zero churn for the common case.
        num_sts = 0 if (capacity_pending or claimed) \
            else (ms.num_slices if ms else 1)
        # Creation events ride the NEXT stage, off the gang's critical
        # path: awaiting each best-effort emission inside its slice child
        # would re-serialize an N-slice cold create on the (deliberately
        # narrow) event lane.
        created_slices: list[str] = []
        try:
            await self._apply_children_stages(
                nb, ms, tpu, num_sts, capacity_provisioned, created_slices)
        except Exception:
            # A stage error skips the services stage — which now carries
            # the creation events. Slices that DID create must still
            # announce themselves (the pre-DAG code emitted each event
            # right after its create); the retry reconcile sees them as
            # pre-existing and would stay silent forever.
            if created_slices:
                try:
                    await self._emit_created_events(nb, created_slices)
                except Exception:
                    # Best-effort by contract: keep the real (stage)
                    # error, but the drop must land in the counter.
                    self.recorder.count_drop()
            raise
        return capacity_pending, capacity_requeue, admission, warm

    async def _scheduler_gate(self, nb: dict, ms):
        """Consult the TPU fleet scheduler (the ``schedule``/``admit``/
        ``preempt`` spans live inside it). Returns the current
        :class:`~kubeflow_tpu.scheduler.runtime.Admission`, or None when
        no scheduler is wired / no fleet is configured / the notebook is
        CPU-only — all of which mean "admit unconditionally".

        A stopped notebook (user stop, culling, or a preemption's stop
        annotation) releases its admission handle here; the normal apply
        path still runs afterwards so the gang actually parks (replicas
        0 everywhere). A gang whose StatefulSets are already live —
        controller restart, scheduler turned on over a running fleet —
        is re-seated (reclaimed), never re-queued."""
        sched = self._scheduler
        if sched is None:
            return None
        key = (namespace_of(nb), name_of(nb))
        if ms is None:
            # Edited from TPU to CPU while Queued/Admitted (the webhook
            # allows spec edits on queued gangs): the gang no longer
            # exists, so drop its queue entry / allocation — otherwise
            # the stale entry holds (or later takes) fleet chips and, if
            # starved, blocks backfill forever. CPU notebooks carry no
            # scheduler status, so the verdict is discarded.
            await sched.release(key, nb)
            return None
        if nbapi.is_stopped(nb):
            return await sched.release(key, nb)
        # Liveness probed unconditionally (not just once the fleet is
        # active) because admission() itself can activate a lazily-
        # discovered fleet — and must then reclaim, not queue, a gang
        # that is already running. A live ProvisioningRequest counts as
        # running for the same reason: it is created only AFTER admission
        # and deleted on park, so across a controller restart it is the
        # proof of admission for a gang still waiting on GKE capacity
        # (no StatefulSet yet) — re-queueing that gang would hand its
        # ledger chips to another while the GKE reservation double-books
        # the physical slice.
        return await sched.admission(
            nb, ms, running=(await self._gang_running(nb, ms)
                             or await self._holds_reservation(nb)))

    async def _park_queued_slices(self, nb: dict) -> None:
        """Scale a Queued gang's leftover slice StatefulSets to zero
        (see the caller for how a Queued gang can own any). Informer
        owner-index first; zero work for the common no-STS queued gang.
        A stale cache at worst defers the park one STS event — the
        queued requeue re-runs this every pass."""
        name, ns = name_of(nb), namespace_of(nb)
        if (self._sts_informer is not None
                and self._sts_informer.has_indexer(OWNER_INDEX)):
            owned = self._sts_informer.by_index(OWNER_INDEX, uid_of(nb))
        else:
            try:
                owned = await self.kube.list(
                    "StatefulSet", ns,
                    label_selector={
                        "matchLabels": {nbapi.NOTEBOOK_NAME_LABEL: name}},
                )
            except ApiError as exc:
                log.debug("queued-slice park LIST for %s/%s failed "
                          "(retried on the queued requeue): %s",
                          ns, name, exc)
                return
        for sts in owned:
            if (deep_get(sts, "spec", "replicas") or 0) > 0:
                try:
                    await self.kube.patch(
                        "StatefulSet", name_of(sts),
                        {"spec": {"replicas": 0}}, ns)
                except (NotFound, ApiError) as exc:
                    log.debug("queued-slice scale-to-0 of %s failed "
                              "(retried on the queued requeue): %s",
                              name_of(sts), exc)

    async def _holds_reservation(self, nb: dict) -> bool:
        """Does this notebook hold a live GKE ProvisioningRequest?
        Informer-checked, so the common no-PR case costs nothing."""
        if not (self.opts.enable_queued_provisioning
                and nbapi.queued_provisioning(nb)):
            return False
        name, ns = name_of(nb), namespace_of(nb)
        cap_name = capacity_name(name)
        if self._pr_informer is not None:
            return self._pr_informer.get(cap_name, ns) is not None
        return await self.kube.get_or_none(
            "ProvisioningRequest", cap_name, ns) is not None

    async def _gang_running(self, nb: dict, ms) -> bool:
        """Is this notebook's gang actively running (slice-0 StatefulSet
        live with replicas > 0)? Informer-cached; shared by the
        scheduler gate (reclaim-vs-queue) and the provisioning gate
        (hold-vs-pass on an unprovisioned request)."""
        sts0 = ms.slice_sts_name(name_of(nb), 0)
        existing = await self._live_sts(sts0, namespace_of(nb))
        return existing is not None and (
            deep_get(existing, "spec", "replicas") or 0) > 0

    async def _check_suspend(self, nb: dict, ms) -> Result | None:
        """Annotation-driven suspend/resume over the drain protocol
        (kubeflow_tpu/migration). Suspend = the SUSPEND annotation
        appears: request a drain (reason ``suspend``), wait for the
        in-pod SDK's checkpoint ack (bounded by the drain grace), then
        park via the stop annotation — so "suspend" is "stop, but my
        training state survives". Resume = the annotation is removed:
        a parked suspend un-parks (the scheduler re-arbitrates and the
        restore hint rides the pod env); a still-draining suspend is
        cancelled. CPU notebooks (no slice, nothing to checkpoint) and
        migration-off park immediately — the pre-migration stop."""
        annotations = annotations_of(nb)
        suspended = nbapi.SUSPEND_ANNOTATION in annotations
        stopped = nbapi.is_stopped(nb)
        reason = migration.drain_reason(annotations)
        ns, name = namespace_of(nb), name_of(nb)
        now = self._now()

        async def patch(anns: dict) -> None:
            await self.kube.patch(
                "Notebook", name, {"metadata": {"annotations": anns}}, ns)

        if suspended and not stopped:
            if not (self.opts.enable_migration and ms
                    and await self._gang_running(nb, ms)):
                # Nothing to checkpoint: CPU notebook, migration off, or
                # a gang with no running pods (queued, provisioning,
                # parked mid-restart) — park immediately; waiting out
                # the drain grace would only delay the stop and emit a
                # spurious deadline warning.
                await patch({nbapi.STOP_ANNOTATION: fmt_iso(now)})
                await self.recorder.event(
                    nb, "Normal", "Suspended", "Suspended (no checkpoint)")
                return None
            requested = migration.drain_requested_at(annotations)
            if requested is None:
                await patch(migration.request_drain_patch("suspend", now))
                await self.recorder.event(
                    nb, "Normal", "SuspendRequested",
                    "Suspend requested; checkpointing before parking "
                    f"(grace {self.opts.drain_grace_seconds:.0f}s)")
                return Result(requeue_after=self.opts.drain_grace_seconds
                              + 0.1)
            if reason != "suspend":
                return None  # a preemption drain owns the marks; its
                             # park satisfies the suspend too
            deadline = requested + self.opts.drain_grace_seconds
            # The park keeps DRAIN_REASON="suspend" as the durable "how
            # it parked" marker — resume (annotation removed while
            # stopped) and derive_state's Parked gate key off it; the
            # request/progress marks clear.
            park_clear = migration.clear_drain_patch(keep_reason=True)
            if migration.drain_acked(annotations):
                await patch({nbapi.STOP_ANNOTATION: fmt_iso(now),
                             **park_clear})
                step = migration.checkpoint_step(annotations)
                await self.recorder.event(
                    nb, "Normal", "Suspended",
                    "Suspended"
                    + (f" (checkpoint @ step {step})"
                       if step is not None else " (checkpoint committed)"))
                return None
            if now >= deadline:
                await patch({nbapi.STOP_ANNOTATION: fmt_iso(now),
                             **park_clear})
                await self.recorder.event(
                    nb, "Warning", "SuspendDeadlineExceeded",
                    f"No checkpoint ack within "
                    f"{self.opts.drain_grace_seconds:.0f}s; suspended "
                    "without a fresh checkpoint")
                return None
            return Result(requeue_after=max(0.1, deadline - now + 0.05))

        if not suspended and reason == "suspend":
            if stopped:
                # Resume: un-park; the scheduler gate re-arbitrates and
                # generate_statefulset stamps the restore hint.
                await patch({nbapi.STOP_ANNOTATION: None,
                             **migration.clear_drain_patch()})
                hint = migration.restore_hint(annotations)
                await self.recorder.event(
                    nb, "Normal", "Resuming",
                    "Resuming"
                    + (f" from checkpoint {hint[0]}"
                       + (f" @ step {hint[1]}"
                          if hint[1] is not None else "")
                       if hint else " (no checkpoint recorded)"))
            else:
                # Suspend cancelled mid-drain: drop the request so the
                # SDK stops checkpointing for a park that isn't coming.
                await patch(migration.clear_drain_patch())
            return None
        if (not stopped and reason and reason != "suspend"
                and migration.drain_requested_at(annotations) is None):
            # Parked-marker hygiene without a scheduler: a cull/preempt
            # park keeps its drain-reason so derive_state can tell a
            # checkpointed park from a plain stop. The fleet scheduler
            # clears it on re-admission; on scheduler-less clusters this
            # is the restart path that does — otherwise a later plain
            # stop would present as "Suspended (checkpoint @ step N)".
            await patch({nbapi.DRAIN_REASON_ANNOTATION: None})
        return None

    async def _warm_claim_gate(self, nb: dict, ms) -> dict | None:
        """Warm pod pools (controllers/warmpool.py): adopt a pre-warmed
        pod for this notebook instead of paying the cold pod + runtime
        start. Returns the warm verdict for status/timeline:
        ``{"state": "claimed", "pod": ...}`` (skip slice StatefulSets —
        the adopted pod IS the slice), ``{"state": "warming", ...}`` (a
        matching pool exists but is EMPTY: the cold path proceeds while
        the pool replenishes, and the miss is surfaced), or None (no
        pool / ineligible / already running cold). Claims route through
        the manager's CAS claim protocol EXCLUSIVELY — enforced by the
        ``warm-pool-contract`` analysis pass."""
        wp = self._warmpool
        annotations = annotations_of(nb)
        claimed_name = annotations.get(nbapi.WARM_CLAIMED_ANNOTATION)
        stopped = nbapi.is_stopped(nb)
        ns, name = namespace_of(nb), name_of(nb)
        clear = {nbapi.WARM_CLAIMED_ANNOTATION: None,
                 nbapi.WARM_CLAIMED_AT_ANNOTATION: None,
                 nbapi.WARM_CLAIMED_IN_ANNOTATION: None}
        if claimed_name:
            pod = await self._claimed_pod(nb, claimed_name)
            adopted = pod is not None and (
                get_meta(pod).get("labels") or {}).get(
                    nbapi.NOTEBOOK_NAME_LABEL) == name
            if stopped or wp is None or ms is None:
                # Park (or the subsystem turned off, or the notebook was
                # edited TPU→CPU): the adopted pod dies with the stop —
                # a restart claims fresh or goes cold; a stale claim
                # must not wedge either path. Only an ADOPTED pod is ours
                # to delete: a stale intent (hand-off never completed)
                # names a pod that is still pool property — or by now
                # another notebook's — so it is cleared without touching
                # the pod.
                if adopted:
                    try:
                        await self.kube.delete("Pod", claimed_name, ns)
                    except (NotFound, ApiError) as exc:
                        log.debug("adopted-pod delete %s on stop failed "
                                  "(GC owner cascade also covers it): "
                                  "%s", claimed_name, exc)
                await self.kube.patch(
                    "Notebook", name,
                    {"metadata": {"annotations": clear}}, ns)
                return None
            if not adopted:
                # Intent without a completed hand-off (a fault landed
                # between the CR stamp and the pod patch): the pod — if
                # it even exists — is still POOL property; clear the
                # stale intent and go cold without touching it.
                await self.kube.patch(
                    "Notebook", name,
                    {"metadata": {"annotations": clear}}, ns)
                return None
            # Broken-pod check against the POD's own container name —
            # an adopted warm pod keeps the pool template's container
            # ("warm"), not the CR's; checking the CR name would let a
            # crashlooping claimed pod wedge readiness forever.
            pod_main = (deep_get(pod, "spec", "containers",
                                 default=[{}]) or [{}])[0].get("name") \
                or _main_container_name(nb)
            if _worker_is_broken(pod, pod_main):
                # Claimed pod broken: transparent cold fallback — THIS
                # reconcile already creates the slice StatefulSets.
                try:
                    await self.kube.delete("Pod", claimed_name, ns)
                except (NotFound, ApiError) as exc:
                    log.debug("broken claimed-pod delete %s failed "
                              "(cold fallback proceeds regardless): %s",
                              claimed_name, exc)
                await self.kube.patch(
                    "Notebook", name,
                    {"metadata": {"annotations": clear}}, ns)
                await self.recorder.event(
                    nb, "Warning", "WarmClaimLost",
                    f"Warm-claimed pod {claimed_name} is broken; "
                    "falling back to the cold start path")
                return None
            return {"state": "claimed", "pod": pod}
        if wp is None or stopped or ms is None \
                or wp.pool_for(nb, ms) is None:
            return None
        if await self._gang_running(nb, ms):
            # Already live on the cold path (restart, scheduler reclaim):
            # claiming now would double-provision the slice.
            return None
        since = self._episode_start(nb)
        pod = await wp.claim(nb, ms, since=since)
        if pod is not None:
            await self.recorder.event(
                nb, "Normal", "WarmClaimed",
                f"Claimed warm pod {name_of(pod)} from the warm pool; "
                "skipping the cold StatefulSet start")
            return {"state": "claimed", "pod": pod,
                    "claimed_in": round(max(0.0, self._now() - since), 3)}
        return {"state": "warming",
                "replenishing": await wp.replenishing_status(nb, ms)}

    async def _claimed_pod(self, nb: dict, pod_name: str) -> dict | None:
        ns = namespace_of(nb)
        if self._pod_informer is not None:
            pod = self._pod_informer.get(pod_name, ns)
            if pod is not None:
                return pod
        return await self.kube.get_or_none("Pod", pod_name, ns)

    def _episode_start(self, nb: dict) -> float:
        """When this startup episode began — the timeline's episode
        boundary (survives re-queues and restarts), falling back to the
        CR's creation time. Feeds the "claimed in Xs" attribution."""
        annotations = annotations_of(nb)
        if self._timeline is not None:
            entries = self._timeline.entries(
                (namespace_of(nb), name_of(nb)), annotations=annotations)
        else:
            entries = timeline_mod.decode(annotations)
        start = timeline_mod.episode_start(entries)
        if start is not None:
            return start["at"]
        created = get_meta(nb).get("creationTimestamp")
        ts = parse_iso(created) if created else None
        return ts if ts is not None else self._now()

    async def _apply_children_stages(
        self, nb: dict, ms, tpu, num_sts: int, capacity_provisioned: bool,
        created_slices: list[str],
    ) -> None:
        await apply_set(
            self.kube,
            [
                Stage("slices", [
                    self._apply_slice_sts(nb, ms, tpu, slice_id,
                                          capacity_provisioned,
                                          created_slices)
                    for slice_id in range(num_sts)
                ]),
                Stage("services", [
                    self._emit_created_events(nb, created_slices),
                    self.generate_service(nb, multi=ms),
                    (self.generate_headless_service(nb, multi=ms)
                     if (tpu and tpu.multi_host) or (ms and ms.multi)
                     else None),
                    (self.generate_virtual_service(nb)
                     if self.opts.use_istio else None),
                    (self.generate_network_policy(nb, tpu)
                     if self.opts.create_network_policies else None),
                    self._ensure_pipeline_rbac(nb),
                    # Covers scale-in (numSlices 4→2) AND the multi→single
                    # transition (numSlices 2→1 renames the STS to the
                    # bare name; the stale -s* StatefulSets must not keep
                    # burning chips). After the slices stage so a rename
                    # creates before it deletes.
                    self._gc_extra_slices(nb, ms) if ms else None,
                ]),
            ],
            cache=self._apply_cache, reader=self._reader, owner=nb,
        )

    async def _capacity_gate(self, nb: dict, ms) -> tuple[bool, bool,
                                                          Result | None]:
        """Queued provisioning: reserve the whole slice's capacity through
        a ProvisioningRequest BEFORE creating any worker — a partially
        scheduled gang on a scarce topology burns quota and wedges
        (every host must land together for ICI). Until Provisioned, no
        StatefulSet exists; the Services are still created so DNS is
        ready the moment pods land. Returns (capacity_pending,
        capacity_provisioned, capacity_requeue)."""
        if not (ms and nbapi.queued_provisioning(nb)
                and self.opts.enable_queued_provisioning):
            return False, True, None
        if nbapi.is_stopped(nb):
            # Parked: the reservation is one-shot — its capacity was
            # consumed (or expired) when the gang went away. Delete the
            # request so a restart queues for FRESH capacity instead of
            # sailing past the gate on a spent Provisioned=True.
            await self._release_capacity(nb)
            return False, True, None
        provisioned, capacity_requeue = await self._ensure_capacity(nb, ms)
        if provisioned:
            return False, True, None
        # The gate holds unless the gang is ACTIVELY running (flag
        # flipped on mid-flight, or the PR deleted from under a live
        # slice — freezing those would block spec drift and flip status
        # to a false capacity wait). A parked STS (replicas 0,
        # reservation released on park) still gates: restart queues for
        # fresh capacity.
        return (not await self._gang_running(nb, ms)), False, \
            capacity_requeue

    async def _apply_slice_sts(
        self, nb: dict, ms, tpu, slice_id: int, capacity_provisioned: bool,
        created_sink: list[str],
    ) -> bool:
        """Build + apply one slice's StatefulSet (an apply_set child —
        slices overlap each other inside the ``slices`` stage). Newly
        created names land in ``created_sink``; their events are emitted
        by the next stage (:meth:`_emit_created_events`)."""
        with span("build_children", kind="StatefulSet", slice=slice_id):
            sts = self.generate_statefulset(
                nb, tpu, multi=ms, slice_id=slice_id,
                capacity_provisioned=capacity_provisioned)
        if self._scheduler is not None:
            flex = self._scheduler.flex_node_selectors(
                (namespace_of(nb), name_of(nb)))
            if flex:
                # Flex (borrowed-host) placement: the workers must land
                # on the HOST pool's nodes — the gang's own shape labels
                # select nothing (that's why it borrowed). Chip request
                # stays the gang's own (sub-host allocation).
                selectors = sts["spec"]["template"]["spec"].setdefault(
                    "nodeSelector", {})
                selectors.update(flex)
        if self.opts.enable_migration:
            await self._stabilize_restore_env(nb, sts)
        if not capacity_provisioned:
            # Sticky consume annotation: when the request is (or has
            # become) unprovisioned over a LIVE gang — e.g. the PR was
            # deleted from under it and recreated — keep whatever the
            # running StatefulSet already carries. Stripping it would
            # diff the template and rolling-restart a healthy slice.
            await self._preserve_consume_annotation(nb, sts)
        created = await self._ensure(nb, sts)
        if created:
            self.m_create.inc()
            created_sink.append(name_of(sts))
        return created

    async def _emit_created_events(self, nb: dict, names: list[str]) -> None:
        """Emit CreatedStatefulSet for every slice the previous stage
        created — concurrently, and overlapping the services stage, so a
        wide cold create never serializes on the event lane's width.
        Consumes ``names``: the rescue emitter in ``_apply_children``
        runs this again when a stage error skipped the services stage,
        and a services-stage SIBLING failure (first-error semantics let
        this child complete first) must not double-emit."""
        if not names:
            return
        batch, names[:] = list(names), []
        await overlap(*(
            self.recorder.event(
                nb, "Normal", "CreatedStatefulSet",
                f"Created StatefulSet {n}")
            for n in batch
        ))

    async def _live_sts(self, name: str, ns: str) -> dict | None:
        """Informer-cached StatefulSet read with apiserver fallback. The
        controller owns StatefulSets, so the informer is always running
        under the manager (a 64-slice notebook would otherwise pay 64
        serialized GETs per reconcile); staleness self-corrects on the
        next STS event."""
        if self._sts_informer is not None:
            return self._sts_informer.get(name, ns)
        return await self.kube.get_or_none("StatefulSet", name, ns)

    async def _stabilize_restore_env(self, nb: dict, sts: dict) -> None:
        """Restore-hint env may only change across a park boundary. For a
        LIVE gang (replicas > 0) the freshly generated template keeps
        exactly the restore env the running pods already have — present
        or absent, with the live values: the hint is moot while the gang
        runs, and adding/updating it (first ack of a drain, a cancelled
        suspend after its ack, an ack→park race) would diff the template
        and rolling-restart pods that nothing intends to disturb. A
        parked or not-yet-created StatefulSet takes the desired hint
        as-is — it rides the same update as the scale-up."""
        live = await self._live_sts(name_of(sts), namespace_of(nb))
        if live is None or not (deep_get(live, "spec", "replicas") or 0):
            return
        restore_keys = (migration.RESTORE_PATH_ENV, migration.RESTORE_STEP_ENV)
        live_env = (deep_get(live, "spec", "template", "spec", "containers",
                             default=[{}]) or [{}])[0].get("env") or []
        live_restore = [dict(e) for e in live_env
                        if e.get("name") in restore_keys]
        main = sts["spec"]["template"]["spec"]["containers"][0]
        env = [e for e in main.get("env", [])
               if e.get("name") not in restore_keys]
        env.extend(live_restore)
        main["env"] = env

    async def _preserve_consume_annotation(self, nb: dict, sts: dict) -> None:
        """Copy the live StatefulSet's consume-provisioning-request
        annotations onto the freshly generated template when the request
        is not (currently) Provisioned. Two cases meet here:

        - PR deleted/recreated under a live consuming gang → the live
          template has the annotation; keeping it avoids a spurious
          rolling restart, and the recreated request reuses the same name.
        - Mid-flight flip (flag turned on over a running gang on a
          cluster without the admission webhook) → the live template has
          no annotation; generating none means no rollout until the
          request actually provisions (an unprovisioned consume reference
          would park replacement pods behind the autoscaler)."""
        live = await self._live_sts(name_of(sts), namespace_of(nb))
        live_anns = deep_get(live, "spec", "template", "metadata",
                             "annotations", default={}) or {}
        if CONSUME_PR_ANNOTATION not in live_anns:
            return
        meta = sts["spec"]["template"].setdefault("metadata", {})
        anns = meta.setdefault("annotations", {})
        anns[CONSUME_PR_ANNOTATION] = live_anns[CONSUME_PR_ANNOTATION]
        if PR_CLASS_ANNOTATION in live_anns:
            anns[PR_CLASS_ANNOTATION] = live_anns[PR_CLASS_ANNOTATION]

    async def _ensure_capacity(self, nb: dict, ms) -> tuple[bool, Result | None]:
        """Reserve the slice's capacity via a GKE ProvisioningRequest
        (queued-provisioning.gke.io). Creates an owned PodTemplate (one
        worker's pod shape — chips + node selectors drive what capacity
        the autoscaler must find) and a ProvisioningRequest asking for
        ``total_hosts`` of them, then reads its conditions:

        - ``Provisioned=True`` → (True, None): create the StatefulSets;
          their pods consume the reservation via CONSUME_PR_ANNOTATION.
        - ``Failed=True`` → Warning event, long requeue (capacity class
          rejected the request; flapping on it would spam the
          autoscaler).
        - otherwise → short requeue while the request queues.

        Both objects are owner-referenced, so they die with the notebook.
        A notebook that turns the flag off keeps its stale request until
        deletion — harmless (Provisioned reservations expire server-side)
        and cheaper than probing for it every reconcile."""
        name, ns = name_of(nb), namespace_of(nb)
        cap_name = capacity_name(name)
        # Steady state: the PR informer already saw Provisioned=True —
        # zero API calls and no throwaway template generation for the
        # rest of the notebook's life.
        cached = (self._pr_informer.get(cap_name, ns)
                  if self._pr_informer is not None else None)
        if cached is not None and any(
            c.get("type") == "Provisioned" and c.get("status") == "True"
            for c in deep_get(cached, "status", "conditions", default=[]) or []
        ):
            return True, None
        # The PR's capacity template must not self-reference the request:
        # the autoscaler matches on shape (resources/selectors), and a
        # consume annotation inside the template it provisions against is
        # at best noise, at worst a circular reference.
        sts = self.generate_statefulset(nb, ms.slice, multi=ms, slice_id=0,
                                        capacity_provisioned=False)
        template = deep_get(sts, "spec", "template", default={})
        pod_template = {
            "apiVersion": "v1",
            "kind": "PodTemplate",
            "metadata": {"name": cap_name, "namespace": ns,
                         "labels": {nbapi.NOTEBOOK_NAME_LABEL: name}},
            "template": template,
        }
        await self._ensure(nb, pod_template)
        pr = {
            "apiVersion": "autoscaling.x-k8s.io/v1beta1",
            "kind": "ProvisioningRequest",
            "metadata": {"name": cap_name, "namespace": ns,
                         "labels": {nbapi.NOTEBOOK_NAME_LABEL: name}},
            "spec": {
                "provisioningClassName": PROVISIONING_CLASS,
                "podSets": [{
                    "podTemplateRef": {"name": cap_name},
                    "count": ms.total_hosts,
                }],
            },
        }
        created = await self._ensure(nb, pr)
        if created:
            await self.recorder.event(
                nb, "Normal", "CapacityRequested",
                f"Created ProvisioningRequest {cap_name} for "
                f"{ms.total_hosts} TPU host(s); workers start once "
                "capacity is provisioned",
            )
        live = await self.kube.get_or_none("ProvisioningRequest", cap_name, ns)
        conditions = deep_get(live, "status", "conditions", default=[]) or []
        by_type = {c.get("type"): c for c in conditions}
        if (by_type.get("Provisioned") or {}).get("status") == "True":
            return True, None
        failed = by_type.get("Failed") or {}
        if failed.get("status") == "True":
            await self.recorder.event(
                nb, "Warning", "CapacityFailed",
                f"ProvisioningRequest {cap_name} failed: "
                f"{failed.get('reason', '')} {failed.get('message', '')}",
            )
            return False, Result(requeue_after=300.0)
        return False, Result(requeue_after=15.0)

    async def _release_capacity(self, nb: dict) -> None:
        """Drop a parked notebook's ProvisioningRequest (informer-checked,
        so steady-state parked notebooks cost nothing). The PodTemplate
        stays — it's inert and the next queue-up reuses the name."""
        name, ns = name_of(nb), namespace_of(nb)
        cap_name = capacity_name(name)
        cached = (self._pr_informer.get(cap_name, ns)
                  if self._pr_informer is not None
                  else await self.kube.get_or_none(
                      "ProvisioningRequest", cap_name, ns))
        if cached is None:
            return
        # Evict from the informer cache regardless of how the delete
        # goes (already-gone, transient apiserver error, success): a
        # restart reconcile can land before the watch task processes the
        # DELETE, and _ensure_capacity's fast path would trust the stale
        # Provisioned=True — sailing past the very gate this release
        # exists to re-arm. If the PR actually still exists, the watch
        # repopulates the cache.
        try:
            await self.kube.delete("ProvisioningRequest", cap_name, ns)
        except NotFound:
            return
        finally:
            if self._pr_informer is not None:
                self._pr_informer.evict(cap_name, ns)
        await self.recorder.event(
            nb, "Normal", "CapacityReleased",
            f"Deleted ProvisioningRequest {cap_name}: the reservation is "
            "one-shot; restarting will queue for fresh capacity",
        )

    async def _ensure_pipeline_rbac(self, nb: dict) -> None:
        """odh notebook_rbac.go:36-154 analogue: if the pipelines Role
        exists in the notebook's namespace, bind the notebook's
        ServiceAccount (pod spec's serviceAccountName, else the profile's
        default-editor) to it via an owned RoleBinding. Skipped silently
        when no pipelines deployment put the Role there."""
        role_name = self.opts.pipeline_access_role
        if not role_name:
            return
        name, ns = name_of(nb), namespace_of(nb)
        if not await self._namespace_has_role(ns, role_name):
            return
        sa = deep_get(nb, "spec", "template", "spec", "serviceAccountName") \
            or "default-editor"
        binding = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": {
                # roleRef is immutable on a real apiserver, so the binding
                # name derives from the role (apply.py's documented
                # copy_rolebinding_fields invariant): a role-name config
                # change creates a fresh binding; the stale one is
                # garbage-collected with the notebook.
                "name": bounded_name(f"pipelines-{role_name}-{name}"),
                "namespace": ns,
                "labels": {nbapi.NOTEBOOK_NAME_LABEL: name},
            },
            "subjects": [
                {"kind": "ServiceAccount", "name": sa, "namespace": ns}
            ],
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "Role",
                "name": role_name,
            },
        }
        await self._ensure(nb, binding)

    async def _namespace_has_role(self, ns: str, role_name: str) -> bool:
        """Role-existence probe with a short negative/positive cache — one
        extra GET per notebook reconcile would otherwise hit the apiserver
        on every pod-status event cluster-wide."""
        now = time.monotonic()
        cached = self._role_probe_cache.get(ns)
        if cached and now - cached[1] < self._role_probe_ttl:
            return cached[0]
        gen = self._role_probe_gen.get(ns, 0)
        exists = await self.kube.get_or_none("Role", role_name, ns) is not None
        if self._role_probe_gen.get(ns, 0) == gen:
            self._role_probe_cache[ns] = (exists, now)
        return exists

    async def _ensure(self, nb: dict, desired: dict) -> bool:
        """reconcile_child with ownership; returns True when newly created."""
        set_controller_owner(desired, nb)
        _, created = await reconcile_child(
            self.kube, desired,
            cache=self._apply_cache, reader=self._reader,
        )
        return created

    # ---- object generation ------------------------------------------------------

    def generate_statefulset(
        self, nb: dict, tpu: TpuSlice | None, *, multi=None, slice_id: int = 0,
        capacity_provisioned: bool = True,
    ) -> dict:
        """Reference: generateStatefulSet (notebook_controller.go:408-484).

        ``multi``/``slice_id``: in multislice mode each slice gets its own
        StatefulSet (``<name>-s<j>``) with slice-static MEGASCALE_* env;
        they all share the notebook's headless Service for DNS.

        ``capacity_provisioned``: whether the notebook's ProvisioningRequest
        (if any) is known Provisioned. The consume-provisioning-request
        annotation is only stamped when True — a rolling update whose
        replacement pods reference an *unprovisioned* request would park
        them behind the autoscaler (the mid-flight-flip case: the flag
        turned on over an already-running gang). Once the request
        provisions, the next reconcile rolls the consume annotation on."""
        name, ns = name_of(nb), namespace_of(nb)
        sts_name = multi.slice_sts_name(name, slice_id) if multi else name
        replicas = 0 if nbapi.is_stopped(nb) else (tpu.num_hosts if tpu else 1)

        pod_spec = deep_get(nb, "spec", "template", "spec", default={})
        pod_spec = {**pod_spec}  # shallow copy; containers replaced below
        containers = [dict(c) for c in pod_spec.get("containers", [])]
        if not containers:
            containers = [{"name": name, "image": "kubeflow-tpu/jupyter-jax:latest"}]
        main = containers[0]
        main.setdefault("name", name)
        main.setdefault(
            "ports",
            [{"containerPort": self.opts.default_serving_port, "name": "notebook-port",
              "protocol": "TCP"}],
        )
        self._set_prefix_env(main, ns, name)
        if self.opts.enable_migration:
            self._set_restore_env(main, nb)

        template_annotations: dict[str, str] = {}
        template_labels: dict[str, str] = {
            STS_LABEL: sts_name,
            nbapi.NOTEBOOK_NAME_LABEL: name,
            "app": name,
        }
        if tpu:
            self._apply_tpu(
                main, pod_spec, template_annotations, template_labels, nb, tpu,
                multi=multi, slice_id=slice_id,
            )
            if (nbapi.queued_provisioning(nb)
                    and self.opts.enable_queued_provisioning
                    and capacity_provisioned):
                # Consume the capacity _ensure_capacity reserved instead
                # of triggering fresh (and possibly partial) scale-up.
                # Gated on the SAME flag as the reconcile gate: with the
                # feature off no request exists, and a consume annotation
                # for a nonexistent request parks the pods forever (the
                # autoscaler won't scale up for them).
                template_annotations[CONSUME_PR_ANNOTATION] = \
                    capacity_name(name)
                template_annotations[PR_CLASS_ANNOTATION] = PROVISIONING_CLASS
        containers[0] = main
        pod_spec["containers"] = containers

        if self.opts.add_fsgroup:
            sc = dict(pod_spec.get("securityContext") or {})
            sc.setdefault("fsGroup", self.opts.fsgroup)
            pod_spec["securityContext"] = sc

        if self.opts.trusted_ca_configmap:
            self._mount_ca_bundle(pod_spec, containers)

        annotations = get_meta(nb).get("annotations") or {}
        if (
            self.opts.auth_proxy_image
            and annotations.get(AUTH_PROXY_ANNOTATION) == "true"
        ):
            containers.append(self._auth_proxy_container(name, ns))

        sts = {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {"name": sts_name, "namespace": ns,
                         "labels": {nbapi.NOTEBOOK_NAME_LABEL: name}},
            "spec": {
                "replicas": replicas,
                # All slices share the notebook's headless Service: every
                # worker of every slice resolves under one DNS zone.
                "serviceName": name + self.opts.workers_service_suffix,
                "selector": {"matchLabels": {STS_LABEL: sts_name}},
                # Slice workers must come up together: sequential (OrderedReady)
                # start would serialise libtpu mesh bootstrap across hosts.
                "podManagementPolicy": "Parallel",
                "template": {
                    "metadata": {
                        "labels": template_labels,
                        "annotations": template_annotations,
                    },
                    "spec": pod_spec,
                },
            },
        }
        return sts

    def _set_restore_env(self, container: dict, nb: dict) -> None:
        """Stamp the migration restore hint (checkpoint path + step) into
        the worker env so in-pod code — sdk.CheckpointManager users, or
        anything reading KFTPU_RESTORE_* — resumes where the drain left
        off. User-provided values win (a notebook that manages its own
        restore keeps doing so). The hint only changes when the SDK
        commits a checkpoint, which is immediately followed by a park —
        so a live gang's template stays stable between drains."""
        hint = migration.restore_hint(annotations_of(nb))
        if hint is None:
            return
        path, step = hint
        env = [dict(e) for e in container.get("env", [])]
        have = {e.get("name") for e in env}
        if migration.RESTORE_PATH_ENV not in have:
            env.append({"name": migration.RESTORE_PATH_ENV, "value": path})
        if step is not None and migration.RESTORE_STEP_ENV not in have:
            env.append({"name": migration.RESTORE_STEP_ENV,
                        "value": str(step)})
        container["env"] = env

    def _set_prefix_env(self, container: dict, ns: str, name: str) -> None:
        """NB_PREFIX tells the server its URL base (notebook_controller.go:392-406)."""
        env = [dict(e) for e in container.get("env", [])]
        prefix = f"/notebook/{ns}/{name}"
        for e in env:
            if e.get("name") == nbapi.PREFIX_ENV_VAR:
                e["value"] = prefix
                break
        else:
            env.append({"name": nbapi.PREFIX_ENV_VAR, "value": prefix})
        container["env"] = env

    def _apply_tpu(
        self,
        main: dict,
        pod_spec: dict,
        template_annotations: dict,
        template_labels: dict,
        nb: dict,
        tpu: TpuSlice,
        *,
        multi=None,
        slice_id: int = 0,
    ) -> None:
        """Wire the slice: selectors, chip requests, slice-static env, webhook
        annotations. Per-worker env (TPU_WORKER_ID) is the pod webhook's job.
        In multislice mode the MEGASCALE_* env and global process space are
        slice-static, so they bake into this slice's template here."""
        name, ns = name_of(nb), namespace_of(nb)
        selectors = dict(pod_spec.get("nodeSelector") or {})
        selectors.update(tpu.node_selectors())
        pod_spec["nodeSelector"] = selectors

        resources = dict(main.get("resources") or {})
        for kind in ("requests", "limits"):
            bucket = dict(resources.get(kind) or {})
            bucket.update(tpu.resource_requests())
            resources[kind] = bucket
        main["resources"] = resources

        headless = name + self.opts.workers_service_suffix
        if multi and multi.multi:
            all_hostnames = multi.worker_hostnames(
                name, headless, ns, self.opts.cluster_domain
            )
            static_env = multi.worker_env(slice_id, 0, all_hostnames)
            template_annotations[nbapi.TPU_SLICE_ID_ANNOTATION] = str(slice_id)
            template_annotations[nbapi.TPU_NUM_SLICES_ANNOTATION] = str(
                multi.num_slices)
        else:
            hostnames = tpu.worker_hostnames(
                name, headless, ns, self.opts.cluster_domain
            )
            static_env = tpu.worker_env(0, hostnames)
        # Per-worker keys are the webhook's job; don't bake worker 0's values
        # into every pod of a multi-host slice.
        for per_worker in ("TPU_WORKER_ID", "JAX_PROCESS_ID"):
            static_env.pop(per_worker, None)
        env = [dict(e) for e in main.get("env", [])]
        have = {e.get("name") for e in env}
        for k, v in static_env.items():
            if k not in have:
                env.append({"name": k, "value": v})
        # Downward-API fallback for the per-worker keys: the STS controller
        # (≥1.28) stamps the ordinal on the pod-index label, so even if the
        # admission webhook is unavailable the workers still get correct
        # ids and the slice can bootstrap its mesh (the webhook, when up,
        # overrides these with plain values). In multislice mode the global
        # JAX_PROCESS_ID = sliceId·hosts + ordinal can NOT come from the
        # pod index — only the webhook computes it; a wrong id would
        # silently collide process ranks, so none is better than wrong.
        fallback_keys = (
            ("TPU_WORKER_ID",) if multi and multi.multi
            else ("TPU_WORKER_ID", "JAX_PROCESS_ID")
        )
        for per_worker in fallback_keys:
            if per_worker not in have:
                env.append({
                    "name": per_worker,
                    "valueFrom": {"fieldRef": {
                        "fieldPath":
                            "metadata.labels['apps.kubernetes.io/pod-index']"
                    }},
                })
        main["env"] = env

        ports = list(main.get("ports", []))
        if not any(p.get("containerPort") == JAX_COORDINATOR_PORT for p in ports):
            ports.append(
                {"containerPort": JAX_COORDINATOR_PORT, "name": "jax-coord",
                 "protocol": "TCP"}
            )
        main["ports"] = ports

        template_annotations[TPU_ACCELERATOR_ANNOTATION] = tpu.accelerator.name
        template_annotations[TPU_TOPOLOGY_ANNOTATION] = tpu.topology_str
        # Label (not annotation) so the per-worker env webhook registration
        # can scope a failurePolicy:Fail entry with an objectSelector —
        # admission must hard-fail for slice pods, stay best-effort for the
        # convenience PodDefault path (manifests/base/webhook.yaml).
        template_labels[nbapi.TPU_SLICE_LABEL] = "true"

    def _mount_ca_bundle(self, pod_spec: dict, containers: list[dict]) -> None:
        """Mount the mirrored CA ConfigMap into every container (reference:
        CheckAndMountCACertBundle, notebook_webhook.go:371-417)."""
        volumes = list(pod_spec.get("volumes") or [])
        if not any(v.get("name") == "trusted-ca" for v in volumes):
            volumes.append(
                {
                    "name": "trusted-ca",
                    "configMap": {
                        "name": CA_BUNDLE_CONFIGMAP,
                        "items": [
                            {"key": CA_BUNDLE_KEY, "path": CA_BUNDLE_KEY}
                        ],
                    },
                }
            )
        pod_spec["volumes"] = volumes
        for ctr in containers:
            mounts = list(ctr.get("volumeMounts") or [])
            if not any(m.get("name") == "trusted-ca" for m in mounts):
                mounts.append(
                    {
                        "name": "trusted-ca",
                        "mountPath": self.opts.ca_bundle_mount_path,
                        "subPath": CA_BUNDLE_KEY,
                        "readOnly": True,
                    }
                )
            ctr["volumeMounts"] = mounts

    async def _mirror_ca_bundle(self, nb: dict) -> None:
        """Copy the controller-namespace CA ConfigMap into the notebook's
        namespace (reference aggregates odh-trusted-ca-bundle,
        notebook_controller.go:253-353)."""
        source = await self.kube.get_or_none(
            "ConfigMap",
            self.opts.trusted_ca_configmap,
            self.opts.controller_namespace,
        )
        if source is None:
            return
        mirror = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {
                "name": CA_BUNDLE_CONFIGMAP,
                "namespace": namespace_of(nb),
            },
            "data": {
                CA_BUNDLE_KEY: (source.get("data") or {}).get(CA_BUNDLE_KEY, "")
                or "\n".join((source.get("data") or {}).values()),
            },
        }
        await reconcile_child(self.kube, mirror, copier=_copy_configmap_data)

    def _auth_proxy_container(self, name: str, ns: str) -> dict:
        """Auth sidecar for meshless clusters (reference oauth-proxy,
        notebook_oauth.go:49-300): proxies the serving port and enforces
        the gateway's identity header."""
        return {
            "name": "auth-proxy",
            "image": self.opts.auth_proxy_image,
            "args": [
                f"--upstream=http://localhost:{self.opts.default_serving_port}",
                f"--http-address=0.0.0.0:{self.opts.auth_proxy_port}",
                f"--prefix=/notebook/{ns}/{name}/",
            ],
            "ports": [
                {"containerPort": self.opts.auth_proxy_port, "name": "auth-proxy",
                 "protocol": "TCP"}
            ],
            "resources": {
                "requests": {"cpu": "100m", "memory": "64Mi"},
                "limits": {"cpu": "100m", "memory": "64Mi"},
            },
        }

    def generate_network_policy(self, nb: dict, tpu: TpuSlice | None) -> dict:
        """Per-notebook NetworkPolicy (reference ReconcileAllNetworkPolicies,
        notebook_network.go:42-211: controller-namespace-only ingress).
        TPU-native addition: slice workers must reach each other for the
        jax.distributed/DCN bootstrap, so intra-slice traffic is allowed."""
        name, ns = name_of(nb), namespace_of(nb)
        ingress: list[dict] = [
            {
                "from": [
                    {
                        "namespaceSelector": {
                            "matchLabels": {"kubernetes.io/metadata.name": gw}
                        }
                    }
                    for gw in self.opts.gateway_namespaces
                ],
                "ports": [
                    {"port": self._serving_target_port(nb), "protocol": "TCP"}
                ],
            }
        ]
        if tpu and tpu.multi_host:
            ingress.append(
                {
                    "from": [
                        {
                            "podSelector": {
                                "matchLabels": {nbapi.NOTEBOOK_NAME_LABEL: name}
                            }
                        }
                    ]
                }
            )
        return {
            "apiVersion": "networking.k8s.io/v1",
            "kind": "NetworkPolicy",
            "metadata": {"name": f"notebook-{name}", "namespace": ns},
            "spec": {
                "podSelector": {
                    "matchLabels": {nbapi.NOTEBOOK_NAME_LABEL: name}
                },
                "policyTypes": ["Ingress"],
                "ingress": ingress,
            },
        }

    def _serving_target_port(self, nb: dict) -> int:
        annotations = get_meta(nb).get("annotations") or {}
        if (
            self.opts.auth_proxy_image
            and annotations.get(AUTH_PROXY_ANNOTATION) == "true"
        ):
            return self.opts.auth_proxy_port
        return self.opts.default_serving_port

    def generate_service(self, nb: dict, multi=None) -> dict:
        """HTTP entrypoint. Reference: generateService (:486-513) — port 80 →
        named port ``http-<name>``. Multi-host twist: route to worker 0 only
        (the Jupyter server runs on worker 0; other workers are compute
        peers), via the stable STS pod-name label. In multislice mode the
        server pod is slice 0's worker 0 (``<name>-s0-0``)."""
        name, ns = name_of(nb), namespace_of(nb)
        sts0 = multi.slice_sts_name(name, 0) if multi else name
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "type": "ClusterIP",
                "selector": {STS_LABEL: sts0, POD_NAME_LABEL: f"{sts0}-0"},
                "ports": [
                    {
                        "name": f"http-{name}"[:63],
                        "port": nbapi.SERVICE_PORT,
                        "targetPort": self._serving_target_port(nb),
                        "protocol": "TCP",
                    }
                ],
            },
        }

    def generate_headless_service(self, nb: dict, multi=None) -> dict:
        """Worker discovery for multi-host slices — the DNS backing
        ``TPU_WORKER_HOSTNAMES`` (SURVEY.md §2.4 row 2). In multislice mode
        one headless Service spans every slice's pods (selected by the
        notebook-name label), so cross-slice DCN peers resolve too."""
        name, ns = name_of(nb), namespace_of(nb)
        selector = (
            {nbapi.NOTEBOOK_NAME_LABEL: name} if multi and multi.multi
            else {STS_LABEL: name}
        )
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name + self.opts.workers_service_suffix,
                         "namespace": ns},
            "spec": {
                "clusterIP": "None",
                "publishNotReadyAddresses": True,
                "selector": selector,
                "ports": [
                    {"name": "jax-coord", "port": JAX_COORDINATOR_PORT,
                     "protocol": "TCP"}
                ],
            },
        }

    def generate_virtual_service(self, nb: dict) -> dict:
        """Reference: generateVirtualService (:519-619) — URL contract
        ``/notebook/<ns>/<name>/``, honoring the rewrite/header annotations
        the vscode-like and rstudio-like images rely on."""
        name, ns = name_of(nb), namespace_of(nb)
        annotations = get_meta(nb).get("annotations") or {}
        prefix = f"/notebook/{ns}/{name}/"
        http: dict = {
            "match": [{"uri": {"prefix": prefix}}],
            "route": [
                {
                    "destination": {
                        "host": f"{name}.{ns}.svc.{self.opts.cluster_domain}",
                        "port": {"number": nbapi.SERVICE_PORT},
                    }
                }
            ],
            "timeout": "300s",
        }
        rewrite = annotations.get(nbapi.ANNOTATION_REWRITE_URI)
        if rewrite:
            http["rewrite"] = {"uri": rewrite}
        headers = annotations.get(nbapi.ANNOTATION_HEADERS_REQUEST_SET)
        if headers:
            import json

            try:
                http["headers"] = {"request": {"set": json.loads(headers)}}
            except ValueError:
                log.warning("notebook %s/%s: bad %s annotation", ns, name,
                            nbapi.ANNOTATION_HEADERS_REQUEST_SET)
        return {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"notebook-{ns}-{name}", "namespace": ns},
            "spec": {
                "hosts": [self.opts.istio_host],
                "gateways": [self.opts.istio_gateway],
                "http": [http],
            },
        }

    async def _gc_extra_slices(self, nb: dict, ms) -> None:
        """Delete slice StatefulSets beyond the current numSlices (scale-in:
        numSlices 4 → 2 must not leave s2/s3 running and burning chips).
        Owned children come from the StatefulSet informer's owner index
        (Manager auto-registers it for every ``owns=`` kind) — a stale
        cache at worst defers the GC to the next STS event; the apiserver
        LIST remains only as the bare-reconciler (no manager) fallback."""
        name, ns = name_of(nb), namespace_of(nb)
        expected = {ms.slice_sts_name(name, j) for j in range(ms.num_slices)}
        if (self._sts_informer is not None
                and self._sts_informer.has_indexer(OWNER_INDEX)):
            owned = self._sts_informer.by_index(OWNER_INDEX, uid_of(nb))
        else:
            try:
                owned = await self.kube.list(
                    "StatefulSet", ns,
                    label_selector={
                        "matchLabels": {nbapi.NOTEBOOK_NAME_LABEL: name}},
                )
            except ApiError as exc:
                log.debug("slice-GC LIST for %s/%s failed (retried "
                          "next reconcile): %s", ns, name, exc)
                return
        for sts in owned:
            if name_of(sts) not in expected:
                try:
                    await self.kube.delete("StatefulSet", name_of(sts), ns)
                except NotFound:
                    pass

    # ---- failure semantics ------------------------------------------------------

    async def _worker_pods(self, nb: dict) -> list[dict]:
        """This notebook's worker pods, from the Pod informer's label index
        — O(workers), not O(cluster pods), and zero apiserver LISTs in the
        steady state. The returned dicts are the informer's cached objects:
        read-only by contract. Bare-reconciler tests (no manager) fall
        back to the apiserver LIST."""
        if (self._pod_informer is not None
                and self._pod_informer.has_indexer(NB_POD_INDEX)):
            return self._pod_informer.by_index(
                NB_POD_INDEX, (namespace_of(nb), name_of(nb)))
        return await self.kube.list(
            "Pod",
            namespace_of(nb),
            label_selector={"matchLabels": {nbapi.NOTEBOOK_NAME_LABEL: name_of(nb)}},
        )

    async def _restart_broken_slice(
        self, nb: dict, ms, pods: list[dict] | None = None
    ) -> Result | None:
        """All-or-nothing slice recovery (the hard part the reference never
        faced with single-pod notebooks, SURVEY.md §7.5): one dead worker
        breaks the whole ICI mesh, so every worker restarts together. In
        multislice mode this spans every slice — all hosts are one
        jax.distributed job, so a broken slice stalls them all.

        Restarts back off exponentially (attempt counter + last-restart
        timestamp persisted as CR annotations, so the damping survives a
        controller restart): a main container that crashes at startup
        would otherwise produce a hot delete→recreate→crash loop. The
        counter resets once every worker reports Ready — a slice that was
        stable and then faults gets a fresh budget. Protocol style after
        the reference's retry/backoff lock removal
        (odh notebook_controller.go:117-145)."""
        tpu = ms.slice if ms else None
        gang = (tpu and tpu.multi_host) or (ms and ms.multi)
        if not gang or nbapi.is_stopped(nb):
            return None
        total_hosts = ms.total_hosts
        ns, name = namespace_of(nb), name_of(nb)
        if pods is None:
            pods = await self._worker_pods(nb)
        main_name = _main_container_name(nb)
        # A disrupted-but-still-running worker (spot preemption, node
        # drain) dooms the slice just as surely as a crashed one: restart
        # all workers now so the replacement gang schedules together
        # instead of limping until the kubelet finishes the eviction.
        disrupted = {
            name_of(p): reason for p in pods
            if (reason := _pod_disruption(p)) is not None
        }
        broken = [
            p for p in pods
            if name_of(p) in disrupted or _worker_is_broken(p, main_name)
        ]
        annotations = annotations_of(nb)
        try:  # annotations are user-writable; garbage must not wedge reconcile
            attempts = int(annotations.get(SLICE_RESTART_ATTEMPTS_ANNOTATION) or 0)
        except ValueError:
            attempts = 0

        if not broken:
            all_ready = len(pods) == total_hosts and all(
                any(c.get("type") == "Ready" and c.get("status") == "True"
                    for c in deep_get(p, "status", "conditions", default=[]))
                for p in pods
            )
            if attempts and all_ready:
                await self.kube.patch(
                    "Notebook", name,
                    {"metadata": {"annotations": {
                        SLICE_RESTART_ATTEMPTS_ANNOTATION: None,
                        SLICE_RESTART_AT_ANNOTATION: None,
                    }}}, ns)
            return None

        if attempts:
            delay = min(
                SLICE_RESTART_BASE_SECONDS * (2 ** (attempts - 1)),
                SLICE_RESTART_MAX_SECONDS,
            )
            try:
                last = float(annotations.get(SLICE_RESTART_AT_ANNOTATION) or 0.0)
            except ValueError:
                last = 0.0
            remaining = delay - (self._now() - last)
            if remaining > 0:
                return Result(requeue_after=remaining)

        names = ", ".join(sorted(name_of(p) for p in broken))
        if disrupted:
            why = ", ".join(
                f"{n} ({r})" for n, r in sorted(disrupted.items()))
            detail = f"Worker(s) {why} disrupted"
            # Don't let a concurrent crash hide behind the preemption:
            # name the workers that failed on their own too.
            crashed = sorted(
                name_of(p) for p in broken if name_of(p) not in disrupted)
            if crashed:
                detail += f"; worker(s) {', '.join(crashed)} failed"
            reason = "SlicePreempted"
        else:
            reason, detail = "SliceRestart", f"Worker(s) {names} failed"
        await self.recorder.event(
            nb,
            "Warning",
            reason,
            f"{detail}; restarting all {total_hosts} workers "
            f"(TPU slices restart atomically; attempt {attempts + 1})",
        )
        await self.kube.patch(
            "Notebook", name,
            {"metadata": {"annotations": {
                SLICE_RESTART_ATTEMPTS_ANNOTATION: str(attempts + 1),
                SLICE_RESTART_AT_ANNOTATION: repr(self._now()),
            }}}, ns)
        for p in pods:
            try:
                await self.kube.delete("Pod", name_of(p), namespace_of(p))
            except NotFound:
                pass
        return None

    async def _check_maintenance(
        self, nb: dict, pods: list[dict] | None = None
    ) -> None:
        """Mirror impending node maintenance onto the CR. TPU hosts get a
        taint (NotebookOptions.maintenance_taints; GKE graceful node
        termination) ahead of a maintenance event — the one advance
        warning a slice gets before it goes down. The controller stamps
        the affected node list into MAINTENANCE_ANNOTATION and emits a
        Warning event, so the UI (and in-notebook tooling watching its
        own CR) can checkpoint to the workspace PVC / GCS while the
        workers are still up. No reference counterpart: single-pod CUDA
        notebooks never had a gang to lose (SURVEY.md §7.5 failure
        semantics)."""
        if not self.opts.maintenance_taints:
            return
        if pods is None:
            pods = await self._worker_pods(nb)
        node_names = {deep_get(p, "spec", "nodeName") for p in pods}
        node_names.discard(None)
        if not node_names:
            # No scheduled workers right now (slice restarting, stopped,
            # or pods still Pending) — hold the last-known state rather
            # than emitting a false MaintenanceCleared while the taint
            # may still be there; the next reconcile with placed pods
            # recomputes it.
            return
        if self._node_informer is not None:
            nodes = self._node_informer.items()
        else:  # bare-reconciler unit tests without a manager
            nodes = await self.kube.list("Node")
        pending = sorted(
            name_of(n) for n in nodes
            if name_of(n) in node_names and any(
                t.get("key") in self.opts.maintenance_taints
                for t in deep_get(n, "spec", "taints", default=[])
            )
        )
        current = annotations_of(nb).get(MAINTENANCE_ANNOTATION)
        want = ",".join(pending) if pending else None
        if want == current:
            return
        await self.kube.patch(
            "Notebook", name_of(nb),
            {"metadata": {"annotations": {MAINTENANCE_ANNOTATION: want}}},
            namespace_of(nb),
        )
        if want:
            await self.recorder.event(
                nb, "Warning", "MaintenancePending",
                f"Node(s) {want} hosting this notebook's TPU workers are "
                "scheduled for maintenance; checkpoint now — the slice "
                "restarts when they go down",
            )
        else:
            await self.recorder.event(
                nb, "Normal", "MaintenanceCleared",
                "Impending-maintenance taints cleared from all worker nodes",
            )

    # ---- status ----------------------------------------------------------------

    async def _mirror_events(
        self, nb: dict, worker_pods: list[dict] | None = None
    ) -> None:
        """Re-emit worker pod events onto the CR so the UI can surface them
        (reference: notebook_controller.go:94-123 event mapping — that
        design is watch-driven, and so is this one: the manager's Event
        informer feeds both the reconcile queue and this cache, so status
        churn costs zero apiserver LISTs per reconcile)."""
        ns, name = namespace_of(nb), name_of(nb)
        if worker_pods is None:
            worker_pods = await self._worker_pods(nb)
        pods = {name_of(p) for p in worker_pods}
        if (self._event_informer is not None
                and self._event_informer.has_indexer(EVENT_POD_INDEX)):
            # O(workers) index lookups instead of scanning every Event in
            # the cache per reconcile.
            events = []
            for pod_name in pods:
                events.extend(self._event_informer.by_index(
                    EVENT_POD_INDEX, (ns, pod_name)))
        elif self._event_informer is not None:
            events = [e for e in self._event_informer.items()
                      if namespace_of(e) == ns]
        else:
            try:
                events = await self.kube.list("Event", ns)
            except ApiError as exc:
                log.debug("event-mirror LIST for %s/%s failed (mirror "
                          "catches up next reconcile): %s", ns, name,
                          exc)
                return
        seen = self._mirrored.setdefault((ns, name), {})
        for ev in events:
            involved = ev.get("involvedObject") or {}
            if involved.get("kind") != "Pod" or involved.get("name") not in pods:
                continue
            ev_name, count = name_of(ev), ev.get("count", 1)
            if seen.get(ev_name) == count:
                continue
            seen[ev_name] = count
            await self.recorder.event(
                nb,
                ev.get("type", "Normal"),
                ev.get("reason", ""),
                f"[pod {involved['name']}] {ev.get('message', '')}",
            )

    async def _update_status(self, nb: dict, ms, *,
                             capacity_pending: bool = False,
                             admission=None, pods: list[dict] | None = None,
                             warm: dict | None = None) -> None:
        """Mirror STS/pod state into the CR (reference :228-349): readyReplicas,
        containerState of worker 0's server container, condition history.
        Multislice: readyReplicas sums across every slice's StatefulSet.
        ``capacity_pending``: queued provisioning hasn't delivered yet —
        surfaced via status.tpu so the UI can say why nothing runs.
        ``admission``: the fleet scheduler's verdict — surfaced as
        ``status.scheduler`` (queue position, waiting chips, preemption
        reason) plus a Queued/Admitted/Preempted condition on each
        transition, which is what JWA's status machine and kubectl
        watchers key off."""
        tpu = ms.slice if ms else None
        ns, name = namespace_of(nb), name_of(nb)
        # Informer cache first: a 64-slice notebook would otherwise pay
        # 64 apiserver GETs per reconcile. The controller owns
        # StatefulSets, so this informer is always running under the
        # manager; staleness self-corrects on the next STS event. The
        # bare-reconciler fallback GETs (per-slice STS + worker-0 pod)
        # are independent reads — overlap them so even the cold path is
        # one RTT deep, not num_slices + 1.
        warm_state = (warm or {}).get("state")
        claimed = warm_state == "claimed"
        pod0_name = f"{ms.slice_sts_name(name, 0) if ms else name}-0"
        if claimed:
            # Warm-claimed notebooks own no StatefulSet — the adopted
            # pod IS the slice; readiness and container state come from
            # it directly (it keeps its warm-pool NAME, so the
            # <sts0>-0 lookup below would miss it).
            if pods is None:
                pods = await self._worker_pods(nb)
            ready = sum(
                1 for p in pods
                if any(c.get("type") == "Ready"
                       and c.get("status") == "True"
                       for c in deep_get(p, "status", "conditions",
                                         default=[])))
            pod0 = (warm or {}).get("pod") or (pods[0] if pods else None)
        else:
            *stss, pod0 = await overlap(
                *[self._live_sts(
                    ms.slice_sts_name(name, j) if ms else name, ns)
                  for j in range(ms.num_slices if ms else 1)],
                (None if self._pod_informer is not None
                 else self.kube.get_or_none("Pod", pod0_name, ns)),
            )
            ready = sum(
                deep_get(sts or {}, "status", "readyReplicas", default=0)
                or 0 for sts in stss)

        container_state: dict = {}
        # Watch cache first (staleness self-corrects on the pod's next
        # event, which re-enqueues this notebook anyway).
        if self._pod_informer is not None and not claimed:
            pod0 = self._pod_informer.get(pod0_name, ns)
        if pod0:
            main_name = _main_container_name(nb)
            statuses = deep_get(pod0, "status", "containerStatuses", default=[])
            for cs in statuses:
                if cs.get("name") == main_name:
                    container_state = cs.get("state", {}) or {}
                    break
            else:
                if statuses:
                    container_state = statuses[0].get("state", {}) or {}

        want_hosts = 0 if nbapi.is_stopped(nb) else (
            ms.total_hosts if ms else 1)
        conditions = list(deep_get(nb, "status", "conditions", default=[]))
        # Quarantine self-heal: reaching the status phase proves this key
        # is reconciling again (a quarantined key never runs), so any
        # Degraded=True the manager stamped flips to False here — the one
        # writer that cannot race the quarantine, because a reconcile that
        # is still failing dies before this line.
        conditions = [
            {**c, "status": "False"}
            if c.get("type") == "Degraded" and c.get("status") == "True"
            else c
            for c in conditions
        ]
        # Scheduler transitions and container transitions interleave in
        # one history, so each family dedups against ITS most recent
        # entry — comparing against the list head would re-insert an
        # unchanged container condition after every scheduler insert
        # (and on every reconcile thereafter), churning real history
        # out of the 8-entry cap.
        prev_head = conditions[0].get("type") if conditions else None
        prev_container = next(
            (c.get("type") for c in conditions
             if c.get("type") in _CONTAINER_CONDITION_TYPES), None)
        sched_status = _scheduler_status_block(admission)
        prev_sched_state = deep_get(nb, "status", "scheduler", "state")
        if (sched_status is not None
                and sched_status["state"] != prev_sched_state
                and prev_head != sched_status["state"]):
            conditions.insert(0, _scheduler_condition(sched_status))
        new_cond = _condition_from_state(container_state)
        if new_cond and new_cond["type"] != prev_container:
            conditions.insert(0, new_cond)
        # Migration lifecycle (kubeflow_tpu/migration): the block mirrors
        # the drain/checkpoint annotations; a NEW committed checkpoint
        # (checkpointedAt changed) earns one `Checkpointed` condition —
        # its own dedup family, keyed on the recorded ack time, so
        # neither scheduler nor container churn re-inserts it.
        mig_status = (_migration_status_block(nb, ready=ready,
                                              want_hosts=want_hosts)
                      if self.opts.enable_migration else None)
        prev_ckpt = deep_get(nb, "status", "migration", "checkpointedAt")
        if (mig_status is not None and mig_status.get("checkpointedAt")
                and mig_status["checkpointedAt"] != prev_ckpt):
            conditions.insert(0, _checkpointed_condition(mig_status))
        conditions = conditions[:8]

        # Warm-pool surface (JWA contract, web/common/status.py): claimed
        # carries the pod + the claim latency ("Starting from warm pool
        # (claimed in Xs)"); warming carries the pool's replenish
        # progress ("Warming pool replenishing (k/n ready)"). Same
        # merge-patch discipline as capacityPending.
        warm_block: dict | None = None
        if claimed:
            warm_block = {"claimed": True}
            wpod = (warm or {}).get("pod")
            if wpod is not None:
                warm_block["pod"] = name_of(wpod)
            claimed_in = (warm or {}).get("claimed_in")
            if claimed_in is None:
                claimed_in = annotations_of(nb).get(
                    nbapi.WARM_CLAIMED_IN_ANNOTATION)
            try:
                warm_block["claimedInSec"] = float(claimed_in)
            except (TypeError, ValueError):
                pass
        elif warm_state == "warming" and (warm or {}).get("replenishing"):
            warm_block = {"replenishing": warm["replenishing"]}
        telemetry_block = self._fold_telemetry(nb, (ns, name))
        status = {
            "readyReplicas": ready,
            "containerState": container_state,
            "conditions": conditions,
            # TPU-native extras (not in the reference): slice rollup for the UI.
            "tpu": {
                "hosts": want_hosts,
                "readyHosts": ready,
                "chips": ms.num_chips if ms else 0,
                "slices": ms.num_slices if ms else 0,
                # Merge-patch semantics: flag present → True; flag stale
                # on the live object → explicit None deletes it; neither
                # → omit (no churn).
                **({"capacityPending": True} if capacity_pending else
                   ({"capacityPending": None}
                    if deep_get(nb, "status", "tpu", "capacityPending")
                    else {})),
                **({"warmPool": warm_block} if warm_block is not None else
                   ({"warmPool": None}
                    if deep_get(nb, "status", "tpu", "warmPool") is not None
                    else {})),
                **({"telemetry": telemetry_block}
                   if telemetry_block is not None else
                   ({"telemetry": None}
                    if deep_get(nb, "status", "tpu", "telemetry") is not None
                    else {})),
            },
        }
        # Same merge-patch discipline as capacityPending: present → set;
        # stale on the live object → explicit None deletes it; neither →
        # omit (no churn for CPU-only / scheduler-off notebooks).
        if sched_status is not None:
            status["scheduler"] = sched_status
        elif deep_get(nb, "status", "scheduler") is not None:
            status["scheduler"] = None
        if mig_status is not None:
            status["migration"] = mig_status
        elif deep_get(nb, "status", "migration") is not None:
            status["migration"] = None
        # Write elision. Two gates:
        # - live status equals the computed one (covers the cold start —
        #   controller restart with an already-converged CR);
        # - per-key last-written hash PAIR: (computed-status hash, hash of
        #   the status the apiserver actually stored for it). The computed
        #   side alone is not enough — merge-patch delete markers
        #   (capacityPending: None) make computed != stored forever, which
        #   would hot-loop the patch; the stored side keeps external drift
        #   repairable — a status someone else rewrote hashes differently
        #   from what we recorded, so the pair misses and we re-patch.
        h = state_hash(status)
        key = (ns, name)
        live_status = deep_get(nb, "status")
        if (live_status == status
                or self._last_status.get(key) == (h, state_hash(live_status))):
            self.m_status_elided.inc()
        else:
            try:
                stored = await self.kube.patch(
                    "Notebook", name, {"status": status}, ns, subresource="status"
                )
                self._last_status[key] = (h, state_hash(stored.get("status")))
            except Conflict:
                # A conflicting status write means this reconcile ran on a
                # stale read — re-raise so the workqueue retries with a
                # fresh one. Swallowing (the old behavior, exposed by the
                # conflict-storm test) left the CR's status stale until
                # the next unrelated event.
                raise
            except ApiError as exc:
                # Non-conflict write failures stay best-effort (the 409
                # path above re-raises): status refreshes on the next
                # event, and failing the whole reconcile for a status
                # tail write would churn healthy children.
                log.debug("status write for %s/%s failed: %s", ns, name,
                          exc)
        stopped = nbapi.is_stopped(nb)
        self._set_gauge_contribution(
            ns, name,
            # Parked: not running even while old pods drain, and its chip
            # demand is released.
            running=1 if (not stopped and ready and ready >= want_hosts)
            else 0,
            chips=0 if stopped else (ms.num_chips if ms else 0),
        )
        await self._record_timeline(nb, ms, sched_status, mig_status,
                                    ready=ready, want_hosts=want_hosts,
                                    warm=warm_state or "")

    async def _record_timeline(self, nb: dict, ms, sched_status,
                               mig_status, *, ready: int,
                               want_hosts: int, warm: str = "") -> None:
        """Fold this reconcile's derived state into the durable lifecycle
        timeline (runtime/timeline.py) and, on a NEW Ready transition,
        score the startup episode against the time-to-ready SLO. One
        record per reconcile; a no-transition call costs a dict lookup."""
        if self._timeline is None:
            return
        sched = sched_status or {}
        mig = mig_status or {}
        state = timeline_mod.derive_lifecycle(
            sched_state=sched.get("state"),
            mig_state=mig.get("state"),
            stopped=nbapi.is_stopped(nb),
            ready=ready, want_hosts=want_hosts,
            reclaimed=sched.get("reclaimed", ""),
            warm=warm)
        reason = (sched.get("reclaimed") or sched.get("reason")
                  or mig.get("reason") or "")
        shape = (f"{ms.num_slices}x{ms.slice.accelerator.name}:"
                 f"{ms.slice.topology_str}" if ms else "")
        key = (namespace_of(nb), name_of(nb))
        if warm == "claimed" and state == timeline_mod.READY:
            # The claim is its own transition (ISSUE 14): a warm pod is
            # often Ready within the claiming reconcile, which would
            # otherwise journal straight to Ready — and the episode
            # could no longer attribute warm vs cold starts. Record
            # Claimed first; dedup in record() keeps later reconciles
            # from repeating it.
            prior = self._timeline.entries(
                key, annotations=annotations_of(nb))
            if not prior or prior[-1]["state"] not in (
                    timeline_mod.CLAIMED, timeline_mod.READY):
                await self._timeline.record(
                    key, timeline_mod.CLAIMED, at=self._now(),
                    reason="warm-pool", trace_id=current_trace_id(),
                    shape=shape, annotations=annotations_of(nb))
        entries = await self._timeline.record(
            key, state, at=self._now(), reason=reason,
            trace_id=current_trace_id(), shape=shape,
            annotations=annotations_of(nb))
        if entries is not None and state == timeline_mod.READY:
            ttr = timeline_mod.time_to_ready(entries)
            if ttr is not None:
                slo.observe("notebook_time_to_ready", ttr, key=key,
                            trace_id=current_trace_id())

    def _fold_telemetry(self, nb: dict, key: tuple) -> dict | None:
        """Decode the SDK's telemetry annotation into the
        ``status.tpu.telemetry`` block and fan the window out — once per
        publish seq — to the SLO engine (``training_step``), the
        manager's Prometheus mirror, and the scheduler's efficiency
        ledger. Returns None (delete the block) when the annotation is
        absent or corrupt; a STALE entry keeps the block with
        ``stale: true`` so JWA can degrade its message rather than
        silently showing week-old MFU as live."""
        entry = telemetry_pub.decode(annotations_of(nb))
        if entry is None:
            self._telemetry.pop(key, None)
            return None
        now = self._now()
        stale = telemetry_pub.is_stale(entry, now)
        self._telemetry[key] = entry
        if not stale and entry["seq"] > self._telemetry_seq.get(key, 0):
            self._telemetry_seq[key] = entry["seq"]
            step_sec = entry.get("step_sec")
            if step_sec is not None:
                slo.observe("training_step", float(step_sec), key=key,
                            trace_id=current_trace_id())
            telemetry_pub.publish_metrics(entry)
            if self._scheduler is not None:
                self._scheduler.note_telemetry(
                    key, entry.get("family") or "unknown",
                    entry.get("mfu"))
        block = {
            "family": entry.get("family") or "unknown",
            "step": entry.get("step", 0),
            "at": entry.get("at"),
            "seq": entry.get("seq"),
        }
        for wire, status_key in (("mfu", "mfu"), ("step_sec", "stepSec"),
                                 ("overlap", "overlap"),
                                 ("tok_s", "tokensPerSec"),
                                 ("compile_sec", "compileSec"),
                                 ("hbm", "hbmBytes"), ("basis", "basis")):
            if entry.get(wire) is not None:
                block[status_key] = entry[wire]
        if stale:
            block["stale"] = True
        return block

    def telemetry_debug_info(self) -> dict:
        """The ``/debug/telemetry`` payload: every notebook's latest
        decoded telemetry entry with live staleness."""
        now = self._now()
        return {
            "stale_after_seconds": telemetry_pub.stale_after_seconds(),
            "notebooks": {
                f"{ns}/{name}": {
                    **entry,
                    "stale": telemetry_pub.is_stale(entry, now),
                    "age_sec": round(now - float(entry.get("at", 0.0)), 1),
                }
                for (ns, name), entry in sorted(self._telemetry.items())
            },
        }

    def _set_gauge_contribution(
        self, ns: str | None, name: str, running: int, chips: int
    ) -> None:
        """Fold one notebook's (running, chips) contribution into the
        per-namespace gauges, incrementally — the previous recompute from
        the informer cache was O(notebooks-in-ns) per reconcile, an O(N²)
        scan across a namespace coming up. Set-per-notebook would be
        wrong the moment a namespace holds two notebooks; per-key deltas
        against a running total are both O(1) and aggregate-correct."""
        old = self._gauge_contrib.get((ns, name), (0, 0))
        if (running, chips) == old:
            return
        if (running, chips) == (0, 0):
            self._gauge_contrib.pop((ns, name), None)
        else:
            self._gauge_contrib[(ns, name)] = (running, chips)
        totals = self._ns_totals.setdefault(ns, [0, 0])
        totals[0] += running - old[0]
        totals[1] += chips - old[1]
        self.m_running.labels(namespace=ns or "").set(totals[0])
        self.m_chips.labels(namespace=ns or "").set(totals[1])


_soonest = soonest  # shared helper (runtime/manager.py), old local name


def _main_container_name(nb: dict) -> str:
    """Name of the TPU worker (server) container — containers[0] of the CR's
    PodSpec by the reference contract, falling back to the CR name."""
    containers = deep_get(nb, "spec", "template", "spec", "containers", default=[])
    return (containers[0].get("name") if containers else None) or name_of(nb)


def _pod_disruption(pod: dict) -> str | None:
    """Classify a worker that is going away through no fault of its own:
    kubelet/scheduler/taint-manager set a ``DisruptionTarget`` condition
    (reason PreemptionByScheduler, DeletionByTaintManager,
    EvictionByEvictionAPI, TerminationByKubelet) on such pods. This is the
    upstream, vendor-neutral signal, so spot-TPU preemptions on GKE and
    plain node drains classify identically. Returns the reason, or None."""
    for c in deep_get(pod, "status", "conditions", default=[]):
        if c.get("type") == "DisruptionTarget" and c.get("status") == "True":
            return c.get("reason") or "Disrupted"
    return None


def _worker_is_broken(pod: dict, main_container: str) -> bool:
    """A worker whose TPU container died — even once, even if kubelet already
    restarted it in place — has broken the slice's ICI mesh: the restarted
    process cannot rejoin (libtpu wires the mesh once at init), so the
    healthy-looking peers are wedged. With restartPolicy Always the pod
    rarely shows phase=Failed or a current terminated state; the durable
    signals are restartCount > 0, a lastState.terminated, or
    CrashLoopBackOff. Slice-atomic deletion resets restartCount to 0 on the
    replacement pods, so this self-clears.

    Scoped to the *main* (TPU worker) container only: a sidecar restart
    (auth-proxy OOM, say) does not break the ICI mesh, and counting it
    would wedge the slice in a permanent restart loop — the main
    container's statuses never clear the sidecar's restartCount."""
    if deep_get(pod, "status", "phase") == "Failed":
        return True
    for cs in deep_get(pod, "status", "containerStatuses", default=[]):
        if cs.get("name") != main_container:
            continue
        if cs.get("restartCount", 0) > 0:
            return True
        if deep_get(cs, "state", "terminated", "exitCode") not in (None, 0):
            return True
        if deep_get(cs, "lastState", "terminated") is not None:
            return True
        if deep_get(cs, "state", "waiting", "reason") in (
            "CrashLoopBackOff", "Error",
        ):
            return True
    return False


def _copy_configmap_data(desired: dict, live: dict) -> bool:
    if live.get("data") != desired.get("data"):
        live["data"] = desired.get("data", {})
        return True
    return False


def _scheduler_status_block(admission) -> dict | None:
    """Admission verdict → the ``status.scheduler`` block. The shape is
    the JWA contract (web/common/status.py): Queued carries position +
    waitingChips + reason — plus, elastic, the reclaim marker ("this
    gang is re-queued because its spot capacity was revoked / it is
    migrating pools") and any pending scale-up intent for its shape;
    Preempted/Draining carry the reason, Admitted is bare."""
    if admission is None:
        return None
    block: dict = {"state": admission.state}
    if admission.state == "Queued":
        block["position"] = admission.position
        block["waitingChips"] = admission.waiting_chips
        block["reason"] = admission.reason
        if getattr(admission, "reclaimed", ""):
            block["reclaimed"] = admission.reclaimed
        if getattr(admission, "scale_up_chips", 0):
            block["scaleUp"] = {
                "chips": admission.scale_up_chips,
                "pendingSeconds": admission.scale_up_pending_sec,
            }
    elif admission.state in ("Preempted", "Draining") and admission.reason:
        block["reason"] = admission.reason
    return block


def _migration_status_block(nb: dict, *, ready: int,
                            want_hosts: int) -> dict | None:
    """Drain/checkpoint annotations → the ``status.migration`` block
    (JWA contract: "Checkpointing before preemption…", "Suspended
    (checkpoint @ step N)", "Restoring from checkpoint"). None for the
    common untouched notebook, so steady-state status stays byte-
    identical to pre-migration."""
    annotations = annotations_of(nb)
    state = migration.derive_state(
        annotations, stopped=nbapi.is_stopped(nb),
        ready_hosts=ready, want_hosts=want_hosts)
    hint = migration.restore_hint(annotations)
    if (state == migration.RUNNING and hint is None
            and migration.drain_requested_at(annotations) is None):
        return None
    block: dict = {"state": state}
    if hint is not None:
        block["checkpointPath"] = hint[0]
        if hint[1] is not None:
            block["checkpointStep"] = hint[1]
    checkpointed = annotations.get(nbapi.CHECKPOINTED_AT_ANNOTATION)
    if checkpointed:
        block["checkpointedAt"] = checkpointed
    # Checkpoint fabric (ISSUE 16): the ack only promises a host-side
    # snapshot — surface the durable-commit trio so JWA can distinguish
    # "uploading (k/N chunks)" from committed, and flag a park whose
    # upload never landed.
    committed = annotations.get(nbapi.CHECKPOINT_COMMITTED_AT_ANNOTATION)
    if committed:
        block["committedAt"] = committed
    if migration.commit_dirty(annotations):
        block["commitDirty"] = True
    progress = migration.upload_progress(annotations)
    if progress is not None:
        block["uploadProgress"] = f"{progress[0]}/{progress[1]}"
    tier = migration.restore_tier(annotations)
    if tier:
        block["restoreTier"] = tier
    reason = migration.drain_reason(annotations)
    if reason:
        block["reason"] = reason
    return block


def _checkpointed_condition(mig_status: dict) -> dict:
    step = mig_status.get("checkpointStep")
    path = mig_status.get("checkpointPath", "")
    return {
        "type": "Checkpointed",
        "status": "True",
        "lastProbeTime": now_iso(),
        "reason": "Migration",
        "message": "checkpoint"
        + (f" @ step {step}" if step is not None else "")
        + (f" committed to {path}" if path else " committed"),
    }


def _scheduler_condition(sched_status: dict) -> dict:
    """One condition per scheduler-state transition
    (Queued → Admitted → Preempted), so the lifecycle is auditable from
    the CR alone (docs/multi-host.md lifecycle diagram)."""
    state = sched_status["state"]
    if state == "Queued":
        message = (f"position {sched_status.get('position', 0)}, waiting "
                   f"for {sched_status.get('waitingChips', 0)} TPU chips")
    elif state == "Preempted":
        message = (f"preempted ({sched_status.get('reason', 'reclaimed')}); "
                   "restart to re-queue")
    elif state == "Draining":
        message = (f"checkpointing before preemption "
                   f"({sched_status.get('reason', 'reclaimed')})")
    else:
        message = "admitted by the TPU fleet scheduler"
    return {
        "type": state,
        "status": "True",
        "lastProbeTime": now_iso(),
        "reason": "TpuFleetScheduler",
        "message": message,
    }


# The condition types _condition_from_state emits — the dedup in
# _update_status scans for the most recent one of these.
_CONTAINER_CONDITION_TYPES = frozenset({"Running", "Waiting", "Terminated"})


def _condition_from_state(state: dict) -> dict | None:
    """ContainerState → NotebookCondition (Running|Waiting|Terminated),
    reference notebook_types.go:46-63 + status mirroring."""
    now = now_iso()
    if "running" in state:
        return {"type": "Running", "status": "True", "lastProbeTime": now}
    if "waiting" in state:
        w = state["waiting"] or {}
        return {
            "type": "Waiting",
            "status": "True",
            "lastProbeTime": now,
            "reason": w.get("reason", ""),
            "message": w.get("message", ""),
        }
    if "terminated" in state:
        t = state["terminated"] or {}
        return {
            "type": "Terminated",
            "status": "True",
            "lastProbeTime": now,
            "reason": t.get("reason", ""),
            "message": t.get("message", ""),
        }
    return None


def provisioning_request_to_notebook(pr: dict) -> list[tuple]:
    """Map ProvisioningRequest events (Provisioned/Failed condition
    flips) back to the waiting Notebook via the notebook-name label."""
    name = (get_meta(pr).get("labels") or {}).get(nbapi.NOTEBOOK_NAME_LABEL)
    if not name:
        return []
    return [(namespace_of(pr), name)]


def pod_to_notebook(pod: dict) -> list[tuple]:
    """Map pod events to their Notebook (reference SetupWithManager watch by
    ``notebook-name`` label, notebook_controller.go:739-787)."""
    name = (get_meta(pod).get("labels") or {}).get(nbapi.NOTEBOOK_NAME_LABEL)
    if not name:
        return []
    return [(namespace_of(pod), name)]


def event_to_notebook(event: dict) -> list[tuple]:
    """Map pod Events to Notebooks by the pod-name → notebook-name convention
    (reference :685-700 strips the trailing ordinal)."""
    involved = event.get("involvedObject") or {}
    if involved.get("kind") != "Pod":
        return []
    pod_name = involved.get("name", "")
    base, _, ordinal = pod_name.rpartition("-")
    if not base or not ordinal.isdigit():
        return []
    return [(event.get("metadata", {}).get("namespace"), base)]


_SCHEDULER_FROM_ENV = object()  # sentinel: build from KFTPU_* env vars
_WARMPOOL_FROM_ENV = object()   # sentinel: build from KFTPU_WARM_POOLS


def setup_notebook_controller(
    mgr: Manager, options: NotebookOptions | None = None,
    *, scheduler=_SCHEDULER_FROM_ENV, warmpool=_WARMPOOL_FROM_ENV,
) -> NotebookReconciler:
    rec = NotebookReconciler(mgr.kube, options, registry=mgr.registry)
    # Durable lifecycle timelines + SLO feeds (runtime/{timeline,slo}.py)
    # ride the manager's shared recorder/engine.
    rec._timeline = getattr(mgr, "timeline", None)
    # /debug/telemetry data source (cmd/controller_manager.py): the
    # reconciler's per-notebook fold of the telemetry annotation.
    mgr.telemetry = rec.telemetry_debug_info
    if scheduler is _SCHEDULER_FROM_ENV:
        # KFTPU_SCHEDULER=off is the kill switch (ISSUE 5): the capacity
        # stage then runs exactly the pre-scheduler gate. On (default),
        # the scheduler stays a transparent pass-through until a fleet
        # is configured (KFTPU_FLEET / ConfigMap / node inference).
        from kubeflow_tpu.scheduler import scheduler_enabled

        if scheduler_enabled():
            from kubeflow_tpu.cmd.envconfig import scheduler_options
            from kubeflow_tpu.scheduler import TpuFleetScheduler

            scheduler = TpuFleetScheduler(
                mgr.kube, scheduler_options(), registry=mgr.registry)
        else:
            scheduler = None
    rec._scheduler = scheduler
    if warmpool is _WARMPOOL_FROM_ENV:
        # Warm pod pools (ISSUE 14): no KFTPU_WARM_POOLS spec (and no
        # ConfigMap source) means no manager at all — the claim gate is
        # a None check and the cold path is byte-for-byte untouched.
        from kubeflow_tpu.cmd.envconfig import warm_pool_options
        from kubeflow_tpu.controllers.warmpool import WarmPoolManager

        wp_opts = warm_pool_options()
        warmpool = (WarmPoolManager(mgr.kube, wp_opts,
                                    registry=mgr.registry)
                    if wp_opts.enabled else None)
    rec._warmpool = warmpool
    if warmpool is not None:
        # One chip ledger: every warm slot holds a scheduler reservation
        # (the first preemption victim), and the scheduler's teardown
        # callback routes cannibalized slots back to the replenisher.
        warmpool.scheduler = rec._scheduler
        if rec._scheduler is not None:
            rec._scheduler.on_warm_reclaimed(warmpool.note_reclaimed)
        mgr.warmpool = warmpool
        mgr.add_background(warmpool.run_replenisher)
    owned_kinds = ["StatefulSet", "Service"] + (
        ["VirtualService"] if rec.opts.use_istio else [])
    mgr.add_controller(
        Controller(
            name="notebook",
            kind="Notebook",
            reconcile=rec.reconcile,
            owns=owned_kinds,
            watches=[
                Watch("Pod", pod_to_notebook),
                Watch("Event", event_to_notebook),
            ] + ([Watch("ProvisioningRequest",
                        provisioning_request_to_notebook)]
                 if rec.opts.enable_queued_provisioning else []),
            coalesce_window=rec.opts.coalesce_window,
        )
    )
    # _mirror_events and _update_status read the watch caches the Watch /
    # owns wiring above already maintains — watch streams instead of a
    # namespace-wide Event LIST + per-slice StatefulSet GETs per reconcile
    # (reference notebook_controller.go:739-787 is watch-driven the same
    # way). The indexers registered here turn every remaining cache scan
    # into an O(1) lookup (client-go AddIndexers; ``owner`` on owned kinds
    # comes from Manager.add_controller).
    rec._event_informer = mgr.informer_for("Event")
    rec._event_informer.add_indexer(EVENT_POD_INDEX, index_event_by_involved_pod)
    rec._sts_informer = mgr.informer_for("StatefulSet")
    rec._nb_informer = mgr.informer_for("Notebook")
    rec._nb_informer.add_indexer(NAMESPACE_INDEX, index_by_namespace)
    if rec._scheduler is not None:
        # A freshly admitted (or preempted) gang reconciles NOW — the
        # queued requeue_after is only the safety net. The Notebook
        # informer saves the scheduler a GET when it events a peer, and
        # /debug/scheduler hangs off the manager (cmd/controller_manager).
        rec._scheduler.on_admitted(lambda key: mgr.enqueue("notebook", key))
        rec._scheduler._nb_informer = rec._nb_informer
        if getattr(rec._scheduler.options, "fleet_spec", "") == "auto":
            rec._scheduler._node_informer = mgr.informer_for("Node")
        if getattr(rec._scheduler.options, "enable_elastic", False):
            # Elastic fleet: spot pools are reclaim-aware — the
            # revocation signal is a Node taint, so the scheduler needs
            # node events even for env/ConfigMap fleets (the auto
            # informer above only exists for label inference). The
            # informer handle also lets a lazily-activated fleet
            # re-scan cached nodes for signals its handler dropped
            # pre-activation.
            rec._scheduler._node_informer = mgr.informer_for("Node")
            sched_ref = rec._scheduler

            def spot_node_handler(event: str, node: dict) -> None:
                if event == "DELETED":
                    sched_ref.note_node_gone(node)
                else:
                    sched_ref.note_node_event(node)

            mgr.informer_for("Node").add_handler(spot_node_handler)
        mgr.scheduler = rec._scheduler
    rec._pod_informer = mgr.informer_for("Pod")
    rec._pod_informer.add_indexer(
        NB_POD_INDEX, index_by_label(nbapi.NOTEBOOK_NAME_LABEL))
    # update(), not rebind: rec._reader closed over this dict in __init__.
    rec._child_informers.update(
        {k: mgr.informer_for(k) for k in owned_kinds})
    if rec.opts.enable_queued_provisioning:
        rec._pr_informer = mgr.informer_for("ProvisioningRequest")
        rec._child_informers["ProvisioningRequest"] = rec._pr_informer
    if rec.opts.maintenance_taints:
        # Maintenance taints land on Nodes, not on anything the Notebook
        # owns — watch Nodes and re-enqueue the notebooks whose workers
        # run there (resolved from the Pod informer cache, zero LISTs).
        # Nodes churn constantly (status heartbeats, label updates), so
        # the handler keys on the *maintenance-taint set* changing — every
        # other Node event is dropped without touching the Pod cache.
        rec._node_informer = mgr.informer_for("Node")
        pod_informer = rec._pod_informer
        pod_informer.add_indexer(POD_NODE_INDEX, index_pod_by_node)
        watched = frozenset(rec.opts.maintenance_taints)
        last_taints: dict[str, frozenset] = {}

        def node_handler(event: str, node: dict) -> None:
            node_name = name_of(node)
            if event == "DELETED":
                now = frozenset()
                last_taints.pop(node_name, None)
            else:
                now = watched & {
                    t.get("key")
                    for t in deep_get(node, "spec", "taints", default=[])
                }
                if last_taints.get(node_name, frozenset()) == now:
                    return
                last_taints[node_name] = now
            # Node-indexed lookup: only this node's pods, not a scan of
            # every pod in the cluster per taint flip.
            for pod in pod_informer.by_index(POD_NODE_INDEX, node_name):
                for key in pod_to_notebook(pod):
                    mgr.enqueue("notebook", key)

        rec._node_informer.add_handler(node_handler)
    if rec.opts.pipeline_access_role:
        # A pipelines Role appearing AFTER notebooks exist must still get
        # bindings (the probe cache alone would leave idle notebooks
        # unbound until some unrelated event): watch Roles, bust the probe
        # cache, and re-enqueue that namespace's notebooks from the
        # informer cache.
        nb_informer = mgr.informer_for("Notebook")

        def role_handler(_event: str, role: dict) -> None:
            if name_of(role) != rec.opts.pipeline_access_role:
                return
            ns = namespace_of(role)
            rec._role_probe_gen[ns] = rec._role_probe_gen.get(ns, 0) + 1
            rec._role_probe_cache.pop(ns, None)
            # Namespace index (registered above): only this namespace's
            # notebooks re-enqueue, without walking the whole cache.
            for nb in nb_informer.by_index(NAMESPACE_INDEX, ns):
                mgr.enqueue("notebook", (ns, name_of(nb)))

        mgr.informer_for("Role").add_handler(role_handler)
    return rec
