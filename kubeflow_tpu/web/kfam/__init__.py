"""Kubeflow Access Management (KFAM) service."""

from kubeflow_tpu.web.kfam.app import create_app

__all__ = ["create_app"]
