"""KFAM: profiles + contributor bindings REST service.

Reference: ``components/access-management/kfam`` — router (routers.go:32-106),
handlers (api_default.go:104-310), binding ⇄ RoleBinding (+ Istio
AuthorizationPolicy) materialisation (bindings.go:79-238), role-name map
(bindings.go:38-46), owner-or-cluster-admin authorization.

Binding model: ``{user: {kind: User, name}, referredNamespace,
roleRef: {kind: ClusterRole, name: admin|edit|view}}`` — materialised as a
RoleBinding ``user-<safe-email>-clusterrole-<role>`` annotated with
user/role (the annotations are the source of truth for listing).
"""

from __future__ import annotations

import re

from aiohttp import web

from kubeflow_tpu.api import profile as profileapi
from kubeflow_tpu.runtime.errors import Invalid, NotFound
from kubeflow_tpu.runtime.objects import deep_get, get_meta, name_of
from kubeflow_tpu.web.common.app import create_base_app, json_error, json_success

# bindings.go:38-46
ROLE_MAP = {"admin": "kubeflow-admin", "edit": "kubeflow-edit", "view": "kubeflow-view"}


def safe_user_name(user: str) -> str:
    return re.sub(r"[^a-z0-9]", "-", user.lower())


def binding_name(user: str, role: str) -> str:
    return f"user-{safe_user_name(user)}-clusterrole-{role}"


def create_app(
    kube,
    *,
    cluster_admins: set[str] | None = None,
    use_istio: bool = False,
    userid_header: str = "kubeflow-userid",
    **kwargs,
) -> web.Application:
    app = create_base_app(kube, userid_header=userid_header, **kwargs)
    app["cluster_admins"] = cluster_admins or set()
    app["use_istio"] = use_istio
    app.add_routes(routes)
    return app


routes = web.RouteTableDef()


async def _is_owner_or_admin(request, namespace: str) -> bool:
    user = request.get("user", "")
    if user in request.app["cluster_admins"]:
        return True
    kube = request.app["kube"]
    profile = await kube.get_or_none("Profile", namespace)
    if profile is None:
        return False
    return profileapi.owner_of(profile).get("name") == user


@routes.get("/kfam/v1/role-clusteradmin")
async def get_cluster_admin(request):
    caller = request.get("user", "")
    user = request.query.get("user", caller)
    # Only admins may query someone else's role.
    if user != caller and caller not in request.app["cluster_admins"]:
        return json_error("forbidden: cannot query another user's role", 403)
    return json_success({"clusterAdmin": user in request.app["cluster_admins"]})


@routes.post("/kfam/v1/profiles")
async def post_profile(request):
    kube = request.app["kube"]
    caller = request.get("user", "")
    body = await request.json()
    name = body.get("name") or deep_get(body, "metadata", "name")
    owner = deep_get(body, "spec", "owner", "name") or body.get("user", caller)
    if not name:
        raise Invalid("profile: name required")
    # A non-admin may only create a profile owned by THEMSELF — otherwise
    # any user could claim any unregistered namespace name for (or as)
    # someone else (same invariant as the dashboard registration flow).
    if owner != caller and caller not in request.app["cluster_admins"]:
        return json_error(
            "forbidden: only cluster admins may create profiles for others", 403
        )
    profile = profileapi.new(name, owner)
    if deep_get(body, "spec", "resourceQuotaSpec"):
        profile["spec"]["resourceQuotaSpec"] = body["spec"]["resourceQuotaSpec"]
    if deep_get(body, "spec", "tpuQuota") is not None:
        profile["spec"]["tpuQuota"] = body["spec"]["tpuQuota"]
    await kube.create("Profile", profile)
    return json_success({"message": f"Profile {name} created"})


@routes.delete("/kfam/v1/profiles/{name}")
async def delete_profile(request):
    kube = request.app["kube"]
    name = request.match_info["name"]
    if not await _is_owner_or_admin(request, name):
        return json_error("forbidden: only the owner or a cluster admin", 403)
    await kube.delete("Profile", name)
    return json_success({"message": f"Profile {name} deleted"})


@routes.get("/kfam/v1/bindings")
async def list_bindings(request):
    kube = request.app["kube"]
    caller = request.get("user", "")
    namespace = request.query.get("namespace")
    role_filter = request.query.get("role")
    user_filter = request.query.get("user")
    bindings = []
    if namespace:
        # Owner, cluster admin, or an existing contributor of the namespace.
        if not await _is_owner_or_admin(request, namespace):
            member = any(
                (get_meta(rb).get("annotations") or {}).get("user") == caller
                for rb in await kube.list("RoleBinding", namespace)
            )
            if not member:
                return json_error(
                    "forbidden: not a member of this namespace", 403
                )
        namespaces = [namespace]
    elif caller in request.app["cluster_admins"]:
        namespaces = [name_of(p) for p in await kube.list("Profile")]
    else:
        return json_error(
            "forbidden: cluster-wide binding listing requires cluster admin", 403
        )
    for ns in namespaces:
        for rb in await kube.list("RoleBinding", ns):
            annotations = get_meta(rb).get("annotations") or {}
            if "user" not in annotations or "role" not in annotations:
                continue
            role = annotations["role"]
            short = next((k for k, v in ROLE_MAP.items() if v == role), role)
            if role_filter and short != role_filter:
                continue
            if user_filter and annotations["user"] != user_filter:
                continue
            bindings.append(
                {
                    "user": {"kind": "User", "name": annotations["user"]},
                    "referredNamespace": ns,
                    "roleRef": {"kind": "ClusterRole", "name": short},
                }
            )
    return json_success({"bindings": bindings})


@routes.post("/kfam/v1/bindings")
async def post_binding(request):
    kube = request.app["kube"]
    body = await request.json()
    user = deep_get(body, "user", "name")
    ns = body.get("referredNamespace")
    role = deep_get(body, "roleRef", "name", default="edit")
    if not user or not ns:
        raise Invalid("binding: user.name and referredNamespace required")
    if role not in ROLE_MAP:
        raise Invalid(f"binding: unknown role {role!r} (admin|edit|view)")
    if not await _is_owner_or_admin(request, ns):
        return json_error("forbidden: only the owner or a cluster admin", 403)
    rb = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": {
            "name": binding_name(user, role),
            "namespace": ns,
            "annotations": {"user": user, "role": ROLE_MAP[role]},
        },
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": ROLE_MAP[role],
        },
        "subjects": [
            {"kind": "User", "name": user, "apiGroup": "rbac.authorization.k8s.io"}
        ],
    }
    await kube.create("RoleBinding", rb)
    if request.app["use_istio"]:
        ap = {
            "apiVersion": "security.istio.io/v1beta1",
            "kind": "AuthorizationPolicy",
            "metadata": {
                "name": binding_name(user, role),
                "namespace": ns,
                "annotations": {"user": user, "role": ROLE_MAP[role]},
            },
            "spec": {
                "rules": [
                    {
                        "when": [
                            {
                                "key": "request.headers[kubeflow-userid]",
                                "values": [user],
                            }
                        ]
                    }
                ]
            },
        }
        await kube.create("AuthorizationPolicy", ap)
    return json_success({"message": f"Binding for {user} in {ns} created"})


@routes.delete("/kfam/v1/bindings")
async def delete_binding(request):
    kube = request.app["kube"]
    body = await request.json()
    user = deep_get(body, "user", "name")
    ns = body.get("referredNamespace")
    role = deep_get(body, "roleRef", "name", default="edit")
    if not user or not ns:
        raise Invalid("binding: user.name and referredNamespace required")
    if not await _is_owner_or_admin(request, ns):
        return json_error("forbidden: only the owner or a cluster admin", 403)
    name = binding_name(user, role)
    await kube.delete("RoleBinding", name, ns)
    if request.app["use_istio"]:
        try:
            await kube.delete("AuthorizationPolicy", name, ns)
        except NotFound:
            pass
    return json_success({"message": f"Binding for {user} in {ns} deleted"})
