"""Web-app backends (the reference's ``crud-web-apps`` layer, SURVEY.md §2.2).

Reference stack: Flask blueprints over the kubernetes python client, one
backend per app (jupyter/volumes/tensorboards) sharing the
``kubeflow.kubeflow.crud_backend`` pip package. Here the backends are
aiohttp applications sharing ``kubeflow_tpu.web.common`` — async end to end,
talking to the same ``KubeApi`` surface the controllers use (FakeKube in
tests, HttpKube in deployment), so the whole stack runs in one process when
embedding and scales out as separate deployments in production.
"""
