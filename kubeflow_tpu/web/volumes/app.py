"""VWA routes: PVC CRUD + PVCViewer lifecycle.

Reference: ``crud-web-apps/volumes/backend/apps/default/routes/
{get,post,delete}.py`` — list pvcs with attached-pod detection (get.py:9-45),
create pvc (post.py:11-27), create/delete viewer (post.py/delete.py:12-52).
"""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api import pvcviewer as pvcapi
from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.runtime.objects import deep_get, name_of
from kubeflow_tpu.web.common.app import create_base_app, json_success
from kubeflow_tpu.web.common.serving import add_spa
from kubeflow_tpu.web.common.auth import ensure
from kubeflow_tpu.web.common.status import events_for


def create_app(kube, **kwargs) -> web.Application:
    app = create_base_app(kube, **kwargs)
    app.add_routes(routes)
    add_spa(app, __file__)
    return app


routes = web.RouteTableDef()


def _ctx(request: web.Request):
    return (
        request.app["kube"],
        request.app["authorizer"],
        request.get("user", ""),
        request.match_info.get("namespace"),
    )


def _claims_to_pods(pods: list[dict], *, exclude_viewers: bool = False) -> dict:
    """claim name → [pod names] from one Pod list (avoids an N+1 list per
    PVC). ``exclude_viewers`` drops pods that exist only to *view* a claim
    (labelled ``pvcviewer`` by the pvcviewer controller) — they must not
    block deleting it."""
    out: dict[str, list[str]] = {}
    for pod in pods:
        if exclude_viewers and "pvcviewer" in (
            deep_get(pod, "metadata", "labels", default={}) or {}
        ):
            continue
        for vol in deep_get(pod, "spec", "volumes", default=[]):
            claim = deep_get(vol, "persistentVolumeClaim", "claimName")
            if claim:
                out.setdefault(claim, []).append(name_of(pod))
    return out


@routes.get("/api/namespaces/{namespace}/pvcs")
async def list_pvcs(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "list", "PersistentVolumeClaim", ns)
    viewers = {
        deep_get(v, "spec", "pvc"): v for v in await kube.list("PVCViewer", ns)
    }
    claims_to_pods = _claims_to_pods(await kube.list("Pod", ns))
    pvcs = []
    for pvc in await kube.list("PersistentVolumeClaim", ns):
        claim = name_of(pvc)
        used_by = claims_to_pods.get(claim, [])
        viewer = viewers.get(claim)
        pvcs.append(
            {
                "name": claim,
                "namespace": ns,
                "capacity": deep_get(
                    pvc, "spec", "resources", "requests", "storage"
                ),
                "modes": deep_get(pvc, "spec", "accessModes", default=[]),
                "class": deep_get(pvc, "spec", "storageClassName"),
                "status": deep_get(pvc, "status", "phase", default="Bound"),
                "usedBy": used_by,
                "viewer": {
                    "name": name_of(viewer),
                    "ready": deep_get(viewer, "status", "ready", default=False),
                    "url": deep_get(viewer, "status", "url"),
                }
                if viewer
                else None,
            }
        )
    return json_success({"pvcs": pvcs})


@routes.post("/api/namespaces/{namespace}/pvcs")
async def post_pvc(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "create", "PersistentVolumeClaim", ns)
    body = await request.json()
    name = body.get("name", "")
    if not name:
        raise Invalid("pvc form: name is required")
    pvc = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "accessModes": body.get("mode") and [body["mode"]]
            or body.get("accessModes", ["ReadWriteOnce"]),
            "resources": {"requests": {"storage": body.get("size", "5Gi")}},
            **(
                {"storageClassName": body["class"]}
                if body.get("class") not in (None, "", "$empty")
                else {}
            ),
        },
    }
    await kube.create("PersistentVolumeClaim", pvc)
    return json_success({"message": f"PVC {name} created"})


@routes.delete("/api/namespaces/{namespace}/pvcs/{name}")
async def delete_pvc(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "delete", "PersistentVolumeClaim", ns)
    used_by = _claims_to_pods(
        await kube.list("Pod", ns), exclude_viewers=True
    ).get(name, [])
    if used_by:
        raise Invalid(f"PVC {name} is in use by pods: {', '.join(used_by)}")
    # Delete the viewer first like the reference (delete.py:24-40).
    for viewer in await kube.list("PVCViewer", ns):
        if deep_get(viewer, "spec", "pvc") == name:
            await kube.delete("PVCViewer", name_of(viewer), ns)
    await kube.delete("PersistentVolumeClaim", name, ns)
    return json_success({"message": f"PVC {name} deleted"})


@routes.get("/api/namespaces/{namespace}/pvcs/{name}/events")
async def pvc_events(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "list", "Event", ns)
    events = await events_for(kube, ns, name, ("PersistentVolumeClaim",))
    return json_success({"events": events})


@routes.post("/api/namespaces/{namespace}/viewers")
async def post_viewer(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "create", "PVCViewer", ns)
    body = await request.json()
    pvc = body.get("pvc", "")
    if not pvc:
        raise Invalid("viewer form: pvc is required")
    viewer = pvcapi.new(pvc, ns, pvc)
    await kube.create("PVCViewer", viewer)
    return json_success({"message": f"PVCViewer for {pvc} created"})


@routes.delete("/api/namespaces/{namespace}/viewers/{name}")
async def delete_viewer(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "delete", "PVCViewer", ns)
    await kube.delete("PVCViewer", name, ns)
    return json_success({"message": f"PVCViewer {name} deleted"})
