"""Volumes web app (VWA) backend."""

from kubeflow_tpu.web.volumes.app import create_app

__all__ = ["create_app"]
