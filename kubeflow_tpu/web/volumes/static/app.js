/* VWA frontend: PVC table with viewer lifecycle + details drawer.
 *
 * The reference's Angular volumes app on the shared KF lib: sortable
 * table, confirm dialogs, snackbars, and a per-PVC drawer with details,
 * live events (backend /pvcs/{name}/events) and YAML.
 */

let tablePoller = null;

function openDetails(p) {
  const drawer = KF.drawer(`Volume ${p.name}`);
  const tabHost = el("div", {});
  drawer.content.append(tabHost);
  const tabs = KF.tabs(tabHost, [
    {
      label: "Overview",
      render: (pane) => {
        pane.append(
          KF.detailsList([
            ["Name", p.name],
            ["Capacity", p.capacity || "—"],
            ["Access modes", (p.modes || []).join(", ")],
            ["Storage class", p.class || "default"],
            ["Status", p.status],
            [
              "Used by",
              (p.usedBy || []).length
                ? el(
                    "span",
                    {},
                    p.usedBy.map((name) => el("span", { class: "chip" }, name))
                  )
                : "nothing",
            ],
            [
              "Viewer",
              p.viewer
                ? p.viewer.ready && p.viewer.url
                  ? el("a", { href: p.viewer.url, target: "_blank" }, "open")
                  : "starting…"
                : "none",
            ],
          ])
        );
      },
    },
    {
      label: "Events",
      render: (pane) => {
        const host = el("div", {});
        pane.append(host);
        async function load() {
          const body = await api(
            `api/namespaces/${ns.get()}/pvcs/${p.name}/events`
          );
          KF.eventsTable(host, body.events);
        }
        load().catch(KF.showError);
        const t = setInterval(() => load().catch(() => {}), 5000);
        return { stop: () => clearInterval(t) };
      },
    },
  ]);
  drawer.onclose = () => tabs.stop();
}

async function refresh() {
  const body = await api(`api/namespaces/${ns.get()}/pvcs`);
  const columns = [
    { title: "Name", render: (p) => p.name, sortKey: (p) => p.name },
    {
      title: "Size",
      render: (p) => p.capacity || "—",
      sortKey: (p) => p.capacity || "",
    },
    { title: "Modes", render: (p) => (p.modes || []).join(", ") },
    { title: "Status", render: (p) => p.status, sortKey: (p) => p.status },
    {
      title: "Used by",
      render: (p) =>
        (p.usedBy || []).length
          ? p.usedBy.map((name) => el("span", { class: "chip" }, name))
          : "—",
    },
    {
      title: "Actions",
      render: (p) =>
        el(
          "span",
          {},
          p.viewer && p.viewer.ready && p.viewer.url
            ? el(
                "a",
                {
                  href: p.viewer.url,
                  target: "_blank",
                  onclick: (ev) => ev.stopPropagation(),
                },
                "Browse"
              )
            : KF.actionButton(
                p.viewer ? "Viewer starting…" : "Open viewer",
                () =>
                  api(`api/namespaces/${ns.get()}/viewers`, {
                    method: "POST",
                    body: JSON.stringify({ pvc: p.name }),
                  }).then(() => {
                    KF.snackbar("Starting viewer for " + p.name);
                    tablePoller.refresh();
                  }, showError)
              ),
          " ",
          p.viewer
            ? KF.actionButton("Close viewer", () =>
                api(`api/namespaces/${ns.get()}/viewers/${p.viewer.name}`, {
                  method: "DELETE",
                }).then(() => tablePoller.refresh(), showError)
              )
            : "",
          " ",
          KF.actionButton(
            "Delete",
            () =>
              KF.confirmDialog({
                title: `Delete volume ${p.name}?`,
                message: "All data on the volume is permanently removed.",
              }).then(
                (ok) =>
                  ok &&
                  api(`api/namespaces/${ns.get()}/pvcs/${p.name}`, {
                    method: "DELETE",
                  }).then(() => {
                    KF.snackbar("Deleting " + p.name);
                    tablePoller.refresh();
                  }, showError)
              ),
            { class: "danger" }
          )
        ),
    },
  ];
  renderTable(document.getElementById("pvc-table"), columns, body.pvcs, {
    onRowClick: openDetails,
    emptyText: "No volumes in this namespace.",
  });
}

const nameInput = document.querySelector('#new-form input[name="name"]');
const nameCheck = nameInput
  ? KF.validate(nameInput, KF.validators.dns1123)
  : () => true;

document.getElementById("new-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "block";
});
document.getElementById("cancel-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "none";
});
document.getElementById("new-form").addEventListener("submit", (ev) => {
  ev.preventDefault();
  if (!nameCheck()) return KF.snackbar("Fix the volume name first.", "error");
  const form = new FormData(ev.target);
  api(`api/namespaces/${ns.get()}/pvcs`, {
    method: "POST",
    body: JSON.stringify({
      name: form.get("name"),
      size: form.get("size"),
      mode: form.get("mode"),
    }),
  }).then(() => {
    document.getElementById("new-form-card").style.display = "none";
    KF.snackbar("Creating volume " + form.get("name"));
    tablePoller.refresh();
  }, showError);
});

document
  .getElementById("ns-slot")
  .append(namespacePicker(() => tablePoller.refresh()));
tablePoller = poll(refresh);
