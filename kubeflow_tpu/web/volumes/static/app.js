/* VWA frontend: PVC table with viewer lifecycle + details drawer.
 *
 * The reference's Angular volumes app on the shared KF lib: sortable
 * table, confirm dialogs, snackbars, and a per-PVC drawer with details,
 * live events (backend /pvcs/{name}/events) and YAML. All user-visible
 * strings route through KF.t (reference: the volumes frontend's xlf
 * translation pipeline, i18n/fr/messages.fr.xlf). */

KF.registerMessages("en", {
  "vwa.drawerTitle": "Volume {name}",
  "vwa.tabOverview": "Overview",
  "vwa.tabEvents": "Events",
  "vwa.capacity": "Capacity",
  "vwa.accessModes": "Access modes",
  "vwa.storageClass": "Storage class",
  "vwa.classDefault": "default",
  "vwa.usedBy": "Used by",
  "vwa.usedByNothing": "nothing",
  "vwa.viewer": "Viewer",
  "vwa.viewerOpen": "open",
  "vwa.viewerStarting": "starting…",
  "vwa.viewerNone": "none",
  "vwa.colSize": "Size",
  "vwa.colModes": "Modes",
  "vwa.colUsedBy": "Used by",
  "vwa.browse": "Browse",
  "vwa.viewerStartingBtn": "Viewer starting…",
  "vwa.openViewer": "Open viewer",
  "vwa.closeViewer": "Close viewer",
  "vwa.startingViewerFor": "Starting viewer for {name}",
  "vwa.deleteTitle": "Delete volume {name}?",
  "vwa.deleteMessage": "All data on the volume is permanently removed.",
  "vwa.deleting": "Deleting {name}",
  "vwa.empty": "No volumes in this namespace.",
  "vwa.fixName": "Fix the volume name first.",
  "vwa.creating": "Creating volume {name}",
  "vwa.title": "Volumes",
  "vwa.namespace": "namespace",
  "vwa.newVolume": "+ New volume",
  "vwa.formTitle": "New volume",
  "vwa.formName": "Name",
  "vwa.formSize": "Size",
  "vwa.formAccessMode": "Access mode",
  "vwa.create": "Create",
  "vwa.loading": "Loading…",
});
KF.registerMessages("de", {
  "vwa.drawerTitle": "Volume {name}",
  "vwa.tabOverview": "Übersicht",
  "vwa.tabEvents": "Ereignisse",
  "vwa.capacity": "Kapazität",
  "vwa.accessModes": "Zugriffsmodi",
  "vwa.storageClass": "Speicherklasse",
  "vwa.classDefault": "Standard",
  "vwa.usedBy": "Verwendet von",
  "vwa.usedByNothing": "nichts",
  "vwa.viewer": "Viewer",
  "vwa.viewerOpen": "öffnen",
  "vwa.viewerStarting": "startet…",
  "vwa.viewerNone": "keiner",
  "vwa.colSize": "Größe",
  "vwa.colModes": "Modi",
  "vwa.colUsedBy": "Verwendet von",
  "vwa.browse": "Durchsuchen",
  "vwa.viewerStartingBtn": "Viewer startet…",
  "vwa.openViewer": "Viewer öffnen",
  "vwa.closeViewer": "Viewer schließen",
  "vwa.startingViewerFor": "Viewer für {name} wird gestartet",
  "vwa.deleteTitle": "Volume {name} löschen?",
  "vwa.deleteMessage": "Alle Daten auf dem Volume werden endgültig entfernt.",
  "vwa.deleting": "{name} wird gelöscht",
  "vwa.empty": "Keine Volumes in diesem Namespace.",
  "vwa.fixName": "Bitte zuerst den Volume-Namen korrigieren.",
  "vwa.creating": "Volume {name} wird erstellt",
  "vwa.title": "Volumes",
  "vwa.namespace": "Namespace",
  "vwa.newVolume": "+ Neues Volume",
  "vwa.formTitle": "Neues Volume",
  "vwa.formName": "Name",
  "vwa.formSize": "Größe",
  "vwa.formAccessMode": "Zugriffsmodus",
  "vwa.create": "Erstellen",
  "vwa.loading": "Lädt…",
});
KF.registerMessages("fr", {
  "vwa.drawerTitle": "Volume {name}",
  "vwa.tabOverview": "Aperçu",
  "vwa.tabEvents": "Événements",
  "vwa.capacity": "Capacité",
  "vwa.accessModes": "Modes d'accès",
  "vwa.storageClass": "Classe de stockage",
  "vwa.classDefault": "défaut",
  "vwa.usedBy": "Utilisé par",
  "vwa.usedByNothing": "rien",
  "vwa.viewer": "Visionneuse",
  "vwa.viewerOpen": "ouvrir",
  "vwa.viewerStarting": "démarrage…",
  "vwa.viewerNone": "aucune",
  "vwa.colSize": "Taille",
  "vwa.colModes": "Modes",
  "vwa.colUsedBy": "Utilisé par",
  "vwa.browse": "Parcourir",
  "vwa.viewerStartingBtn": "Visionneuse en démarrage…",
  "vwa.openViewer": "Ouvrir la visionneuse",
  "vwa.closeViewer": "Fermer la visionneuse",
  "vwa.startingViewerFor": "Démarrage de la visionneuse pour {name}",
  "vwa.deleteTitle": "Supprimer le volume {name} ?",
  "vwa.deleteMessage":
    "Toutes les données du volume seront définitivement supprimées.",
  "vwa.deleting": "Suppression de {name}",
  "vwa.empty": "Aucun volume dans ce namespace.",
  "vwa.fixName": "Corrigez d'abord le nom du volume.",
  "vwa.creating": "Création du volume {name}",
  "vwa.title": "Volumes",
  "vwa.namespace": "namespace",
  "vwa.newVolume": "+ Nouveau volume",
  "vwa.formTitle": "Nouveau volume",
  "vwa.formName": "Nom",
  "vwa.formSize": "Taille",
  "vwa.formAccessMode": "Mode d'accès",
  "vwa.create": "Créer",
  "vwa.loading": "Chargement…",
});

let tablePoller = null;

function openDetails(p) {
  const drawer = KF.drawer(KF.t("vwa.drawerTitle", { name: p.name }));
  const tabHost = el("div", {});
  drawer.content.append(tabHost);
  const tabs = KF.tabs(tabHost, [
    {
      label: KF.t("vwa.tabOverview"),
      render: (pane) => {
        pane.append(
          KF.detailsList([
            [KF.t("table.name"), p.name],
            [KF.t("vwa.capacity"), p.capacity || "—"],
            [KF.t("vwa.accessModes"), (p.modes || []).join(", ")],
            [KF.t("vwa.storageClass"), p.class || KF.t("vwa.classDefault")],
            [KF.t("table.status"), p.status],
            [
              KF.t("vwa.usedBy"),
              (p.usedBy || []).length
                ? el(
                    "span",
                    {},
                    p.usedBy.map((name) => el("span", { class: "chip" }, name))
                  )
                : KF.t("vwa.usedByNothing"),
            ],
            [
              KF.t("vwa.viewer"),
              p.viewer
                ? p.viewer.ready && p.viewer.url
                  ? el("a", { href: p.viewer.url, target: "_blank" },
                       KF.t("vwa.viewerOpen"))
                  : KF.t("vwa.viewerStarting")
                : KF.t("vwa.viewerNone"),
            ],
          ])
        );
      },
    },
    {
      label: KF.t("vwa.tabEvents"),
      render: (pane) => {
        const host = el("div", {});
        pane.append(host);
        async function load() {
          const body = await api(
            `api/namespaces/${ns.get()}/pvcs/${p.name}/events`
          );
          KF.eventsTable(host, body.events);
        }
        load().catch(KF.showError);
        const t = setInterval(() => load().catch(() => {}), 5000);
        return { stop: () => clearInterval(t) };
      },
    },
  ]);
  drawer.onclose = () => tabs.stop();
}

async function refresh() {
  const body = await api(`api/namespaces/${ns.get()}/pvcs`);
  const columns = [
    { title: () => KF.t("table.name"),
      render: (p) => p.name, sortKey: (p) => p.name },
    {
      title: () => KF.t("vwa.colSize"),
      render: (p) => p.capacity || "—",
      sortKey: (p) => p.capacity || "",
    },
    { title: () => KF.t("vwa.colModes"),
      render: (p) => (p.modes || []).join(", ") },
    { title: () => KF.t("table.status"),
      render: (p) => p.status, sortKey: (p) => p.status },
    {
      title: () => KF.t("vwa.colUsedBy"),
      render: (p) =>
        (p.usedBy || []).length
          ? p.usedBy.map((name) => el("span", { class: "chip" }, name))
          : "—",
    },
    {
      title: () => KF.t("table.actions"),
      render: (p) =>
        el(
          "span",
          {},
          p.viewer && p.viewer.ready && p.viewer.url
            ? el(
                "a",
                {
                  href: p.viewer.url,
                  target: "_blank",
                  onclick: (ev) => ev.stopPropagation(),
                },
                KF.t("vwa.browse")
              )
            : KF.actionButton(
                p.viewer ? KF.t("vwa.viewerStartingBtn")
                         : KF.t("vwa.openViewer"),
                () =>
                  api(`api/namespaces/${ns.get()}/viewers`, {
                    method: "POST",
                    body: JSON.stringify({ pvc: p.name }),
                  }).then(() => {
                    KF.snackbar(
                      KF.t("vwa.startingViewerFor", { name: p.name }));
                    tablePoller.refresh();
                  }, showError)
              ),
          " ",
          p.viewer
            ? KF.actionButton(KF.t("vwa.closeViewer"), () =>
                api(`api/namespaces/${ns.get()}/viewers/${p.viewer.name}`, {
                  method: "DELETE",
                }).then(() => tablePoller.refresh(), showError)
              )
            : "",
          " ",
          KF.actionButton(
            KF.t("action.delete"),
            () =>
              KF.confirmDialog({
                title: KF.t("vwa.deleteTitle", { name: p.name }),
                message: KF.t("vwa.deleteMessage"),
              }).then(
                (ok) =>
                  ok &&
                  api(`api/namespaces/${ns.get()}/pvcs/${p.name}`, {
                    method: "DELETE",
                  }).then(() => {
                    KF.snackbar(KF.t("vwa.deleting", { name: p.name }));
                    tablePoller.refresh();
                  }, showError)
              ),
            { class: "danger" }
          )
        ),
    },
  ];
  renderTable(document.getElementById("pvc-table"), columns, body.pvcs, {
    onRowClick: openDetails,
    emptyText: KF.t("vwa.empty"),
    pageSize: 25,
    filterable: true,
  });
}

const nameInput = document.querySelector('#new-form input[name="name"]');
const nameCheck = nameInput
  ? KF.validate(nameInput, KF.validators.dns1123)
  : () => true;

document.getElementById("new-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "block";
});
document.getElementById("cancel-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "none";
});
document.getElementById("new-form").addEventListener("submit", (ev) => {
  ev.preventDefault();
  if (!nameCheck()) return KF.snackbar(KF.t("vwa.fixName"), "error");
  const form = new FormData(ev.target);
  api(`api/namespaces/${ns.get()}/pvcs`, {
    method: "POST",
    body: JSON.stringify({
      name: form.get("name"),
      size: form.get("size"),
      mode: form.get("mode"),
    }),
  }).then(() => {
    document.getElementById("new-form-card").style.display = "none";
    KF.snackbar(KF.t("vwa.creating", { name: form.get("name") }));
    tablePoller.refresh();
  }, showError);
});

document
  .getElementById("ns-slot")
  .append(namespacePicker(() => tablePoller.refresh()), " ", KF.localePicker());
KF.localizeDocument();
KF.onLocaleChange(() => refresh().catch(() => {}));
tablePoller = poll(refresh);
