/* VWA frontend: PVC table with viewer lifecycle. */

async function refresh() {
  const body = await api(`api/namespaces/${ns.get()}/pvcs`);
  const columns = [
    { title: "Name", render: (p) => p.name },
    { title: "Size", render: (p) => p.capacity || "-" },
    { title: "Modes", render: (p) => (p.modes || []).join(", ") },
    { title: "Status", render: (p) => p.status },
    {
      title: "Used by",
      render: (p) =>
        (p.usedBy || []).length
          ? p.usedBy.map((name) => el("span", { class: "chip" }, name))
          : "—",
    },
    {
      title: "Actions",
      render: (p) =>
        el(
          "span",
          {},
          p.viewer && p.viewer.ready && p.viewer.url
            ? el("a", { href: p.viewer.url, target: "_blank" }, "Browse")
            : el(
                "button",
                {
                  onclick: () =>
                    api(`api/namespaces/${ns.get()}/viewers`, {
                      method: "POST",
                      body: JSON.stringify({ pvc: p.name }),
                    }).then(refresh, showError),
                },
                p.viewer ? "Viewer starting…" : "Open viewer"
              ),
          " ",
          el(
            "button",
            { class: "danger",
              onclick: () =>
                confirm(`Delete volume ${p.name}?`) &&
                api(`api/namespaces/${ns.get()}/pvcs/${p.name}`, {
                  method: "DELETE",
                }).then(refresh, showError),
            },
            "Delete"
          )
        ),
    },
  ];
  renderTable(document.getElementById("pvc-table"), columns, body.pvcs);
}

document.getElementById("new-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "block";
});
document.getElementById("cancel-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "none";
});
document.getElementById("new-form").addEventListener("submit", (ev) => {
  ev.preventDefault();
  const form = new FormData(ev.target);
  api(`api/namespaces/${ns.get()}/pvcs`, {
    method: "POST",
    body: JSON.stringify({
      name: form.get("name"),
      size: form.get("size"),
      mode: form.get("mode"),
    }),
  }).then(() => {
    document.getElementById("new-form-card").style.display = "none";
    refresh();
  }, showError);
});

document
  .getElementById("ns-slot")
  .append(namespacePicker(() => refresh().catch(showError)));
poll(refresh);
