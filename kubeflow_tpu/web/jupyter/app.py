"""JWA routes.

Reference: ``crud-web-apps/jupyter/backend/apps/common/routes/get.py:13-126``
(config/pvcs/poddefaults/notebooks/pod/logs/events/gpu-vendors),
``apps/default/routes/post.py:12-77`` (dry-run-first create),
``apps/common/routes/patch.py`` (stop/start), DELETE foreground.

REST contract kept wire-compatible:
``/api/namespaces/<ns>/notebooks[...]``, plus ``/api/tpus`` replacing
``/api/gpus`` (accelerator+topology options instead of vendor limitsKeys).
"""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime.errors import Invalid, NotFound
from kubeflow_tpu.runtime.objects import deep_get, get_meta, name_of, now_iso
from kubeflow_tpu.web.common.app import create_base_app, json_success
from kubeflow_tpu.web.common.serving import add_spa
from kubeflow_tpu.web.common.auth import ensure
from kubeflow_tpu.web.common.status import events_for, filter_events, process_status
from kubeflow_tpu.web.jupyter.form import notebook_from_form
from kubeflow_tpu.web.jupyter.spawner_config import load_config, tpu_options


def create_app(kube, *, config: dict | None = None, config_path: str | None = None,
               **kwargs) -> web.Application:
    app = create_base_app(kube, **kwargs)
    app["config"] = config or load_config(config_path)
    app.add_routes(routes)
    # Serving workload class (KFTPU_SERVING, kubeflow_tpu/serving): the
    # InferenceService routes register only with the switch on, so =off
    # keeps the JWA HTTP surface byte-for-byte notebook-only.
    from kubeflow_tpu.serving import serving_enabled

    if serving_enabled():
        app.add_routes(serving_routes)
    add_spa(app, __file__)
    return app


routes = web.RouteTableDef()


def _ctx(request: web.Request):
    return (
        request.app["kube"],
        request.app["authorizer"],
        request.get("user", ""),
        request.match_info.get("namespace"),
    )


def _events_by_notebook(events: list[dict]) -> dict[str, list[dict]]:
    """Bucket one Event list by notebook name (one list call per request,
    not per notebook)."""
    out: dict[str, list[dict]] = {}
    for ev in events:
        involved = ev.get("involvedObject") or {}
        if involved.get("kind") == "Notebook" and involved.get("name"):
            out.setdefault(involved["name"], []).append(ev)
    return out


async def _notebook_events(kube, ns: str, name: str) -> list[dict]:
    return await events_for(kube, ns, name, ("Notebook",))


@routes.get("/api/config")
async def get_config(request):
    return json_success({"config": request.app["config"]})


@routes.get("/api/tpus")
async def get_tpus(request):
    """Replaces the reference's /api/namespaces/<ns>/gpus vendor scan
    (get.py:101-126): TPU options are static facts of the fleet, served
    from the topology library."""
    return json_success({"tpus": tpu_options()})


@routes.get("/api/namespaces/{namespace}/notebooks")
async def list_notebooks(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "list", "Notebook", ns)
    events = _events_by_notebook(await kube.list("Event", ns))
    notebooks = []
    for nb in await kube.list("Notebook", ns):
        status = process_status(nb, events.get(name_of(nb), []))
        notebooks.append(_summarize(nb, status))
    return json_success({"notebooks": notebooks})


def _summarize(nb: dict, status) -> dict:
    meta = get_meta(nb)
    containers = deep_get(nb, "spec", "template", "spec", "containers", default=[{}])
    tpu = deep_get(nb, "spec", "tpu")
    return {
        "name": meta.get("name"),
        "namespace": meta.get("namespace"),
        "serverType": (meta.get("annotations") or {}).get(
            nbapi.SERVER_TYPE_ANNOTATION, "jupyter"
        ),
        "age": meta.get("creationTimestamp"),
        # The culler's annotation (reference JWA "Last activity" column).
        "lastActivity": (meta.get("annotations") or {}).get(
            nbapi.LAST_ACTIVITY_ANNOTATION
        ),
        "image": containers[0].get("image", ""),
        "cpu": deep_get(containers[0], "resources", "requests", "cpu"),
        "memory": deep_get(containers[0], "resources", "requests", "memory"),
        "tpu": tpu,
        "tpuStatus": deep_get(nb, "status", "tpu"),
        "status": {"phase": status.phase, "message": status.message},
        "labels": meta.get("labels") or {},
        "annotations": meta.get("annotations") or {},
    }


@routes.get("/api/namespaces/{namespace}/notebooks/{name}")
async def get_notebook(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "get", "Notebook", ns)
    nb = await kube.get("Notebook", name, ns)
    events = await _notebook_events(kube, ns, name)
    # NB: key must not be "status" — that would clobber the envelope's
    # numeric status field in json_success.
    return json_success(
        {"notebook": nb,
         "processedStatus": process_status(nb, events).__dict__}
    )


@routes.get("/api/namespaces/{namespace}/notebooks/{name}/pod")
async def get_notebook_pod(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "get", "Pod", ns)
    pods = await kube.list(
        "Pod", ns, label_selector={"matchLabels": {nbapi.NOTEBOOK_NAME_LABEL: name}}
    )
    if not pods:
        raise NotFound(f"no pods for notebook {name}")
    return json_success({"pod": pods[0], "pods": pods})


@routes.get("/api/namespaces/{namespace}/notebooks/{name}/pod/{pod}/logs")
async def get_pod_logs(request):
    """Reference: get.py logs route — worker pod logs for the details UI."""
    kube, authz, user, ns = _ctx(request)
    pod = request.match_info["pod"]
    await ensure(authz, user, "get", "Pod", ns)
    logs = await kube.pod_logs(pod, ns, tail_lines=500)
    return json_success({"logs": logs.splitlines()})


@routes.get("/api/namespaces/{namespace}/notebooks/{name}/events")
async def get_notebook_events(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "list", "Event", ns)
    events = await _notebook_events(kube, ns, name)
    # Recreated server with the same name: hide the prior incarnation's
    # events (reference get_notebook_events creationTimestamp filter).
    nb = await kube.get_or_none("Notebook", name, ns)
    if nb is not None:
        events = filter_events(nb, events)
    return json_success({"events": events})


@routes.get("/api/namespaces/{namespace}/pvcs")
async def list_pvcs(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "list", "PersistentVolumeClaim", ns)
    return json_success({"pvcs": await kube.list("PersistentVolumeClaim", ns)})


@routes.get("/api/namespaces/{namespace}/poddefaults")
async def list_poddefaults(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "list", "PodDefault", ns)
    pds = await kube.list("PodDefault", ns)
    # The UI shows label + description pairs (get.py:36-50).
    contents = [
        {
            "label": _pd_label(pd),
            "desc": deep_get(pd, "spec", "desc", default=name_of(pd)),
        }
        for pd in pds
    ]
    return json_success({"poddefaults": contents})


def _pd_label(pd: dict) -> str:
    match_labels = deep_get(pd, "spec", "selector", "matchLabels", default={})
    return next(iter(match_labels), name_of(pd))


@routes.post("/api/namespaces/{namespace}/notebooks")
async def post_notebook(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "create", "Notebook", ns)
    body = await request.json()
    nb, pvcs = notebook_from_form(request.app["config"], body, ns, user)
    if pvcs:
        await ensure(authz, user, "create", "PersistentVolumeClaim", ns)
    # Notebook FIRST: if its create fails (name taken, webhook rejection)
    # no PVCs are orphaned; pods just stay Pending until the claims land a
    # moment later (the reference gets the same guarantee via dry-runs,
    # post.py:51-58).
    await kube.create("Notebook", nb)
    for pvc in pvcs:
        if await kube.get_or_none("PersistentVolumeClaim", name_of(pvc), ns) is None:
            await kube.create("PersistentVolumeClaim", pvc)
    return json_success({"message": f"Notebook {name_of(nb)} created"}, status=200)


@routes.post("/api/namespaces/{namespace}/notebooks/yaml")
async def post_notebook_yaml(request):
    """Create a Notebook from raw YAML (the shared lib's editor dialog —
    reference parity with kubeflow-common-lib's monaco editor module).
    Kind and namespace are enforced server-side; everything else goes
    through the normal admission chain (defaulting, validation, catalog)."""
    import yaml  # lazy like every yaml use here: dependencies = [] by design

    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "create", "Notebook", ns)
    raw = await request.text()
    try:
        nb = yaml.safe_load(raw)
    except yaml.YAMLError as e:
        raise Invalid(f"could not parse YAML: {e}")
    if not isinstance(nb, dict) or nb.get("kind") != nbapi.KIND:
        raise Invalid("YAML must be a single Notebook manifest")
    meta = nb.setdefault("metadata", {})
    if not isinstance(meta, dict) or not isinstance(
        meta.get("annotations", {}), dict
    ):
        raise Invalid("metadata (and metadata.annotations) must be mappings")
    if meta.get("namespace") not in (None, ns):
        raise Invalid(
            f"metadata.namespace {meta.get('namespace')!r} does not match "
            f"the request namespace {ns!r}"
        )
    meta["namespace"] = ns
    # Creator is the authenticated user, never the manifest's claim (the
    # form path stamps it the same way — an audit field must not be
    # spoofable through the YAML door).
    meta.setdefault("annotations", {})[nbapi.CREATOR_ANNOTATION] = user
    await kube.create("Notebook", nb)
    return json_success({"message": f"Notebook {name_of(nb)} created"})


@routes.patch("/api/namespaces/{namespace}/notebooks/{name}")
async def patch_notebook(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "patch", "Notebook", ns)
    body = await request.json()
    if "stopped" not in body:
        raise Invalid("PATCH body must contain 'stopped'")
    if body["stopped"]:
        annotations = {nbapi.STOP_ANNOTATION: now_iso()}
    else:
        annotations = {nbapi.STOP_ANNOTATION: None}
    await kube.patch(
        "Notebook", name, {"metadata": {"annotations": annotations}}, ns
    )
    return json_success({"message": f"Notebook {name} updated"})


@routes.delete("/api/namespaces/{namespace}/notebooks/{name}")
async def delete_notebook(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "delete", "Notebook", ns)
    await kube.delete("Notebook", name, ns)
    return json_success({"message": f"Notebook {name} deleted"})


# ---- serving workload class (registered only with KFTPU_SERVING on) ----------

serving_routes = web.RouteTableDef()


def _summarize_serving(isvc: dict) -> dict:
    from kubeflow_tpu.web.common.status import process_serving_status

    meta = get_meta(isvc)
    status = process_serving_status(isvc)
    return {
        "name": meta.get("name"),
        "namespace": meta.get("namespace"),
        "age": meta.get("creationTimestamp"),
        "tpu": deep_get(isvc, "spec", "tpu"),
        "scaling": deep_get(isvc, "spec", "scaling"),
        "serving": deep_get(isvc, "status", "serving"),
        "readyReplicas": deep_get(isvc, "status", "readyReplicas"),
        "status": {"phase": status.phase, "message": status.message},
    }


@serving_routes.get("/api/namespaces/{namespace}/inferenceservices")
async def list_inferenceservices(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "list", "InferenceService", ns)
    services = [
        _summarize_serving(isvc)
        for isvc in await kube.list("InferenceService", ns)
    ]
    return json_success({"inferenceservices": services})


@serving_routes.get("/api/namespaces/{namespace}/inferenceservices/{name}")
async def get_inferenceservice(request):
    from kubeflow_tpu.web.common.status import process_serving_status

    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "get", "InferenceService", ns)
    isvc = await kube.get("InferenceService", name, ns)
    return json_success(
        {"inferenceservice": isvc,
         "processedStatus": process_serving_status(isvc).__dict__})


@serving_routes.delete("/api/namespaces/{namespace}/inferenceservices/{name}")
async def delete_inferenceservice(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "delete", "InferenceService", ns)
    await kube.delete("InferenceService", name, ns)
    return json_success({"message": f"InferenceService {name} deleted"})
