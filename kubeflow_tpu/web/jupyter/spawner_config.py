"""The admin-facing spawner configuration ("flag system" of the spawner UI).

Reference: ``crud-web-apps/jupyter/backend/apps/common/yaml/
spawner_ui_config.yaml:10-220`` — per-field ``value`` / ``options`` /
``readOnly``; the server enforces readOnly regardless of what the form
POSTs (form.py:16-60).

TPU-native delta: the reference's ``gpus.vendors`` block
(nvidia.com/gpu / amd.com/gpu, yaml:120-141) is replaced by a ``tpus``
block of accelerator **types + topologies** derived from the topology
library — the UI renders a slice picker, not a count spinner, because chip
count alone under-specifies a slice.
"""

from __future__ import annotations

import copy

from kubeflow_tpu.tpu.topology import ACCELERATORS, TpuSlice

SERVER_TYPE_JUPYTER = "jupyter"      # NB_PREFIX-aware images
SERVER_TYPE_GROUP_ONE = "group-one"  # vscode-like: rewrite to /
SERVER_TYPE_GROUP_TWO = "group-two"  # rstudio-like: X-RStudio-Root-Path header


def tpu_options() -> list[dict]:
    """Accelerator picker options straight from the topology library."""
    out = []
    for acc in ACCELERATORS.values():
        topologies = []
        for topo in acc.topologies:
            s = TpuSlice.parse(acc.name, topo)
            topologies.append(
                {
                    "topology": topo,
                    "chips": s.num_chips,
                    "hosts": s.num_hosts,
                    "multiHost": s.multi_host,
                }
            )
        out.append(
            {
                "accelerator": acc.name,
                "gkeAccelerator": acc.gke_accelerator,
                "hbmGiBPerChip": acc.hbm_gib_per_chip,
                "topologies": topologies,
            }
        )
    return out


DEFAULT_CONFIG: dict = {
    "image": {
        "value": "kubeflow-tpu/jupyter-jax:latest",
        "options": [
            "kubeflow-tpu/jupyter-scipy:latest",
            "kubeflow-tpu/jupyter-jax:latest",
            "kubeflow-tpu/jupyter-jax-full:latest",
            "kubeflow-tpu/jupyter-pytorch-xla:latest",
            "kubeflow-tpu/jupyter-pytorch-xla-full:latest",
        ],
        "readOnly": False,
    },
    "imageGroupOne": {
        "value": "kubeflow-tpu/codeserver-python:latest",
        "options": ["kubeflow-tpu/codeserver-python:latest"],
    },
    "imageGroupTwo": {
        "value": "kubeflow-tpu/rstudio-tidyverse:latest",
        "options": ["kubeflow-tpu/rstudio-tidyverse:latest"],
    },
    "allowCustomImage": True,
    "imagePullPolicy": {"value": "IfNotPresent", "readOnly": False},
    "cpu": {"value": "0.5", "limitFactor": "1.2", "readOnly": False},
    "memory": {"value": "1.0Gi", "limitFactor": "1.2", "readOnly": False},
    # The TPU block (replaces the reference's gpus.vendors).
    "tpus": {
        "value": "none",
        "readOnly": False,
        "options": tpu_options(),
    },
    "workspaceVolume": {
        "value": {
            "mount": "/home/jovyan",
            "newPvc": {
                "metadata": {"name": "{notebook-name}-workspace"},
                "spec": {
                    "resources": {"requests": {"storage": "5Gi"}},
                    "accessModes": ["ReadWriteOnce"],
                },
            },
        },
        "readOnly": False,
    },
    "dataVolumes": {"value": [], "readOnly": False},
    "shm": {"value": True, "readOnly": False},
    "configurations": {"value": [], "readOnly": False},
    "affinityConfig": {"value": "", "options": [], "readOnly": False},
    "tolerationGroup": {
        "value": "",
        "options": [
            {
                "groupKey": "tpu-reserved",
                "displayName": "TPU reserved pool",
                "tolerations": [
                    {"key": "google.com/tpu", "operator": "Exists",
                     "effect": "NoSchedule"}
                ],
            }
        ],
        "readOnly": False,
    },
    "environment": {"value": {}, "readOnly": False},
}


def load_config(path: str | None = None) -> dict:
    """Admin config from YAML (mounted ConfigMap in deployment) merged over
    the defaults; None → defaults."""
    config = copy.deepcopy(DEFAULT_CONFIG)
    if path:
        import yaml

        with open(path) as f:
            loaded = yaml.safe_load(f) or {}
        config.update(loaded.get("spawnerFormDefaults", loaded))
    return config


def get_form_value(config: dict, body: dict, field: str, body_field: str | None = None):
    """readOnly enforcement (form.py:16-60): a readOnly field always takes
    the admin-configured value, no matter what the form sent."""
    entry = config.get(field, {})
    if not isinstance(entry, dict):
        return entry
    if entry.get("readOnly"):
        return entry.get("value")
    return body.get(body_field or field, entry.get("value"))
