"""Jupyter web app (JWA) backend — notebook CRUD for the spawner UI."""

from kubeflow_tpu.web.jupyter.app import create_app

__all__ = ["create_app"]
