"""Form → Notebook CR assembly.

Reference: ``crud-web-apps/jupyter/backend/apps/common/form.py`` (setters
for image/cpu/memory/gpus/tolerations/affinity/shm/configurations, composed
by ``apps/default/routes/post.py:12-77`` over ``notebook_template.yaml``).
Ours builds the CR directly (the template is the ``api.notebook.new``
contract), with the same readOnly enforcement and the TPU picker replacing
the GPU vendor spinner.
"""

from __future__ import annotations

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.web.jupyter.spawner_config import (
    SERVER_TYPE_GROUP_ONE,
    SERVER_TYPE_GROUP_TWO,
    SERVER_TYPE_JUPYTER,
    get_form_value,
)


def notebook_from_form(config: dict, body: dict, namespace: str, user: str) -> tuple[dict, list[dict]]:
    """→ (notebook CR, PVCs to create). Raises Invalid on bad input."""
    name = body.get("name", "")
    if not name:
        raise Invalid("form: name is required")

    server_type = body.get("serverType", SERVER_TYPE_JUPYTER)
    image = _image_for(config, body, server_type)

    cpu = str(get_form_value(config, body, "cpu"))
    memory = str(get_form_value(config, body, "memory"))
    cpu_limit = _scaled(cpu, config.get("cpu", {}).get("limitFactor"))
    memory_limit = _scaled_mem(memory, config.get("memory", {}).get("limitFactor"))

    container: dict = {
        "name": name,
        "image": image,
        "imagePullPolicy": get_form_value(config, body, "imagePullPolicy"),
        "resources": {
            "requests": {"cpu": cpu, "memory": memory},
            "limits": {"cpu": cpu_limit, "memory": memory_limit},
        },
        "env": [],
        "volumeMounts": [],
    }
    pod_spec: dict = {"containers": [container], "volumes": []}

    for k, v in (get_form_value(config, body, "environment") or {}).items():
        container["env"].append({"name": k, "value": str(v)})

    pvcs = _apply_volumes(config, body, name, namespace, pod_spec, container)

    if get_form_value(config, body, "shm"):
        pod_spec["volumes"].append(
            {"name": "dshm", "emptyDir": {"medium": "Memory"}}
        )
        container["volumeMounts"].append({"name": "dshm", "mountPath": "/dev/shm"})

    _apply_tolerations(config, body, pod_spec)
    _apply_affinity(config, body, pod_spec)

    nb = {
        "apiVersion": nbapi.API_VERSION,
        "kind": nbapi.KIND,
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": dict(body.get("labels") or {}),
            "annotations": {
                nbapi.SERVER_TYPE_ANNOTATION: server_type,
                nbapi.CREATOR_ANNOTATION: user,
            },
        },
        "spec": {"template": {"spec": pod_spec}},
    }
    # Record the spawner's image pick so the admission catalog can pin it
    # (odh's last-image-selection contract, notebook_webhook.go:556). Any
    # tagged, non-digest image qualifies — the catalog key is the full
    # repository path (e.g. "kubeflow-tpu/jupyter-jax").
    if ":" in image.rsplit("/", 1)[-1] and "@sha256:" not in image:
        nb["metadata"]["annotations"][nbapi.IMAGE_SELECTION_ANNOTATION] = image
    if server_type == SERVER_TYPE_GROUP_ONE:
        nb["metadata"]["annotations"][nbapi.ANNOTATION_REWRITE_URI] = "/"
    elif server_type == SERVER_TYPE_GROUP_TWO:
        nb["metadata"]["annotations"][nbapi.ANNOTATION_HEADERS_REQUEST_SET] = (
            '{"X-RStudio-Root-Path": "/notebook/%s/%s/"}' % (namespace, name)
        )

    # "configurations": labels selecting PodDefaults to apply (yaml:163-171).
    for label in get_form_value(config, body, "configurations") or []:
        nb["metadata"]["labels"][label] = "true"
        nb["spec"]["template"].setdefault("metadata", {}).setdefault(
            "labels", {}
        )[label] = "true"

    tpu = _tpu_from_form(config, body)
    if tpu:
        nb["spec"]["tpu"] = tpu
    return nb, pvcs


def _image_for(config: dict, body: dict, server_type: str) -> str:
    field = {
        SERVER_TYPE_JUPYTER: "image",
        SERVER_TYPE_GROUP_ONE: "imageGroupOne",
        SERVER_TYPE_GROUP_TWO: "imageGroupTwo",
    }.get(server_type)
    if field is None:
        raise Invalid(f"form: unknown serverType {server_type!r}")
    if body.get("customImage") and config.get("allowCustomImage", True):
        return str(body["customImage"]).strip()
    return get_form_value(config, body, field, "image")


def _tpu_from_form(config: dict, body: dict) -> dict | None:
    """TPU picker (replaces the reference's gpus vendor/num block)."""
    entry = config.get("tpus", {})
    if entry.get("readOnly"):
        value = entry.get("value")
        if not value or value == "none":
            return None
        return dict(value)
    tpu = body.get("tpu")
    if not tpu or tpu in ("none", {}):
        return None
    if not isinstance(tpu, dict) or "accelerator" not in tpu:
        raise Invalid("form: tpu must be {accelerator, topology[, numSlices]}")
    out = {
        "accelerator": str(tpu["accelerator"]),
        "topology": str(tpu.get("topology", "1x1")),
    }
    num_slices = tpu.get("numSlices")
    # Strict typing BEFORE the default-membership test: `true == 1` and
    # `1.0 == 1` in Python, so a membership check first would silently
    # admit bools/floats as "one slice" instead of rejecting them.
    if num_slices is not None and (
        isinstance(num_slices, bool) or not isinstance(num_slices, (int, str))
    ):
        raise Invalid(f"form: numSlices must be an integer, got {num_slices!r}")
    if num_slices not in (None, "", 1, "1"):
        try:
            out["numSlices"] = int(num_slices)
        except ValueError:
            raise Invalid(f"form: numSlices must be an integer, got {num_slices!r}")
    queued = tpu.get("queuedProvisioning")
    if queued not in (None, False, True):
        raise Invalid(
            f"form: queuedProvisioning must be a boolean, got {queued!r}")
    if queued:
        out["queuedProvisioning"] = True
    return out


def _apply_volumes(config, body, name, namespace, pod_spec, container) -> list[dict]:
    """Workspace + data volumes; '{notebook-name}' templating like the
    reference; returns new PVCs to create (dry-run-first in the route)."""
    pvcs: list[dict] = []

    def add_volume(spec: dict, default_mount: str, idx: int) -> None:
        mount = spec.get("mount", default_mount)
        if "existingSource" in spec:
            source = spec["existingSource"]
            vol_name = f"vol-{idx}"
            pod_spec["volumes"].append({"name": vol_name, **source})
        else:
            new_pvc = spec.get("newPvc") or {}
            pvc_name = (
                (new_pvc.get("metadata") or {}).get("name")
                or f"{name}-vol-{idx}"
            ).replace("{notebook-name}", name)
            pvc = {
                "apiVersion": "v1",
                "kind": "PersistentVolumeClaim",
                "metadata": {"name": pvc_name, "namespace": namespace},
                "spec": new_pvc.get("spec")
                or {
                    "accessModes": ["ReadWriteOnce"],
                    "resources": {"requests": {"storage": "5Gi"}},
                },
            }
            pvcs.append(pvc)
            vol_name = pvc_name
            pod_spec["volumes"].append(
                {
                    "name": vol_name,
                    "persistentVolumeClaim": {"claimName": pvc_name},
                }
            )
        container["volumeMounts"].append({"name": vol_name, "mountPath": mount})

    workspace = get_form_value(config, body, "workspaceVolume")
    if workspace:
        add_volume(dict(workspace), "/home/jovyan", 0)
    for i, vol in enumerate(get_form_value(config, body, "dataVolumes") or [], 1):
        add_volume(dict(vol), f"/home/jovyan/data-{i}", i)
    return pvcs


def _apply_tolerations(config, body, pod_spec) -> None:
    group_key = get_form_value(config, body, "tolerationGroup")
    if not group_key:
        return
    for group in config.get("tolerationGroup", {}).get("options", []):
        if group.get("groupKey") == group_key:
            pod_spec["tolerations"] = list(group.get("tolerations", []))
            return
    raise Invalid(f"form: unknown tolerationGroup {group_key!r}")


def _apply_affinity(config, body, pod_spec) -> None:
    affinity_key = get_form_value(config, body, "affinityConfig")
    if not affinity_key:
        return
    for option in config.get("affinityConfig", {}).get("options", []):
        if option.get("configKey") == affinity_key:
            pod_spec["affinity"] = option.get("affinity", {})
            return
    raise Invalid(f"form: unknown affinityConfig {affinity_key!r}")


def _scaled(value: str, factor) -> str:
    if factor in (None, "", "none"):
        return value
    try:
        return str(round(float(value) * float(factor), 3))
    except ValueError:
        return value


def _scaled_mem(value: str, factor) -> str:
    if factor in (None, "", "none"):
        return value
    for suffix in ("Gi", "Mi", "Ki", "G", "M", "K"):
        if value.endswith(suffix):
            try:
                scaled = float(value[: -len(suffix)]) * float(factor)
                return f"{round(scaled, 3)}{suffix}"
            except ValueError:
                return value
    return _scaled(value, factor)
