/* JWA frontend: resource table + spawner form + details drawer.
 *
 * The reference's Angular jupyter app distilled onto the shared KF lib:
 * sortable resource table with status icons and polling, TPU
 * accelerator/topology pickers from /api/tpus, confirm dialogs, and a
 * details drawer with Overview / TPU slice / Conditions / Events / Logs /
 * YAML tabs wired to the backend's pod, events and logs routes.
 */

/* Static-chrome + form-label keys (the dynamic strings use the common
 * jwa.* / table.* / action.* catalogs in kubeflow.js). */
KF.registerMessages("en", {
  "jwa.title": "Notebook Servers",
  "jwa.namespace": "namespace",
  "jwa.fromYaml": "From YAML",
  "jwa.fromYamlTitle": "Create a Notebook from a raw manifest",
  "jwa.newNotebook": "+ New notebook",
  "jwa.formTitle": "New notebook server",
  "jwa.formName": "Name",
  "jwa.formServerType": "Server type",
  "jwa.formImage": "Image",
  "jwa.formCustomImage": "Custom image",
  "jwa.formTopology": "Topology",
  "jwa.formSlices": "Slices",
  "jwa.formCapacity": "Capacity",
  "jwa.queuedHint":
    "queue a ProvisioningRequest (start when capacity is reserved)",
  "jwa.formAdvanced": "Advanced",
  "jwa.formWorkspaceVolume": "Workspace volume",
  "jwa.formDataVolumes": "Data volumes",
  "jwa.formConfigurations": "Configurations",
  "jwa.noneAvailable": "none available",
  "jwa.formSharedMemory": "Shared memory",
  "jwa.shmMount": "mount",
  "jwa.launch": "Launch",
});
KF.registerMessages("de", {
  "jwa.title": "Notebook-Server",
  "jwa.namespace": "Namespace",
  "jwa.fromYaml": "Aus YAML",
  "jwa.fromYamlTitle": "Notebook aus einem Roh-Manifest erstellen",
  "jwa.newNotebook": "+ Neues Notebook",
  "jwa.formTitle": "Neuer Notebook-Server",
  "jwa.formName": "Name",
  "jwa.formServerType": "Server-Typ",
  "jwa.formImage": "Image",
  "jwa.formCustomImage": "Eigenes Image",
  "jwa.formTopology": "Topologie",
  "jwa.formSlices": "Slices",
  "jwa.formCapacity": "Kapazität",
  "jwa.queuedHint":
    "ProvisioningRequest einreihen (Start, sobald Kapazität reserviert ist)",
  "jwa.formAdvanced": "Erweitert",
  "jwa.formWorkspaceVolume": "Workspace-Volume",
  "jwa.formDataVolumes": "Daten-Volumes",
  "jwa.formConfigurations": "Konfigurationen",
  "jwa.noneAvailable": "keine verfügbar",
  "jwa.formSharedMemory": "Gemeinsamer Speicher",
  "jwa.shmMount": "einhängen:",
  "jwa.launch": "Starten",
});
KF.registerMessages("fr", {
  "jwa.title": "Serveurs de notebooks",
  "jwa.namespace": "namespace",
  "jwa.fromYaml": "Depuis YAML",
  "jwa.fromYamlTitle": "Créer un Notebook à partir d'un manifeste brut",
  "jwa.newNotebook": "+ Nouveau notebook",
  "jwa.formTitle": "Nouveau serveur de notebooks",
  "jwa.formName": "Nom",
  "jwa.formServerType": "Type de serveur",
  "jwa.formImage": "Image",
  "jwa.formCustomImage": "Image personnalisée",
  "jwa.formTopology": "Topologie",
  "jwa.formSlices": "Slices",
  "jwa.formCapacity": "Capacité",
  "jwa.queuedHint":
    "mettre en file une ProvisioningRequest (démarre quand la capacité " +
    "est réservée)",
  "jwa.formAdvanced": "Avancé",
  "jwa.formWorkspaceVolume": "Volume d'espace de travail",
  "jwa.formDataVolumes": "Volumes de données",
  "jwa.formConfigurations": "Configurations",
  "jwa.noneAvailable": "aucune disponible",
  "jwa.formSharedMemory": "Mémoire partagée",
  "jwa.shmMount": "monter",
  "jwa.launch": "Lancer",
});

let tpuCatalog = [];
let tablePoller = null;

/* Shared catalogs for the volume panels (KF.volumePanel): PVCs are
 * per-namespace; storage classes are cluster-scoped. The same object is
 * handed to every panel, so a namespace change refreshes them all. */
const volumeCatalogs = { pvcs: [], storageClasses: [], defaultClass: "" };
let workspacePanel = null;
let dataVolumesList = null;

function renderVolumeForms() {
  workspacePanel = KF.volumePanel({ kind: "workspace",
                                    catalogs: volumeCatalogs });
  document.getElementById("workspace-volume-slot")
    .replaceChildren(workspacePanel.root);
  dataVolumesList = KF.dataVolumesForm(
    document.getElementById("data-volumes-slot"), volumeCatalogs);
}

async function loadStorageCatalogs() {
  const [classes, dflt] = await Promise.all([
    api("api/storageclasses").catch(() => ({ storageClasses: [] })),
    api("api/storageclasses/default").catch(
      () => ({ defaultStorageClass: "" })),
  ]);
  volumeCatalogs.storageClasses = classes.storageClasses || [];
  volumeCatalogs.defaultClass = dflt.defaultStorageClass || "";
  renderVolumeForms();
}

async function loadNamespaceCatalogs() {
  /* PVCs for the volume panels + PodDefaults for configurations —
   * refetched on namespace change. */
  const [pvcs, pds] = await Promise.all([
    api(`api/namespaces/${ns.get()}/pvcs`).catch(() => ({ pvcs: [] })),
    api(`api/namespaces/${ns.get()}/poddefaults`).catch(() => ({
      poddefaults: [],
    })),
  ]);
  // The backend hands back raw PVC objects; the panels want name+size.
  volumeCatalogs.pvcs = (pvcs.pvcs || []).map((p) => ({
    name: ((p.metadata || {}).name) || p.name || "",
    capacity:
      ((((p.spec || {}).resources || {}).requests || {}).storage) ||
      p.capacity || "",
  })).filter((p) => p.name);
  renderVolumeForms();
  const slot = document.getElementById("configurations-slot");
  const options = pds.poddefaults || [];
  slot.classList.toggle("muted", !options.length);
  slot.replaceChildren(
    options.length
      ? options.map((pd) =>
          el(
            "label",
            { style: { display: "inline-flex", gap: "6px", marginRight: "14px" } },
            el("input", {
              type: "checkbox",
              name: "configuration",
              value: pd.label,
              style: { width: "auto" },
            }),
            pd.desc || pd.label
          )
        )
      : KF.t("jwa.noneAvailable")
  );
}

let spawnerConfig = {};

// Reference spawner_ui_config: image options per server type (jupyter-like
// NB_PREFIX images, vscode-like group-one, rstudio-like group-two).
const IMAGE_KEY_BY_TYPE = {
  jupyter: "image",
  "group-one": "imageGroupOne",
  "group-two": "imageGroupTwo",
};

function selectedServerType() {
  const checked = document.querySelector('input[name="serverType"]:checked');
  return checked ? checked.value : "jupyter";
}

function renderImageOptions() {
  const key = IMAGE_KEY_BY_TYPE[selectedServerType()];
  const images = (spawnerConfig[key] && spawnerConfig[key].options) || [];
  document
    .getElementById("image-select")
    .replaceChildren(...images.map((img) => el("option", { value: img }, img)));
}

async function loadCatalogs() {
  const [tpus, config] = await Promise.all([api("api/tpus"), api("api/config")]);
  tpuCatalog = tpus.tpus;
  spawnerConfig = config.config;

  document.getElementById("tpu-help-slot").replaceChildren(
    KF.helpPopover(
      "Accelerator + topology pick a whole TPU slice: multi-host " +
        "topologies spawn one worker pod per host with TPU_WORKER_* wired " +
        "for jax.distributed."
    )
  );

  const accSelect = document.getElementById("tpu-acc");
  // NB: replaceChildren stringifies arrays — always spread node lists.
  accSelect.replaceChildren(
    el("option", { value: "" }, "none (CPU)"),
    ...tpuCatalog.map((t) => el("option", { value: t.accelerator }, t.accelerator))
  );
  accSelect.addEventListener("change", renderTopologies);
  renderTopologies();

  for (const radio of document.querySelectorAll('input[name="serverType"]')) {
    radio.addEventListener("change", renderImageOptions);
  }
  renderImageOptions();
}

function renderTopologies() {
  const acc = document.getElementById("tpu-acc").value;
  const topoSelect = document.getElementById("tpu-topo");
  const entry = tpuCatalog.find((t) => t.accelerator === acc);
  topoSelect.replaceChildren(
    ...(entry ? entry.topologies : []).map((t) =>
      el(
        "option",
        { value: t.topology },
        `${t.topology} — ${t.chips} chips, ${t.hosts} host${t.hosts > 1 ? "s" : ""}`
      )
    )
  );
  renderNumSlices();
}

function renderNumSlices() {
  /* Multislice (DCN-joined slices) and queued provisioning only make
   * sense with a TPU selected: show those controls then, hide (and
   * reset) them for CPU. */
  const acc = document.getElementById("tpu-acc").value;
  const input = document.getElementById("num-slices");
  const label = document.getElementById("num-slices-label");
  const show = acc ? "" : "none";
  input.style.display = show;
  label.style.display = show;
  if (!acc) input.value = "1";
  document.getElementById("queued-label").style.display = show;
  document.getElementById("queued-row").style.display =
    acc ? "inline-flex" : "none";
  if (!acc) document.getElementById("queued-prov").checked = false;
}

/* ---------------- details drawer ---------------------------------------- */

let openDrawerFor = null;

function openDetails(nb) {
  const name = nb.name;
  if (openDrawerFor === name) return;
  openDrawerFor = name;
  // Deep-linkable (the reference's per-resource details route).
  if (location.hash !== `#/notebook/${name}`) {
    history.replaceState(null, "", `#/notebook/${name}`);
  }
  const drawer = KF.drawer(`Notebook ${name}`);
  const tabHost = el("div", {});
  drawer.content.append(tabHost);

  const podsFor = () =>
    api(`api/namespaces/${ns.get()}/notebooks/${name}/pod`).then((body) =>
      body.pods.map((p) => ({
        name: p.metadata.name,
        ready: (p.status && p.status.phase) === "Running",
      }))
    );

  const tabs = KF.tabs(tabHost, [
    {
      label: "Overview",
      render: (pane) => {
        const status = el("div", {});
        const slice = el("div", {});
        pane.append(
          el("h4", {}, "Status"),
          status,
          el("h4", {}, "TPU slice"),
          slice
        );
        async function load() {
          const body = await api(
            `api/namespaces/${ns.get()}/notebooks/${name}`
          );
          const meta = body.notebook.metadata || {};
          const ps = body.processedStatus || {};
          status.replaceChildren(
            KF.detailsList([
              ["Status", KF.statusDot(ps.phase, ps.message)],
              ["Message", ps.message],
              ["Image", nb.image],
              ["CPU / Memory", `${nb.cpu || "—"} / ${nb.memory || "—"}`],
              ["Created", KF.ageCell(meta.creationTimestamp, " ago")],
              [
                "Connect",
                el(
                  "a",
                  { href: KF.urls.notebook(ns.get(), name), target: "_blank" },
                  KF.urls.notebook(ns.get(), name)
                ),
              ],
            ])
          );
          const pods = await podsFor().catch(() => []);
          const nbAnns =
            (body.notebook.metadata && body.notebook.metadata.annotations) ||
            {};
          KF.sliceRollup(
            slice,
            body.notebook.spec && body.notebook.spec.tpu,
            body.notebook.status && body.notebook.status.tpu,
            pods,
            {
              maintenancePending:
                nbAnns["notebooks.kubeflow.org/maintenance-pending"],
            }
          );
        }
        load().catch(KF.showError);
        const t = setInterval(() => load().catch(() => {}), 5000);
        return { stop: () => clearInterval(t) };
      },
    },
    {
      label: "Conditions",
      render: (pane) => {
        const host = el("div", {});
        pane.append(host);
        api(`api/namespaces/${ns.get()}/notebooks/${name}`)
          .then((body) =>
            KF.conditionsTable(
              host,
              (body.notebook.status && body.notebook.status.conditions) || []
            )
          )
          .catch(KF.showError);
      },
    },
    {
      label: "Events",
      render: (pane) => {
        const host = el("div", {});
        pane.append(host);
        async function load() {
          const body = await api(
            `api/namespaces/${ns.get()}/notebooks/${name}/events`
          );
          KF.eventsTable(host, body.events);
        }
        load().catch(KF.showError);
        const t = setInterval(() => load().catch(() => {}), 5000);
        return { stop: () => clearInterval(t) };
      },
    },
    {
      label: "Env",
      render: (pane) => {
        /* Worker-0 environment grouped by source — the TPU_ and JAX_
         * wiring is the first thing to check when a slice won't
         * bootstrap. */
        const host = el("div", {});
        pane.append(host);
        KF.withSpinner(
          host,
          api(`api/namespaces/${ns.get()}/notebooks/${name}/pod`),
          (slot, body) => {
            const containers =
              ((body.pods[0] || {}).spec || {}).containers || [];
            const env = ((containers[0] || {}).env || []).map((e) => ({
              key: e.name,
              value:
                e.value !== undefined
                  ? e.value
                  : e.valueFrom
                    ? "(downward API)"
                    : "",
            }));
            const groups = [
              {
                name: "TPU slice",
                vars: env.filter((v) => v.key.startsWith("TPU_")),
              },
              {
                name: "JAX / megascale",
                vars: env.filter(
                  (v) =>
                    v.key.startsWith("JAX_") || v.key.startsWith("MEGASCALE_")
                ),
              },
              {
                name: "Other",
                vars: env.filter(
                  (v) =>
                    !v.key.startsWith("TPU_") &&
                    !v.key.startsWith("JAX_") &&
                    !v.key.startsWith("MEGASCALE_")
                ),
              },
            ].filter((group) => group.vars.length);
            KF.varsGroupsTable(slot, groups);
          }
        ).catch(() => {});
      },
    },
    {
      label: "Logs",
      render: (pane) => {
        const host = el("div", {});
        pane.append(host);
        let viewer = null;
        podsFor()
          .then((pods) => {
            viewer = KF.logsViewer(host, pods, (pod) =>
              api(
                `api/namespaces/${ns.get()}/notebooks/${name}/pod/${pod}/logs`
              ).then((body) => body.logs)
            );
          })
          .catch((err) => {
            host.replaceChildren(
              el("p", { class: "muted" }, "No pods yet: " + err.message)
            );
          });
        return { stop: () => viewer && viewer.stop() };
      },
    },
    {
      label: "YAML",
      render: (pane) => {
        const host = el("div", {});
        pane.append(host);
        api(`api/namespaces/${ns.get()}/notebooks/${name}`)
          .then((body) => KF.yamlView(host, body.notebook))
          .catch(KF.showError);
      },
    },
  ]);
  drawer.onclose = () => {
    tabs.stop();
    openDrawerFor = null;
    if (location.hash.startsWith("#/notebook/")) {
      history.replaceState(null, "", location.pathname);
    }
  };
}

function openDetailsFromHash() {
  const match = location.hash.match(/^#\/notebook\/([a-z0-9-]+)$/);
  if (!match) return;
  api(`api/namespaces/${ns.get()}/notebooks/${match[1]}`)
    .then((body) => {
      const nb = body.notebook;
      const containers =
        (((nb.spec || {}).template || {}).spec || {}).containers || [{}];
      openDetails({
        name: match[1],
        image: containers[0].image || "",
        cpu: null,
        memory: null,
      });
    })
    .catch(() => {});
}

/* ---------------- list table -------------------------------------------- */

async function refresh() {
  const body = await api(`api/namespaces/${ns.get()}/notebooks`);
  const columns = [
    {
      title: () => KF.t("table.status"),
      render: (nb) => statusDot(nb.status.phase, nb.status.message),
      sortKey: (nb) => nb.status.phase,
    },
    { title: () => KF.t("table.name"), render: (nb) => nb.name, sortKey: (nb) => nb.name },
    {
      title: () => KF.t("table.image"),
      render: (nb) => nb.image.split("/").pop(),
      sortKey: (nb) => nb.image,
    },
    { title: () => KF.t("table.cpu"), render: (nb) => nb.cpu || "—" },
    { title: () => KF.t("table.memory"), render: (nb) => nb.memory || "—" },
    {
      title: () => KF.t("table.tpu"),
      render: (nb) =>
        nb.tpu
          ? el(
              "span",
              {},
              el(
                "span",
                { class: "chip" },
                `${nb.tpu.accelerator} ${nb.tpu.topology}` +
                  (nb.tpu.numSlices > 1 ? ` ×${nb.tpu.numSlices}` : "")
              ),
              nb.tpuStatus
                ? `${nb.tpuStatus.readyHosts}/${nb.tpuStatus.hosts} hosts`
                : ""
            )
          : "—",
      sortKey: (nb) => (nb.tpu ? nb.tpu.accelerator : ""),
    },
    {
      title: () => KF.t("table.age"),
      render: (nb) => KF.ageCell(nb.age),
      sortKey: (nb) => nb.age || "",
    },
    {
      title: () => KF.t("table.lastActivity"),
      render: (nb) => (nb.lastActivity ? KF.ageCell(nb.lastActivity, " ago") : "—"),
      sortKey: (nb) => nb.lastActivity || "",
    },
    {
      title: () => KF.t("table.actions"),
      render: (nb) => {
        const stopped = nb.status.phase === "stopped";
        return el(
          "span",
          {},
          KF.actionButton(stopped ? KF.t("action.start") : KF.t("action.stop"), () =>
            api(`api/namespaces/${ns.get()}/notebooks/${nb.name}`, {
              method: "PATCH",
              body: JSON.stringify({ stopped: !stopped }),
            }).then(() => {
              KF.snackbar(
                (stopped ? "Starting " : "Stopping ") + nb.name
              );
              tablePoller.refresh();
            }, showError)
          ),
          " ",
          KF.actionButton(
            KF.t("action.delete"),
            () =>
              KF.confirmDialog({
                title: `Delete notebook ${nb.name}?`,
                message:
                  "The notebook's pods are deleted; workspace volumes are kept.",
              }).then(
                (ok) =>
                  ok &&
                  api(`api/namespaces/${ns.get()}/notebooks/${nb.name}`, {
                    method: "DELETE",
                  }).then(() => {
                    KF.snackbar("Deleting " + nb.name);
                    tablePoller.refresh();
                  }, showError)
              ),
            { class: "danger" }
          ),
          " ",
          el(
            "a",
            {
              href: KF.urls.notebook(ns.get(), nb.name),
              target: "_blank",
              onclick: (ev) => ev.stopPropagation(),
            },
            KF.t("action.connect")
          )
        );
      },
    },
  ];
  renderTable(document.getElementById("notebook-table"), columns, body.notebooks, {
    onRowClick: openDetails,
    emptyText: KF.t("jwa.empty"),
    pageSize: 25,
    filterable: true,
  });
}

/* ---------------- spawner form ------------------------------------------ */

const nameInput = document.querySelector('#new-form input[name="name"]');
const cpuInput = document.querySelector('#new-form input[name="cpu"]');
const memInput = document.querySelector('#new-form input[name="memory"]');
const checks = [
  KF.validate(nameInput, KF.validators.dns1123),
  KF.validate(cpuInput, KF.validators.positiveNumber),
  KF.validate(memInput, KF.validators.memoryQuantity),
];

/* Advanced options: collapsed by default; extra environment variables as
 * a KEY=VALUE chips input (feeds the backend's `environment` form field),
 * plus the admin-defined toleration preset when the config offers one. */
let extraEnv = [];
document.getElementById("advanced-slot").append(
  KF.advancedSection("Advanced options", (pane) => {
    // Admin presets share one builder: label + select with a "none"
    // option, keyed by the config's option-key field. Call sites pass
    // the id as a literal attrs object so static DOM-contract checks
    // can see which ids the JS creates.
    const presetSelect = (attrs, label, options, keyField) =>
      options.length
        ? [
            el(
              "label",
              { style: { display: "block", margin: "10px 0 4px" } },
              label
            ),
            el(
              "select",
              Object.assign({ style: { width: "auto" } }, attrs),
              el("option", { value: "" }, "none"),
              ...options.map((opt) =>
                el(
                  "option",
                  { value: opt[keyField] },
                  opt.displayName || opt[keyField]
                )
              )
            ),
          ]
        : [];
    pane.append(
      el("label", { style: { display: "block", marginBottom: "4px" } },
        "Environment variables (KEY=VALUE)"),
      KF.chipsInput(extraEnv, (values) => {
        extraEnv = values;
      }, {
        placeholder: "e.g. JAX_LOG_LEVEL=INFO",
        validate: (value) =>
          /^[A-Za-z_][A-Za-z0-9_]*=.*$/.test(value)
            ? null
            : "Use KEY=VALUE (key: letters, digits, underscores).",
      }),
      ...presetSelect(
        { id: "toleration-group" }, "Toleration preset",
        (spawnerConfig.tolerationGroup &&
          spawnerConfig.tolerationGroup.options) || [],
        "groupKey"
      ),
      ...presetSelect(
        { id: "affinity-config" }, "Affinity preset",
        (spawnerConfig.affinityConfig &&
          spawnerConfig.affinityConfig.options) || [],
        "configKey"
      )
    );
  })
);

document.getElementById("new-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "block";
});
document.getElementById("yaml-btn").addEventListener("click", () => {
  const template = [
    "apiVersion: kubeflow.org/v1",
    "kind: Notebook",
    "metadata:",
    "  name: my-notebook",
    "spec:",
    "  tpu:",
    "    accelerator: v5e",
    '    topology: "2x2"',
    "  template:",
    "    spec:",
    "      containers:",
    "        - name: my-notebook",
    "          image: kubeflow-tpu/jupyter-jax:latest",
    "",
  ].join("\n");
  KF.yamlEditDialog({
    title: "Create Notebook from YAML",
    initial: template,
    submitText: "Create",
    onSubmit: (text) =>
      api(`api/namespaces/${ns.get()}/notebooks/yaml`, {
        method: "POST",
        headers: { "Content-Type": "application/yaml" },
        body: text,
      }),
  }).then((created) => {
    if (created) {
      KF.snackbar("Notebook created");
      tablePoller.refresh();
    }
  });
});
document.getElementById("cancel-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "none";
});
document.getElementById("new-form").addEventListener("submit", (ev) => {
  ev.preventDefault();
  if (!checks.every((check) => check())) {
    KF.snackbar("Fix the highlighted fields first.", "error");
    return;
  }
  const form = new FormData(ev.target);
  const payload = {
    name: form.get("name"),
    serverType: form.get("serverType") || "jupyter",
    cpu: form.get("cpu"),
    memory: form.get("memory"),
  };
  if (form.get("customImage")) payload.customImage = form.get("customImage");
  else payload.image = form.get("image");
  if (form.get("tpu-acc")) {
    payload.tpu = {
      accelerator: form.get("tpu-acc"),
      topology: form.get("tpu-topo"),
    };
    const slices = parseInt(form.get("numSlices"), 10);
    if (slices > 1) payload.tpu.numSlices = slices;
    if (document.getElementById("queued-prov").checked) {
      payload.tpu.queuedProvisioning = true;
    }
  }
  /* Volumes: the panels own the whole story (new-vs-existing, size,
   * class, access mode, mount). A "none" workspace explicitly suppresses
   * the config default; data volumes are included only when present. */
  payload.workspaceVolume = workspacePanel ? workspacePanel.value() : null;
  const dataVols = dataVolumesList ? dataVolumesList.value() : [];
  if (dataVols.length) payload.dataVolumes = dataVols;
  payload.shm = !!form.get("shm");
  const configurations = [
    ...ev.target.querySelectorAll('input[name="configuration"]:checked'),
  ].map((box) => box.value);
  if (configurations.length) payload.configurations = configurations;
  if (extraEnv.length) {
    payload.environment = {};
    for (const entry of extraEnv) {
      const eq = entry.indexOf("=");
      if (eq > 0) payload.environment[entry.slice(0, eq)] = entry.slice(eq + 1);
    }
  }
  const tolerationSelect = document.getElementById("toleration-group");
  if (tolerationSelect && tolerationSelect.value) {
    payload.tolerationGroup = tolerationSelect.value;
  }
  const affinitySelect = document.getElementById("affinity-config");
  if (affinitySelect && affinitySelect.value) {
    payload.affinityConfig = affinitySelect.value;
  }
  api(`api/namespaces/${ns.get()}/notebooks`, {
    method: "POST",
    body: JSON.stringify(payload),
  }).then(() => {
    document.getElementById("new-form-card").style.display = "none";
    KF.snackbar("Creating notebook " + payload.name);
    tablePoller.refresh();
  }, showError);
});

document.getElementById("ns-slot").append(
  namespacePicker(() => {
    tablePoller.refresh();
    loadNamespaceCatalogs().catch(() => {});
  }),
  " ",
  KF.localePicker()
);
/* Locale switch re-renders the live table (headers, status labels,
 * action buttons) AND the already-built volume panels (mode selects,
 * field labels) in place — refresh() alone left the form in the old
 * locale until a namespace change happened to rebuild it. */
KF.localizeDocument();
KF.onLocaleChange(() => {
  renderVolumeForms();
  refresh().catch(() => {});
});
loadCatalogs().catch(showError);
loadStorageCatalogs().catch(() => {});
loadNamespaceCatalogs().catch(() => {});
tablePoller = poll(refresh);
openDetailsFromHash();
window.addEventListener("hashchange", openDetailsFromHash);
