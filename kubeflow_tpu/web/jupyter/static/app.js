/* JWA frontend: table + spawner form (the reference's Angular app distilled;
   TPU accelerator/topology pickers come from /api/tpus). */

let tpuCatalog = [];

async function loadCatalogs() {
  const [tpus, config] = await Promise.all([
    api("api/tpus"),
    api("api/config"),
  ]);
  tpuCatalog = tpus.tpus;

  const accSelect = document.getElementById("tpu-acc");
  // NB: replaceChildren stringifies arrays — always spread node lists.
  accSelect.replaceChildren(
    el("option", { value: "" }, "none (CPU)"),
    ...tpuCatalog.map((t) =>
      el("option", { value: t.accelerator }, t.accelerator)
    )
  );
  accSelect.addEventListener("change", renderTopologies);
  renderTopologies();

  const imageSelect = document.getElementById("image-select");
  const images = (config.config.image && config.config.image.options) || [];
  imageSelect.replaceChildren(
    ...images.map((img) => el("option", { value: img }, img))
  );
}

function renderTopologies() {
  const acc = document.getElementById("tpu-acc").value;
  const topoSelect = document.getElementById("tpu-topo");
  const entry = tpuCatalog.find((t) => t.accelerator === acc);
  topoSelect.replaceChildren(
    ...(entry ? entry.topologies : []).map((t) =>
      el(
        "option",
        { value: t.topology },
        `${t.topology} — ${t.chips} chips, ${t.hosts} host${t.hosts > 1 ? "s" : ""}`
      )
    )
  );
}

async function refresh() {
  const body = await api(`api/namespaces/${ns.get()}/notebooks`);
  const columns = [
    {
      title: "Status",
      render: (nb) => statusDot(nb.status.phase, nb.status.message),
    },
    { title: "Name", render: (nb) => nb.name },
    { title: "Image", render: (nb) => nb.image.split("/").pop() },
    { title: "CPU", render: (nb) => nb.cpu || "-" },
    { title: "Memory", render: (nb) => nb.memory || "-" },
    {
      title: "TPU",
      render: (nb) =>
        nb.tpu
          ? el(
              "span",
              {},
              el("span", { class: "chip" }, `${nb.tpu.accelerator} ${nb.tpu.topology}`),
              nb.tpuStatus
                ? `${nb.tpuStatus.readyHosts}/${nb.tpuStatus.hosts} hosts`
                : ""
            )
          : "—",
    },
    {
      title: "Actions",
      render: (nb) => {
        const stopped = nb.status.phase === "stopped";
        return el(
          "span",
          {},
          el(
            "button",
            {
              onclick: () =>
                api(`api/namespaces/${ns.get()}/notebooks/${nb.name}`, {
                  method: "PATCH",
                  body: JSON.stringify({ stopped: !stopped }),
                }).then(refresh, showError),
            },
            stopped ? "Start" : "Stop"
          ),
          " ",
          el(
            "button",
            { class: "danger",
              onclick: () =>
                confirm(`Delete notebook ${nb.name}?`) &&
                api(`api/namespaces/${ns.get()}/notebooks/${nb.name}`, {
                  method: "DELETE",
                }).then(refresh, showError),
            },
            "Delete"
          ),
          " ",
          el(
            "a",
            { href: `/notebook/${ns.get()}/${nb.name}/`, target: "_blank" },
            "Connect"
          )
        );
      },
    },
  ];
  renderTable(document.getElementById("notebook-table"), columns, body.notebooks);
}

document.getElementById("new-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "block";
});
document.getElementById("cancel-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "none";
});
document.getElementById("new-form").addEventListener("submit", (ev) => {
  ev.preventDefault();
  const form = new FormData(ev.target);
  const payload = {
    name: form.get("name"),
    cpu: form.get("cpu"),
    memory: form.get("memory"),
  };
  if (form.get("customImage")) payload.customImage = form.get("customImage");
  else payload.image = form.get("image");
  if (form.get("tpu-acc")) {
    payload.tpu = {
      accelerator: form.get("tpu-acc"),
      topology: form.get("tpu-topo"),
    };
  }
  api(`api/namespaces/${ns.get()}/notebooks`, {
    method: "POST",
    body: JSON.stringify(payload),
  }).then(() => {
    document.getElementById("new-form-card").style.display = "none";
    refresh();
  }, showError);
});

document
  .getElementById("ns-slot")
  .append(namespacePicker(() => refresh().catch(showError)));
loadCatalogs().catch(showError);
poll(refresh);
