"""Tensorboards web app (TWA) backend."""

from kubeflow_tpu.web.tensorboards.app import create_app

__all__ = ["create_app"]
