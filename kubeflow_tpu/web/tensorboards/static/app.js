/* TWA frontend on the shared KF lib: sortable table, confirm dialogs,
 * snackbars, details drawer with the logspath scheme explained. */

let tablePoller = null;

function schemeOf(logspath) {
  if (!logspath) return "unknown";
  if (logspath.startsWith("pvc://")) return "PVC subpath";
  if (logspath.startsWith("gs://")) return "GCS bucket (XLA profiler traces)";
  if (logspath.startsWith("s3://")) return "S3 bucket";
  return "path";
}

function openDetails(tb) {
  const drawer = KF.drawer(`TensorBoard ${tb.name}`);
  const eventsHost = el("div", {});
  drawer.content.append(
    KF.detailsList([
      ["Name", tb.name],
      ["Status", KF.statusDot(tb.ready ? "ready" : "waiting", "")],
      ["Logs path", tb.logspath],
      ["Source", schemeOf(tb.logspath)],
      [
        "Open",
        el(
          "a",
          { href: KF.urls.tensorboard(ns.get(), tb.name), target: "_blank" },
          KF.urls.tensorboard(ns.get(), tb.name)
        ),
      ],
    ]),
    el(
      "p",
      { class: "muted" },
      "gs:// paths serve XLA/TPU profiler traces captured with ",
      el("code", {}, "jax.profiler"),
      " — open the Profile tab inside TensorBoard."
    ),
    el("h4", {}, "Events"),
    eventsHost
  );
  api(`api/namespaces/${ns.get()}/tensorboards/${tb.name}/events`).then(
    (body) => KF.eventsTable(eventsHost, body.events),
    () => eventsHost.append(el("p", { class: "muted" }, "No events."))
  );
}

async function refresh() {
  const body = await api(`api/namespaces/${ns.get()}/tensorboards`);
  const columns = [
    {
      title: "Status",
      render: (tb) => statusDot(tb.ready ? "ready" : "waiting", ""),
      sortKey: (tb) => (tb.ready ? 0 : 1),
    },
    { title: "Name", render: (tb) => tb.name, sortKey: (tb) => tb.name },
    {
      title: "Logs path",
      render: (tb) => tb.logspath,
      sortKey: (tb) => tb.logspath || "",
    },
    { title: "Source", render: (tb) => schemeOf(tb.logspath) },
    {
      title: "Actions",
      render: (tb) =>
        el(
          "span",
          {},
          el(
            "a",
            {
              href: KF.urls.tensorboard(ns.get(), tb.name),
              target: "_blank",
              onclick: (ev) => ev.stopPropagation(),
            },
            "Open"
          ),
          " ",
          KF.actionButton(
            "Delete",
            () =>
              KF.confirmDialog({
                title: `Delete TensorBoard ${tb.name}?`,
                message: "The server is removed; the logs themselves are kept.",
              }).then(
                (ok) =>
                  ok &&
                  api(`api/namespaces/${ns.get()}/tensorboards/${tb.name}`, {
                    method: "DELETE",
                  }).then(() => {
                    KF.snackbar("Deleting " + tb.name);
                    tablePoller.refresh();
                  }, showError)
              ),
            { class: "danger" }
          )
        ),
    },
  ];
  renderTable(document.getElementById("tb-table"), columns, body.tensorboards, {
    onRowClick: openDetails,
    emptyText: "No TensorBoards in this namespace.",
  });
}

const nameInput = document.querySelector('#new-form input[name="name"]');
const nameCheck = nameInput
  ? KF.validate(nameInput, KF.validators.dns1123)
  : () => true;

document.getElementById("new-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "block";
});
document.getElementById("cancel-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "none";
});
document.getElementById("new-form").addEventListener("submit", (ev) => {
  ev.preventDefault();
  if (!nameCheck()) return KF.snackbar("Fix the name first.", "error");
  const form = new FormData(ev.target);
  api(`api/namespaces/${ns.get()}/tensorboards`, {
    method: "POST",
    body: JSON.stringify({
      name: form.get("name"),
      logspath: form.get("logspath"),
      profilerPlugin: form.get("profiler") === "on",
    }),
  }).then(() => {
    document.getElementById("new-form-card").style.display = "none";
    KF.snackbar("Creating TensorBoard " + form.get("name"));
    tablePoller.refresh();
  }, showError);
});

async function loadLogspathSuggestions() {
  /* pvc:// + gs:// templates for the logspath field, fed by the backend's
   * pvcs route (reference TWA form). */
  const input = document.querySelector('input[name="logspath"]');
  if (!input) return;
  let datalist = document.getElementById("logspath-options");
  if (!datalist) {
    datalist = el("datalist", { id: "logspath-options" });
    document.body.append(datalist);
    input.setAttribute("list", "logspath-options");
  }
  const body = await api(`api/namespaces/${ns.get()}/pvcs`).catch(() => ({
    pvcs: [],
  }));
  datalist.replaceChildren(
    ...body.pvcs.map((p) => el("option", { value: `pvc://${p.name}/logs` })),
    el("option", { value: "gs://your-bucket/tensorboard" })
  );
}

document.getElementById("ns-slot").append(
  namespacePicker(() => {
    tablePoller.refresh();
    loadLogspathSuggestions();
  })
);
tablePoller = poll(refresh);
loadLogspathSuggestions();
