/* TWA frontend. */

async function refresh() {
  const body = await api(`api/namespaces/${ns.get()}/tensorboards`);
  const columns = [
    {
      title: "Status",
      render: (tb) => statusDot(tb.ready ? "ready" : "waiting", ""),
    },
    { title: "Name", render: (tb) => tb.name },
    { title: "Logs path", render: (tb) => tb.logspath },
    {
      title: "Actions",
      render: (tb) =>
        el(
          "span",
          {},
          el(
            "a",
            { href: `/tensorboard/${ns.get()}/${tb.name}/`, target: "_blank" },
            "Open"
          ),
          " ",
          el(
            "button",
            { class: "danger",
              onclick: () =>
                confirm(`Delete ${tb.name}?`) &&
                api(`api/namespaces/${ns.get()}/tensorboards/${tb.name}`, {
                  method: "DELETE",
                }).then(refresh, showError),
            },
            "Delete"
          )
        ),
    },
  ];
  renderTable(document.getElementById("tb-table"), columns, body.tensorboards);
}

document.getElementById("new-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "block";
});
document.getElementById("cancel-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "none";
});
document.getElementById("new-form").addEventListener("submit", (ev) => {
  ev.preventDefault();
  const form = new FormData(ev.target);
  api(`api/namespaces/${ns.get()}/tensorboards`, {
    method: "POST",
    body: JSON.stringify({
      name: form.get("name"),
      logspath: form.get("logspath"),
      profilerPlugin: form.get("profiler") === "on",
    }),
  }).then(() => {
    document.getElementById("new-form-card").style.display = "none";
    refresh();
  }, showError);
});

document
  .getElementById("ns-slot")
  .append(namespacePicker(() => refresh().catch(showError)));
poll(refresh);
