/* TWA frontend on the shared KF lib: sortable table, confirm dialogs,
 * snackbars, details drawer with the logspath scheme explained. All
 * user-visible strings route through KF.t (reference: the tensorboards
 * frontend's xlf translation pipeline). */

KF.registerMessages("en", {
  "twa.drawerTitle": "TensorBoard {name}",
  "twa.logsPath": "Logs path",
  "twa.source": "Source",
  "twa.open": "Open",
  "twa.schemeUnknown": "unknown",
  "twa.schemePvc": "PVC subpath",
  "twa.schemeGcs": "GCS bucket (XLA profiler traces)",
  "twa.schemeS3": "S3 bucket",
  "twa.schemePath": "path",
  "twa.profilerHintPre": "gs:// paths serve XLA/TPU profiler traces captured with ",
  "twa.profilerHintPost": " — open the Profile tab inside TensorBoard.",
  "twa.events": "Events",
  "twa.noEvents": "No events.",
  "twa.deleteTitle": "Delete TensorBoard {name}?",
  "twa.deleteMessage": "The server is removed; the logs themselves are kept.",
  "twa.deleting": "Deleting {name}",
  "twa.empty": "No TensorBoards in this namespace.",
  "twa.fixName": "Fix the name first.",
  "twa.creating": "Creating TensorBoard {name}",
  "twa.title": "TensorBoards",
  "twa.namespace": "namespace",
  "twa.newTensorboard": "+ New TensorBoard",
  "twa.formTitle": "New TensorBoard",
  "twa.formName": "Name",
  "twa.formLogspath": "Logs path",
  "twa.formProfiler": "XLA profiler",
  "twa.create": "Create",
});
KF.registerMessages("de", {
  "twa.drawerTitle": "TensorBoard {name}",
  "twa.logsPath": "Log-Pfad",
  "twa.source": "Quelle",
  "twa.open": "Öffnen",
  "twa.schemeUnknown": "unbekannt",
  "twa.schemePvc": "PVC-Unterpfad",
  "twa.schemeGcs": "GCS-Bucket (XLA-Profiler-Traces)",
  "twa.schemeS3": "S3-Bucket",
  "twa.schemePath": "Pfad",
  "twa.profilerHintPre": "gs://-Pfade liefern XLA/TPU-Profiler-Traces, aufgezeichnet mit ",
  "twa.profilerHintPost": " — den Profile-Tab in TensorBoard öffnen.",
  "twa.events": "Ereignisse",
  "twa.noEvents": "Keine Ereignisse.",
  "twa.deleteTitle": "TensorBoard {name} löschen?",
  "twa.deleteMessage": "Der Server wird entfernt; die Logs selbst bleiben erhalten.",
  "twa.deleting": "{name} wird gelöscht",
  "twa.empty": "Keine TensorBoards in diesem Namespace.",
  "twa.fixName": "Bitte zuerst den Namen korrigieren.",
  "twa.creating": "TensorBoard {name} wird erstellt",
  "twa.title": "TensorBoards",
  "twa.namespace": "Namespace",
  "twa.newTensorboard": "+ Neues TensorBoard",
  "twa.formTitle": "Neues TensorBoard",
  "twa.formName": "Name",
  "twa.formLogspath": "Log-Pfad",
  "twa.formProfiler": "XLA-Profiler",
  "twa.create": "Erstellen",
});
KF.registerMessages("fr", {
  "twa.drawerTitle": "TensorBoard {name}",
  "twa.logsPath": "Chemin des logs",
  "twa.source": "Source",
  "twa.open": "Ouvrir",
  "twa.schemeUnknown": "inconnu",
  "twa.schemePvc": "sous-chemin PVC",
  "twa.schemeGcs": "bucket GCS (traces du profileur XLA)",
  "twa.schemeS3": "bucket S3",
  "twa.schemePath": "chemin",
  "twa.profilerHintPre":
    "les chemins gs:// servent des traces du profileur XLA/TPU " +
    "capturées avec ",
  "twa.profilerHintPost":
    " — ouvrez l'onglet Profile dans TensorBoard.",
  "twa.events": "Événements",
  "twa.noEvents": "Aucun événement.",
  "twa.deleteTitle": "Supprimer le TensorBoard {name} ?",
  "twa.deleteMessage":
    "Le serveur est supprimé ; les logs eux-mêmes sont conservés.",
  "twa.deleting": "Suppression de {name}",
  "twa.empty": "Aucun TensorBoard dans ce namespace.",
  "twa.fixName": "Corrigez d'abord le nom.",
  "twa.creating": "Création du TensorBoard {name}",
  "twa.title": "TensorBoards",
  "twa.namespace": "namespace",
  "twa.newTensorboard": "+ Nouveau TensorBoard",
  "twa.formTitle": "Nouveau TensorBoard",
  "twa.formName": "Nom",
  "twa.formLogspath": "Chemin des logs",
  "twa.formProfiler": "Profileur XLA",
  "twa.create": "Créer",
});

let tablePoller = null;

function schemeOf(logspath) {
  if (!logspath) return KF.t("twa.schemeUnknown");
  if (logspath.startsWith("pvc://")) return KF.t("twa.schemePvc");
  if (logspath.startsWith("gs://")) return KF.t("twa.schemeGcs");
  if (logspath.startsWith("s3://")) return KF.t("twa.schemeS3");
  return KF.t("twa.schemePath");
}

function openDetails(tb) {
  const drawer = KF.drawer(KF.t("twa.drawerTitle", { name: tb.name }));
  const eventsHost = el("div", {});
  drawer.content.append(
    KF.detailsList([
      [KF.t("table.name"), tb.name],
      [KF.t("table.status"), KF.statusDot(tb.ready ? "ready" : "waiting", "")],
      [KF.t("twa.logsPath"), tb.logspath],
      [KF.t("twa.source"), schemeOf(tb.logspath)],
      [
        KF.t("twa.open"),
        el(
          "a",
          { href: KF.urls.tensorboard(ns.get(), tb.name), target: "_blank" },
          KF.urls.tensorboard(ns.get(), tb.name)
        ),
      ],
    ]),
    el(
      "p",
      { class: "muted" },
      KF.t("twa.profilerHintPre"),
      el("code", {}, "jax.profiler"),
      KF.t("twa.profilerHintPost")
    ),
    el("h4", {}, KF.t("twa.events")),
    eventsHost
  );
  api(`api/namespaces/${ns.get()}/tensorboards/${tb.name}/events`).then(
    (body) => KF.eventsTable(eventsHost, body.events),
    () => eventsHost.append(el("p", { class: "muted" }, KF.t("twa.noEvents")))
  );
}

async function refresh() {
  const body = await api(`api/namespaces/${ns.get()}/tensorboards`);
  const columns = [
    {
      title: () => KF.t("table.status"),
      render: (tb) => statusDot(tb.ready ? "ready" : "waiting", ""),
      sortKey: (tb) => (tb.ready ? 0 : 1),
    },
    { title: () => KF.t("table.name"),
      render: (tb) => tb.name, sortKey: (tb) => tb.name },
    {
      title: () => KF.t("twa.logsPath"),
      render: (tb) => tb.logspath,
      sortKey: (tb) => tb.logspath || "",
    },
    { title: () => KF.t("twa.source"),
      render: (tb) => schemeOf(tb.logspath) },
    {
      title: () => KF.t("table.actions"),
      render: (tb) =>
        el(
          "span",
          {},
          el(
            "a",
            {
              href: KF.urls.tensorboard(ns.get(), tb.name),
              target: "_blank",
              onclick: (ev) => ev.stopPropagation(),
            },
            KF.t("twa.open")
          ),
          " ",
          KF.actionButton(
            KF.t("action.delete"),
            () =>
              KF.confirmDialog({
                title: KF.t("twa.deleteTitle", { name: tb.name }),
                message: KF.t("twa.deleteMessage"),
              }).then(
                (ok) =>
                  ok &&
                  api(`api/namespaces/${ns.get()}/tensorboards/${tb.name}`, {
                    method: "DELETE",
                  }).then(() => {
                    KF.snackbar(KF.t("twa.deleting", { name: tb.name }));
                    tablePoller.refresh();
                  }, showError)
              ),
            { class: "danger" }
          )
        ),
    },
  ];
  renderTable(document.getElementById("tb-table"), columns, body.tensorboards, {
    onRowClick: openDetails,
    emptyText: KF.t("twa.empty"),
    pageSize: 25,
    filterable: true,
  });
}

const nameInput = document.querySelector('#new-form input[name="name"]');
const nameCheck = nameInput
  ? KF.validate(nameInput, KF.validators.dns1123)
  : () => true;

document.getElementById("new-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "block";
});
document.getElementById("cancel-btn").addEventListener("click", () => {
  document.getElementById("new-form-card").style.display = "none";
});
document.getElementById("new-form").addEventListener("submit", (ev) => {
  ev.preventDefault();
  if (!nameCheck()) return KF.snackbar(KF.t("twa.fixName"), "error");
  const form = new FormData(ev.target);
  api(`api/namespaces/${ns.get()}/tensorboards`, {
    method: "POST",
    body: JSON.stringify({
      name: form.get("name"),
      logspath: form.get("logspath"),
      profilerPlugin: form.get("profiler") === "on",
    }),
  }).then(() => {
    document.getElementById("new-form-card").style.display = "none";
    KF.snackbar(KF.t("twa.creating", { name: form.get("name") }));
    tablePoller.refresh();
  }, showError);
});

async function loadLogspathSuggestions() {
  /* pvc:// + gs:// templates for the logspath field, fed by the backend's
   * pvcs route (reference TWA form). */
  const input = document.querySelector('input[name="logspath"]');
  if (!input) return;
  let datalist = document.getElementById("logspath-options");
  if (!datalist) {
    datalist = el("datalist", { id: "logspath-options" });
    document.body.append(datalist);
    input.setAttribute("list", "logspath-options");
  }
  const body = await api(`api/namespaces/${ns.get()}/pvcs`).catch(() => ({
    pvcs: [],
  }));
  datalist.replaceChildren(
    ...body.pvcs.map((p) => el("option", { value: `pvc://${p.name}/logs` })),
    el("option", { value: "gs://your-bucket/tensorboard" })
  );
}

document.getElementById("ns-slot").append(
  namespacePicker(() => {
    tablePoller.refresh();
    loadLogspathSuggestions();
  }),
  " ",
  KF.localePicker()
);
KF.localizeDocument();
KF.onLocaleChange(() => refresh().catch(() => {}));
tablePoller = poll(refresh);
loadLogspathSuggestions();
