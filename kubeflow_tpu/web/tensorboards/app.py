"""TWA routes: Tensorboard CRUD.

Reference: ``crud-web-apps/tensorboards/backend/app/routes/{get,post,delete}.py``.
"""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api import tensorboard as tbapi
from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.runtime.objects import deep_get, get_meta, name_of
from kubeflow_tpu.web.common.app import create_base_app, json_success
from kubeflow_tpu.web.common.serving import add_spa
from kubeflow_tpu.web.common.auth import ensure
from kubeflow_tpu.web.common.status import events_for, filter_events


def create_app(kube, **kwargs) -> web.Application:
    app = create_base_app(kube, **kwargs)
    app.add_routes(routes)
    add_spa(app, __file__)
    return app


routes = web.RouteTableDef()


def _ctx(request: web.Request):
    return (
        request.app["kube"],
        request.app["authorizer"],
        request.get("user", ""),
        request.match_info.get("namespace"),
    )


@routes.get("/api/namespaces/{namespace}/tensorboards")
async def list_tensorboards(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "list", "Tensorboard", ns)
    tensorboards = [
        {
            "name": name_of(tb),
            "namespace": ns,
            "logspath": deep_get(tb, "spec", "logspath"),
            "ready": bool(deep_get(tb, "status", "readyReplicas", default=0)),
            "age": get_meta(tb).get("creationTimestamp"),
        }
        for tb in await kube.list("Tensorboard", ns)
    ]
    return json_success({"tensorboards": tensorboards})


@routes.post("/api/namespaces/{namespace}/tensorboards")
async def post_tensorboard(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "create", "Tensorboard", ns)
    body = await request.json()
    name, logspath = body.get("name", ""), body.get("logspath", "")
    if not name or not logspath:
        raise Invalid("tensorboard form: name and logspath are required")
    tb = tbapi.new(name, ns, logspath, profiler=bool(body.get("profilerPlugin")))
    await kube.create("Tensorboard", tb)
    return json_success({"message": f"Tensorboard {name} created"})


@routes.get("/api/namespaces/{namespace}/pvcs")
async def list_pvcs(request):
    """PVC names for the pvc:// logspath picker (the reference TWA serves
    pvcs + poddefaults alongside tensorboards for its form)."""
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "list", "PersistentVolumeClaim", ns)
    pvcs = [
        {
            "name": name_of(pvc),
            "capacity": deep_get(pvc, "spec", "resources", "requests", "storage"),
            "modes": deep_get(pvc, "spec", "accessModes", default=[]),
        }
        for pvc in await kube.list("PersistentVolumeClaim", ns)
    ]
    return json_success({"pvcs": pvcs})


@routes.get("/api/namespaces/{namespace}/poddefaults")
async def list_poddefaults(request):
    kube, authz, user, ns = _ctx(request)
    await ensure(authz, user, "list", "PodDefault", ns)
    contents = [
        {
            "label": next(
                iter(deep_get(pd, "spec", "selector", "matchLabels", default={})),
                name_of(pd),
            ),
            "desc": deep_get(pd, "spec", "desc", default=name_of(pd)),
        }
        for pd in await kube.list("PodDefault", ns)
    ]
    return json_success({"poddefaults": contents})


@routes.get("/api/namespaces/{namespace}/tensorboards/{name}/events")
async def tensorboard_events(request):
    """Events involving the Tensorboard CR or its Deployment (the details
    drawer's events table — VWA's pvc_events twin). Filtered to the
    current incarnation like the JWA events route."""
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "list", "Event", ns)
    events = await events_for(kube, ns, name, ("Tensorboard", "Deployment"))
    tb = await kube.get_or_none("Tensorboard", name, ns)
    if tb is not None:
        events = filter_events(tb, events)
    return json_success({"events": events})


@routes.delete("/api/namespaces/{namespace}/tensorboards/{name}")
async def delete_tensorboard(request):
    kube, authz, user, ns = _ctx(request)
    name = request.match_info["name"]
    await ensure(authz, user, "delete", "Tensorboard", ns)
    await kube.delete("Tensorboard", name, ns)
    return json_success({"message": f"Tensorboard {name} deleted"})
