"""Notebook status state machine for the UI.

Reference: ``crud-web-apps/jupyter/backend/apps/common/status.py:9-57`` —
phases [ready|waiting|warning|terminating|stopped], derived in priority
order from: age, stop annotation, deletionTimestamp, readyReplicas,
containerState, conditions, then warning Events.

Multi-host twist: "ready" compares readyReplicas against the slice's host
count (``status.tpu.hosts``), not the reference's hard-coded 1.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime import slo
from kubeflow_tpu.runtime import timeline as timeline_mod
from kubeflow_tpu.runtime.objects import deep_get, get_meta, parse_iso

READY = "ready"
WAITING = "waiting"
WARNING = "warning"
TERMINATING = "terminating"
STOPPED = "stopped"


@dataclass(frozen=True)
class Status:
    phase: str
    message: str


def _age_seconds(notebook: dict) -> float:
    created = get_meta(notebook).get("creationTimestamp")
    ts = parse_iso(created) if created else None
    if ts is None:
        return 1e9
    return max(0.0, time.time() - ts)


def event_stamp(ev: dict) -> str:
    """The one event-timestamp precedence rule (lastTimestamp →
    eventTime → metadata.creationTimestamp) — shared by the filter below
    and the dashboard activity feed so it can't drift."""
    return (
        ev.get("lastTimestamp") or ev.get("eventTime")
        or deep_get(ev, "metadata", "creationTimestamp") or ""
    )


def filter_events(notebook: dict, events: list[dict]) -> list[dict]:
    """Drop events that predate the CR — a recreated server with the same
    name must not surface the previous incarnation's errors (reference
    ``crud-web-apps/jupyter/backend/apps/common/status.py``
    get_notebook_events creationTimestamp filter)."""
    created = get_meta(notebook).get("creationTimestamp")
    created_ts = parse_iso(created) if created else None
    if created_ts is None:
        return list(events)
    out = []
    for ev in events:
        stamp = event_stamp(ev)
        ts = parse_iso(stamp) if stamp else None
        if ts is None or ts >= created_ts:
            out.append(ev)
    return out


def _pending_since(notebook: dict) -> float | None:
    """Start of the current startup episode, from the durable lifecycle
    timeline's episode boundary (survives re-queues and manager
    restarts). Deliberately timeline-only — age since creation would
    misread a long-RUNNING server that was later re-queued, and a
    pre-timeline CR has no trustworthy episode start; None = never
    guess a breach."""
    entries = timeline_mod.decode(
        get_meta(notebook).get("annotations") or {})
    start = timeline_mod.episode_start(entries)
    return start["at"] if start is not None else None


def _time_to_ready_breach(notebook: dict) -> dict | None:
    """The JWA "waiting longer than expected" signal: the pending episode
    has outlived the ``notebook_time_to_ready`` objective
    (KFTPU_SLO_NOTEBOOK_TIME_TO_READY). Returns the message pieces, or
    None inside the objective."""
    threshold, target = slo.objective_for("notebook_time_to_ready")
    since = _pending_since(notebook)
    if since is None:
        return None
    waited = time.time() - since
    if waited <= threshold:
        return None
    meta = get_meta(notebook)
    return {
        "percentile": f"p{target * 100:g}",
        "threshold": threshold,
        "waited": waited,
        "explain": (f"/debug/scheduler/explain/"
                    f"{meta.get('namespace', '')}/{meta.get('name', '')}"),
    }


def _breach_message(breach: dict, reason: str) -> str:
    return (f"Waiting longer than expected "
            f"({breach['percentile']} objective {breach['threshold']:g}s, "
            f"waiting {breach['waited']:.0f}s) — {reason}; "
            f"explain: {breach['explain']} on the controller manager")


def process_status(notebook: dict, events: list[dict] | None = None) -> Status:
    meta = get_meta(notebook)
    annotations = meta.get("annotations") or {}
    ready = deep_get(notebook, "status", "readyReplicas", default=0) or 0
    container_state = deep_get(notebook, "status", "containerState", default={})
    conditions = deep_get(notebook, "status", "conditions", default=[])
    want_hosts = deep_get(notebook, "status", "tpu", "hosts", default=1) or 1

    # Poison-pill quarantine first (runtime/manager.py stamps the
    # Degraded condition): reconciliation is SUSPENDED, so every other
    # signal below is frozen at quarantine time — nothing is more
    # actionable than saying so. Conditions are newest-first history; the
    # most recent Degraded entry wins (False = released, fall through).
    for c in conditions:
        if c.get("type") == "Degraded":
            if c.get("status") == "True":
                return Status(
                    WARNING,
                    "Reconciliation suspended after repeated errors "
                    f"({c.get('reason', 'ReconcileQuarantined')}) — edit "
                    "the notebook to retry, or ask an operator to requeue "
                    "it (POST /debug/queue/requeue on the controller "
                    "manager)",
                )
            break

    # Fleet-scheduler verdicts first (controllers/notebook.py writes
    # status.scheduler): a Queued gang is waiting *by design*, with a
    # position and a chip count the user can act on — more specific than
    # the provisioning wait and any age/pod-state heuristic below.
    sched = deep_get(notebook, "status", "scheduler", default={}) or {}
    mig = deep_get(notebook, "status", "migration", default={}) or {}
    if sched.get("state") == "Queued":
        # Elastic-fleet refinements first — each is more specific than
        # the generic queue position:
        if sched.get("reclaimed") == "spot-reclaim":
            step = mig.get("checkpointStep")
            ckpt = (f"checkpoint @ step {step}" if step is not None
                    else "checkpoint saved")
            return Status(
                WAITING,
                f"Reclaimed from spot capacity ({ckpt}, re-queued at "
                f"position {sched.get('position', 0)})",
            )
        if sched.get("reclaimed") == "defrag":
            return Status(
                WAITING,
                f"Migrating to pack pool (re-queued at position "
                f"{sched.get('position', 0)})",
            )
        scale_up = sched.get("scaleUp") or {}
        if scale_up.get("chips"):
            pending = scale_up.get("pendingSeconds", 0) or 0
            return Status(
                WAITING,
                f"Waiting for pool scale-up ({scale_up['chips']} chips "
                f"requested, intent pending {pending:.0f}s)",
            )
        breach = _time_to_ready_breach(notebook)
        if breach is not None:
            # Past the time-to-ready objective: escalate to a warning
            # whose reason is the SAME machine answer the explain
            # endpoint serves (status.scheduler.reason comes from
            # schedule_preview, the explain endpoint's source).
            return Status(
                WARNING,
                _breach_message(
                    breach,
                    f"{sched.get('reason') or 'queued for TPU capacity'} "
                    f"(position {sched.get('position', 0)})"),
            )
        return Status(
            WAITING,
            f"Queued for TPU capacity (position {sched.get('position', 0)},"
            f" waiting for {sched.get('waitingChips', 0)} chips)",
        )
    if sched.get("state") == "Draining":
        reason = sched.get("reason") or "capacity reclaimed"
        if reason == "defrag":
            return Status(
                WAITING,
                "Migrating to pack pool (checkpointing)…",
            )
        if reason == "spot-reclaim":
            return Status(
                WAITING,
                "Checkpointing before spot capacity is reclaimed…",
            )
        return Status(
            WAITING,
            f"Checkpointing before preemption ({reason})…",
        )
    if sched.get("state") == "Preempted" and ready == 0:
        reason = sched.get("reason") or "capacity reclaimed"
        step = mig.get("checkpointStep")
        restore = (
            f"; restarts resume from checkpoint @ step {step}"
            if step is not None and mig.get("checkpointedAt")
            else ""
        )
        return Status(
            STOPPED,
            f"Preempted by the TPU fleet scheduler ({reason}); "
            f"restart the server to re-queue{restore}",
        )

    # Queued provisioning: nothing runs yet *by design* — more specific
    # than any age/pod-state heuristic below, so it goes first.
    if deep_get(notebook, "status", "tpu", "capacityPending"):
        return Status(
            WAITING,
            "Waiting for TPU capacity (queued ProvisioningRequest)",
        )

    # Warm pod pools (ISSUE 14, controllers/warmpool.py): a claimed
    # notebook starting up says HOW it is starting (the warm path is the
    # product's headline — surface it); a pool caught empty says why the
    # cold path ran and how close the pool is to refilled. Both only
    # matter pre-Ready — a Running server falls through to the normal
    # Ready message.
    warm_pool = deep_get(notebook, "status", "tpu", "warmPool",
                         default={}) or {}
    if warm_pool.get("claimed") and ready < want_hosts \
            and nbapi.STOP_ANNOTATION not in annotations:
        claimed_in = warm_pool.get("claimedInSec")
        return Status(
            WAITING,
            "Starting from warm pool"
            + (f" (claimed in {claimed_in:g}s)"
               if isinstance(claimed_in, (int, float)) else ""),
        )
    repl = warm_pool.get("replenishing") or {}
    if repl and ready < want_hosts \
            and nbapi.STOP_ANNOTATION not in annotations:
        return Status(
            WAITING,
            f"Warming pool replenishing ({repl.get('ready', 0)}/"
            f"{repl.get('size', 0)} ready); starting cold",
        )

    # Brand-new CR: show a benign waiting message for the first seconds.
    if not container_state and not conditions and _age_seconds(notebook) <= 10:
        return Status(WAITING, "Waiting for StatefulSet to create the underlying Pod.")

    if nbapi.STOP_ANNOTATION in annotations:
        if ready == 0:
            if mig.get("state") == "Parked":
                step = mig.get("checkpointStep")
                base = (f"Suspended (checkpoint @ step {step})"
                        if step is not None
                        else "Suspended (checkpoint saved)")
                # Checkpoint fabric: the park happened at the snapshot
                # ack — say so while the durable upload is still in
                # flight, and flag a park whose upload never landed
                # (restore may fall back to an older committed step).
                if mig.get("commitDirty"):
                    return Status(
                        WARNING,
                        base + " — checkpoint upload did not complete; "
                        "restore may use an older committed step",
                    )
                if (mig.get("uploadProgress")
                        and not mig.get("committedAt")):
                    return Status(
                        STOPPED,
                        base + f" — checkpoint uploading "
                        f"({mig['uploadProgress']} chunks)",
                    )
                return Status(STOPPED, base)
            return Status(STOPPED, "No Pods are currently running for this Notebook Server.")
        return Status(WAITING, "Notebook Server is stopping.")

    if meta.get("deletionTimestamp"):
        return Status(TERMINATING, "Deleting this Notebook Server.")

    # Re-admitted with a checkpoint hint: workers are coming up and will
    # restore where the drain left off — more specific than the generic
    # partial-readiness message below.
    if mig.get("state") == "Restoring" and ready < want_hosts:
        step = mig.get("checkpointStep")
        # Checkpoint fabric: name the tier that served the restore —
        # a staging hit is the fast path, object storage the fallback.
        tier = mig.get("restoreTier")
        source = {"staging": "Restoring from local staging tier",
                  "remote": "Restoring from object storage"}.get(
                      tier, "Restoring from checkpoint")
        return Status(
            WAITING,
            source
            + (f" (step {step})" if step is not None else "")
            + f" ({ready}/{want_hosts} workers ready)",
        )

    if ready >= want_hosts and ready > 0:
        # Impending node maintenance (controller-mirrored taint): the
        # server is still up — say so, but tell the user to checkpoint.
        pending = annotations.get(nbapi.MAINTENANCE_ANNOTATION)
        if pending:
            return Status(
                READY,
                f"Running — node maintenance pending on {pending}; "
                "checkpoint your work",
            )
        # The webhook reverted a live pod-affecting edit (restart
        # blocking, reference maybeRestartRunningNotebook): the change
        # was NOT applied — say so, and say what to do.
        if annotations.get(nbapi.UPDATE_PENDING_ANNOTATION):
            return Status(
                READY,
                "Running — a configuration change was blocked while the "
                "server is running; stop it and re-apply the change",
            )
        # Training telemetry (ISSUE 18, controllers/notebook.py folds the
        # SDK's annotation into status.tpu.telemetry): a Running server
        # that is mid-training says so, with the achieved MFU when the
        # profiler knew its FLOPs basis. A STALE entry (publisher gone
        # quiet past KFTPU_TELEMETRY_STALE_SECONDS) must not present
        # week-old MFU as live — degrade to saying the telemetry is
        # stale instead.
        telem = deep_get(notebook, "status", "tpu", "telemetry",
                         default={}) or {}
        if telem.get("step"):
            workers = (f" ({ready}/{want_hosts} TPU workers)"
                       if want_hosts > 1 else "")
            if telem.get("stale"):
                return Status(
                    READY,
                    f"Running{workers} — training telemetry stale "
                    f"(last step {telem['step']})",
                )
            mfu = telem.get("mfu")
            mfu_part = (f", {mfu:.0%} MFU"
                        if isinstance(mfu, (int, float)) else "")
            return Status(
                READY,
                f"Running{workers} — Training: step {telem['step']}"
                f"{mfu_part} ({telem.get('family') or 'unknown'})",
            )
        if want_hosts > 1:
            return Status(READY, f"Running ({ready}/{want_hosts} TPU workers)")
        return Status(READY, "Running")

    waiting = container_state.get("waiting")
    if waiting is not None:
        reason = waiting.get("reason", "Undefined")
        if reason == "PodInitializing":
            return Status(WAITING, reason)
        message = waiting.get("message", "No available message for container state.")
        return Status(WARNING, f"{reason}: {message}")

    for condition in conditions:
        if condition.get("reason"):
            return Status(
                WARNING, f"{condition['reason']}: {condition.get('message', '')}"
            )

    # Partially-ready slice: surface progress rather than a generic warning.
    if 0 < ready < want_hosts:
        breach = _time_to_ready_breach(notebook)
        if breach is not None:
            return Status(
                WARNING,
                _breach_message(
                    breach,
                    f"waiting for TPU workers ({ready}/{want_hosts} "
                    "ready)"),
            )
        return Status(WAITING, f"Waiting for TPU workers ({ready}/{want_hosts} ready)")

    for ev in sorted(
        filter_events(notebook, events or []),
        key=lambda e: e.get("lastTimestamp", ""),
        reverse=True,
    ):
        if ev.get("type") == "Warning":
            return Status(WARNING, ev.get("message", ""))

    return Status(
        WARNING, "Couldn't find any information for the status of this notebook."
    )


def process_serving_status(isvc: dict) -> Status:
    """InferenceService status state machine for the UI — the serving
    analogue of :func:`process_status`. Priority order mirrors the
    controller's state derivation (serving/controller.py): quarantine,
    park lifecycle, fleet queueing, readiness."""
    meta = get_meta(isvc)
    serving = deep_get(isvc, "status", "serving", default={}) or {}
    state = serving.get("state") or ""
    for c in deep_get(isvc, "status", "conditions", default=[]):
        if c.get("type") == "Degraded":
            if c.get("status") == "True":
                return Status(
                    WARNING,
                    "Reconciliation suspended after repeated errors "
                    f"({c.get('reason', 'ReconcileQuarantined')})")
            break
    if meta.get("deletionTimestamp"):
        return Status(TERMINATING, "Deleting this InferenceService.")
    if state == "Parked":
        ckpt = serving.get("parkedCheckpoint") or {}
        step = ckpt.get("step")
        return Status(
            STOPPED,
            "Scaled to zero — parked warm standby"
            + (f" (checkpoint @ step {step})" if step is not None
               else " (checkpoint saved)" if ckpt else "")
            + "; the first request restores it")
    if state == "Parking":
        return Status(WAITING, "Idle — checkpointing before scale-to-zero…")
    # Engine-v2 data-plane conditions (ISSUE 19) outrank the steady
    # states below: a Ready service that is swapping models or queueing
    # requests behind KV-cache pressure should say so, not "Serving".
    swap = serving.get("modelSwap") or {}
    if swap.get("model"):
        if swap.get("warm"):
            return Status(
                WAITING,
                f"Swapping model {swap['model']} "
                "(warm standby, weights resident)")
        return Status(
            WAITING,
            f"Swapping model {swap['model']} (cold: init + compile)")
    kv = serving.get("kvPressure") or {}
    blocks_short = kv.get("blocksShort") or 0
    if blocks_short > 0:
        return Status(
            WAITING,
            f"Queued behind KV-cache pressure ({blocks_short} "
            "blocks short)")
    if state == "Queued":
        return Status(
            WAITING,
            f"All {serving.get('queuedReplicas', 0)} replica(s) queued "
            "for TPU capacity")
    if state == "Scaling":
        queued = serving.get("queuedReplicas", 0)
        note = (f"; {queued} replica(s) queued for TPU capacity"
                if queued else "")
        ready = deep_get(isvc, "status", "readyReplicas", default=0) or 0
        return Status(
            WAITING,
            f"Scaling to {serving.get('desiredReplicas', 0)} replica(s) "
            f"({ready} worker(s) ready{note})")
    if state == "Ready":
        n = serving.get("admittedReplicas", 0)
        return Status(READY,
                      f"Serving ({n} replica(s), "
                      f"{deep_get(isvc, 'status', 'readyReplicas', default=0) or 0} "
                      "worker(s) ready)")
    if _age_seconds(isvc) <= 10:
        return Status(WAITING, "Waiting for the serving controller.")
    return Status(WARNING,
                  "Couldn't find any information for the status of this "
                  "InferenceService.")


async def events_for(kube, namespace: str, name: str, kinds: tuple) -> list[dict]:
    """One Event list call filtered to the involved object — shared by the
    per-app events routes (JWA pod/CR events, VWA pvc_events, TWA
    tensorboard_events) so involvedObject matching evolves in one place."""
    return [
        ev for ev in await kube.list("Event", namespace)
        if (ev.get("involvedObject") or {}).get("name") == name
        and (ev.get("involvedObject") or {}).get("kind") in kinds
    ]
