"""SPA serving: static assets + index (reference: crud_backend/serving.py —
serve the bundle and set the CSRF cookie on index loads; the cookie here is
set by the CSRF middleware on any safe request)."""

from __future__ import annotations

from pathlib import Path

from aiohttp import web

COMMON_STATIC = Path(__file__).resolve().parent / "static"


def add_spa(app: web.Application, module_file: str) -> None:
    """Mount the caller's ``static/`` sibling dir: shared assets at
    /static/common, app assets at /static/app, index.html at /.
    Call as ``add_spa(app, __file__)``."""
    app_static = Path(module_file).resolve().parent / "static"

    async def index(_request: web.Request) -> web.FileResponse:
        return web.FileResponse(app_static / "index.html")

    app.router.add_get("/", index)
    app.router.add_static("/static/common", COMMON_STATIC)
    app.router.add_static("/static/app", app_static)
