"""Authentication + authorization for the web backends.

Reference: ``crud_backend/authn.py:13-67`` (trusted ``kubeflow-userid``
header injected by the auth proxy at the gateway; the backend never sees
credentials) and ``crud_backend/authz.py:45-132`` (SubjectAccessReview per
request: may <user> <verb> <resource> in <namespace>?).
"""

from __future__ import annotations

from typing import Protocol

from kubeflow_tpu.runtime.errors import Forbidden
from kubeflow_tpu.runtime.scheme import DEFAULT_SCHEME

USERID_HEADER = "kubeflow-userid"  # crud_backend/settings.py:3-6


class Authorizer(Protocol):
    async def check(
        self, user: str, verb: str, kind: str, namespace: str | None
    ) -> bool: ...


class AllowAll:
    """Dev-mode authorizer (reference APP_SECURE_COOKIES/dev config)."""

    async def check(self, user, verb, kind, namespace) -> bool:
        return True


class SarAuthorizer:
    """SubjectAccessReview-backed authorizer (authz.py:45-132): delegates the
    decision to the cluster's RBAC by creating a SAR and reading
    ``status.allowed``."""

    def __init__(self, kube):
        self.kube = kube

    async def check(self, user, verb, kind, namespace) -> bool:
        gvk = DEFAULT_SCHEME.by_kind(kind)
        sar = {
            "apiVersion": "authorization.k8s.io/v1",
            "kind": "SubjectAccessReview",
            "metadata": {"generateName": "web-app-sar-"},
            "spec": {
                "user": user,
                "resourceAttributes": {
                    "group": gvk.group,
                    "resource": gvk.plural,
                    "verb": verb,
                    "namespace": namespace,
                },
            },
        }
        created = await self.kube.create("SubjectAccessReview", sar)
        return bool((created.get("status") or {}).get("allowed"))


async def ensure(
    authorizer: Authorizer, user: str, verb: str, kind: str, namespace: str | None
) -> None:
    if not await authorizer.check(user, verb, kind, namespace):
        raise Forbidden(
            f"User {user!r} cannot {verb} {kind} in namespace {namespace!r}"
        )
