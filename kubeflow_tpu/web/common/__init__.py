"""Shared CRUD-backend library (reference: ``crud-web-apps/common/backend/
kubeflow/kubeflow/crud_backend`` — app factory, authn, authz, CSRF, status).
"""

from kubeflow_tpu.web.common.app import create_base_app, json_error, json_success
from kubeflow_tpu.web.common.auth import AllowAll, Authorizer, SarAuthorizer

__all__ = [
    "create_base_app",
    "json_success",
    "json_error",
    "Authorizer",
    "AllowAll",
    "SarAuthorizer",
]
