/* Shared frontend helpers: CSRF-aware fetch, table rendering, namespace
   state (the reference's kubeflow-common-lib backend service + polling
   modules, distilled). */

function getCookie(name) {
  const m = document.cookie.match(new RegExp("(?:^|; )" + name + "=([^;]*)"));
  return m ? decodeURIComponent(m[1]) : null;
}

async function api(path, options = {}) {
  const headers = Object.assign(
    { "Content-Type": "application/json" },
    options.headers || {}
  );
  const method = (options.method || "GET").toUpperCase();
  if (method !== "GET" && method !== "HEAD") {
    const token = getCookie("XSRF-TOKEN");
    if (token) headers["X-XSRF-TOKEN"] = token;
  }
  const resp = await fetch(path, Object.assign({}, options, { headers }));
  const body = await resp.json().catch(() => ({}));
  if (!resp.ok || body.success === false) {
    throw new Error(body.log || resp.status + " " + resp.statusText);
  }
  return body;
}

function el(tag, attrs = {}, ...children) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) {
    if (k === "onclick") node.addEventListener("click", v);
    else if (k === "class") node.className = v;
    else node.setAttribute(k, v);
  }
  for (const child of children.flat()) {
    node.append(child instanceof Node ? child : document.createTextNode(child));
  }
  return node;
}

function statusDot(phase, message) {
  return el(
    "span",
    { class: "status", title: message || "" },
    el("span", { class: "dot " + phase }),
    phase
  );
}

function renderTable(container, columns, rows) {
  container.replaceChildren(
    el(
      "table",
      {},
      el("thead", {}, el("tr", {}, columns.map((c) => el("th", {}, c.title)))),
      el(
        "tbody",
        {},
        rows.map((row) =>
          el("tr", {}, columns.map((c) => el("td", {}, c.render(row))))
        )
      )
    )
  );
}

const ns = {
  get() {
    return localStorage.getItem("kubeflow.namespace") || "kubeflow-user";
  },
  set(value) {
    localStorage.setItem("kubeflow.namespace", value);
  },
};

function namespacePicker(onChange) {
  const input = el("input", { value: ns.get(), style: "width:180px" });
  input.addEventListener("change", () => {
    ns.set(input.value);
    onChange(input.value);
  });
  return input;
}

function showError(err) {
  const banner = document.getElementById("error-banner");
  if (!banner) return alert(err.message || err);
  banner.textContent = String(err.message || err);
  banner.style.display = "block";
  setTimeout(() => (banner.style.display = "none"), 8000);
}

function poll(fn, intervalMs = 4000) {
  fn().catch(showError);
  return setInterval(() => fn().catch(() => {}), intervalMs);
}
