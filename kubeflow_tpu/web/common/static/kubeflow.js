/* Shared frontend library for the kubeflow-tpu web apps.
 *
 * Buildless equivalent of the reference's kubeflow-common-lib
 * (crud-web-apps/common/frontend/kubeflow-common-lib/projects/kubeflow/src/lib):
 * backend service w/ CSRF header injection, exponential-backoff poller,
 * resource-table (dynamic columns, status icons, sorting, row actions),
 * logs-viewer, conditions-table, events-table, details-list,
 * confirm-dialog, snack-bar, namespace selector, form validators,
 * date-time utils, tabs, a YAML view, a details drawer, a TPU slice
 * rollup panel and a dependency-free sparkline — one namespace (KF), no
 * framework, no bundler.
 *
 * Backward-compatible globals (api, el, ns, renderTable, statusDot,
 * namespacePicker, showError, poll) are kept as aliases at the bottom.
 */

const KF = {};

/* ---------------- i18n (reference: frontends' translation infra) --------
 *
 * Message-catalog layer: KF.t(key, params) resolves through the active
 * locale's catalog, falls back to English, then to the key itself.
 * Catalogs are plain objects; apps extend them with KF.registerMessages.
 * The chosen locale persists in localStorage and a change notifies
 * subscribers so live views re-render in place. */

KF.i18n = {
  locale: "en",
  fallback: "en",
  catalogs: { en: {}, de: {}, fr: {} },
  listeners: [],
  available: function () {
    return Object.keys(KF.i18n.catalogs).sort();
  },
};

KF.registerMessages = function (locale, messages) {
  KF.i18n.catalogs[locale] = Object.assign(
    KF.i18n.catalogs[locale] || {},
    messages
  );
};

KF.hasMessage = function (key) {
  const cat = KF.i18n.catalogs[KF.i18n.locale] || {};
  const fall = KF.i18n.catalogs[KF.i18n.fallback] || {};
  return cat[key] !== undefined || fall[key] !== undefined;
};

KF.t = function (key, params) {
  const cat = KF.i18n.catalogs[KF.i18n.locale] || {};
  const fall = KF.i18n.catalogs[KF.i18n.fallback] || {};
  let msg = cat[key];
  if (msg === undefined) msg = fall[key];
  if (msg === undefined) msg = key;
  if (params) {
    for (const [k, v] of Object.entries(params)) {
      msg = msg.split("{" + k + "}").join(String(v));
    }
  }
  return msg;
};

KF.setLocale = function (locale) {
  KF.i18n.locale = locale;
  try {
    localStorage.setItem("kf.locale", locale);
  } catch (err) {
    /* storage-less context (sandboxed iframe) — session-only locale */
  }
  for (const fn of KF.i18n.listeners.slice()) {
    try {
      fn(locale);
    } catch (err) {
      /* one subscriber's render error must not stop the others */
    }
  }
};

KF.onLocaleChange = function (fn) {
  KF.i18n.listeners.push(fn);
  return function () {
    const at = KF.i18n.listeners.indexOf(fn);
    if (at >= 0) KF.i18n.listeners.splice(at, 1);
  };
};

/* Static-HTML localization: elements marked data-i18n="key" get their
 * text from the catalog; data-i18n-attr="placeholder:key;title:key2"
 * localizes attributes. The first call subscribes to locale changes so
 * the static chrome re-renders with the dynamic views. */
KF.localizeDocument = function (root) {
  const scope = root || document;
  for (const node of scope.querySelectorAll("[data-i18n]")) {
    node.textContent = KF.t(node.getAttribute("data-i18n"));
  }
  for (const node of scope.querySelectorAll("[data-i18n-attr]")) {
    for (const pair of node.getAttribute("data-i18n-attr").split(";")) {
      const at = pair.indexOf(":");
      if (at > 0) {
        node.setAttribute(pair.slice(0, at), KF.t(pair.slice(at + 1)));
      }
    }
  }
  if (!KF.localizeDocument._subscribed) {
    KF.localizeDocument._subscribed = true;
    KF.onLocaleChange(() => KF.localizeDocument(root));
  }
};

KF.localePicker = function () {
  const select = document.createElement("select");
  select.className = "kf-locale-picker";
  select.setAttribute("aria-label", "language");
  select.style.width = "auto";
  for (const loc of KF.i18n.available()) {
    const opt = document.createElement("option");
    opt.value = loc;
    opt.append(document.createTextNode(loc));
    if (loc === KF.i18n.locale) opt.setAttribute("selected", "selected");
    select.append(opt);
  }
  select.addEventListener("change", () => KF.setLocale(select.value));
  return select;
};

/* Common-lib message catalogs. English is the fallback source of truth;
 * German proves the pipe end-to-end (picker → setLocale → re-render). */
KF.registerMessages("en", {
  "status.ready": "Running",
  "status.waiting": "Starting",
  "status.warning": "Error",
  "status.terminating": "Deleting",
  "status.stopped": "Stopped",
  "table.status": "Status",
  "table.name": "Name",
  "table.image": "Image",
  "table.cpu": "CPU",
  "table.memory": "Memory",
  "table.tpu": "TPU",
  "table.age": "Age",
  "table.lastActivity": "Last activity",
  "table.actions": "Actions",
  "table.filterPlaceholder": "Filter rows",
  "table.noMatches": 'No rows match "{query}".',
  "table.prevPage": "Previous",
  "table.nextPage": "Next",
  "table.pageInfo": "{first}–{last} of {total}",
  "action.start": "Start",
  "action.stop": "Stop",
  "action.delete": "Delete",
  "action.connect": "Connect",
  "common.none": "none",
  "common.cancel": "Cancel",
  "common.loading": "Loading…",
  "common.apply": "Apply",
  "common.chipPlaceholder": "add value, press Enter",
  "jwa.empty": "No notebook servers in this namespace.",
});
KF.registerMessages("de", {
  "status.ready": "Läuft",
  "status.waiting": "Startet",
  "status.warning": "Fehler",
  "status.terminating": "Wird gelöscht",
  "status.stopped": "Gestoppt",
  "table.status": "Status",
  "table.name": "Name",
  "table.image": "Image",
  "table.cpu": "CPU",
  "table.memory": "Speicher",
  "table.tpu": "TPU",
  "table.age": "Alter",
  "table.lastActivity": "Letzte Aktivität",
  "table.actions": "Aktionen",
  "table.filterPlaceholder": "Zeilen filtern",
  "table.noMatches": 'Keine Zeilen passen auf "{query}".',
  "table.prevPage": "Zurück",
  "table.nextPage": "Weiter",
  "table.pageInfo": "{first}–{last} von {total}",
  "action.start": "Starten",
  "action.stop": "Stoppen",
  "action.delete": "Löschen",
  "action.connect": "Verbinden",
  "common.none": "keine",
  "common.cancel": "Abbrechen",
  "common.loading": "Lädt…",
  "common.apply": "Übernehmen",
  "common.chipPlaceholder": "Wert eingeben, Enter drücken",
  "jwa.empty": "Keine Notebook-Server in diesem Namespace.",
});
/* French — the locale the reference actually ships xlf catalogs for
 * (volumes/frontend/i18n/fr/messages.fr.xlf). */
KF.registerMessages("fr", {
  "status.ready": "En cours",
  "status.waiting": "Démarrage",
  "status.warning": "Erreur",
  "status.terminating": "Suppression",
  "status.stopped": "Arrêté",
  "table.status": "Statut",
  "table.name": "Nom",
  "table.image": "Image",
  "table.cpu": "CPU",
  "table.memory": "Mémoire",
  "table.tpu": "TPU",
  "table.age": "Âge",
  "table.lastActivity": "Dernière activité",
  "table.actions": "Actions",
  "table.filterPlaceholder": "Filtrer les lignes",
  "table.noMatches": 'Aucune ligne ne correspond à "{query}".',
  "table.prevPage": "Précédent",
  "table.nextPage": "Suivant",
  "table.pageInfo": "{first}–{last} sur {total}",
  "action.start": "Démarrer",
  "action.stop": "Arrêter",
  "action.delete": "Supprimer",
  "action.connect": "Connecter",
  "common.none": "aucun",
  "common.cancel": "Annuler",
  "common.loading": "Chargement…",
  "common.apply": "Appliquer",
  "common.chipPlaceholder": "saisir une valeur, puis Entrée",
  "jwa.empty": "Aucun serveur de notebooks dans ce namespace.",
});

/* Restore the persisted locale (after the catalogs exist). */
try {
  const saved = localStorage.getItem("kf.locale");
  if (saved) KF.i18n.locale = saved;
} catch (err) {
  /* storage-less context: default locale */
}

/* ---------------- backend service (lib/services/backend) ---------------- */

KF.getCookie = function (name) {
  const m = document.cookie.match(new RegExp("(?:^|; )" + name + "=([^;]*)"));
  return m ? decodeURIComponent(m[1]) : null;
};

KF.api = async function (path, options = {}) {
  const headers = Object.assign(
    { "Content-Type": "application/json" },
    options.headers || {}
  );
  const method = (options.method || "GET").toUpperCase();
  if (method !== "GET" && method !== "HEAD") {
    const token = KF.getCookie("XSRF-TOKEN");
    if (token) headers["X-XSRF-TOKEN"] = token;
  }
  const resp = await fetch(path, Object.assign({}, options, { headers }));
  const body = await resp.json().catch(() => ({}));
  if (!resp.ok || body.success === false) {
    throw new Error(body.log || resp.status + " " + resp.statusText);
  }
  return body;
};

/* ---------------- DOM helper ------------------------------------------- */

KF.el = function (tag, attrs = {}, ...children) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) {
    if (v === undefined || v === null) continue; // e.g. conditional disabled
    if (k.startsWith("on") && typeof v === "function") {
      node.addEventListener(k.slice(2), v);
    } else if (k === "class") node.className = v;
    else if (k === "style" && typeof v === "object") Object.assign(node.style, v);
    else node.setAttribute(k, v);
  }
  for (const child of children.flat(Infinity)) {
    if (child == null) continue;
    node.append(child instanceof Node ? child : document.createTextNode(child));
  }
  return node;
};

/* ---------------- poller (lib/polling) --------------------------------- */

/* Exponential-backoff poller like the reference's Poller: on success the
 * period resets to `base`; on failure it doubles up to `max`. stop() ends
 * it; the returned handle exposes refresh() for user-triggered reloads. */
KF.poller = function (fn, { base = 4000, max = 60000 } = {}) {
  let period = base;
  let timer = null;
  let stopped = false;
  async function tick(showErrors) {
    try {
      await fn();
      period = base;
    } catch (err) {
      period = Math.min(period * 2, max);
      if (showErrors) KF.showError(err);
    }
    if (!stopped) timer = setTimeout(() => tick(false), period);
  }
  tick(true);
  return {
    stop() {
      stopped = true;
      clearTimeout(timer);
    },
    refresh() {
      clearTimeout(timer);
      return tick(true);
    },
  };
};

/* ---------------- status icon (lib/resource-table/status) --------------- */

KF.STATUS_TITLES = {
  ready: "Running",
  waiting: "Starting",
  warning: "Error",
  terminating: "Deleting",
  stopped: "Stopped",
};

KF.statusDot = function (phase, message) {
  const label = KF.hasMessage("status." + phase)
    ? KF.t("status." + phase)
    : KF.STATUS_TITLES[phase] || phase;
  return KF.el(
    "span",
    { class: "status", title: message || "" },
    KF.el("span", { class: "dot " + phase, "aria-hidden": "true" }),
    label
  );
};

/* ---------------- date-time (lib/date-time) ----------------------------- */

KF.age = function (timestamp) {
  if (!timestamp) return "—";
  const sec = Math.max(0, (Date.now() - Date.parse(timestamp)) / 1000);
  if (sec < 120) return Math.floor(sec) + "s";
  if (sec < 7200) return Math.floor(sec / 60) + "m";
  if (sec < 172800) return Math.floor(sec / 3600) + "h";
  return Math.floor(sec / 86400) + "d";
};

/* Absolute timestamp, UTC, second resolution — the tooltip form of the
 * reference's date-time module ("2026-07-30 09:14:05 UTC"). */
KF.formatDate = function (timestamp) {
  if (!timestamp) return "—";
  const d = new Date(Date.parse(timestamp));
  const pad = (n) => String(n).padStart(2, "0");
  return (
    d.getUTCFullYear() + "-" + pad(d.getUTCMonth() + 1) + "-" +
    pad(d.getUTCDate()) + " " + pad(d.getUTCHours()) + ":" +
    pad(d.getUTCMinutes()) + ":" + pad(d.getUTCSeconds()) + " UTC"
  );
};

/* Relative age with the absolute time as a hover tooltip — what every
 * "Age"/"Last activity" table cell should render. */
KF.ageCell = function (timestamp, suffix) {
  return KF.el(
    "span",
    { class: "kf-age", title: KF.formatDate(timestamp) },
    KF.age(timestamp) + (timestamp && suffix ? suffix : "")
  );
};

/* ---------------- resource table (lib/resource-table) ------------------- */

/* columns: [{title, render(row) -> Node|string, sortKey?(row) -> any}]
 * opts: {onRowClick(row), emptyText} — rows get a click affordance when
 * onRowClick is provided (the reference's details navigation). */
KF.renderTable = function (container, columns, rows, opts = {}) {
  const state = (container._kfSort = container._kfSort || { idx: -1, dir: 1 });
  // Filter + pagination state live with the sort state so a data poll
  // re-render keeps the user's page and query (reference resource-table:
  // MatPaginator + filter predicate).
  if (state.page === undefined) state.page = 0;
  if (state.query === undefined) state.query = "";
  let filtered = rows;
  if (opts.filterable && state.query) {
    const q = state.query.toLowerCase();
    // Match on what the user SEES (the referenced MatTable filters
    // displayed data): each cell's RENDERED text, minus button labels —
    // raw row fields would false-match on invisible data (ISO
    // timestamps rendered as ages, raw phase keys rendered as localized
    // labels) and never match computed cells, while action-button
    // labels ("Delete") would match every row. Button text is excluded
    // STRUCTURALLY (skip the button subtree while walking) rather than
    // by substring removal from the row's text — a row whose own data
    // contains "Delete" must stay matchable.
    const cellText = (v) => {
      if (v == null) return "";
      if (typeof v === "string" || typeof v === "number") return String(v);
      if (Array.isArray(v)) return v.map(cellText).join(" ");
      // Text leaves FIRST: in a real browser Text nodes expose a (defined,
      // empty) childNodes NodeList, so the element walk below would
      // otherwise reduce every text leaf to "".
      if (v.nodeType === 3) return v.textContent || "";
      if (v.tagName === "BUTTON") return " ";
      if (v.childNodes !== undefined) {
        let text = "";
        for (const child of v.childNodes) text += cellText(child);
        return text;
      }
      return v.textContent !== undefined ? v.textContent : "";
    };
    // Per-row filter text is computed ONCE per rows array (and locale)
    // and reused across keystrokes — re-invoking every column's
    // render() per keystroke scaled as rows × columns × keypresses. A
    // data poll passes a fresh rows array, which invalidates the cache.
    let cache = container._kfFilterText;
    if (!cache || cache.rows !== rows || cache.locale !== KF.i18n.locale) {
      cache = container._kfFilterText = {
        rows,
        locale: KF.i18n.locale,
        text: rows.map((row) =>
          columns
            .map((c) => cellText(c.render(row)))
            .join(" ")
            .toLowerCase()
        ),
      };
    }
    filtered = rows.filter((row, i) => cache.text[i].includes(q));
  }
  const pageSize = opts.pageSize || 0;
  const pages = pageSize ? Math.max(1, Math.ceil(filtered.length / pageSize))
                         : 1;
  if (state.page >= pages) state.page = pages - 1;
  const sorted = filtered.slice();
  if (state.idx >= 0 && columns[state.idx] && columns[state.idx].sortKey) {
    const key = columns[state.idx].sortKey;
    sorted.sort((a, b) => {
      const [ka, kb] = [key(a), key(b)];
      return (ka > kb ? 1 : ka < kb ? -1 : 0) * state.dir;
    });
  }
  const pageRows = pageSize
    ? sorted.slice(state.page * pageSize, (state.page + 1) * pageSize)
    : sorted;
  // Stashed on the container so long-lived listeners (the reused filter
  // input) always re-render with the LATEST rows, not the closure from
  // the render that created them.
  const rerender = container._kfRerender =
    () => KF.renderTable(container, columns, rows, opts);
  const head = KF.el(
    "tr",
    {},
    columns.map((c, idx) => {
      /* title may be a thunk (e.g. () => KF.t(...)) so headers follow
       * the active locale on every render. */
      const label = typeof c.title === "function" ? c.title() : c.title;
      if (!c.sortKey) return KF.el("th", { scope: "col" }, label);
      /* a11y: the WAI-ARIA sortable-table pattern — the <th> KEEPS its
       * columnheader semantics (scope=col, aria-sort lives here; it is
       * only valid on column/row headers) and the interactive part is a
       * real <button> nested inside. After the sort re-render, focus is
       * restored onto the same column's button, so keyboard users can
       * toggle direction without re-tabbing through the page. */
      const sort = () => {
        state.dir = state.idx === idx ? -state.dir : 1;
        state.idx = idx;
        state.refocus = idx;
        KF.renderTable(container, columns, rows, opts);
      };
      return KF.el(
        "th",
        {
          scope: "col",
          class: "sortable" + (state.idx === idx ? " sorted" : ""),
          "aria-sort":
            state.idx !== idx
              ? "none"
              : state.dir > 0
                ? "ascending"
                : "descending",
        },
        KF.el(
          "button",
          { class: "kf-sort-btn", onclick: sort },
          label,
          state.idx === idx ? (state.dir > 0 ? " ▲" : " ▼") : ""
        )
      );
    })
  );
  const body = pageRows.length
    ? pageRows.map((row) =>
        KF.el(
          "tr",
          opts.onRowClick
            ? {
                class: "clickable",
                tabindex: "0",
                onclick: () => opts.onRowClick(row),
                onkeydown: (ev) => {
                  /* Only when the ROW itself is focused: Enter on a
                   * nested action button bubbles here too, and firing
                   * the row would stack the drawer on the button's own
                   * dialog. */
                  const within = ev.target && ev.target.closest &&
                    ev.target.closest("button, a, input, select, textarea");
                  if (ev.key === "Enter" && !within) opts.onRowClick(row);
                  /* Arrow-key roving between data rows (WAI-ARIA grid
                   * navigation): focus moves to the adjacent clickable
                   * row without tabbing through its action buttons. */
                  if ((ev.key === "ArrowDown" || ev.key === "ArrowUp") &&
                      !within) {
                    const tr = ev.target.closest("tr");
                    const sib = tr && (ev.key === "ArrowDown"
                      ? tr.nextElementSibling
                      : tr.previousElementSibling);
                    if (sib && sib.focus) {
                      ev.preventDefault();
                      sib.focus();
                    }
                  }
                },
              }
            : {},
          columns.map((c) => KF.el("td", {}, c.render(row)))
        )
      )
    : [
        KF.el(
          "tr",
          {},
          KF.el(
            "td",
            { colspan: String(columns.length), class: "muted" },
            rows.length && opts.filterable && state.query
              ? KF.t("table.noMatches", { query: state.query })
              : opts.emptyText || "Nothing here yet."
          )
        ),
      ];
  const chrome = [];
  let refocusFilter = null;
  if (opts.filterable) {
    // The input element is REUSED across re-renders (stashed on the
    // container): replacing it per keystroke would reset the caret
    // position and abort IME composition in a real browser — the
    // oninput handler re-renders only the rows/pager around it.
    let input = container._kfFilterInput;
    if (input && document.activeElement === input) {
      // replaceChildren detaches the element, which drops focus in a
      // real browser (element state — value, selection — survives).
      refocusFilter = input;
    }
    if (!input) {
      input = container._kfFilterInput = KF.el("input", {
        class: "kf-table-filter",
        type: "search",
        value: state.query,
        oninput: (ev) => {
          state.query = (ev.target && ev.target.value) || "";
          state.page = 0;
          container._kfRerender();
        },
      });
    }
    // Placeholder/label follow the active locale on every render.
    input.setAttribute("placeholder", KF.t("table.filterPlaceholder"));
    input.setAttribute("aria-label", KF.t("table.filterPlaceholder"));
    chrome.push(KF.el("div", { class: "kf-table-toolbar" }, input));
  }
  container.replaceChildren(
    ...chrome,
    KF.el("table", {}, KF.el("thead", {}, head), KF.el("tbody", {}, body))
  );
  if (pageSize && (filtered.length > pageSize || state.page > 0)) {
    /* Pager (reference: MatPaginator): range info + prev/next as real
     * buttons, disabled at the bounds, labels localized. */
    const first = state.page * pageSize + 1;
    const last = Math.min(filtered.length, (state.page + 1) * pageSize);
    const move = (delta) => () => {
      state.page += delta;
      rerender();
    };
    container.append(
      KF.el(
        "div",
        { class: "kf-table-pager" },
        KF.el("button", {
          class: "kf-page-prev",
          "aria-label": KF.t("table.prevPage"),
          disabled: state.page === 0 ? "disabled" : undefined,
          onclick: move(-1),
        }, "‹ " + KF.t("table.prevPage")),
        KF.el("span", { class: "kf-page-info", "aria-live": "polite" },
              KF.t("table.pageInfo",
                   { first, last, total: filtered.length })),
        KF.el("button", {
          class: "kf-page-next",
          "aria-label": KF.t("table.nextPage"),
          disabled: state.page >= pages - 1 ? "disabled" : undefined,
          onclick: move(1),
        }, KF.t("table.nextPage") + " ›")
      )
    );
  }
  if (refocusFilter) refocusFilter.focus();
  if (state.refocus !== undefined) {
    const idx = state.refocus;
    delete state.refocus;
    const buttons = container.querySelectorAll("th .kf-sort-btn");
    // nth sortable column: count sortable columns up to idx
    const at = columns.slice(0, idx).filter((c) => c.sortKey).length;
    if (buttons[at]) buttons[at].focus();
  }
};

/* Action buttons that stop row-click propagation (so a Delete click never
 * opens the details drawer underneath it). */
KF.actionButton = function (label, onclick, opts = {}) {
  return KF.el(
    "button",
    {
      class: opts.class || "",
      title: opts.title || "",
      onclick: (ev) => {
        ev.stopPropagation();
        onclick(ev);
      },
    },
    label
  );
};

/* ---------------- details list (lib/details-list) ----------------------- */

KF.detailsList = function (pairs) {
  return KF.el(
    "dl",
    { class: "details-list" },
    pairs
      .filter(([, v]) => v !== undefined && v !== null && v !== "")
      .map(([k, v]) => [
        KF.el("dt", {}, k),
        KF.el("dd", {}, v instanceof Node ? v : String(v)),
      ])
  );
};

/* ---------------- conditions table (lib/conditions-table) --------------- */

KF.conditionsTable = function (container, conditions) {
  KF.renderTable(
    container,
    [
      { title: "Type", render: (c) => c.type || "—" },
      { title: "Status", render: (c) => c.status || "—" },
      { title: "Reason", render: (c) => c.reason || "—" },
      { title: "Message", render: (c) => c.message || "—" },
      {
        title: "Last probe",
        render: (c) => KF.age(c.lastProbeTime || c.lastTransitionTime),
      },
    ],
    conditions || [],
    { emptyText: "No conditions reported." }
  );
};

/* ---------------- events table ------------------------------------------ */

KF.eventsTable = function (container, events) {
  const rows = (events || [])
    .slice()
    .sort((a, b) => (b.lastTimestamp || "").localeCompare(a.lastTimestamp || ""));
  KF.renderTable(
    container,
    [
      {
        title: "Type",
        render: (e) =>
          KF.el(
            "span",
            { class: e.type === "Warning" ? "event-warning" : "" },
            e.type || "Normal"
          ),
      },
      { title: "Reason", render: (e) => e.reason || "—" },
      { title: "Message", render: (e) => e.message || "—" },
      { title: "Count", render: (e) => String(e.count || 1) },
      { title: "Last seen", render: (e) => KF.age(e.lastTimestamp) },
    ],
    rows,
    { emptyText: "No events." }
  );
};

/* ---------------- logs viewer (lib/logs-viewer) ------------------------- */

/* fetchLogs(podName) -> Promise<string[]>; pods: [{name}] for the worker
 * picker (multi-host slices have one log stream per worker). */
KF.logsViewer = function (container, pods, fetchLogs) {
  const pre = KF.el("pre", { class: "logs" }, KF.t("common.loading"));
  const picker = KF.el(
    "select",
    { style: { width: "auto" } },
    (pods || []).map((p) => KF.el("option", { value: p.name }, p.name))
  );
  let timer = null;
  let follow = true;
  async function load() {
    if (!picker.value) {
      pre.textContent = "No pods.";
      return;
    }
    try {
      const lines = await fetchLogs(picker.value);
      pre.textContent = lines.length ? lines.join("\n") : "(no output yet)";
      if (follow) pre.scrollTop = pre.scrollHeight;
    } catch (err) {
      pre.textContent = "Could not fetch logs: " + (err.message || err);
    }
  }
  const followBtn = KF.el(
    "button",
    {
      onclick: () => {
        follow = !follow;
        followBtn.textContent = follow ? "Following ✓" : "Follow";
      },
    },
    "Following ✓"
  );
  const downloadBtn = KF.el(
    "button",
    {
      onclick: () => {
        const blob = new Blob([pre.textContent], { type: "text/plain" });
        const a = KF.el("a", {
          href: URL.createObjectURL(blob),
          download: (picker.value || "pod") + ".log",
        });
        a.click();
        URL.revokeObjectURL(a.href);
      },
    },
    "Download"
  );
  picker.addEventListener("change", load);
  container.replaceChildren(
    KF.el(
      "div",
      { class: "logs-toolbar" },
      KF.el("span", { class: "muted" }, "worker"),
      picker,
      followBtn,
      downloadBtn
    ),
    pre
  );
  load();
  timer = setInterval(load, 5000);
  return {
    stop() {
      clearInterval(timer);
    },
  };
};

/* ---------------- confirm dialog (lib/confirm-dialog) ------------------- */

KF._dialogIds = 0;

/* Modal layering: every modal (dialogs, the drawer) registers here, and
 * only the TOPMOST layer reacts to Escape — a confirm dialog opened from
 * drawer content must not take the drawer down with it. */
KF._modalStack = [];
KF._isTopModal = function (token) {
  return KF._modalStack[KF._modalStack.length - 1] === token;
};
KF._popModal = function (token) {
  const at = KF._modalStack.indexOf(token);
  if (at >= 0) KF._modalStack.splice(at, 1);
};

/* Modal focus trap (WAI-ARIA dialog pattern): Tab/Shift+Tab cycle
 * within the panel instead of escaping into the aria-modal-inerted page
 * behind it. Call from the modal's keydown handler. */
KF._trapTab = function (panel, ev) {
  if (ev.key !== "Tab") return;
  const items = Array.from(
    panel.querySelectorAll(
      "button, a, input, select, textarea, [tabindex]")
  ).filter((n) => !n.disabled && n.getAttribute("tabindex") !== "-1");
  if (!items.length) return;
  const first = items[0];
  const last = items[items.length - 1];
  const active = document.activeElement;
  const inside = panel.contains(active);
  if (ev.shiftKey && (!inside || active === first)) {
    ev.preventDefault();
    last.focus();
  } else if (!ev.shiftKey && (!inside || active === last)) {
    ev.preventDefault();
    first.focus();
  }
};

KF.confirmDialog = function ({ title, message, confirmText }) {
  return new Promise((resolve) => {
    const overlay = KF.el("div", { class: "kf-overlay" });
    const titleId = "kf-dialog-title-" + ++KF._dialogIds;
    const token = {};
    /* a11y: restore focus to the opener when the dialog closes (WAI-ARIA
     * dialog pattern) — keyboard users otherwise land back at <body>. */
    const opener = document.activeElement || null;
    function close(result) {
      overlay.remove();
      document.removeEventListener("keydown", onKey);
      KF._popModal(token);
      if (opener && opener.focus) opener.focus();
      resolve(result);
    }
    function onKey(ev) {
      if (!KF._isTopModal(token)) return;
      if (ev.key === "Escape") close(false);
      else KF._trapTab(panel, ev);
    }
    document.addEventListener("keydown", onKey);
    KF._modalStack.push(token);
    const confirmBtn = KF.el(
      "button",
      { class: "danger", onclick: () => close(true) },
      confirmText || KF.t("action.delete")
    );
    const panel = KF.el(
      "div",
      { class: "kf-dialog", role: "dialog", "aria-modal": "true",
        "aria-labelledby": titleId },
      KF.el("h3", { id: titleId }, title),
      KF.el("p", {}, message),
      KF.el(
        "div",
        { class: "kf-dialog-actions" },
        KF.el("button", { onclick: () => close(false) },
              KF.t("common.cancel")),
        confirmBtn
      )
    );
    overlay.append(panel);
    overlay.addEventListener("click", (ev) => {
      if (ev.target === overlay) close(false);
    });
    document.body.append(overlay);
    confirmBtn.focus();
  });
};

/* ---------------- code editor (lib/editor) ------------------------------ */

/* YAML line tokenizer for the editor's highlight layer: returns a list of
 * spans for one line. Recognizes comments, `key:` heads (with list dashes),
 * quoted strings, numbers, booleans/null. Token classes are kf-tok-*. */
KF.highlightYamlLine = function (line) {
  const out = [];
  const tok = (cls, text) =>
    out.push(KF.el("span", { class: "kf-tok-" + cls }, text));
  // Whole-line comment (possibly indented).
  const cm = line.match(/^(\s*)(#.*)$/);
  if (cm) {
    if (cm[1]) tok("plain", cm[1]);
    tok("comment", cm[2]);
    return out;
  }
  let rest = line;
  // `  - key:` / `key:` head — the indent+dash stays plain, the key colors.
  const km = rest.match(/^(\s*(?:-\s+)?)([A-Za-z0-9_.\/-]+)(:)(\s|$)/);
  if (km) {
    if (km[1]) tok("plain", km[1]);
    tok("key", km[2]);
    tok("plain", km[3] + km[4]);
    rest = rest.slice(km[0].length);
  } else {
    const dm = rest.match(/^(\s*-\s+)/);
    if (dm) {
      tok("plain", dm[1]);
      rest = rest.slice(dm[1].length);
    }
  }
  // Value part: strings / numbers / booleans / trailing comment.
  while (rest.length) {
    let m;
    if ((m = rest.match(/^("[^"]*"?|'[^']*'?)/))) tok("string", m[1]);
    else if ((m = rest.match(/^(#.*)$/))) tok("comment", m[1]);
    else if ((m = rest.match(/^(-?\d+(?:\.\d+)?)(?![A-Za-z0-9_.-])/)))
      tok("number", m[1]);
    else if ((m = rest.match(/^(true|false|null)(?![A-Za-z0-9_-])/)))
      tok("bool", m[1]);
    else if ((m = rest.match(/^(\s+|[^\s"'#]+)/))) tok("plain", m[1]);
    else {
      tok("plain", rest);
      break;
    }
    rest = rest.slice(m[1].length);
  }
  return out;
};

/* Line-numbered, syntax-highlighted editor — the buildless stand-in for
 * the monaco bundle in the reference's lib/editor: a transparent textarea
 * overlaid on a highlight layer, a line-number gutter that tracks edits
 * and scrolling, and Tab inserting two spaces at the caret instead of
 * leaving the field. Returns {root, textarea, getValue, setValue}. */
KF.codeEditor = function (initial, opts = {}) {
  const gutter = KF.el("div", { class: "kf-code-gutter", "aria-hidden": "true" });
  const hl = KF.el("pre", { class: "kf-code-hl", "aria-hidden": "true" });
  const textarea = KF.el("textarea", {
    class: "kf-code-input " + (opts.textareaClass || ""),
    spellcheck: "false",
  });
  textarea.value = initial || "";
  function render() {
    const lines = textarea.value.split("\n");
    gutter.replaceChildren(
      ...lines.map((_, i) => KF.el("div", {}, String(i + 1)))
    );
    hl.replaceChildren(
      ...lines.map((line) =>
        KF.el("div", { class: "kf-code-line" },
          line ? KF.highlightYamlLine(line) : " ")
      )
    );
    if (opts.onChange) opts.onChange(textarea.value);
  }
  textarea.addEventListener("input", render);
  textarea.addEventListener("scroll", () => {
    hl.scrollTop = textarea.scrollTop;
    hl.scrollLeft = textarea.scrollLeft;
    gutter.scrollTop = textarea.scrollTop;
  });
  textarea.addEventListener("keydown", (ev) => {
    if (ev.key !== "Tab") return;
    ev.preventDefault();
    const start = textarea.selectionStart;
    const end = textarea.selectionEnd;
    const v = textarea.value;
    textarea.value = v.slice(0, start) + "  " + v.slice(end);
    textarea.setSelectionRange(start + 2, start + 2);
    render();
  });
  render();
  const root = KF.el(
    "div",
    { class: "kf-code-editor" },
    gutter,
    KF.el("div", { class: "kf-code-area" }, hl, textarea)
  );
  return {
    root,
    textarea,
    getValue() {
      return textarea.value;
    },
    setValue(v) {
      textarea.value = v;
      render();
    },
  };
};

/* ---------------- YAML editor dialog (lib/editor) ----------------------- */

/* Manifest editor dialog over KF.codeEditor. onSubmit receives the raw
 * YAML text and may throw/reject — the error renders inline and the
 * dialog stays open for another attempt. */
KF.yamlEditDialog = function ({ title, initial = "", submitText, onSubmit }) {
  submitText = submitText || KF.t("common.apply");
  return new Promise((resolve) => {
    const overlay = KF.el("div", { class: "kf-overlay" });
    const errorBox = KF.el("pre", {
      class: "kf-yaml-error",
      style: { color: "#c5221f", whiteSpace: "pre-wrap", display: "none" },
    });
    const editor = KF.codeEditor(initial, { textareaClass: "kf-yaml-editor" });
    const textarea = editor.textarea;
    const titleId = "kf-dialog-title-" + ++KF._dialogIds;
    const token = {};
    const opener = document.activeElement || null;
    let pending = false;
    function close(result) {
      if (pending) return; // no cancel while the submit is in flight
      overlay.remove();
      document.removeEventListener("keydown", onKey);
      KF._popModal(token);
      if (opener && opener.focus) opener.focus();
      resolve(result);
    }
    function onKey(ev) {
      if (!KF._isTopModal(token)) return;
      if (ev.key === "Escape") close(false);
      else KF._trapTab(panel, ev);
    }
    async function submit() {
      if (pending) return; // double-click guard while onSubmit is in flight
      pending = true;
      submitBtn.disabled = true;
      try {
        await onSubmit(textarea.value);
        pending = false;
        close(true);
      } catch (err) {
        errorBox.textContent = String((err && err.message) || err);
        errorBox.style.display = "block";
      } finally {
        pending = false;
        submitBtn.disabled = false;
      }
    }
    document.addEventListener("keydown", onKey);
    KF._modalStack.push(token);
    const submitBtn = KF.el(
      "button", { class: "primary", onclick: submit }, submitText
    );
    const panel = KF.el(
      "div",
      { class: "kf-dialog kf-dialog-wide", role: "dialog",
        "aria-modal": "true", "aria-labelledby": titleId },
      KF.el("h3", { id: titleId }, title),
      editor.root,
      errorBox,
      KF.el(
        "div",
        { class: "kf-dialog-actions" },
        KF.el("button", { onclick: () => close(false) },
              KF.t("common.cancel")),
        submitBtn
      )
    );
    overlay.append(panel);
    overlay.addEventListener("click", (ev) => {
      if (ev.target === overlay) close(false);
    });
    document.body.append(overlay);
    textarea.focus();
  });
};

/* ---------------- snackbar (lib/snack-bar) ------------------------------ */

KF.snackbar = function (message, kind = "info") {
  let host = document.getElementById("kf-snackbar-host");
  if (!host) {
    host = KF.el("div", { id: "kf-snackbar-host" });
    document.body.append(host);
  }
  /* a11y: polite live region for info, assertive alert for errors —
   * screen readers announce the toast without focus moving. */
  const bar = KF.el(
    "div",
    kind === "error"
      ? { class: "kf-snackbar " + kind, role: "alert" }
      : { class: "kf-snackbar " + kind, role: "status",
          "aria-live": "polite" },
    message
  );
  host.append(bar);
  setTimeout(() => bar.classList.add("visible"), 10);
  setTimeout(() => {
    bar.classList.remove("visible");
    setTimeout(() => bar.remove(), 300);
  }, 4000);
};

KF.showError = function (err) {
  const banner = document.getElementById("error-banner");
  const text = String((err && err.message) || err);
  if (!banner) return KF.snackbar(text, "error");
  banner.textContent = text;
  banner.style.display = "block";
  setTimeout(() => (banner.style.display = "none"), 8000);
};

/* ---------------- namespace state (lib/namespace-select) ---------------- */

/* localStorage-backed like the reference's central-dashboard namespace
 * sharing; a `storage` listener keeps iframed sub-apps in sync. */
KF.ns = {
  KEY: "kubeflow.namespace",
  get() {
    return localStorage.getItem(KF.ns.KEY) || "kubeflow-user";
  },
  set(value) {
    localStorage.setItem(KF.ns.KEY, value);
  },
  onChange(fn) {
    window.addEventListener("storage", (ev) => {
      if (ev.key === KF.ns.KEY) fn(ev.newValue);
    });
  },
};

KF.namespacePicker = function (onChange) {
  const input = KF.el("input", {
    value: KF.ns.get(),
    style: { width: "180px" },
    list: "kf-ns-options",
  });
  // Datalist fed from the common /api/namespaces route: free text still
  // works (multi-tenant users may lack list-namespace rights).
  if (!document.getElementById("kf-ns-options")) {
    const datalist = KF.el("datalist", { id: "kf-ns-options" });
    document.body.append(datalist);
    KF.api("api/namespaces")
      .then((body) =>
        datalist.replaceChildren(
          ...body.namespaces.map((name) => KF.el("option", { value: name }))
        )
      )
      .catch(() => {});
  }
  input.addEventListener("change", () => {
    KF.ns.set(input.value);
    onChange(input.value);
  });
  KF.ns.onChange((value) => {
    input.value = value;
    onChange(value);
  });
  return input;
};

/* ---------------- form validators (lib/form) ---------------------------- */

KF.validators = {
  /* DNS-1123 label — the reference's resource-name validator. */
  dns1123: (value) =>
    /^[a-z0-9]([-a-z0-9]*[a-z0-9])?$/.test(value) && value.length <= 63
      ? null
      : "Use lowercase letters, digits and dashes (max 63 chars).",
  positiveNumber: (value) =>
    Number(value) > 0 ? null : "Must be a positive number.",
  memoryQuantity: (value) =>
    /^[0-9]+(\.[0-9]+)?(Ei|Pi|Ti|Gi|Mi|Ki|E|P|T|G|M|k)?$/.test(value)
      ? null
      : "Use a Kubernetes quantity, e.g. 1.5Gi.",
};

/* Attach a validator to an input: red border + title on invalid. Returns
 * () => boolean for submit-time checks. */
KF.validate = function (input, validator) {
  function check() {
    const err = validator(input.value);
    input.classList.toggle("invalid", !!err);
    input.title = err || "";
    /* a11y: announce validity to assistive tech, not only via color. */
    if (err) input.setAttribute("aria-invalid", "true");
    else input.removeAttribute("aria-invalid");
    return !err;
  }
  input.addEventListener("input", check);
  return check;
};

/* ---------------- tabs ------------------------------------------------- */

/* tabs: [{label, render(pane) (may return cleanup.stop)}]
 * a11y: the WAI-ARIA tabs pattern — tablist/tab/tabpanel roles,
 * aria-selected state, Arrow-key roving between tabs. */
KF.tabs = function (container, tabs) {
  const bar = KF.el("div", { class: "kf-tabs", role: "tablist" });
  const pane = KF.el("div", { class: "kf-tab-pane", role: "tabpanel" });
  let cleanup = null;
  function select(idx) {
    if (cleanup && cleanup.stop) cleanup.stop();
    cleanup = null;
    [...bar.children].forEach((b, i) => {
      b.classList.toggle("active", i === idx);
      b.setAttribute("aria-selected", i === idx ? "true" : "false");
      b.setAttribute("tabindex", i === idx ? "0" : "-1");
    });
    pane.replaceChildren();
    cleanup = tabs[idx].render(pane) || null;
  }
  tabs.forEach((tab, idx) =>
    bar.append(
      KF.el(
        "button",
        {
          class: "kf-tab",
          role: "tab",
          onclick: () => select(idx),
          onkeydown: (ev) => {
            const delta = ev.key === "ArrowRight" ? 1
              : ev.key === "ArrowLeft" ? -1 : 0;
            if (!delta) return;
            ev.preventDefault();
            const next = (idx + delta + tabs.length) % tabs.length;
            select(next);
            bar.children[next].focus();
          },
        },
        tab.label
      )
    )
  );
  container.replaceChildren(bar, pane);
  select(0);
  return {
    stop() {
      if (cleanup && cleanup.stop) cleanup.stop();
    },
  };
};

/* ---------------- YAML view (lib/editor, read-only) --------------------- */

KF.toYaml = function (value, indent = 0) {
  const pad = "  ".repeat(indent);
  if (value === null || value === undefined) return "null";
  if (typeof value !== "object") {
    const s = String(value);
    return typeof value === "string" &&
      (s === "" || /[:#{}\[\],&*>|%@`"']|^\s|\s$|^[\d.-]/.test(s))
      ? JSON.stringify(s)
      : s;
  }
  if (Array.isArray(value)) {
    if (!value.length) return "[]";
    return value
      .map((item) => {
        if (item !== null && typeof item === "object") {
          const body = KF.toYaml(item, indent + 1);
          return pad + "-\n" + body;
        }
        return pad + "- " + KF.toYaml(item, 0);
      })
      .join("\n");
  }
  const keys = Object.keys(value);
  if (!keys.length) return "{}";
  return keys
    .map((k) => {
      const v = value[k];
      if (v !== null && typeof v === "object" && Object.keys(v).length) {
        return pad + k + ":\n" + KF.toYaml(v, indent + 1);
      }
      return pad + k + ": " + KF.toYaml(v, 0);
    })
    .join("\n");
};

KF.yamlView = function (container, obj) {
  container.replaceChildren(KF.el("pre", { class: "yaml" }, KF.toYaml(obj)));
};

/* ---------------- details drawer --------------------------------------- */

/* Slide-in panel hosting a details page (the reference's per-resource
 * details route, drawer-style so the table stays live behind it). */
KF.drawer = function (title) {
  const content = KF.el("div", { class: "kf-drawer-content" });
  let onClose = null;
  const overlay = KF.el("div", { class: "kf-overlay kf-drawer-overlay" });
  /* a11y: full modal-dialog focus management — focus moves INTO the
   * drawer on open (aria-modal declares the page behind it inert, so
   * leaving focus on the opening row would strand assistive tech) and
   * returns to the opener on close. */
  const opener = document.activeElement || null;
  function onDrawerKey(ev) {
    if (ev.key === "Escape") close();
    else KF._trapTab(panel, ev);
  }
  function close() {
    document.removeEventListener("keydown", onDrawerKey);
    overlay.remove();
    if (opener && opener.focus) opener.focus();
    if (onClose) onClose();
  }
  document.addEventListener("keydown", onDrawerKey);
  const closeBtn = KF.el(
    "button", { onclick: close, "aria-label": "close" }, "✕");
  const panel = KF.el(
    "div",
    { class: "kf-drawer", role: "dialog", "aria-modal": "true",
      "aria-label": String(title) },
    KF.el(
      "div",
      { class: "kf-drawer-head" },
      KF.titleActionsToolbar({ title, actions: [closeBtn] })
    ),
    content
  );
  overlay.addEventListener("click", (ev) => {
    if (ev.target === overlay) close();
  });
  overlay.append(panel);
  document.body.append(overlay);
  closeBtn.focus();
  return {
    content,
    close,
    set onclose(fn) {
      onClose = fn;
    },
  };
};

/* ---------------- TPU slice rollup -------------------------------------- */

/* The panel the reference never needed: worker-by-worker slice health.
 * tpu: spec.tpu {accelerator, topology}; tpuStatus: status.tpu
 * {hosts, readyHosts, chips}; pods: [{name, ready}] worker pod list. */
KF.sliceRollup = function (container, tpu, tpuStatus, pods, opts = {}) {
  if (!tpu) {
    container.replaceChildren(
      KF.el("p", { class: "muted" }, "CPU-only notebook (no TPU slice).")
    );
    return;
  }
  const hosts = (tpuStatus && tpuStatus.hosts) || 1;
  const ready = (tpuStatus && tpuStatus.readyHosts) || 0;
  const chips = (tpuStatus && tpuStatus.chips) || "?";
  const banners = [];
  if (tpuStatus && tpuStatus.capacityPending) {
    banners.push(
      KF.el(
        "p",
        { class: "kf-capacity-banner" },
        "⏳ Waiting for TPU capacity — a queued ProvisioningRequest is ",
        "reserving all " + hosts + " host(s); workers start when it is ",
        "provisioned."
      )
    );
  }
  if (opts.maintenancePending) {
    banners.push(
      KF.el(
        "p",
        { class: "kf-maintenance-banner" },
        "⚠ Node maintenance pending on " + opts.maintenancePending +
          " — checkpoint your work; the slice restarts when the node(s) " +
          "go down."
      )
    );
  }
  const workers = KF.el(
    "div",
    { class: "slice-grid" },
    Array.from({ length: hosts }, (_, i) => {
      const pod = (pods || []).find((p) => p.name && p.name.endsWith("-" + i));
      const phase = pod ? (pod.ready ? "ready" : "waiting") : "stopped";
      return KF.el(
        "div",
        { class: "slice-worker " + phase, title: pod ? pod.name : "no pod" },
        KF.el("span", { class: "dot " + phase, "aria-hidden": "true" }),
        "worker-" + i
      );
    })
  );
  container.replaceChildren(
    ...banners,
    KF.detailsList([
      ["Accelerator", tpu.accelerator],
      ["Topology", tpu.topology],
      ["Slices", tpu.numSlices > 1 ? String(tpu.numSlices) : null],
      ["Chips", String(chips)],
      ["Hosts ready", ready + " / " + hosts],
    ]),
    workers
  );
};

/* ---------------- help popover (lib/help-popover) ----------------------- */

/* A "?" affordance that toggles an inline popover. Click anywhere else
 * (or Escape) closes it; only one popover is open at a time. One pair of
 * module-level document listeners serves every instance — per-instance
 * registration would leak a listener (and pin its detached popover) for
 * each re-render. */
KF.closeAllPopovers = function () {
  document
    .querySelectorAll(".kf-popover")
    .forEach((p) => (p.style.display = "none"));
};
document.addEventListener("click", KF.closeAllPopovers);
document.addEventListener("keydown", (ev) => {
  if (ev.key === "Escape") KF.closeAllPopovers();
});

KF.helpPopover = function (text) {
  const pop = KF.el("span", { class: "kf-popover", role: "tooltip" }, text);
  pop.style.display = "none";
  const icon = KF.el(
    "button",
    {
      class: "kf-help",
      "aria-label": "help",
      onclick: (ev) => {
        ev.stopPropagation();
        const open = pop.style.display !== "none";
        KF.closeAllPopovers();
        pop.style.display = open ? "none" : "inline-block";
      },
    },
    "?"
  );
  return KF.el("span", { class: "kf-help-slot" }, icon, pop);
};

/* ---------------- loading spinner (lib/loading-spinner) ----------------- */

KF.spinner = function (label) {
  return KF.el(
    "span",
    { class: "kf-spinner", role: "status" },
    KF.el("span", { class: "kf-spinner-dot" }),
    label || KF.t("common.loading")
  );
};

/* Swap a container to a spinner until the promise settles; renders the
 * resolved value through `render(container, value)` or the error through
 * KF.showError. Returns the promise for chaining. */
KF.withSpinner = function (container, promise, render) {
  container.replaceChildren(KF.spinner());
  return promise.then(
    (value) => {
      container.replaceChildren();
      render(container, value);
      return value;
    },
    (err) => {
      container.replaceChildren(
        KF.el("p", { class: "muted" }, "Failed: " + (err.message || err))
      );
      throw err;
    }
  );
};

/* ---------------- variables groups table (lib/variables-groups-table) --- */

/* Grouped key/value rows with collapsible group headers — the reference's
 * variables-groups-table (env vars grouped by their PodDefault/source).
 * groups: [{name, vars: [{key, value}]}]. */
KF.varsGroupsTable = function (container, groups) {
  container.replaceChildren(
    ...((groups || []).length
      ? groups.map((group) => {
          const body = KF.el(
            "table",
            { class: "kf-vars" },
            KF.el(
              "tbody",
              {},
              group.vars.map((v) =>
                KF.el(
                  "tr",
                  {},
                  KF.el("td", { class: "kf-var-key" }, v.key),
                  KF.el(
                    "td",
                    { class: "kf-var-value" },
                    v.value === undefined || v.value === null ? "—" : v.value
                  )
                )
              )
            )
          );
          const head = KF.el(
            "button",
            {
              class: "kf-vars-group-head",
              onclick: () => {
                const hidden = body.style.display === "none";
                body.style.display = hidden ? "" : "none";
                head.textContent =
                  (hidden ? "▾ " : "▸ ") + group.name +
                  ` (${group.vars.length})`;
              },
            },
            `▾ ${group.name} (${group.vars.length})`
          );
          return KF.el("div", { class: "kf-vars-group" }, head, body);
        })
      : [KF.el("p", { class: "muted" }, "No variables.")])
  );
};

/* ---------------- advanced form section --------------------------------- */

/* Collapsible "Advanced options" wrapper (the reference spawner's
 * advanced panels). Starts collapsed; render(pane) runs once on first
 * expand so hidden controls stay cheap. */
KF.advancedSection = function (title, render) {
  const pane = KF.el("div", { class: "kf-advanced-pane" });
  pane.style.display = "none";
  let rendered = false;
  const toggle = KF.el(
    "button",
    {
      class: "kf-advanced-toggle",
      type: "button",
      onclick: () => {
        const hidden = pane.style.display === "none";
        pane.style.display = hidden ? "block" : "none";
        toggle.textContent = (hidden ? "▾ " : "▸ ") + title;
        if (hidden && !rendered) {
          rendered = true;
          render(pane);
        }
      },
    },
    "▸ " + title
  );
  return KF.el("div", { class: "kf-advanced" }, toggle, pane);
};

/* ---------------- chips input (advanced form control) ------------------- */

/* Free-form list-of-strings input: type + Enter adds a chip, ✕ removes.
 * onChange receives the current list. */
/* opts.validate(value) -> error string | null rejects bad entries at
 * Enter time (red border + title) instead of silently dropping them at
 * submit time. */
/* ---------------- volume forms (reference: jupyter form-new/volume) ------
 *
 * Per-volume panel with new-vs-existing choice; "new" edits name
 * (with {notebook-name} templating), size, storage class and access
 * mode; "existing" picks a PVC. value() emits the backend's
 * workspaceVolume/dataVolumes contract (web/jupyter/form.py
 * _apply_volumes): {newPvc: {metadata, spec}, mount} |
 * {existingSource: {persistentVolumeClaim}, mount} | null.
 * Mirrors form-workspace-volume / form-data-volumes / volume/new/*
 * (name, size, storage-class, access-modes sub-components). */

KF.ACCESS_MODES = ["ReadWriteOnce", "ReadWriteMany", "ReadOnlyMany"];

KF.volumePanel = function (opts = {}) {
  const kind = opts.kind || "data"; // "workspace" | "data"
  const catalogs = opts.catalogs || {}; // {pvcs, storageClasses, defaultClass}
  const modes = kind === "workspace"
    ? ["new", "existing", "none"]
    : ["new", "existing"];
  const modeLabels = {
    new: KF.t("volumes.typeNew"),
    existing: KF.t("volumes.typeExisting"),
    none: KF.t("volumes.typeNone"),
  };

  const root = KF.el("div", { class: "kf-volume-panel" });
  const body = KF.el("div", {});
  const modeSelect = KF.el(
    "select",
    { class: "kf-volume-mode", style: { width: "auto" }, onchange: render },
    modes.map((m) => KF.el("option", { value: m }, modeLabels[m]))
  );
  if (opts.mode) modeSelect.value = opts.mode;

  const state = {
    name: opts.name || (kind === "workspace"
      ? "{notebook-name}-workspace"
      : `{notebook-name}-datavol-${opts.index || 1}`),
    sizeGi: opts.sizeGi || (kind === "workspace" ? "10" : "5"),
    storageClass: "",         // "" = cluster default
    accessMode: "ReadWriteOnce",
    existing: "",
    mount: opts.mount || (kind === "workspace"
      ? "/home/jovyan"
      : `/home/jovyan/data-${opts.index || 1}`),
  };

  function field(labelKey, control) {
    return KF.el(
      "label",
      { class: "kf-volume-field",
        style: { display: "block", margin: "6px 0" } },
      KF.el("span", { style: { display: "inline-block", minWidth: "110px" } },
            KF.t(labelKey)),
      control
    );
  }

  function bound(attrs, key, tag = "input") {
    const node = KF.el(tag, Object.assign({
      value: state[key],
      oninput: (ev) => { state[key] = ev.target.value; },
      onchange: (ev) => { state[key] = ev.target.value; },
    }, attrs));
    if (tag === "input") node.value = state[key];
    return node;
  }

  function render() {
    const mode = modeSelect.value;
    if (mode === "none") {
      body.replaceChildren(
        KF.el("p", { class: "muted" }, KF.t("volumes.noneHint")));
      return;
    }
    if (mode === "existing") {
      const pvcs = catalogs.pvcs || [];
      const pick = KF.el(
        "select",
        { class: "kf-volume-existing", style: { width: "auto" },
          onchange: (ev) => { state.existing = ev.target.value; } },
        pvcs.length
          ? pvcs.map((p) => KF.el(
              "option", { value: p.name },
              `${p.name} (${p.capacity || "?"})`))
          : [KF.el("option", { value: "" }, KF.t("volumes.noPvcs"))]
      );
      if (pvcs.length && !state.existing) state.existing = pvcs[0].name;
      if (state.existing) pick.value = state.existing;
      body.replaceChildren(
        field("volumes.existingPvc", pick),
        field("volumes.mount", bound({ class: "kf-volume-mount" }, "mount"))
      );
      return;
    }
    const classes = catalogs.storageClasses || [];
    const classSelect = KF.el(
      "select",
      { class: "kf-volume-class", style: { width: "auto" },
        onchange: (ev) => { state.storageClass = ev.target.value; } },
      KF.el("option", { value: "" },
            KF.t("volumes.defaultClass",
                 { name: catalogs.defaultClass || "—" })),
      classes.map((c) => KF.el("option", { value: c }, c))
    );
    if (state.storageClass) classSelect.value = state.storageClass;
    const modeSel = KF.el(
      "select",
      { class: "kf-volume-access", style: { width: "auto" },
        onchange: (ev) => { state.accessMode = ev.target.value; } },
      KF.ACCESS_MODES.map((m) => KF.el("option", { value: m }, m))
    );
    modeSel.value = state.accessMode;
    body.replaceChildren(
      field("volumes.name", bound({ class: "kf-volume-name" }, "name")),
      field("volumes.size", KF.el(
        "span", {},
        bound({ class: "kf-volume-size", type: "number", min: "1",
                style: { width: "70px" } }, "sizeGi"),
        " Gi")),
      field("volumes.class", classSelect),
      field("volumes.accessMode", modeSel),
      field("volumes.mount", bound({ class: "kf-volume-mount" }, "mount"))
    );
  }

  render();
  root.append(modeSelect, body);
  return {
    root,
    get mode() {
      return modeSelect.value;
    },
    value() {
      const mode = modeSelect.value;
      if (mode === "none") return null;
      if (mode === "existing") {
        if (!state.existing) return null;
        return {
          existingSource: {
            persistentVolumeClaim: { claimName: state.existing },
          },
          mount: state.mount,
        };
      }
      // A cleared number input yields "" — fall back to the panel's
      // default rather than emitting the invalid quantity "Gi" (the
      // apiserver rejects it with an opaque parse error).
      const size = parseInt(state.sizeGi, 10);
      const sizeGi = Number.isFinite(size) && size >= 1
        ? size
        : (kind === "workspace" ? 10 : 5);
      const spec = {
        accessModes: [state.accessMode],
        resources: { requests: { storage: `${sizeGi}Gi` } },
      };
      if (state.storageClass) spec.storageClassName = state.storageClass;
      return {
        newPvc: { metadata: { name: state.name }, spec },
        mount: state.mount,
      };
    },
  };
};

KF.dataVolumesForm = function (container, catalogs = {}) {
  /* N removable volume panels + the two add buttons (reference
   * form-data-volumes: addNewVolume / attachExistingVolume). */
  const panels = [];
  const list = KF.el("div", {});
  let counter = 0;

  function add(mode) {
    counter += 1;
    const panel = KF.volumePanel({
      kind: "data", index: counter, mode, catalogs,
    });
    const row = KF.el(
      "div",
      { class: "kf-data-volume", style: { margin: "6px 0" } },
      panel.root,
      KF.actionButton(KF.t("action.delete"), () => {
        const at = panels.indexOf(panel);
        if (at >= 0) panels.splice(at, 1);
        row.remove();
      }, { class: "danger" })
    );
    panels.push(panel);
    list.append(row);
  }

  container.replaceChildren(
    list,
    KF.el("div", { style: { marginTop: "4px" } },
      KF.actionButton(KF.t("volumes.addNew"), () => add("new")),
      " ",
      KF.actionButton(KF.t("volumes.attachExisting"), () => add("existing"))
    )
  );
  return {
    add,
    value() {
      return panels.map((p) => p.value()).filter(Boolean);
    },
  };
};

KF.registerMessages("en", {
  "volumes.typeNew": "New volume",
  "volumes.typeExisting": "Existing volume",
  "volumes.typeNone": "No volume",
  "volumes.noneHint": "The server runs on ephemeral storage only.",
  "volumes.name": "Name",
  "volumes.size": "Size",
  "volumes.class": "Storage class",
  "volumes.defaultClass": "cluster default ({name})",
  "volumes.accessMode": "Access mode",
  "volumes.mount": "Mount path",
  "volumes.existingPvc": "PVC",
  "volumes.noPvcs": "no PVCs in this namespace",
  "volumes.addNew": "+ Add new volume",
  "volumes.attachExisting": "+ Attach existing volume",
});
KF.registerMessages("de", {
  "volumes.typeNew": "Neues Volume",
  "volumes.typeExisting": "Vorhandenes Volume",
  "volumes.typeNone": "Kein Volume",
  "volumes.noneHint": "Der Server läuft nur mit flüchtigem Speicher.",
  "volumes.name": "Name",
  "volumes.size": "Größe",
  "volumes.class": "Speicherklasse",
  "volumes.defaultClass": "Cluster-Standard ({name})",
  "volumes.accessMode": "Zugriffsmodus",
  "volumes.mount": "Mount-Pfad",
  "volumes.existingPvc": "PVC",
  "volumes.noPvcs": "keine PVCs in diesem Namespace",
  "volumes.addNew": "+ Neues Volume",
  "volumes.attachExisting": "+ Vorhandenes Volume anhängen",
});
KF.registerMessages("fr", {
  "volumes.typeNew": "Nouveau volume",
  "volumes.typeExisting": "Volume existant",
  "volumes.typeNone": "Aucun volume",
  "volumes.noneHint": "Le serveur utilise uniquement un stockage éphémère.",
  "volumes.name": "Nom",
  "volumes.size": "Taille",
  "volumes.class": "Classe de stockage",
  "volumes.defaultClass": "défaut du cluster ({name})",
  "volumes.accessMode": "Mode d'accès",
  "volumes.mount": "Chemin de montage",
  "volumes.existingPvc": "PVC",
  "volumes.noPvcs": "aucun PVC dans ce namespace",
  "volumes.addNew": "+ Ajouter un volume",
  "volumes.attachExisting": "+ Attacher un volume existant",
});

KF.chipsInput = function (initial, onChange, { placeholder, validate } = {}) {
  const values = (initial || []).slice();
  const list = KF.el("span", { class: "kf-chips" });
  function renderChips() {
    list.replaceChildren(
      ...values.map((value, idx) =>
        KF.el(
          "span",
          { class: "chip" },
          value,
          KF.el(
            "button",
            {
              type: "button",
              class: "kf-chip-x",
              onclick: () => {
                values.splice(idx, 1);
                renderChips();
                onChange(values.slice());
              },
            },
            "✕"
          )
        )
      )
    );
  }
  const input = KF.el("input", {
    placeholder: placeholder || KF.t("common.chipPlaceholder"),
    style: { width: "200px" },
  });
  input.addEventListener("keydown", (ev) => {
    if (ev.key !== "Enter") return;
    ev.preventDefault();
    const value = (input.value || "").trim();
    if (!value || values.includes(value)) return;
    const err = validate ? validate(value) : null;
    input.classList.toggle("invalid", !!err);
    input.title = err || "";
    if (err) return;
    values.push(value);
    input.value = "";
    renderChips();
    onChange(values.slice());
  });
  renderChips();
  return KF.el("span", { class: "kf-chips-input" }, list, input);
};

/* ---------------- title-actions toolbar (lib/title-actions-toolbar) ----- */

/* Page/drawer header row: back affordance, title + subtitle on the left,
 * action buttons on the right — the reference's title-actions-toolbar. */
KF.titleActionsToolbar = function ({ title, subtitle, actions, onBack }) {
  return KF.el(
    "div",
    { class: "kf-toolbar" },
    onBack
      ? KF.el(
          "button",
          { class: "kf-toolbar-back", "aria-label": "back", onclick: onBack },
          "←"
        )
      : null,
    KF.el(
      "div",
      { class: "kf-toolbar-titles" },
      KF.el("h2", {}, title),
      subtitle ? KF.el("span", { class: "muted" }, subtitle) : null
    ),
    KF.el("div", { class: "kf-toolbar-actions" }, actions || [])
  );
};

/* ---------------- app URLs (lib/urls) ----------------------------------- */

/* The L7 URL contract in one place — every link the mesh routes
 * (/notebook/<ns>/<name>/, /tensorboard/..., /pvcviewer/...) is built
 * here so the scheme can't drift per app. */
KF.urls = {
  notebook: (ns, name) =>
    "/notebook/" + encodeURIComponent(ns) + "/" + encodeURIComponent(name) + "/",
  tensorboard: (ns, name) =>
    "/tensorboard/" + encodeURIComponent(ns) + "/" + encodeURIComponent(name) + "/",
  pvcviewer: (ns, name) =>
    "/pvcviewer/" + encodeURIComponent(ns) + "/" + encodeURIComponent(name) + "/",
};

/* ---------------- sparkline (dashboard metrics) ------------------------- */

/* Dependency-free time-series mini chart; points: [{timestamp, value}]. */
KF.sparkline = function (canvas, points, { stroke = "#1a73e8" } = {}) {
  const ctx = canvas.getContext("2d");
  const w = (canvas.width = canvas.clientWidth * 2 || 600);
  const h = (canvas.height = canvas.clientHeight * 2 || 120);
  ctx.clearRect(0, 0, w, h);
  if (!points || points.length < 2) {
    ctx.fillStyle = "#5f6368";
    ctx.font = "24px system-ui";
    ctx.fillText("no data", 12, h / 2);
    return;
  }
  const xs = points.map((p) => p.timestamp);
  const ys = points.map((p) => p.value);
  const [x0, x1] = [Math.min(...xs), Math.max(...xs)];
  const [y0, y1] = [Math.min(...ys), Math.max(...ys)];
  const sx = (x) => ((x - x0) / (x1 - x0 || 1)) * (w - 16) + 8;
  const sy = (y) => h - 8 - ((y - y0) / (y1 - y0 || 1)) * (h - 16);
  ctx.beginPath();
  ctx.strokeStyle = stroke;
  ctx.lineWidth = 3;
  points.forEach((p, i) =>
    i
      ? ctx.lineTo(sx(p.timestamp), sy(p.value))
      : ctx.moveTo(sx(p.timestamp), sy(p.value))
  );
  ctx.stroke();
};

/* ---------------- legacy global aliases --------------------------------- */

const getCookie = KF.getCookie;
const api = KF.api;
const el = KF.el;
const statusDot = KF.statusDot;
const renderTable = KF.renderTable;
const ns = KF.ns;
const namespacePicker = KF.namespacePicker;
const showError = KF.showError;
function poll(fn, intervalMs = 4000) {
  return KF.poller(fn, { base: intervalMs });
}
