"""aiohttp app factory with the cross-cutting middleware stack.

Reference: ``crud_backend/__init__.py:16-35`` (create_app) with:

- authn: trusted userid header (authn.py:34-67) — 401 when absent unless a
  dev default user is configured
- CSRF double-submit cookie (csrf.py:59-113): safe methods set/refresh the
  ``XSRF-TOKEN`` cookie; mutating methods must echo it in ``X-XSRF-TOKEN``
- error mapping: ApiError subclasses → JSON envelope with the right HTTP
  status (the reference's ``{success, status, log}`` envelope)
- liveness/readiness blueprint (probes.py)
- /metrics in Prometheus text format (the reference exposes metrics from
  controllers only; here every backend serves its registry)
"""

from __future__ import annotations

import logging
import secrets
import time

from aiohttp import web

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.runtime.errors import ApiError, Unauthorized
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.web.common.auth import USERID_HEADER, AllowAll, Authorizer

log = logging.getLogger(__name__)

CSRF_COOKIE = "XSRF-TOKEN"
CSRF_HEADER = "X-XSRF-TOKEN"
SAFE_METHODS = {"GET", "HEAD", "OPTIONS"}
REQUEST_ID_HEADER = "X-Request-Id"


def _is_probe_path(path: str) -> bool:
    """Probe/scrape endpoints bypass authn/CSRF. Matched by last segment so
    the exemption holds under path-prefixed subapp mounting (WEBAPP=all
    serves /jupyter/healthz etc.)."""
    return path.rstrip("/").rsplit("/", 1)[-1] in ("healthz", "readyz", "metrics")


def json_success(payload: dict | None = None, status: int = 200) -> web.Response:
    return web.json_response({"success": True, "status": status, **(payload or {})},
                             status=status)


def json_error(message: str, status: int = 500) -> web.Response:
    return web.json_response(
        {"success": False, "status": status, "log": message}, status=status
    )


def create_base_app(
    kube,
    *,
    authorizer: Authorizer | None = None,
    userid_header: str = USERID_HEADER,
    userid_prefix: str = "",
    dev_default_user: str | None = None,
    csrf_protect: bool = True,
    secure_cookies: bool | None = None,
    registry: Registry | None = None,
) -> web.Application:
    # Secure cookies default on like the reference (APP_SECURE_COOKIES,
    # crud_backend/config.py): HTTPS deployments must not send the CSRF
    # double-submit cookie cleartext. Dev/test over plain http sets the
    # env var (or the kwarg) to false.
    if secure_cookies is None:
        import os

        secure_cookies = (
            os.environ.get("APP_SECURE_COOKIES", "true").lower() != "false"
        )
    registry = registry or global_registry
    m_requests = registry.counter(
        "web_app_requests_total", "Backend HTTP requests", ["method", "status"]
    )
    m_duration = registry.histogram(
        "web_request_duration_seconds",
        "Backend HTTP request latency per route",
        ["route", "method"],
    )

    def _route_of(request: web.Request) -> str:
        """The matched route PATTERN (bounded label cardinality), not the
        raw path — /api/namespaces, not whatever the client typed."""
        resource = getattr(request.match_info.route, "resource", None)
        canonical = getattr(resource, "canonical", None)
        return canonical or "unmatched"

    @web.middleware
    async def request_id_middleware(request: web.Request, handler):
        """Correlation + latency, outermost: every request runs under a
        trace whose id comes from (or becomes) the X-Request-Id header —
        the same header the controllers stamp on their apiserver calls —
        and every response echoes it. The per-route duration histogram
        observes even error responses."""
        rid = request.headers.get(REQUEST_ID_HEADER) or tracing.new_trace_id()
        request["request_id"] = rid
        t0 = time.perf_counter()
        try:
            with tracing.span(
                "http_request", trace_id=rid,
                method=request.method, path=request.path,
            ):
                resp = await handler(request)
            resp.headers[REQUEST_ID_HEADER] = rid
            return resp
        except web.HTTPException as e:
            # aiohttp HTTP exceptions ARE responses; echo the id on them.
            e.headers[REQUEST_ID_HEADER] = rid
            raise
        finally:
            # Every request lands in the histogram — error responses and
            # escaped exceptions included.
            m_duration.labels(
                route=_route_of(request), method=request.method
            ).observe(time.perf_counter() - t0)

    @web.middleware
    async def error_middleware(request: web.Request, handler):
        try:
            resp = await handler(request)
        except web.HTTPException:
            raise
        except ApiError as e:
            log.info("%s %s -> %s", request.method, request.path, e.reason)
            resp = json_error(e.message, e.code)
        except Exception:
            log.exception("%s %s failed", request.method, request.path)
            resp = json_error("internal error", 500)
        m_requests.labels(method=request.method, status=str(resp.status)).inc()
        return resp

    @web.middleware
    async def authn_middleware(request: web.Request, handler):
        if _is_probe_path(request.path):
            return await handler(request)
        user = request.headers.get(userid_header)
        if user is None:
            if dev_default_user is None:
                raise Unauthorized(f"missing {userid_header} header")
            user = dev_default_user
        if userid_prefix and user.startswith(userid_prefix):
            user = user[len(userid_prefix):]
        request["user"] = user
        return await handler(request)

    @web.middleware
    async def csrf_middleware(request: web.Request, handler):
        if not csrf_protect or _is_probe_path(request.path):
            return await handler(request)
        cookie = request.cookies.get(CSRF_COOKIE)
        if request.method not in SAFE_METHODS:
            header = request.headers.get(CSRF_HEADER)
            if not cookie or not header or not secrets.compare_digest(cookie, header):
                return json_error("CSRF token missing or invalid", 403)
        resp = await handler(request)
        if request.method in SAFE_METHODS and not cookie:
            # Secure by default like the reference (APP_SECURE_COOKIES,
            # csrf.py) — double-submit cookies must not travel cleartext
            # on HTTPS deployments. Dev mode (plain http) turns it off.
            resp.set_cookie(
                CSRF_COOKIE, secrets.token_urlsafe(32),
                samesite="Strict", secure=secure_cookies, httponly=False,
            )
        return resp

    app = web.Application(
        middlewares=[
            request_id_middleware,
            error_middleware,
            authn_middleware,
            csrf_middleware,
        ]
    )
    app["kube"] = kube
    app["authorizer"] = authorizer or AllowAll()
    # The resolved identity contract, for introspection (/debug) — never
    # re-derive from env, the kwargs are the truth.
    app["userid_header"] = userid_header
    app["userid_prefix"] = userid_prefix

    async def healthz(_request):
        return web.json_response({"status": "ok"})

    async def metrics(_request):
        return web.Response(text=registry.expose(), content_type="text/plain")

    async def namespaces(_request):
        """Common to every app (reference crud_backend/routes/get.py:10-15):
        namespace names for the UI's picker."""
        names = sorted(
            (ns.get("metadata") or {}).get("name", "")
            for ns in await kube.list("Namespace")
        )
        return json_success({"namespaces": [n for n in names if n]})

    DEFAULT_SC_ANNOTATIONS = (
        "storageclass.kubernetes.io/is-default-class",
        "storageclass.beta.kubernetes.io/is-default-class",  # GKE legacy
    )

    async def storageclasses(_request):
        """Names for the volume form's class picker (reference
        crud_backend/routes/get.py:18-23)."""
        names = sorted(
            (sc.get("metadata") or {}).get("name", "")
            for sc in await kube.list("StorageClass")
        )
        return json_success({"storageClasses": [n for n in names if n]})

    async def default_storageclass(_request):
        """The cluster default, or "" when none is marked (reference
        crud_backend/routes/get.py:26-52 — both annotation spellings)."""
        for sc in await kube.list("StorageClass"):
            annotations = (sc.get("metadata") or {}).get("annotations") or {}
            if any(annotations.get(key) == "true"
                   for key in DEFAULT_SC_ANNOTATIONS):
                return json_success(
                    {"defaultStorageClass": sc["metadata"]["name"]})
        return json_success({"defaultStorageClass": ""})

    app.router.add_get("/healthz", healthz)
    app.router.add_get("/readyz", healthz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/api/namespaces", namespaces)
    app.router.add_get("/api/storageclasses", storageclasses)
    app.router.add_get("/api/storageclasses/default", default_storageclass)
    return app
