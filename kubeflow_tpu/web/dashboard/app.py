"""Dashboard BFF: namespace/workgroup aggregation + cluster metrics.

Reference: ``components/centraldashboard/app`` — workgroup/registration flow
against KFAM (api_workgroup.ts, 394 LoC), k8s info (k8s_service.ts), metrics
abstraction with pluggable drivers (metrics_service.ts:1-53,
prometheus_metrics_service.ts:1-90), user header middleware
(attach_user_middleware.ts), env contract in server.ts:27-37.

The KFAM dependency is injected as an in-process callable boundary (the
reference's HTTP hop): pass ``kfam_client=HttpKfam(url)`` in production or
leave the default in-process implementation when KFAM shares the process.

TPU-native metrics: alongside the reference's CPU/memory panels the
dashboard aggregates TPU chip demand per namespace straight from the
apiserver (pod resource requests), so the landing page answers "who is
holding chips" without a Prometheus round-trip.
"""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api import profile as profileapi
from kubeflow_tpu.runtime.errors import Invalid
from kubeflow_tpu.runtime.objects import deep_get, get_meta, name_of
from kubeflow_tpu.tpu.topology import TPU_RESOURCE
from kubeflow_tpu.web.common.app import create_base_app, json_success
from kubeflow_tpu.web.common.serving import add_spa
from kubeflow_tpu.web.common.auth import ensure

DEFAULT_LINKS = [
    {"type": "item", "link": "/jupyter/", "text": "Notebooks", "icon": "book"},
    {"type": "item", "link": "/tensorboards/", "text": "TensorBoards",
     "icon": "assessment"},
    {"type": "item", "link": "/volumes/", "text": "Volumes",
     "icon": "device:storage"},
]


def create_app(
    kube,
    *,
    links: list[dict] | None = None,
    settings: dict | None = None,
    registration_flow: bool = True,
    metrics_service=None,
    kfam_client=None,
    cluster_admins: set[str] | None = None,
    **kwargs,
) -> web.Application:
    import os

    from kubeflow_tpu.web.dashboard.kfam import HttpKfam, InProcessKfam
    from kubeflow_tpu.web.dashboard.metrics import metrics_service_from_env

    app = create_base_app(kube, **kwargs)
    app["links"] = links or DEFAULT_LINKS
    app["settings"] = settings or {}
    app["registration_flow"] = registration_flow
    app["metrics_service"] = metrics_service or metrics_service_from_env(
        dict(os.environ)
    )
    # KFAM boundary (reference PROFILES_KFAM_SERVICE_HOST): HTTP hop when a
    # split KFAM deployment is configured, in-process otherwise.
    kfam_url = os.environ.get("KFAM_URL")
    app["kfam"] = kfam_client or (
        HttpKfam(kfam_url) if kfam_url
        else InProcessKfam(kube, cluster_admins=cluster_admins)
    )
    app.add_routes(routes)
    add_spa(app, __file__)

    async def _close_clients(app):
        await app["metrics_service"].close()
        if hasattr(app["kfam"], "close"):
            await app["kfam"].close()

    app.on_cleanup.append(_close_clients)
    return app


routes = web.RouteTableDef()


async def _namespaces_for(kube, user: str) -> list[dict]:
    """Namespaces the user owns or contributes to (api_workgroup.ts
    getWorkgroupInfo): owner annotation or KFAM binding annotations.
    Contributor lookups across namespaces run concurrently — this backs the
    dashboard landing page, so no serial per-profile round-trips."""
    import asyncio

    profiles = await kube.list("Profile")

    async def role_in(profile: dict) -> dict | None:
        ns = name_of(profile)
        if profileapi.owner_of(profile).get("name") == user:
            return {"namespace": ns, "role": "owner", "user": user}
        for rb in await kube.list("RoleBinding", ns):
            annotations = get_meta(rb).get("annotations") or {}
            if annotations.get("user") == user and "role" in annotations:
                role = annotations["role"].removeprefix("kubeflow-")
                return {"namespace": ns, "role": role, "user": user}
        return None

    results = await asyncio.gather(*(role_in(p) for p in profiles))
    return [r for r in results if r]


@routes.get("/api/workgroup/exists")
async def workgroup_exists(request):
    kube, user = request.app["kube"], request.get("user", "")
    namespaces = await _namespaces_for(kube, user)
    return json_success(
        {
            "hasAuth": True,
            "hasWorkgroup": any(n["role"] == "owner" for n in namespaces),
            "user": user,
            "registrationFlowAllowed": request.app["registration_flow"],
        }
    )


@routes.get("/api/workgroup/env-info")
async def env_info(request):
    kube, user = request.app["kube"], request.get("user", "")
    namespaces = await _namespaces_for(kube, user)
    return json_success(
        {
            "user": user,
            "namespaces": namespaces,
            "platform": {"provider": "gke", "logoutUrl": "/logout"},
            "isClusterAdmin": False,
        }
    )


@routes.post("/api/workgroup/create")
async def create_workgroup(request):
    """Self-serve registration (api_workgroup.ts create flow): the user's
    first profile, named from their email local part."""
    kube, user = request.app["kube"], request.get("user", "")
    if not request.app["registration_flow"]:
        raise Invalid("registration flow is disabled")
    # The namespace name is DERIVED from the authenticated identity, never
    # taken from the body — a body override would let any user claim any
    # unregistered namespace name (e.g. kube-system) as their profile.
    name = user.split("@")[0].replace(".", "-").lower()
    await kube.create("Profile", profileapi.new(name, user))
    return json_success({"message": f"Created namespace {name}"})


@routes.delete("/api/workgroup/nuke-self")
async def nuke_self(request):
    """Self-serve deregistration (reference api_workgroup.ts nuke-self):
    delete every profile the caller owns; cascade removes the namespaces."""
    kube, user = request.app["kube"], request.get("user", "")
    from kubeflow_tpu.api import profile as papi

    deleted = []
    for profile in await kube.list("Profile"):
        if papi.owner_of(profile).get("name") == user:
            await kube.delete("Profile", name_of(profile))
            deleted.append(name_of(profile))
    if not deleted:
        raise Invalid(f"user {user!r} owns no profiles")
    return json_success({"message": f"Deleted profiles: {', '.join(deleted)}"})


@routes.get("/api/workgroup/get-contributors/{namespace}")
async def get_contributors(request):
    """Reference api_workgroup.ts get-contributors/:namespace."""
    kfam, user = request.app["kfam"], request.get("user", "")
    namespace = request.match_info["namespace"]
    users = await kfam.list_contributors(user, namespace)
    return json_success({"contributors": users})


@routes.post("/api/workgroup/add-contributor/{namespace}")
async def add_contributor(request):
    """Reference api_workgroup.ts add-contributor/:namespace."""
    kfam, user = request.app["kfam"], request.get("user", "")
    namespace = request.match_info["namespace"]
    body = await request.json()
    await kfam.add_contributor(user, namespace, body.get("contributor", ""))
    return json_success(
        {"contributors": await kfam.list_contributors(user, namespace)}
    )


@routes.delete("/api/workgroup/remove-contributor/{namespace}")
async def remove_contributor(request):
    """Reference api_workgroup.ts remove-contributor/:namespace."""
    kfam, user = request.app["kfam"], request.get("user", "")
    namespace = request.match_info["namespace"]
    body = await request.json()
    await kfam.remove_contributor(user, namespace, body.get("contributor", ""))
    return json_success(
        {"contributors": await kfam.list_contributors(user, namespace)}
    )


@routes.get("/api/dashboard-links")
async def dashboard_links(request):
    return json_success({"menuLinks": request.app["links"]})


@routes.get("/debug")
async def debug_info(request):
    """Deployment self-description (reference server.ts /debug): who the
    request resolved to and which env contract is active."""
    from kubeflow_tpu.runtime.deployment import controller_namespace

    return json_success({
        "user": request.get("user", ""),
        "kfamBoundary": type(request.app["kfam"]).__name__,
        "metricsDriver": type(request.app["metrics_service"]).__name__,
        "registrationFlowAllowed": request.app["registration_flow"],
        "controllerNamespace": controller_namespace(),
        "headersForIdentity": {
            "USERID_HEADER": request.app["userid_header"],
            "USERID_PREFIX": request.app["userid_prefix"],
        },
    })


@routes.get("/api/dashboard-settings")
async def dashboard_settings(request):
    """Admin settings blob (reference api.ts /dashboard-settings: the
    links ConfigMap's data["settings"] JSON; default {})."""
    return json_success({"settings": request.app.get("settings") or {}})


@routes.get("/api/activities/{namespace}")
async def activities(request):
    """Recent events in the namespace, newest first (reference api.ts
    /activities/:namespace → k8sService.getEventsForNamespace)."""
    kube = request.app["kube"]
    ns = request.match_info["namespace"]
    await ensure(
        request.app["authorizer"], request.get("user", ""), "list", "Event", ns
    )
    from kubeflow_tpu.web.common.status import event_stamp as stamp

    events = await kube.list("Event", ns)
    events.sort(key=stamp, reverse=True)
    return json_success({
        "activities": [
            {
                "time": stamp(ev),
                "type": ev.get("type", "Normal"),
                "reason": ev.get("reason", ""),
                "message": ev.get("message", ""),
                "involved": {
                    "kind": (ev.get("involvedObject") or {}).get("kind", ""),
                    "name": (ev.get("involvedObject") or {}).get("name", ""),
                },
            }
            for ev in events[:100]
        ]
    })


@routes.get("/api/metrics")
async def cluster_metrics(request):
    """Time-series metrics via the configured driver (reference
    ``server.ts`` /api/metrics + resource-chart.js consumption): query
    params ``type`` (node_cpu|pod_cpu|pod_mem|tpu_duty) and ``interval``
    (Last5m..Last180m)."""
    from kubeflow_tpu.web.dashboard.metrics import INTERVALS_MIN, QUERIES

    svc = request.app["metrics_service"]
    series = request.query.get("type", "node_cpu")
    interval = request.query.get("interval", "Last15m")
    if series not in QUERIES or interval not in INTERVALS_MIN:
        raise Invalid(f"unknown metrics type/interval {series!r}/{interval!r}")
    points = await svc.query(series, interval)
    return json_success(
        {
            "type": series,
            "interval": interval,
            "points": [p.to_dict() for p in points],
            **svc.charts_link(),
        }
    )


@routes.get("/api/namespaces/{namespace}/tpu-usage")
async def tpu_usage(request):
    """TPU chip demand in a namespace, from pod resource requests."""
    kube = request.app["kube"]
    ns = request.match_info["namespace"]
    await ensure(
        request.app["authorizer"], request.get("user", ""), "list", "Pod", ns
    )
    chips_requested = 0
    pods = []
    for pod in await kube.list("Pod", ns):
        pod_chips = 0
        for ctr in deep_get(pod, "spec", "containers", default=[]):
            val = deep_get(ctr, "resources", "requests", TPU_RESOURCE)
            if val is not None:
                pod_chips += int(val)
        if pod_chips:
            pods.append({"pod": name_of(pod), "chips": pod_chips})
            chips_requested += pod_chips
    quota = await kube.get_or_none("ResourceQuota", profileapi.QUOTA_NAME, ns)
    limit = deep_get(quota or {}, "spec", "hard", profileapi.TPU_QUOTA_KEY)
    return json_success(
        {
            "namespace": ns,
            "chipsRequested": chips_requested,
            "chipsQuota": int(limit) if limit is not None else None,
            "pods": pods,
        }
    )
