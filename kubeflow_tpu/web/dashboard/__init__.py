"""Central dashboard backend-for-frontend."""

from kubeflow_tpu.web.dashboard.app import create_app

__all__ = ["create_app"]
