/* Dashboard frontend: workgroup bootstrap, app links, namespaces, TPU
 * usage, and time-series metrics panels (sparklines over /api/metrics —
 * the reference's resource-chart.js over the pluggable metrics service).
 * All user-visible strings route through KF.t (reference: the
 * centraldashboard's i18n pipeline). */

KF.registerMessages("en", {
  "cd.metricTpuDuty": "TPU duty cycle",
  "cd.metricNodeCpu": "Node CPU",
  "cd.metricPodMem": "Pod memory",
  "cd.noQuota": "no quota",
  "cd.quota": "quota {n}",
  "cd.chipsRequested": "{n} chips requested in {ns} ({quota})",
  "cd.noTpuPods": "No TPU pods running.",
  "cd.noRecentEvents": "No recent events in {ns}.",
  "cd.loading": "loading…",
  "cd.noDataInRange": "no data in range",
  "cd.noMetricsBackend": "no metrics backend configured (set PROMETHEUS_URL)",
  "cd.latest": "latest: {value} ({label})",
  "cd.metricsUnavailable": "metrics unavailable: {message}",
  "cd.contributorsTitle": "Contributors — {ns}",
  "cd.loadingCap": "Loading…",
  "cd.remove": "Remove",
  "cd.noContributors": "No contributors yet.",
  "cd.contributorsHint":
    "Contributors get edit access to every app in this namespace.",
  "cd.contributorAdded": "Contributor added",
  "cd.add": "Add",
  "cd.colNamespace": "Namespace",
  "cd.colRole": "Role",
  "cd.colContributors": "Contributors",
  "cd.manage": "Manage",
  "cd.emptyNamespaces": "No namespaces yet — register a workgroup below.",
  "cd.workgroupCreated": "Workgroup created",
  "cd.title": "Kubeflow TPU",
  "cd.welcome": "Welcome",
  "cd.noWorkspaceYet": "You don't have a workspace namespace yet.",
  "cd.createMyNamespace": "Create my namespace",
  "cd.applications": "Applications",
  "cd.myNamespaces": "My namespaces",
  "cd.tpuUsage": "TPU usage",
  "cd.recentActivity": "Recent activity",
  "cd.clusterMetrics": "Cluster metrics",
  "cd.selectNamespace": "Select a namespace above.",
  "cd.ago": " ago",
});
KF.registerMessages("de", {
  "cd.metricTpuDuty": "TPU-Auslastung",
  "cd.metricNodeCpu": "Node-CPU",
  "cd.metricPodMem": "Pod-Speicher",
  "cd.noQuota": "kein Kontingent",
  "cd.quota": "Kontingent {n}",
  "cd.chipsRequested": "{n} Chips angefordert in {ns} ({quota})",
  "cd.noTpuPods": "Keine TPU-Pods laufen.",
  "cd.noRecentEvents": "Keine aktuellen Ereignisse in {ns}.",
  "cd.loading": "lädt…",
  "cd.noDataInRange": "keine Daten im Zeitraum",
  "cd.noMetricsBackend":
    "kein Metrik-Backend konfiguriert (PROMETHEUS_URL setzen)",
  "cd.latest": "aktuell: {value} ({label})",
  "cd.metricsUnavailable": "Metriken nicht verfügbar: {message}",
  "cd.contributorsTitle": "Mitwirkende — {ns}",
  "cd.loadingCap": "Lädt…",
  "cd.remove": "Entfernen",
  "cd.noContributors": "Noch keine Mitwirkenden.",
  "cd.contributorsHint":
    "Mitwirkende erhalten Schreibzugriff auf alle Apps in diesem Namespace.",
  "cd.contributorAdded": "Mitwirkende(r) hinzugefügt",
  "cd.add": "Hinzufügen",
  "cd.colNamespace": "Namespace",
  "cd.colRole": "Rolle",
  "cd.colContributors": "Mitwirkende",
  "cd.manage": "Verwalten",
  "cd.emptyNamespaces":
    "Noch keine Namespaces — unten eine Workgroup registrieren.",
  "cd.workgroupCreated": "Workgroup erstellt",
  "cd.title": "Kubeflow TPU",
  "cd.welcome": "Willkommen",
  "cd.noWorkspaceYet": "Sie haben noch keinen Workspace-Namespace.",
  "cd.createMyNamespace": "Meinen Namespace erstellen",
  "cd.applications": "Anwendungen",
  "cd.myNamespaces": "Meine Namespaces",
  "cd.tpuUsage": "TPU-Nutzung",
  "cd.recentActivity": "Aktuelle Aktivität",
  "cd.clusterMetrics": "Cluster-Metriken",
  "cd.selectNamespace": "Oben einen Namespace auswählen.",
  "cd.ago": " zuvor",
});
KF.registerMessages("fr", {
  "cd.metricTpuDuty": "Taux d'occupation TPU",
  "cd.metricNodeCpu": "CPU du nœud",
  "cd.metricPodMem": "Mémoire des pods",
  "cd.noQuota": "pas de quota",
  "cd.quota": "quota {n}",
  "cd.chipsRequested": "{n} puces demandées dans {ns} ({quota})",
  "cd.noTpuPods": "Aucun pod TPU en cours.",
  "cd.noRecentEvents": "Aucun événement récent dans {ns}.",
  "cd.loading": "chargement…",
  "cd.noDataInRange": "aucune donnée sur la période",
  "cd.noMetricsBackend":
    "aucun backend de métriques configuré (définir PROMETHEUS_URL)",
  "cd.latest": "dernier : {value} ({label})",
  "cd.metricsUnavailable": "métriques indisponibles : {message}",
  "cd.contributorsTitle": "Contributeurs — {ns}",
  "cd.loadingCap": "Chargement…",
  "cd.remove": "Retirer",
  "cd.noContributors": "Aucun contributeur pour l'instant.",
  "cd.contributorsHint":
    "Les contributeurs ont un accès en écriture à toutes les " +
    "applications de ce namespace.",
  "cd.contributorAdded": "Contributeur ajouté",
  "cd.add": "Ajouter",
  "cd.colNamespace": "Namespace",
  "cd.colRole": "Rôle",
  "cd.colContributors": "Contributeurs",
  "cd.manage": "Gérer",
  "cd.emptyNamespaces":
    "Aucun namespace — enregistrez un groupe de travail ci-dessous.",
  "cd.workgroupCreated": "Groupe de travail créé",
  "cd.title": "Kubeflow TPU",
  "cd.welcome": "Bienvenue",
  "cd.noWorkspaceYet":
    "Vous n'avez pas encore de namespace d'espace de travail.",
  "cd.createMyNamespace": "Créer mon namespace",
  "cd.applications": "Applications",
  "cd.myNamespaces": "Mes namespaces",
  "cd.tpuUsage": "Utilisation TPU",
  "cd.recentActivity": "Activité récente",
  "cd.clusterMetrics": "Métriques du cluster",
  "cd.selectNamespace": "Sélectionnez un namespace ci-dessus.",
  "cd.ago": " plus tôt",
});

const METRIC_PANELS = [
  { type: "tpu_duty", labelKey: "cd.metricTpuDuty" },
  { type: "node_cpu", labelKey: "cd.metricNodeCpu" },
  { type: "pod_mem", labelKey: "cd.metricPodMem" },
];

async function loadLinks() {
  const body = await api("api/dashboard-links");
  document
    .getElementById("links")
    .replaceChildren(
      ...body.menuLinks.map((link) =>
        el("a", { href: link.link, style: "margin-right:24px" }, link.text)
      )
    );
}

async function loadTpuUsage(namespace) {
  const body = await api(`api/namespaces/${namespace}/tpu-usage`);
  const target = document.getElementById("tpu-table");
  const quota = body.chipsQuota == null
    ? KF.t("cd.noQuota")
    : KF.t("cd.quota", { n: body.chipsQuota });
  target.classList.remove("muted");
  target.replaceChildren(
    el("p", {}, KF.t("cd.chipsRequested",
                     { n: body.chipsRequested, ns: namespace, quota })),
    body.pods.length
      ? el(
          "div",
          {},
          body.pods.map((p) =>
            el("span", { class: "chip" }, `${p.pod}: ${p.chips}`)
          )
        )
      : el("p", { class: "muted" }, KF.t("cd.noTpuPods"))
  );
}

async function loadActivities(namespace) {
  /* Reference /api/activities/:namespace — the landing page's "recent
   * activity" feed of namespace events, newest first. */
  const body = await api(`api/activities/${namespace}`);
  const target = document.getElementById("activities");
  target.classList.remove("muted");
  target.replaceChildren(
    body.activities.length
      ? el(
          "ul",
          { class: "activity-feed" },
          body.activities.slice(0, 15).map((a) =>
            el(
              "li",
              { class: a.type === "Warning" ? "event-warning" : "" },
              KF.ageCell(a.time, KF.t("cd.ago")),
              el("span", { class: "muted" }, " — "),
              `${a.involved.kind} ${a.involved.name}: ${a.reason} `,
              el("span", { class: "muted" }, a.message)
            )
          )
        )
      : el("p", { class: "muted" },
           KF.t("cd.noRecentEvents", { ns: namespace }))
  );
}

async function loadMetrics() {
  const host = document.getElementById("metrics-panels");
  if (!host) return;
  for (const panel of METRIC_PANELS) {
    let slot = document.getElementById("metric-" + panel.type);
    if (!slot) {
      slot = el(
        "div",
        { id: "metric-" + panel.type, class: "card" },
        el("h4", { class: "metric-title" }, KF.t(panel.labelKey)),
        el("canvas", { class: "spark" }),
        el("p", { class: "muted metric-note" }, KF.t("cd.loading"))
      );
      host.append(slot);
    } else {
      slot.querySelector(".metric-title").textContent = KF.t(panel.labelKey);
    }
    try {
      const body = await api(
        `api/metrics?type=${panel.type}&interval=Last15m`
      );
      KF.sparkline(slot.querySelector("canvas"), body.points);
      const note = slot.querySelector(".metric-note");
      if (!body.points.length) {
        note.textContent = body.resourceChartsLink
          ? KF.t("cd.noDataInRange")
          : KF.t("cd.noMetricsBackend");
      } else {
        const last = body.points[body.points.length - 1];
        note.textContent = KF.t("cd.latest", {
          value: last.value.toFixed(3),
          label: last.label || panel.type,
        });
      }
    } catch (err) {
      slot.querySelector(".metric-note").textContent =
        KF.t("cd.metricsUnavailable", { message: err.message });
    }
  }
}

function openContributors(n) {
  /* Manage-contributors drawer (the reference dashboard's manage-users
   * view over KFAM bindings). Only owners can mutate; others see a 403
   * surfaced in the list area. */
  const drawer = KF.drawer(KF.t("cd.contributorsTitle", { ns: n.namespace }));
  const list = el("div", {}, KF.t("cd.loadingCap"));
  const emailInput = el("input", {
    placeholder: "someone@example.com",
    style: { width: "260px" },
  });
  async function load() {
    try {
      const body = await api(
        `api/workgroup/get-contributors/${n.namespace}`
      );
      list.replaceChildren(
        body.contributors.length
          ? el(
              "ul",
              {},
              body.contributors.map((email) =>
                el(
                  "li",
                  { style: { marginBottom: "6px" } },
                  email + " ",
                  KF.actionButton(KF.t("cd.remove"), () =>
                    api(
                      `api/workgroup/remove-contributor/${n.namespace}`,
                      {
                        method: "DELETE",
                        body: JSON.stringify({ contributor: email }),
                      }
                    ).then(load, KF.showError)
                  , { class: "danger" })
                )
              )
            )
          : el("p", { class: "muted" }, KF.t("cd.noContributors"))
      );
    } catch (err) {
      list.replaceChildren(el("p", { class: "muted" }, err.message));
    }
  }
  drawer.content.append(
    el("p", { class: "muted" }, KF.t("cd.contributorsHint")),
    list,
    el(
      "div",
      { style: { display: "flex", gap: "8px", marginTop: "12px" } },
      emailInput,
      el(
        "button",
        {
          class: "primary",
          onclick: () =>
            api(`api/workgroup/add-contributor/${n.namespace}`, {
              method: "POST",
              body: JSON.stringify({ contributor: emailInput.value }),
            }).then(() => {
              emailInput.value = "";
              KF.snackbar(KF.t("cd.contributorAdded"));
              load();
            }, KF.showError),
        },
        KF.t("cd.add")
      )
    )
  );
  load();
}

async function refresh() {
  const info = await api("api/workgroup/env-info");
  document.getElementById("user-slot").textContent = info.user;
  const exists = await api("api/workgroup/exists");
  document.getElementById("register-card").style.display =
    exists.hasWorkgroup || !exists.registrationFlowAllowed ? "none" : "block";
  renderTable(
    document.getElementById("ns-table"),
    [
      {
        title: () => KF.t("cd.colNamespace"),
        render: (n) =>
          el(
            "a",
            {
              href: "#",
              onclick: (ev) => {
                ev.preventDefault();
                KF.ns.set(n.namespace);
                loadTpuUsage(n.namespace).catch(showError);
                loadActivities(n.namespace).catch(showError);
              },
            },
            n.namespace
          ),
        sortKey: (n) => n.namespace,
      },
      { title: () => KF.t("cd.colRole"), render: (n) => n.role },
      {
        title: () => KF.t("cd.colContributors"),
        render: (n) =>
          n.role === "owner"
            ? KF.actionButton(KF.t("cd.manage"), () => openContributors(n))
            : "—",
      },
    ],
    info.namespaces,
    { emptyText: KF.t("cd.emptyNamespaces"), pageSize: 25, filterable: true }
  );
  if (info.namespaces.length) {
    loadTpuUsage(info.namespaces[0].namespace).catch(() => {});
    loadActivities(info.namespaces[0].namespace).catch(() => {});
  }
  await loadMetrics();
}

document.getElementById("register-btn").addEventListener("click", () => {
  api("api/workgroup/create", { method: "POST", body: "{}" }).then(
    () => {
      KF.snackbar(KF.t("cd.workgroupCreated"));
      refresh().catch(showError);
    },
    showError
  );
});

const localeSlot = document.getElementById("locale-slot");
if (localeSlot) localeSlot.append(KF.localePicker());
KF.localizeDocument();
KF.onLocaleChange(() => refresh().catch(() => {}));
loadLinks().catch(showError);
poll(refresh, 10000);
