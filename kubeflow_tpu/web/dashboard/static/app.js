/* Dashboard frontend: workgroup bootstrap, app links, namespaces, TPU
 * usage, and time-series metrics panels (sparklines over /api/metrics —
 * the reference's resource-chart.js over the pluggable metrics service). */

const METRIC_PANELS = [
  { type: "tpu_duty", label: "TPU duty cycle" },
  { type: "node_cpu", label: "Node CPU" },
  { type: "pod_mem", label: "Pod memory" },
];

async function loadLinks() {
  const body = await api("api/dashboard-links");
  document
    .getElementById("links")
    .replaceChildren(
      ...body.menuLinks.map((link) =>
        el("a", { href: link.link, style: "margin-right:24px" }, link.text)
      )
    );
}

async function loadTpuUsage(namespace) {
  const body = await api(`api/namespaces/${namespace}/tpu-usage`);
  const target = document.getElementById("tpu-table");
  const quota = body.chipsQuota == null ? "no quota" : `quota ${body.chipsQuota}`;
  target.classList.remove("muted");
  target.replaceChildren(
    el("p", {}, `${body.chipsRequested} chips requested in ${namespace} (${quota})`),
    body.pods.length
      ? el(
          "div",
          {},
          body.pods.map((p) =>
            el("span", { class: "chip" }, `${p.pod}: ${p.chips}`)
          )
        )
      : el("p", { class: "muted" }, "No TPU pods running.")
  );
}

async function loadMetrics() {
  const host = document.getElementById("metrics-panels");
  if (!host) return;
  for (const panel of METRIC_PANELS) {
    let slot = document.getElementById("metric-" + panel.type);
    if (!slot) {
      slot = el(
        "div",
        { id: "metric-" + panel.type, class: "card" },
        el("h4", {}, panel.label),
        el("canvas", { class: "spark" }),
        el("p", { class: "muted metric-note" }, "loading…")
      );
      host.append(slot);
    }
    try {
      const body = await api(
        `api/metrics?type=${panel.type}&interval=Last15m`
      );
      KF.sparkline(slot.querySelector("canvas"), body.points);
      const note = slot.querySelector(".metric-note");
      if (!body.points.length) {
        note.textContent = body.resourceChartsLink
          ? "no data in range"
          : "no metrics backend configured (set PROMETHEUS_URL)";
      } else {
        const last = body.points[body.points.length - 1];
        note.textContent = `latest: ${last.value.toFixed(3)} (${last.label || panel.type})`;
      }
    } catch (err) {
      slot.querySelector(".metric-note").textContent =
        "metrics unavailable: " + err.message;
    }
  }
}

async function refresh() {
  const info = await api("api/workgroup/env-info");
  document.getElementById("user-slot").textContent = info.user;
  const exists = await api("api/workgroup/exists");
  document.getElementById("register-card").style.display =
    exists.hasWorkgroup || !exists.registrationFlowAllowed ? "none" : "block";
  renderTable(
    document.getElementById("ns-table"),
    [
      {
        title: "Namespace",
        render: (n) =>
          el(
            "a",
            {
              href: "#",
              onclick: (ev) => {
                ev.preventDefault();
                KF.ns.set(n.namespace);
                loadTpuUsage(n.namespace).catch(showError);
              },
            },
            n.namespace
          ),
        sortKey: (n) => n.namespace,
      },
      { title: "Role", render: (n) => n.role },
    ],
    info.namespaces,
    { emptyText: "No namespaces yet — register a workgroup below." }
  );
  if (info.namespaces.length) {
    loadTpuUsage(info.namespaces[0].namespace).catch(() => {});
  }
  await loadMetrics();
}

document.getElementById("register-btn").addEventListener("click", () => {
  api("api/workgroup/create", { method: "POST", body: "{}" }).then(
    () => {
      KF.snackbar("Workgroup created");
      refresh().catch(showError);
    },
    showError
  );
});

loadLinks().catch(showError);
poll(refresh, 10000);
