/* Dashboard frontend: workgroup bootstrap, app links, namespaces, TPU
 * usage, and time-series metrics panels (sparklines over /api/metrics —
 * the reference's resource-chart.js over the pluggable metrics service). */

const METRIC_PANELS = [
  { type: "tpu_duty", label: "TPU duty cycle" },
  { type: "node_cpu", label: "Node CPU" },
  { type: "pod_mem", label: "Pod memory" },
];

async function loadLinks() {
  const body = await api("api/dashboard-links");
  document
    .getElementById("links")
    .replaceChildren(
      ...body.menuLinks.map((link) =>
        el("a", { href: link.link, style: "margin-right:24px" }, link.text)
      )
    );
}

async function loadTpuUsage(namespace) {
  const body = await api(`api/namespaces/${namespace}/tpu-usage`);
  const target = document.getElementById("tpu-table");
  const quota = body.chipsQuota == null ? "no quota" : `quota ${body.chipsQuota}`;
  target.classList.remove("muted");
  target.replaceChildren(
    el("p", {}, `${body.chipsRequested} chips requested in ${namespace} (${quota})`),
    body.pods.length
      ? el(
          "div",
          {},
          body.pods.map((p) =>
            el("span", { class: "chip" }, `${p.pod}: ${p.chips}`)
          )
        )
      : el("p", { class: "muted" }, "No TPU pods running.")
  );
}

async function loadActivities(namespace) {
  /* Reference /api/activities/:namespace — the landing page's "recent
   * activity" feed of namespace events, newest first. */
  const body = await api(`api/activities/${namespace}`);
  const target = document.getElementById("activities");
  target.classList.remove("muted");
  target.replaceChildren(
    body.activities.length
      ? el(
          "ul",
          { class: "activity-feed" },
          body.activities.slice(0, 15).map((a) =>
            el(
              "li",
              { class: a.type === "Warning" ? "event-warning" : "" },
              KF.ageCell(a.time, " ago"), el("span", { class: "muted" }, " — "),
              `${a.involved.kind} ${a.involved.name}: ${a.reason} `,
              el("span", { class: "muted" }, a.message)
            )
          )
        )
      : el("p", { class: "muted" }, `No recent events in ${namespace}.`)
  );
}

async function loadMetrics() {
  const host = document.getElementById("metrics-panels");
  if (!host) return;
  for (const panel of METRIC_PANELS) {
    let slot = document.getElementById("metric-" + panel.type);
    if (!slot) {
      slot = el(
        "div",
        { id: "metric-" + panel.type, class: "card" },
        el("h4", {}, panel.label),
        el("canvas", { class: "spark" }),
        el("p", { class: "muted metric-note" }, "loading…")
      );
      host.append(slot);
    }
    try {
      const body = await api(
        `api/metrics?type=${panel.type}&interval=Last15m`
      );
      KF.sparkline(slot.querySelector("canvas"), body.points);
      const note = slot.querySelector(".metric-note");
      if (!body.points.length) {
        note.textContent = body.resourceChartsLink
          ? "no data in range"
          : "no metrics backend configured (set PROMETHEUS_URL)";
      } else {
        const last = body.points[body.points.length - 1];
        note.textContent = `latest: ${last.value.toFixed(3)} (${last.label || panel.type})`;
      }
    } catch (err) {
      slot.querySelector(".metric-note").textContent =
        "metrics unavailable: " + err.message;
    }
  }
}

function openContributors(n) {
  /* Manage-contributors drawer (the reference dashboard's manage-users
   * view over KFAM bindings). Only owners can mutate; others see a 403
   * surfaced in the list area. */
  const drawer = KF.drawer(`Contributors — ${n.namespace}`);
  const list = el("div", {}, "Loading…");
  const emailInput = el("input", {
    placeholder: "someone@example.com",
    style: { width: "260px" },
  });
  async function load() {
    try {
      const body = await api(
        `api/workgroup/get-contributors/${n.namespace}`
      );
      list.replaceChildren(
        body.contributors.length
          ? el(
              "ul",
              {},
              body.contributors.map((email) =>
                el(
                  "li",
                  { style: { marginBottom: "6px" } },
                  email + " ",
                  KF.actionButton("Remove", () =>
                    api(
                      `api/workgroup/remove-contributor/${n.namespace}`,
                      {
                        method: "DELETE",
                        body: JSON.stringify({ contributor: email }),
                      }
                    ).then(load, KF.showError)
                  , { class: "danger" })
                )
              )
            )
          : el("p", { class: "muted" }, "No contributors yet.")
      );
    } catch (err) {
      list.replaceChildren(el("p", { class: "muted" }, err.message));
    }
  }
  drawer.content.append(
    el("p", { class: "muted" },
      "Contributors get edit access to every app in this namespace."),
    list,
    el(
      "div",
      { style: { display: "flex", gap: "8px", marginTop: "12px" } },
      emailInput,
      el(
        "button",
        {
          class: "primary",
          onclick: () =>
            api(`api/workgroup/add-contributor/${n.namespace}`, {
              method: "POST",
              body: JSON.stringify({ contributor: emailInput.value }),
            }).then(() => {
              emailInput.value = "";
              KF.snackbar("Contributor added");
              load();
            }, KF.showError),
        },
        "Add"
      )
    )
  );
  load();
}

async function refresh() {
  const info = await api("api/workgroup/env-info");
  document.getElementById("user-slot").textContent = info.user;
  const exists = await api("api/workgroup/exists");
  document.getElementById("register-card").style.display =
    exists.hasWorkgroup || !exists.registrationFlowAllowed ? "none" : "block";
  renderTable(
    document.getElementById("ns-table"),
    [
      {
        title: "Namespace",
        render: (n) =>
          el(
            "a",
            {
              href: "#",
              onclick: (ev) => {
                ev.preventDefault();
                KF.ns.set(n.namespace);
                loadTpuUsage(n.namespace).catch(showError);
                loadActivities(n.namespace).catch(showError);
              },
            },
            n.namespace
          ),
        sortKey: (n) => n.namespace,
      },
      { title: "Role", render: (n) => n.role },
      {
        title: "Contributors",
        render: (n) =>
          n.role === "owner"
            ? KF.actionButton("Manage", () => openContributors(n))
            : "—",
      },
    ],
    info.namespaces,
    { emptyText: "No namespaces yet — register a workgroup below." }
  );
  if (info.namespaces.length) {
    loadTpuUsage(info.namespaces[0].namespace).catch(() => {});
    loadActivities(info.namespaces[0].namespace).catch(() => {});
  }
  await loadMetrics();
}

document.getElementById("register-btn").addEventListener("click", () => {
  api("api/workgroup/create", { method: "POST", body: "{}" }).then(
    () => {
      KF.snackbar("Workgroup created");
      refresh().catch(showError);
    },
    showError
  );
});

loadLinks().catch(showError);
poll(refresh, 10000);
