/* Dashboard frontend: workgroup bootstrap, app links, namespaces, TPU usage. */

async function loadLinks() {
  const body = await api("api/dashboard-links");
  document
    .getElementById("links")
    .replaceChildren(
      ...body.menuLinks.map((link) =>
        el("a", { href: link.link, style: "margin-right:24px" }, link.text)
      )
    );
}

async function loadTpuUsage(namespace) {
  const body = await api(`api/namespaces/${namespace}/tpu-usage`);
  const target = document.getElementById("tpu-table");
  const quota = body.chipsQuota == null ? "no quota" : `quota ${body.chipsQuota}`;
  target.classList.remove("muted");
  target.replaceChildren(
    el("p", {}, `${body.chipsRequested} chips requested in ${namespace} (${quota})`),
    body.pods.length
      ? el(
          "div",
          {},
          body.pods.map((p) =>
            el("span", { class: "chip" }, `${p.pod}: ${p.chips}`)
          )
        )
      : el("p", { class: "muted" }, "No TPU pods running.")
  );
}

async function refresh() {
  const info = await api("api/workgroup/env-info");
  document.getElementById("user-slot").textContent = info.user;
  const exists = await api("api/workgroup/exists");
  document.getElementById("register-card").style.display =
    exists.hasWorkgroup || !exists.registrationFlowAllowed ? "none" : "block";
  renderTable(
    document.getElementById("ns-table"),
    [
      {
        title: "Namespace",
        render: (n) =>
          el("a", { href: "#", onclick: (ev) => {
            ev.preventDefault();
            loadTpuUsage(n.namespace).catch(showError);
          } }, n.namespace),
      },
      { title: "Role", render: (n) => n.role },
    ],
    info.namespaces
  );
  if (info.namespaces.length) {
    loadTpuUsage(info.namespaces[0].namespace).catch(() => {});
  }
}

document.getElementById("register-btn").addEventListener("click", () => {
  api("api/workgroup/create", { method: "POST", body: "{}" }).then(
    refresh,
    showError
  );
});

loadLinks().catch(showError);
poll(refresh, 10000);
