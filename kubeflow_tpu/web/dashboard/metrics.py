"""Pluggable time-series metrics drivers for the dashboard.

Reference: ``components/centraldashboard/app/metrics_service.ts:1-53``
(driver interface + Interval/TimeSeriesPoint contract),
``prometheus_metrics_service.ts:1-90`` (PromQL range queries),
``metrics_service_factory.ts`` (env-driven driver selection). The
Stackdriver driver of the reference is GCP-console-specific; its slot here
is the charts-link passthrough.

TPU-first addition: a ``tpu_duty_cycle`` series (the GKE TPU device plugin
exports per-chip duty cycle; `avg by (node)` of it is the fleet-health
panel the reference's CPU charts play for GPUs — idle chips show up
immediately).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Protocol

INTERVALS_MIN = {
    "Last5m": 5,
    "Last15m": 15,
    "Last30m": 30,
    "Last60m": 60,
    "Last180m": 180,
}

# PromQL per series type — node/pod CPU + pod memory mirror the reference's
# queries; tpu_duty is ours.
QUERIES = {
    "node_cpu": "sum(rate(node_cpu_seconds_total[5m])) by (instance)",
    "pod_cpu": "sum(rate(container_cpu_usage_seconds_total[5m]))",
    "pod_mem": "sum(container_memory_usage_bytes)",
    "tpu_duty": "avg(tpu_duty_cycle_percent) by (node)",
}


@dataclass(frozen=True)
class TimeSeriesPoint:
    timestamp: float   # seconds since epoch
    label: str
    value: float

    def to_dict(self) -> dict:
        return {"timestamp": self.timestamp, "label": self.label,
                "value": self.value}


class MetricsService(Protocol):
    async def query(self, series: str, interval: str) -> list[TimeSeriesPoint]:
        """Return the named series over the interval."""
        ...

    def charts_link(self) -> dict:
        """{resourceChartsLink, resourceChartsLinkText} for the UI button."""
        ...

    async def close(self) -> None: ...


class PrometheusMetricsService:
    """Range queries against a Prometheus-compatible HTTP API.

    ``fetch_json`` is injectable for tests; the default drives aiohttp at
    ``<url>/api/v1/query_range``.
    """

    def __init__(
        self,
        url: str,
        *,
        dashboard_url: str | None = None,
        step_seconds: int = 10,
        queries: dict[str, str] | None = None,
        fetch_json=None,
        clock=time.time,
    ):
        self.url = url.rstrip("/")
        self.dashboard_url = dashboard_url
        self.step_seconds = step_seconds
        self.queries = queries or QUERIES
        self._fetch_json = fetch_json or self._http_fetch
        self._clock = clock
        self._session = None

    async def _http_fetch(self, params: dict) -> dict:
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=15)
            )
        async with self._session.get(
            f"{self.url}/api/v1/query_range", params=params
        ) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def query(self, series: str, interval: str) -> list[TimeSeriesPoint]:
        if series not in self.queries:
            raise KeyError(f"unknown series {series!r}")
        minutes = INTERVALS_MIN.get(interval)
        if minutes is None:
            raise KeyError(f"unknown interval {interval!r}")
        end = self._clock()
        payload = await self._fetch_json(
            {
                "query": self.queries[series],
                "start": f"{end - minutes * 60:.3f}",
                "end": f"{end:.3f}",
                "step": str(self.step_seconds),
            }
        )
        return self._parse_matrix(payload)

    @staticmethod
    def _parse_matrix(payload: dict) -> list[TimeSeriesPoint]:
        """Prometheus ``matrix`` result → flat point list (the reference's
        convertToTimeSeriesPoints, label = joined metric labels)."""
        data = (payload or {}).get("data") or {}
        if data.get("resultType") != "matrix":
            return []
        points: list[TimeSeriesPoint] = []
        for series in data.get("result", []):
            labels = series.get("metric") or {}
            label = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            for ts, value in series.get("values", []):
                try:
                    points.append(TimeSeriesPoint(float(ts), label, float(value)))
                except (TypeError, ValueError):
                    continue
        return points

    def charts_link(self) -> dict:
        return {
            "resourceChartsLink": self.dashboard_url,
            "resourceChartsLinkText": "View in dashboard",
        }


class NullMetricsService:
    """No metrics backend configured — the factory default, like the
    reference dashboard without PROMETHEUS_URL."""

    async def query(self, series: str, interval: str) -> list[TimeSeriesPoint]:
        return []

    def charts_link(self) -> dict:
        return {"resourceChartsLink": None, "resourceChartsLinkText": ""}

    async def close(self) -> None:
        return None


def metrics_service_from_env(env: dict) -> MetricsService:
    """Driver selection (reference metrics_service_factory.ts): the
    PROMETHEUS_URL env turns the Prometheus driver on."""
    url = env.get("PROMETHEUS_URL")
    if url:
        return PrometheusMetricsService(
            url, dashboard_url=env.get("METRICS_DASHBOARD")
        )
    return NullMetricsService()
