"""Pluggable time-series metrics drivers for the dashboard.

Reference: ``components/centraldashboard/app/metrics_service.ts:1-53``
(driver interface + Interval/TimeSeriesPoint contract),
``prometheus_metrics_service.ts:1-90`` (PromQL range queries),
``metrics_service_factory.ts`` (env-driven driver selection). The
Stackdriver driver of the reference is GCP-console-specific; its slot here
is the charts-link passthrough.

TPU-first addition: a ``tpu_duty_cycle`` series (the GKE TPU device plugin
exports per-chip duty cycle; `avg by (node)` of it is the fleet-health
panel the reference's CPU charts play for GPUs — idle chips show up
immediately).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Protocol

INTERVALS_MIN = {
    "Last5m": 5,
    "Last15m": 15,
    "Last30m": 30,
    "Last60m": 60,
    "Last180m": 180,
}

# PromQL per series type — node/pod CPU + pod memory mirror the reference's
# queries; tpu_duty is ours.
QUERIES = {
    "node_cpu": "sum(rate(node_cpu_seconds_total[5m])) by (instance)",
    "pod_cpu": "sum(rate(container_cpu_usage_seconds_total[5m]))",
    "pod_mem": "sum(container_memory_usage_bytes)",
    "tpu_duty": "avg(tpu_duty_cycle_percent) by (node)",
}


@dataclass(frozen=True)
class TimeSeriesPoint:
    timestamp: float   # seconds since epoch
    label: str
    value: float

    def to_dict(self) -> dict:
        return {"timestamp": self.timestamp, "label": self.label,
                "value": self.value}


class MetricsService(Protocol):
    async def query(self, series: str, interval: str) -> list[TimeSeriesPoint]:
        """Return the named series over the interval."""
        ...

    def charts_link(self) -> dict:
        """{resourceChartsLink, resourceChartsLinkText} for the UI button."""
        ...

    async def close(self) -> None: ...


class _AiohttpSession:
    """Shared lazy aiohttp session lifecycle for the HTTP drivers."""

    _session = None

    async def _session_get(self):
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=15)
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class PrometheusMetricsService(_AiohttpSession):
    """Range queries against a Prometheus-compatible HTTP API.

    ``fetch_json`` is injectable for tests; the default drives aiohttp at
    ``<url>/api/v1/query_range``.
    """

    def __init__(
        self,
        url: str,
        *,
        dashboard_url: str | None = None,
        step_seconds: int = 10,
        queries: dict[str, str] | None = None,
        fetch_json=None,
        clock=time.time,
    ):
        self.url = url.rstrip("/")
        self.dashboard_url = dashboard_url
        self.step_seconds = step_seconds
        self.queries = queries or QUERIES
        self._fetch_json = fetch_json or self._http_fetch
        self._clock = clock
        self._session = None

    async def _http_fetch(self, params: dict) -> dict:
        session = await self._session_get()
        async with session.get(
            f"{self.url}/api/v1/query_range", params=params
        ) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def query(self, series: str, interval: str) -> list[TimeSeriesPoint]:
        if series not in self.queries:
            raise KeyError(f"unknown series {series!r}")
        minutes = INTERVALS_MIN.get(interval)
        if minutes is None:
            raise KeyError(f"unknown interval {interval!r}")
        end = self._clock()
        payload = await self._fetch_json(
            {
                "query": self.queries[series],
                "start": f"{end - minutes * 60:.3f}",
                "end": f"{end:.3f}",
                "step": str(self.step_seconds),
            }
        )
        return self._parse_matrix(payload)

    @staticmethod
    def _parse_matrix(payload: dict) -> list[TimeSeriesPoint]:
        """Prometheus ``matrix`` result → flat point list (the reference's
        convertToTimeSeriesPoints, label = joined metric labels)."""
        data = (payload or {}).get("data") or {}
        if data.get("resultType") != "matrix":
            return []
        points: list[TimeSeriesPoint] = []
        for series in data.get("result", []):
            labels = series.get("metric") or {}
            label = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            for ts, value in series.get("values", []):
                try:
                    points.append(TimeSeriesPoint(float(ts), label, float(value)))
                except (TypeError, ValueError):
                    continue
        return points

    def charts_link(self) -> dict:
        return {
            "resourceChartsLink": self.dashboard_url,
            "resourceChartsLinkText": "View in dashboard",
        }


class CloudMonitoringMetricsService(_AiohttpSession):
    """Google Cloud Monitoring driver (the reference's Stackdriver service,
    ``stackdriver_metrics_service.ts``) — REST against
    ``monitoring.googleapis.com/v3 timeSeries.list``, auth via the GCE
    metadata server's workload-identity token (cached until ~expiry).

    Metric types mirror the reference's ``kubernetes.io`` choices, plus the
    TPU-first ``tpu.googleapis.com`` duty-cycle series that replaces its
    GPU story. ``fetch_json``/``fetch_token`` are injectable for tests.
    """

    METRIC_TYPES = {
        "node_cpu": "kubernetes.io/node/cpu/allocatable_utilization",
        "pod_cpu": "kubernetes.io/container/cpu/limit_utilization",
        "pod_mem": "kubernetes.io/container/memory/used_bytes",
        "tpu_duty": "tpu.googleapis.com/accelerator/duty_cycle",
    }
    _TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                  "instance/service-accounts/default/token")

    def __init__(self, project: str, *, cluster: str | None = None,
                 fetch_json=None, fetch_token=None, clock=time.time):
        self.project = project
        self.cluster = cluster
        self._fetch_json = fetch_json or self._http_fetch
        self._fetch_token = fetch_token or self._http_token
        self._clock = clock
        self._token: tuple[str, float] | None = None  # (token, expiry)

    async def _http_token(self) -> tuple[str, float]:
        session = await self._session_get()
        async with session.get(
            self._TOKEN_URL, headers={"Metadata-Flavor": "Google"}
        ) as resp:
            resp.raise_for_status()
            body = await resp.json()
        return body["access_token"], self._clock() + body.get("expires_in", 300)

    async def _token_value(self) -> str:
        if self._token is None or self._clock() > self._token[1] - 60:
            self._token = await self._fetch_token()
        return self._token[0]

    async def _http_fetch(self, params: dict) -> dict:
        session = await self._session_get()
        url = (f"https://monitoring.googleapis.com/v3/projects/"
               f"{self.project}/timeSeries")
        headers = {"Authorization": f"Bearer {await self._token_value()}"}
        async with session.get(url, params=params, headers=headers) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def query(self, series: str, interval: str) -> list[TimeSeriesPoint]:
        metric_type = self.METRIC_TYPES.get(series)
        minutes = INTERVALS_MIN.get(interval)
        if metric_type is None or minutes is None:
            raise KeyError(f"unknown series/interval {series!r}/{interval!r}")
        end = self._clock()

        def rfc3339(t: float) -> str:
            return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))

        filt = f'metric.type="{metric_type}"'
        if self.cluster:
            # Without this a multi-cluster project charts every cluster's
            # nodes under colliding labels.
            filt += f' AND resource.label.cluster_name="{self.cluster}"'
        params = {
            "filter": filt,
            "interval.startTime": rfc3339(end - minutes * 60),
            "interval.endTime": rfc3339(end),
            "aggregation.alignmentPeriod": "60s",
            "aggregation.perSeriesAligner": "ALIGN_MEAN",
        }
        points: list[TimeSeriesPoint] = []
        for _page in range(10):  # bounded: 10 pages ≫ any sane dashboard
            payload = await self._fetch_json(params)
            points.extend(self._parse_time_series(payload))
            token = (payload or {}).get("nextPageToken")
            if not token:
                break
            params = {**params, "pageToken": token}
        return points

    @staticmethod
    def _parse_time_series(payload: dict) -> list[TimeSeriesPoint]:
        """timeSeries.list response → flat point list (the reference's
        proto-Timestamp handling, minus the proto)."""
        import calendar

        points: list[TimeSeriesPoint] = []
        for ts in (payload or {}).get("timeSeries", []):
            labels = {**(ts.get("resource") or {}).get("labels", {}),
                      **(ts.get("metric") or {}).get("labels", {})}
            label = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            for p in ts.get("points", []):
                stamp = ((p.get("interval") or {}).get("endTime") or "")
                value = p.get("value") or {}
                raw = value.get("doubleValue", value.get("int64Value"))
                try:
                    when = calendar.timegm(
                        time.strptime(stamp[:19], "%Y-%m-%dT%H:%M:%S")
                    )
                    points.append(TimeSeriesPoint(float(when), label, float(raw)))
                except (TypeError, ValueError):
                    continue
        return points

    def charts_link(self) -> dict:
        return {
            "resourceChartsLink":
                f"https://console.cloud.google.com/monitoring?project={self.project}",
            "resourceChartsLinkText": "View in Cloud Monitoring",
        }


class NullMetricsService:
    """No metrics backend configured — the factory default, like the
    reference dashboard without PROMETHEUS_URL."""

    async def query(self, series: str, interval: str) -> list[TimeSeriesPoint]:
        return []

    def charts_link(self) -> dict:
        return {"resourceChartsLink": None, "resourceChartsLinkText": ""}

    async def close(self) -> None:
        return None


def metrics_service_from_env(env: dict) -> MetricsService:
    """Driver selection (reference metrics_service_factory.ts):
    PROMETHEUS_URL turns the Prometheus driver on;
    CLOUD_MONITORING_PROJECT the Cloud Monitoring (Stackdriver) one.
    Prometheus wins when both are set (it is the in-cluster choice)."""
    url = env.get("PROMETHEUS_URL")
    if url:
        return PrometheusMetricsService(
            url, dashboard_url=env.get("METRICS_DASHBOARD")
        )
    project = env.get("CLOUD_MONITORING_PROJECT")
    if project:
        return CloudMonitoringMetricsService(
            project, cluster=env.get("CLOUD_MONITORING_CLUSTER")
        )
    return NullMetricsService()
