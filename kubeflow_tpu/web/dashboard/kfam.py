"""KFAM client boundary for the dashboard BFF.

Reference: the Express dashboard talks to KFAM over HTTP
(``centraldashboard/app/api_workgroup.ts`` handleContributor /
getContributors, env ``PROFILES_KFAM_SERVICE_HOST``, server.ts:27-37).
Two drivers here: ``HttpKfam`` reproduces that hop for split deployments;
``InProcessKfam`` collapses it when KFAM shares the process (the single
controller-manager shape this framework prefers, SURVEY.md §7c).
"""

from __future__ import annotations

import re

from kubeflow_tpu.runtime.errors import Forbidden, Invalid, NotFound
from kubeflow_tpu.runtime.objects import deep_get, get_meta, name_of

EMAIL_RGX = re.compile(r"^[^@\s]+@[^@\s]+\.[^@\s]+$")


class InProcessKfam:
    """Contributor management straight against the apiserver, with the
    same owner-or-cluster-admin gate KFAM's HTTP handlers apply."""

    def __init__(self, kube, *, cluster_admins: set[str] | None = None,
                 use_istio: bool = False):
        self.kube = kube
        self.cluster_admins = cluster_admins or set()
        self.use_istio = use_istio

    async def _ensure_owner(self, caller: str, namespace: str) -> None:
        if caller in self.cluster_admins:
            return
        profile = await self.kube.get_or_none("Profile", namespace)
        if profile is None:
            raise NotFound(f"no profile for namespace {namespace!r}")
        owner = deep_get(profile, "spec", "owner", default={}) or {}
        if owner.get("name") != caller:
            raise Forbidden(
                f"only the owner of {namespace!r} (or a cluster admin) "
                "may manage contributors"
            )

    async def list_contributors(self, caller: str, namespace: str) -> list[str]:
        # Reference getContributors: bindings filtered to role=contributor.
        from kubeflow_tpu.web.kfam.app import ROLE_MAP

        await self._ensure_owner(caller, namespace)
        users = []
        for rb in await self.kube.list("RoleBinding", namespace):
            annotations = get_meta(rb).get("annotations") or {}
            if annotations.get("role") == ROLE_MAP.get("edit") and \
                    annotations.get("user"):
                users.append(annotations["user"])
        return sorted(set(users))

    async def add_contributor(self, caller: str, namespace: str,
                              email: str) -> None:
        from kubeflow_tpu.web.kfam.app import ROLE_MAP, binding_name

        if not EMAIL_RGX.match(email or ""):
            raise Invalid(f"contributor must be an email, got {email!r}")
        await self._ensure_owner(caller, namespace)
        role = ROLE_MAP["edit"]
        rb = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": {
                "name": binding_name(email, "edit"),
                "namespace": namespace,
                "annotations": {"user": email, "role": role},
            },
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": role,
            },
            "subjects": [
                {"kind": "User", "name": email,
                 "apiGroup": "rbac.authorization.k8s.io"}
            ],
        }
        await self.kube.create("RoleBinding", rb)

    async def remove_contributor(self, caller: str, namespace: str,
                                 email: str) -> None:
        from kubeflow_tpu.web.kfam.app import binding_name

        await self._ensure_owner(caller, namespace)
        await self.kube.delete(
            "RoleBinding", binding_name(email, "edit"), namespace
        )


class HttpKfam:
    """The reference's HTTP hop: every call forwards the caller identity in
    the userid header so KFAM applies its own authz."""

    def __init__(self, base_url: str, *,
                 userid_header: str = "kubeflow-userid"):
        self.base_url = base_url.rstrip("/")
        self.userid_header = userid_header
        self._session = None

    async def _request(self, method: str, path: str, caller: str,
                       json_body=None):
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=15)
            )
        async with self._session.request(
            method,
            self.base_url + path,
            headers={self.userid_header: caller},
            json=json_body,
        ) as resp:
            body = await resp.json()
            if resp.status >= 400 or body.get("success") is False:
                raise Invalid(body.get("log") or f"KFAM HTTP {resp.status}")
            return body

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def list_contributors(self, caller: str, namespace: str) -> list[str]:
        body = await self._request(
            "GET", f"/kfam/v1/bindings?namespace={namespace}&role=edit", caller
        )
        return sorted(
            {b["user"]["name"] for b in body.get("bindings", [])}
        )

    async def add_contributor(self, caller: str, namespace: str,
                              email: str) -> None:
        if not EMAIL_RGX.match(email or ""):
            raise Invalid(f"contributor must be an email, got {email!r}")
        await self._request(
            "POST", "/kfam/v1/bindings", caller,
            {
                "user": {"kind": "User", "name": email},
                "referredNamespace": namespace,
                "roleRef": {"kind": "ClusterRole", "name": "edit"},
            },
        )

    async def remove_contributor(self, caller: str, namespace: str,
                                 email: str) -> None:
        await self._request(
            "DELETE", "/kfam/v1/bindings", caller,
            {
                "user": {"kind": "User", "name": email},
                "referredNamespace": namespace,
                "roleRef": {"kind": "ClusterRole", "name": "edit"},
            },
        )
