"""In-notebook client: slice introspection, distributed bootstrap, and
preemption-aware checkpointing.

Everything a notebook needs to act on the control plane's TPU wiring,
with zero configuration — every input is env the controller/webhook
injected (tpu/topology.py worker_env + webhooks/tpu.py per-ordinal
patch) or in-cluster credentials the pod already has:

    from kubeflow_tpu import sdk

    info = sdk.SliceInfo.from_env()       # who am I in the slice?
    sdk.initialize_distributed()          # jax.distributed from env

    mgr = sdk.CheckpointManager("gs://bucket/run7",
                                save_interval_steps=100)
    guard = sdk.CheckpointGuard(mgr)
    for step in range(start, n_steps):
        params, loss = train_step(params, batch)
        guard.step(step, params)          # scheduled saves (the manager's
                                          # cadence) + an immediate save
                                          # when the controller flags
                                          # impending node maintenance

The maintenance signal is the ``notebooks.kubeflow.org/maintenance-pending``
annotation the notebook controller mirrors from GKE's
impending-node-termination taints (controllers/notebook.py
_check_maintenance) — the notebook reads its *own* CR through the
in-cluster apiserver, a GET the profile controller's RBAC already allows
(default-editor can read notebooks in its namespace).

The reference has no counterpart: its notebooks are single pods whose
death loses nothing but kernel state (SURVEY.md §5 checkpoint/resume is
PVC persistence alone). A TPU slice loses a training run.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import time
import urllib.request
from dataclasses import dataclass

from kubeflow_tpu.api import keys
from kubeflow_tpu.api.notebook import (
    DRAIN_REQUESTED_ANNOTATION,
    MAINTENANCE_ANNOTATION,
    SUSPEND_ANNOTATION,
)
from kubeflow_tpu.migration import protocol as _migration
from kubeflow_tpu.utils.checkpoint import CheckpointManager

__all__ = [
    "CheckpointGuard",
    "CheckpointManager",
    "MaintenanceWatcher",
    "SliceInfo",
    "capture_profile",
    "initialize_distributed",
    "resume",
    "start_profiler_server",
    "suspend",
    "telemetry_publisher",
    "trace",
    "warm_idle",
]

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
_log = logging.getLogger(__name__)


@dataclass(frozen=True)
class SliceInfo:
    """This worker's place in the slice/multislice, parsed from the env
    contract in tpu/topology.py worker_env / MultiSlice.worker_env."""

    worker_id: int
    num_workers: int
    hostnames: tuple[str, ...]
    process_id: int
    num_processes: int
    coordinator_address: str | None
    slice_id: int
    num_slices: int
    accelerator_type: str | None
    topology: str | None
    namespace: str | None
    notebook: str | None

    @classmethod
    def from_env(cls, environ=os.environ) -> "SliceInfo":
        hostnames = tuple(
            h for h in (environ.get("TPU_WORKER_HOSTNAMES") or "").split(",")
            if h
        )
        ns = name = None
        prefix = environ.get("NB_PREFIX") or ""
        parts = prefix.strip("/").split("/")
        if len(parts) == 3 and parts[0] == "notebook":
            ns, name = parts[1], parts[2]
        worker_id = int(environ.get("TPU_WORKER_ID") or 0)
        return cls(
            worker_id=worker_id,
            num_workers=max(len(hostnames), 1),
            hostnames=hostnames,
            process_id=int(environ.get("JAX_PROCESS_ID") or worker_id),
            num_processes=int(
                environ.get("JAX_NUM_PROCESSES") or max(len(hostnames), 1)),
            coordinator_address=environ.get("JAX_COORDINATOR_ADDRESS"),
            slice_id=int(environ.get("MEGASCALE_SLICE_ID") or 0),
            num_slices=int(environ.get("MEGASCALE_NUM_SLICES") or 1),
            accelerator_type=environ.get("TPU_ACCELERATOR_TYPE"),
            topology=environ.get("TPU_TOPOLOGY"),
            namespace=ns,
            notebook=name,
        )

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def initialize_distributed(environ=os.environ) -> bool:
    """``jax.distributed.initialize`` from the injected env. Returns True
    when a multi-process world was initialized, False for the single-host
    no-op (so the same notebook code runs on a v5e-4 and a v5p-128).
    Idempotent: a second call is a no-op."""
    info = SliceInfo.from_env(environ)
    if info.num_processes <= 1 or not info.coordinator_address:
        return False
    import jax

    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return True  # already initialized
    jax.distributed.initialize(
        coordinator_address=info.coordinator_address,
        num_processes=info.num_processes,
        process_id=info.process_id,
    )
    return True


PROFILER_PORT = 9999
_profiler_port: int | None = None


def start_profiler_server(port: int = PROFILER_PORT) -> None:
    """``jax.profiler.start_server`` on the conventional port — the
    target of TensorBoard's profile-plugin "capture" button (SURVEY §5:
    the ``jax.profiler.start_server`` convention in images). Point a
    ``Tensorboard`` CR with ``spec.profilerPlugin: true`` at the
    notebook's DNS name to capture live. Idempotent: re-running the
    setup cell is a no-op (jax allows one server per process)."""
    global _profiler_port
    if _profiler_port is not None:
        if _profiler_port >= 0 and port != _profiler_port:
            # jax allows one server per process; a move is impossible —
            # say so instead of silently ignoring the new port.
            _log.warning(
                "profiler server already on port %d; cannot move to %d "
                "(one server per process)", _profiler_port, port)
        return
    import jax

    try:
        jax.profiler.start_server(port)
        _profiler_port = port
    except ValueError:
        # A server already runs in this process (started outside the
        # sdk) — on an unknown port, so record the sentinel rather than
        # a port we can't confirm (a later mismatch warning would state
        # the inverse of reality).
        _log.warning("profiler server already running; reusing it")
        _profiler_port = -1


def trace(logdir: str):
    """Context manager writing an XLA/TPU trace under ``logdir`` —
    readable by a ``Tensorboard`` CR with ``spec.profilerPlugin: true``
    over the same PVC/GCS path (controllers/tensorboard.py)::

        with sdk.trace("/home/jovyan/logs"):
            params, loss = train_step(params, batch)
            loss.block_until_ready()
    """
    import jax

    return jax.profiler.trace(logdir)


# Where capture_profile() writes when the caller doesn't say: the same
# path the notebook images mount for TensorBoard logs, so a Tensorboard
# CR with spec.profilerPlugin: true over the shared PVC/GCS prefix picks
# the trace up with no extra wiring (controllers/tensorboard.py).
TELEMETRY_LOGDIR_ENV = "KFTPU_TELEMETRY_LOGDIR"
DEFAULT_TRACE_LOGDIR = "/home/jovyan/logs"


def capture_profile(logdir: str | None = None, *, environ=os.environ):
    """Context manager dumping a ``jax.profiler`` trace where the
    Tensorboard CR can serve it — :func:`trace` with the logdir resolved
    from ``KFTPU_TELEMETRY_LOGDIR`` (controller-injectable) and falling
    back to the images' TensorBoard log mount::

        with sdk.capture_profile():
            params, loss = train_step(params, batch)
            loss.block_until_ready()

    Point a ``Tensorboard`` CR with ``spec.profilerPlugin: true`` at the
    same PVC/GCS path to browse the trace (docs/operations.md "Training
    telemetry & profiler traces")."""
    if logdir is None:
        logdir = environ.get(TELEMETRY_LOGDIR_ENV) or DEFAULT_TRACE_LOGDIR
    return trace(logdir)


def telemetry_publisher(*, environ=os.environ, patcher=None, registry=None):
    """Build a :class:`kubeflow_tpu.telemetry.TelemetryPublisher` writing
    to this notebook's own CR (the write half mirrors the drain-ack
    transport: stdlib-only, ServiceAccount-credentialed). Pass the result
    as ``trainer.fit(..., publisher=...)`` next to a ``StepProfiler``.
    Raises ValueError outside the controller's env unless ``patcher`` is
    given (tests inject a recorder taking the full merge-patch body)."""
    from kubeflow_tpu.telemetry import TelemetryPublisher

    if patcher is None:
        annotations_patcher = _identity_patcher(environ)

        def patcher(body: dict) -> None:
            annotations_patcher(
                (body.get("metadata") or {}).get("annotations") or {})

    return TelemetryPublisher(patcher, registry=registry, environ=environ)


def _in_cluster_fetch(namespace: str, name: str):
    """Build a () -> annotations fetcher reading this notebook's CR via the
    in-cluster apiserver with the pod's ServiceAccount (stdlib-only — a
    notebook image need not carry an HTTP client library)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"  # bare IPv6 apiserver address (IPv6-only clusters)
    url = (f"https://{host}:{port}{keys.NOTEBOOKS_API_PATH_PREFIX}"
           f"{namespace}/notebooks/{name}")
    ctx = ssl.create_default_context(cafile=os.path.join(_SA_DIR, "ca.crt"))

    def fetch() -> dict:
        with open(os.path.join(_SA_DIR, "token")) as f:
            token = f.read().strip()
        req = urllib.request.Request(
            url, headers={"Authorization": f"Bearer {token}"})
        with urllib.request.urlopen(req, context=ctx, timeout=10) as resp:
            obj = json.loads(resp.read())
        return (obj.get("metadata") or {}).get("annotations") or {}

    return fetch


def _in_cluster_url(namespace: str, name: str) -> str:
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"
    return (f"https://{host}:{port}{keys.NOTEBOOKS_API_PATH_PREFIX}"
            f"{namespace}/notebooks/{name}")


def _in_cluster_patcher(namespace: str, name: str):
    """Build an annotations-merge-patcher for this notebook's own CR —
    the write half of the drain protocol (checkpoint ack, suspend). Same
    stdlib-only, ServiceAccount-credentialed transport as the fetch."""
    url = _in_cluster_url(namespace, name)
    ctx = ssl.create_default_context(cafile=os.path.join(_SA_DIR, "ca.crt"))

    def patch_annotations(annotations: dict) -> None:
        with open(os.path.join(_SA_DIR, "token")) as f:
            token = f.read().strip()
        body = json.dumps(
            {"metadata": {"annotations": annotations}}).encode()
        req = urllib.request.Request(
            url, data=body, method="PATCH",
            headers={
                "Authorization": f"Bearer {token}",
                "Content-Type": "application/merge-patch+json",
            })
        with urllib.request.urlopen(req, context=ctx, timeout=10) as resp:
            resp.read()

    return patch_annotations


def _identity_patcher(environ=os.environ):
    info = SliceInfo.from_env(environ)
    if not (info.namespace and info.notebook):
        raise ValueError(
            "not running under the controller (no NB_PREFIX); "
            "pass patcher= explicitly")
    return _in_cluster_patcher(info.namespace, info.notebook)


def suspend(*, environ=os.environ, patcher=None) -> None:
    """Ask the control plane to checkpoint-and-park this notebook: stamps
    the suspend annotation; the notebook controller requests a drain, the
    training loop's CheckpointGuard acks it, and the server parks with
    "Suspended (checkpoint @ step N)". Resume with :func:`resume` (before
    the park completes), ``kubectl annotate notebook <name>
    notebooks.kubeflow.org/suspend-``, or the UI's start button."""
    import datetime

    patcher = patcher or _identity_patcher(environ)
    patcher({SUSPEND_ANNOTATION: datetime.datetime.now(
        datetime.timezone.utc).isoformat()})


def resume(*, environ=os.environ, patcher=None) -> None:
    """Clear the suspend annotation: cancels a drain still in flight; a
    notebook already parked un-parks on the controller's next reconcile
    and restores from its checkpoint hint."""
    patcher = patcher or _identity_patcher(environ)
    patcher({SUSPEND_ANNOTATION: None})


# ---- warm pod pools (ISSUE 14, controllers/warmpool.py) ------------------------

# Env contract of the warm-idle shim (docs/operations.md "Warm pools &
# cold-start"). WARM_IDLE_ENV is also stamped by the pool controller's
# slot template (controllers/warmpool.py keeps a matching constant).
WARM_IDLE_ENV = "KFTPU_WARM_IDLE"
WARM_CLAIM_FILE_ENV = "KFTPU_WARM_CLAIM_FILE"
# Downward-API volume path the pool pod template mounts: pod annotations
# as `key="value"` lines, updated live — how the claim annotation reaches
# the shim without any apiserver credential.
DEFAULT_CLAIM_FILE = "/etc/podinfo/annotations"


def _read_downward_claim(path: str) -> str | None:
    """Parse the downward-API annotations file for the warm-claim
    annotation (``key="escaped value"`` lines)."""
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    for line in text.splitlines():
        k, sep, v = line.partition("=")
        if not sep or k.strip() != keys.TPU_WARM_CLAIM:
            continue
        v = v.strip()
        if len(v) >= 2 and v.startswith('"') and v.endswith('"'):
            v = v[1:-1].encode().decode("unicode_escape")
        return v or None
    return None


def warm_idle(*, environ=os.environ, poll_seconds: float = 1.0,
              fetch_claim=None, init_devices: bool = True,
              max_wait: float | None = None, _sleep=time.sleep) -> str | None:
    """Hold a warm-pool pod fully started until it is claimed.

    This is what makes a warm pod actually WARM: the persistent compile
    cache is enabled and seeded from the image's fingerprint manifest
    (``utils/compilecache.seed_cache``), ``jax`` is imported and the
    device client attached — so a claimed pod has already paid the
    interpreter, import, backend-attach, and (seeded) compile phases of
    the cold-start waterfall. Then it parks, polling the downward-API
    annotations file for the claim annotation the claim protocol stamps
    (:data:`kubeflow_tpu.api.keys.TPU_WARM_CLAIM`). Returns the claim
    value (``"<ns>/<name>/<nonce>"``) — the shim then execs the real
    notebook server with the injected env — or None when ``max_wait``
    expires (tests; production pods wait forever)."""
    from kubeflow_tpu.utils import compilecache

    cache_dir = compilecache.enable_persistent_cache()
    seeded = compilecache.seed_cache(cache_dir=cache_dir)
    _log.info(
        "warm idle: compile cache %s ready=%s (seeded %d, skipped %d)",
        cache_dir, seeded["ready"], seeded["seeded"], seeded["skipped"])
    if init_devices:
        try:
            import jax

            jax.devices()  # force the backend/device-client attach
        except Exception:  # noqa: BLE001 — a warm pod without devices is
            # still warm for interpreter+imports; claiming it beats cold.
            _log.warning("warm idle: jax device init failed; staying warm "
                         "for interpreter/imports only", exc_info=True)
    if fetch_claim is None:
        path = environ.get(WARM_CLAIM_FILE_ENV) or DEFAULT_CLAIM_FILE

        def fetch_claim(path=path):
            return _read_downward_claim(path)

    t0 = time.monotonic()
    while True:
        try:
            claim = fetch_claim()
        except Exception:  # noqa: BLE001 — a flaky read must not kill the
            # warm pod; the next poll retries.
            _log.debug("warm-idle claim poll failed", exc_info=True)
            claim = None
        if claim:
            return claim
        if max_wait is not None and time.monotonic() - t0 >= max_wait:
            return None
        _sleep(poll_seconds)


class MaintenanceWatcher:
    """Polls this notebook's CR for the controller's maintenance-pending
    annotation. ``check()`` for in-loop use (CheckpointGuard), or
    ``start(callback)`` for a daemon thread that fires once per
    pending-transition with the affected node list."""

    def __init__(self, fetch=None, *, interval: float = 30.0,
                 environ=os.environ):
        if fetch is None:
            info = SliceInfo.from_env(environ)
            if not (info.namespace and info.notebook):
                raise ValueError(
                    "not running under the controller (no NB_PREFIX); "
                    "pass fetch= explicitly")
            fetch = _in_cluster_fetch(info.namespace, info.notebook)
        self._fetch = fetch
        self.interval = interval
        self._last: str | None = None
        self._last_at = 0.0
        # Full annotation snapshot from the last successful fetch: the
        # drain protocol (CheckpointGuard) reads more than the
        # maintenance key from the same rate-limited poll.
        self._ann: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def check(self, *, max_age: float | None = None) -> str | None:
        """Current pending-node list ("" semantics: None = clear). Rate
        limited to one apiserver GET per ``interval`` (or ``max_age``);
        between polls the cached answer is returned — cheap enough for a
        per-training-step call."""
        age_limit = self.interval if max_age is None else max_age
        now = time.monotonic()
        if now - self._last_at >= age_limit:
            self._last_at = now
            try:
                self._ann = self._fetch() or {}
                self._last = self._ann.get(MAINTENANCE_ANNOTATION) or None
            except Exception:  # noqa: BLE001 — a flaky apiserver read must
                # not take down the training loop; serve the cached view.
                _log.debug("maintenance poll failed; keeping cached "
                           "annotations", exc_info=True)
        return self._last

    def annotations(self, *, max_age: float | None = None) -> dict:
        """The CR's annotations from the same rate-limited cache as
        ``check()`` — the drain/suspend protocol reads its request marks
        here. Last-known-good on fetch errors, like ``check()``."""
        self.check(max_age=max_age)
        return self._ann

    def _poll(self, stop: threading.Event) -> str | None:
        """The poller thread's fetch. Commits to the shared check() cache
        only while this generation is live — a stopped generation's
        wedged fetch returning late must not poison ``_last`` for direct
        check() callers (CheckpointGuard) or a successor poller."""
        try:
            ann = self._fetch() or {}
            val = ann.get(MAINTENANCE_ANNOTATION) or None
        except Exception:  # noqa: BLE001 — same policy as check()
            return self._last
        if not stop.is_set():
            self._ann = ann
            self._last = val
            self._last_at = time.monotonic()
        return val

    def start(self, callback) -> None:
        """callback(nodes: str) fires once each time maintenance becomes
        pending (not per poll). A callback exception is logged, not
        fatal — the watcher keeps watching (same policy as check()'s
        fetch errors). start() after stop() resumes watching; start()
        while already watching is a no-op (re-running a notebook cell
        must not stack a second poller)."""
        if self._thread is not None and self._thread.is_alive():
            return
        # Each generation gets ITS OWN event, bound into the closure: a
        # stop() whose join times out (fetch wedged) followed by start()
        # replaces self._stop — the old thread must keep seeing the set
        # event, or it would un-suppress and fire its stale callback
        # alongside the new poller.
        stop = self._stop = threading.Event()  # restartable after stop()

        def loop():
            armed = True
            # Poll before the first wait: a window already pending when the
            # watcher starts must fire now, not up to `interval` later —
            # that delay is exactly the time before a node termination.
            while True:
                if stop.is_set():
                    return  # stop() raced the first poll: no late fetch
                            # or callback on torn-down state
                pending = self._poll(stop)
                if stop.is_set():
                    return  # stop() landed mid-fetch: no late callback
                if pending and armed:
                    armed = False
                    try:
                        callback(pending)
                    except Exception:  # noqa: BLE001
                        _log.exception(
                            "maintenance callback failed; still watching")
                elif not pending:
                    armed = True
                if stop.wait(self.interval):
                    return

        self._thread = threading.Thread(
            target=loop, name="kftpu-maintenance-watch", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# Coordinated-signal bits (one broadcast carries both verdicts).
_MAINTENANCE_BIT = 1
_DRAIN_BIT = 2


class CheckpointGuard:
    """Checkpoint on the manager's schedule — and immediately when the
    control plane says the slice is about to lose a node, or asks the
    gang to drain (preemption, idle cull, user suspend).

    Wraps utils/checkpoint.CheckpointManager: ``step()`` defers scheduled
    saves to the manager (its ``save_interval_steps`` is the one cadence
    knob), and forces an out-of-schedule save (then blocks until it
    commits) the first time the maintenance annotation appears. One
    forced save per pending-transition — a long maintenance window
    doesn't re-save every step.

    **Drain protocol** (kubeflow_tpu/migration): when the drain-requested
    annotation appears, the guard saves immediately and **acks** by
    patching the checkpointed-at / path / step annotations onto its own
    CR — the control plane then parks the gang and, on re-admission,
    stamps the same path/step back into the pod env as the restore hint.
    With a :class:`kubeflow_tpu.checkpoint.CheckpointFabric` manager the
    save is snapshot-then-ack: the ack goes out as soon as device arrays
    are copied to host, the background uploader finishes during graceful
    termination, and the durable-commit mark
    (``checkpoint-committed-at``) lands when the manifest does — call
    :meth:`close` (or use the guard as a context manager) so teardown
    blocks on the commit. With a plain CheckpointManager the legacy
    synchronous save-wait-ack runs and the ack carries the commit mark.
    After the ack the loop may keep stepping; the park arrives as a
    normal scale-to-zero. ``drained`` reports that an ack was committed
    this session.

    **Multi-host:** an Orbax save is a collective — every process must
    save the *same* step. Per-worker watchers poll on their own clocks,
    so the pending/drain decision is made by process 0 alone and
    broadcast to the others (``broadcast_one_to_all``) every
    ``sync_every_steps`` steps. Call ``step()`` from every process with
    the same step number (the normal SPMD loop); the collective only
    runs on sync steps, so its cost amortizes. Single-process worlds —
    and workers whose coordination client is not (yet) initialized, e.g.
    joining mid-run — skip the collective and degrade to local-only
    checks instead of raising into the training loop."""

    def __init__(self, manager: CheckpointManager,
                 watcher: MaintenanceWatcher | None = None, *,
                 sync_every_steps: int = 16, environ=os.environ,
                 patcher=None):
        self.manager = manager
        self.watcher = watcher or MaintenanceWatcher(environ=environ)
        self.sync_every_steps = max(1, sync_every_steps)
        self._armed = True
        self._drain_armed = True
        self._environ = environ
        self._patcher = patcher
        self._ack_pending_step: int | None = None
        # Durable-commit patch that failed (flaky apiserver) — retried on
        # sync steps and flushed by close(), like the ack retry. Holds
        # (for_request,) so an echo-less retry is still distinguishable
        # from "nothing pending".
        self._commit_pending: tuple[str | None] | None = None
        self._restore_tier_stamped = False
        self._progress_last = 0.0
        self._warned_local_only = False
        self.drained = False

    @property
    def _fabric(self) -> bool:
        """Snapshot-then-ack is available when the manager speaks the
        checkpoint fabric's async surface."""
        return hasattr(self.manager, "save_async")

    def _local_signals(self) -> int:
        ann = self.watcher.annotations()
        bits = 0
        if ann.get(MAINTENANCE_ANNOTATION):
            bits |= _MAINTENANCE_BIT
        if (ann.get(DRAIN_REQUESTED_ANNOTATION)
                and not _migration.drain_acked(ann)):
            bits |= _DRAIN_BIT
        return bits

    def _signals_coordinated(self) -> int:
        """Process 0's watcher verdict (maintenance + drain bits), agreed
        on by every process — degrading to this process's own local check
        when the distributed client is unavailable (single-process world,
        or a worker that joined before ``jax.distributed`` came up): a
        missing coordination service must never raise into the training
        loop, and local-only checks still converge because every worker
        polls the same CR."""
        try:
            import jax

            count = jax.process_count()
            index = jax.process_index() if count > 1 else 0
        except Exception:  # noqa: BLE001 — uninitialized backend/client
            count, index = 1, 0
        if count == 1:
            return self._local_signals()
        local = self._local_signals() if index == 0 else 0
        try:
            import numpy as np
            from jax.experimental import multihost_utils

            return int(multihost_utils.broadcast_one_to_all(np.int32(local)))
        except Exception:  # noqa: BLE001 — coordination client not ready
            if not self._warned_local_only:
                self._warned_local_only = True
                _log.warning(
                    "multi-host coordination unavailable; degrading to "
                    "local-only maintenance/drain checks")
            return self._local_signals()

    def _pending_coordinated(self) -> bool:
        """Back-compat shim: the maintenance bit of the coordinated
        signals."""
        return bool(self._signals_coordinated() & _MAINTENANCE_BIT)

    def _is_writer(self) -> bool:
        """Annotation patches are process-0 only — one writer."""
        try:
            import jax

            return jax.process_count() <= 1 or jax.process_index() == 0
        except Exception:  # uninitialized jax backend ⇒ single-process
            return True

    def _try_ack(self, step: int, *, committed: bool = False) -> None:
        """Patch the checkpoint ack onto this notebook's CR (process 0
        only — one writer). Failure re-arms the pending ack; the next
        sync step retries without re-saving. ``committed=True`` (the
        synchronous legacy path, where the save is already durable when
        the ack goes out) folds the commit mark into the same patch; the
        fabric path stamps it separately from the uploader's commit
        callback."""
        if not self._is_writer():
            self._ack_pending_step = None
            return
        if self._patcher is None:
            try:
                self._patcher = _identity_patcher(self._environ)
            except ValueError:
                _log.warning("cannot ack drain: no notebook identity and "
                             "no patcher provided")
                self._ack_pending_step = None
                return
        directory = getattr(self.manager, "directory", "") or ""
        # Echo the request being answered: ack detection compares the
        # echo, not timestamps from two different clocks (pod vs
        # controller — skew must not make acks invisible).
        for_request = self.watcher.annotations().get(
            DRAIN_REQUESTED_ANNOTATION)
        now = time.time()
        patch = _migration.ack_patch(
            directory, step, now, for_request=for_request)
        if committed:
            patch.update(_migration.commit_patch(
                now, for_request=for_request))
        try:
            self._patcher(patch)
            self._ack_pending_step = None
        except Exception:  # noqa: BLE001 — flaky apiserver; retry later
            _log.warning("drain ack patch failed; retrying next sync step")
            self._ack_pending_step = step

    def _try_commit_mark(self, for_request: str | None) -> None:
        """Stamp the durable-commit mark (fabric uploader callback, or a
        sync-step / close() retry after a failed stamp)."""
        if not self._is_writer() or self._patcher is None:
            self._commit_pending = None
            return
        try:
            self._patcher(_migration.commit_patch(
                time.time(), for_request=for_request))
            self._commit_pending = None
        except Exception:  # noqa: BLE001 — flaky apiserver; retry later
            _log.warning("checkpoint commit mark failed; retrying")
            self._commit_pending = (for_request,)

    def _mark_progress(self, done: int, total: int) -> None:
        """Best-effort, rate-limited "k/N chunks" progress mark (JWA's
        parked-uncommitted message). Runs on the uploader thread."""
        if not self._is_writer() or self._patcher is None:
            return
        now = time.monotonic()
        if done < total and now - self._progress_last < 0.5:
            return
        self._progress_last = now
        try:
            self._patcher(_migration.progress_patch(done, total))
        except Exception:  # noqa: BLE001 — purely a UI progress mark
            _log.debug("upload progress mark failed (best-effort)",
                       exc_info=True)

    def _mark_checkpointing(self) -> None:
        """Best-effort progress mark so the UI can say "Checkpointing…"
        while a large snapshot streams out."""
        if self._patcher is None:
            try:
                self._patcher = _identity_patcher(self._environ)
            except ValueError:
                return
        import datetime

        try:
            self._patcher({
                keys.NOTEBOOK_CHECKPOINTING_AT:
                    datetime.datetime.now(
                        datetime.timezone.utc).isoformat()})
        except Exception:  # noqa: BLE001 — purely a UI progress mark
            _log.debug("checkpointing-at progress mark failed "
                       "(best-effort)", exc_info=True)

    def _drain_save(self, step: int, pytree) -> bool:
        """One drain checkpoint. With the fabric: snapshot-then-ack —
        ``save_async`` returns once device arrays are copied to host, the
        ack goes out immediately, and the uploader's commit callback
        stamps the durable-commit mark when the manifest lands (the
        scheduler's commit wait watches for it). Without the fabric:
        the legacy synchronous save-wait-ack, with the commit mark folded
        into the ack (the save IS durable by then)."""
        if not self._fabric:
            saved = self.manager.save(step, pytree, force=True)
            self.manager.wait()  # the ack promises a COMMITTED save
            self._try_ack(step, committed=True)
            return saved
        # Echo captured NOW: the commit must answer the drain that
        # triggered this save even if a new drain lands mid-upload.
        for_request = self.watcher.annotations().get(
            DRAIN_REQUESTED_ANNOTATION)
        self.manager.save_async(
            step, pytree,
            on_progress=self._mark_progress,
            on_commit=lambda _step, _secs:
                self._try_commit_mark(for_request))
        self._try_ack(step)  # snapshot done — ack before the upload
        return True

    def _mark_restore_tier(self) -> None:
        """Best-effort, once: record which tier served the fabric's
        restore ("staging" / "remote") so JWA can say "Restoring from
        local staging tier" vs "…from object storage"."""
        self._restore_tier_stamped = True
        last = getattr(self.manager, "last_restore", None)
        if not last or not last.get("tier"):
            return
        if not self._is_writer() or self._patcher is None:
            return
        try:
            self._patcher(_migration.restore_tier_patch(last["tier"]))
        except Exception:  # noqa: BLE001 — purely a UI mark
            _log.debug("restore tier mark failed (best-effort)",
                       exc_info=True)

    def step(self, step: int, pytree) -> bool:
        if step % self.sync_every_steps == 0:
            if not self._restore_tier_stamped:
                self._mark_restore_tier()
            if self._ack_pending_step is not None:
                self._try_ack(self._ack_pending_step)
            if self._commit_pending is not None:
                self._try_commit_mark(self._commit_pending[0])
            signals = self._signals_coordinated()
            if signals & _DRAIN_BIT:
                if self._drain_armed:
                    self._drain_armed = False
                    self._mark_checkpointing()
                    saved = self._drain_save(step, pytree)
                    self.drained = True
                    return saved
            else:
                self._drain_armed = True
            if signals & _MAINTENANCE_BIT:
                if self._armed:
                    self._armed = False
                    saved = self.manager.save(step, pytree, force=True)
                    self.manager.wait()  # commit before the node goes away
                    return saved
            else:
                self._armed = True
        return self.manager.save(step, pytree)

    def close(self) -> None:
        """Teardown: block until any in-flight async save durably
        commits (the fabric's close() waits on its uploader, leaving no
        orphaned temp files), then flush a commit mark whose patch
        failed. Safe to call twice; the graceful-termination path after
        a park runs this so the upload outlives the ack."""
        close = getattr(self.manager, "close", None)
        if callable(close):
            close()
        if self._commit_pending is not None:
            self._try_commit_mark(self._commit_pending[0])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _main() -> None:
    """``python -m kubeflow_tpu.sdk`` — print this worker's slice identity
    as one JSON line (the in-pod debugging companion to
    ``python -m kubeflow_tpu.probe``). ``--warm-idle`` runs the warm-pool
    hold loop instead (the pool controller's slot pod command)."""
    import dataclasses
    import json
    import sys

    if "--warm-idle" in sys.argv[1:]:
        claim = warm_idle()
        print(json.dumps({"claimed": claim}))
        return
    print(json.dumps(dataclasses.asdict(SliceInfo.from_env())))


if __name__ == "__main__":
    _main()
