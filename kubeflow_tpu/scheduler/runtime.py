"""Async runtime of the TPU fleet scheduler.

The single admission point between a Notebook CR and its slice
StatefulSets: the notebook controller's capacity stage calls
:meth:`TpuFleetScheduler.admission` before creating any slice, and
:meth:`TpuFleetScheduler.release` on stop/delete. The pure policy core
(:mod:`kubeflow_tpu.scheduler.policy`) makes every decision; this layer
adds what the cluster needs around it:

- fleet discovery (env spec, ConfigMap, or Node-label inference);
- preemption actuation — victims are stop-annotated (the notebook
  reconciler parks the whole gang, never a slice subset) and the
  preemption is recorded so their status can say why;
- transition side effects: ``Queued``/``Admitted``/``Preempted`` Events,
  the admitted-at annotation culling's idle clock needs, and re-enqueues
  so a freshly admitted notebook reconciles immediately;
- observability: ``schedule``/``admit``/``preempt`` tracing phases,
  Prometheus gauges/counters/histogram, and the ``/debug/scheduler``
  payload.

With no fleet configured the scheduler is a transparent no-op (every
admission passes through, zero API writes) — exactly today's behavior,
which is also what the ``KFTPU_SCHEDULER=off`` kill switch restores.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime.errors import ApiError
from kubeflow_tpu.runtime.events import EventRecorder
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import (
    annotations_of,
    fmt_iso,
    name_of,
    namespace_of,
    parse_iso,
)
from kubeflow_tpu.runtime.tracing import span
from kubeflow_tpu.migration import protocol as migration
from kubeflow_tpu.scheduler.fleet import Fleet
from kubeflow_tpu.scheduler.policy import (
    GangRequest,
    PolicyConfig,
    PolicyQueue,
)

log = logging.getLogger(__name__)

# Priority classes from a CR annotation; plain integers are accepted too.
PRIORITY_ANNOTATION = nbapi.PRIORITY_ANNOTATION
PRIORITY_CLASSES = {"low": -100, "normal": 0, "high": 100, "critical": 200}

FLEET_CONFIGMAP_KEY = "fleet"
_CONFIGMAP_RETRY_SECONDS = 30.0


async def load_fleet_from_configmap(kube, name: str,
                                    namespace: str) -> Fleet | None:
    """The ONE reader of the fleet ConfigMap — shared by the scheduler's
    ``_ensure_fleet`` and the webhook's can-never-fit ceiling
    (webhooks/notebook.py), so the spec key and the bad-spec tolerance
    cannot drift apart between the two admission layers. Returns None
    when the ConfigMap/key is absent or the spec is malformed (a broken
    spec must not block admissions or wedge the scheduler); callers own
    their caching/retry policy."""
    cm = await kube.get_or_none("ConfigMap", name, namespace)
    spec = ((cm or {}).get("data") or {}).get(FLEET_CONFIGMAP_KEY) or ""
    if not spec.strip():
        return None
    try:
        return Fleet.parse(spec)
    except Exception:
        log.exception("bad fleet spec in ConfigMap %s/%s", namespace, name)
        return None


def parse_priority(value: str | None) -> int:
    if not value:
        return 0
    v = value.strip().lower()
    if v in PRIORITY_CLASSES:
        return PRIORITY_CLASSES[v]
    try:
        return int(v)
    except ValueError:
        return 0


@dataclass(frozen=True)
class Admission:
    """What the capacity stage gets back."""

    state: str                 # "Admitted" | "Queued" | "Preempted" | "Draining"
    position: int = 0
    reason: str = ""
    waiting_chips: int = 0
    # Draining only: how soon the controller must reconcile again so the
    # grace deadline fires even if the SDK never acks.
    requeue_after: float = 0.0

    @property
    def admitted(self) -> bool:
        return self.state == "Admitted"


@dataclass
class _Drain:
    """In-memory side of one in-flight drain (the durable side lives in
    the victim's annotations — migration/protocol.py)."""

    reason: str                # "idle" | "priority"
    for_key: tuple             # beneficiary waiting on the chips
    chips: int
    requested_at: float
    deadline: float


@dataclass
class SchedulerOptions:
    """Env contract (cmd/envconfig.py scheduler_options)."""

    # "" → no explicit fleet; "auto" → infer from Node labels; otherwise a
    # Fleet.parse spec ("pool-a=v5e:4x4:2,...").
    fleet_spec: str = ""
    # ConfigMap (controller namespace) with the same spec under
    # data["fleet"]; tried when fleet_spec is empty. None disables.
    fleet_configmap: str | None = None
    controller_namespace: str = "kubeflow-tpu"
    weights: dict = field(default_factory=dict)   # namespace → weight
    aging_seconds: float = 300.0
    aging_max_boost: int = 4
    starvation_reserve_seconds: float = 900.0
    enable_preemption: bool = True
    idle_preempt_after_seconds: float = 1800.0
    # Requeue cadence for queued notebooks — a safety net; admissions
    # re-enqueue the winner immediately.
    queued_requeue_seconds: float = 10.0
    # Preempt-to-checkpoint (kubeflow_tpu/migration): preemption requests
    # a drain and only frees the ledger once the victim acks a committed
    # checkpoint (or the grace deadline fires — chips are never held
    # hostage). The DATACLASS default is off so bare construction keeps
    # the pre-migration immediate-stop semantics byte-for-byte; the
    # production env wiring (cmd/envconfig.py, KFTPU_MIGRATION, default
    # on) is what turns it on.
    enable_migration: bool = False
    drain_grace_seconds: float = migration.DEFAULT_DRAIN_GRACE_SECONDS


class TpuFleetScheduler:
    def __init__(
        self,
        kube,
        options: SchedulerOptions | None = None,
        *,
        fleet: Fleet | None = None,
        registry: Registry | None = None,
    ):
        self.kube = kube
        self.options = options or SchedulerOptions()
        self.recorder = EventRecorder(kube, "tpu-fleet-scheduler",
                                      registry=registry)
        if fleet is None and self.options.fleet_spec and \
                self.options.fleet_spec != "auto":
            fleet = Fleet.parse(self.options.fleet_spec)  # fail fast
        self.policy = PolicyQueue(
            fleet=fleet or Fleet(),
            config=PolicyConfig(
                aging_seconds=self.options.aging_seconds,
                aging_max_boost=self.options.aging_max_boost,
                starvation_reserve_seconds=(
                    self.options.starvation_reserve_seconds),
                enable_preemption=self.options.enable_preemption,
                idle_preempt_after_seconds=(
                    self.options.idle_preempt_after_seconds),
                deferred_preemption=self.options.enable_migration,
            ),
        )
        self._now = time.time
        self._node_informer = None          # set by setup wiring
        self._nb_informer = None
        self._enqueue_cbs: list = []
        # key → "Queued"|"Admitted" (last surfaced state, for transition
        # events); key → preemption reason for stopped victims; key →
        # reason for victims whose stop patch FAILED and must be retried
        # on their next reconcile (the ledger already re-assigned their
        # chips — without the retry the victim would run forever).
        self._state: dict[tuple, str] = {}
        self._preempted: dict[tuple, str] = {}
        self._stop_pending: dict[tuple, str] = {}
        # key → in-flight drain (preempt-to-checkpoint): the victim still
        # holds its chips while it checkpoints; finalized on ack or when
        # the grace deadline fires.
        self._draining: dict[tuple, _Drain] = {}
        self._fleet_next_try = 0.0
        # Debounce for full arbitration passes (see Admission below).
        self._last_pass_gen = -1
        self._last_pass_at = float("-inf")
        self._gauge_ns: set = set()
        self._gauge_pools: set = set()
        registry = registry or global_registry
        self.m_queue_depth = registry.gauge(
            "tpu_scheduler_queue_depth",
            "Gangs waiting for TPU fleet admission")
        self.m_admitted_ns = registry.gauge(
            "tpu_scheduler_admitted_chips",
            "TPU chips admitted by the fleet scheduler", ["namespace"])
        self.m_admitted_pool = registry.gauge(
            "tpu_scheduler_pool_admitted_chips",
            "TPU chips admitted per node pool", ["pool"])
        self.m_preemptions = registry.counter(
            "tpu_scheduler_preemptions_total",
            "Gangs preempted to reclaim chips", ["reason"])
        self.m_wait = registry.histogram(
            "tpu_scheduler_admission_wait_seconds",
            "Queue wait from submission to admission")
        self.m_drain = registry.histogram(
            "tpu_scheduler_drain_seconds",
            "Drain request to checkpoint-ack round trip")
        self.m_drain_fallback = registry.counter(
            "tpu_scheduler_drain_fallback_total",
            "Drains that hit the grace deadline and hard-stopped "
            "without a checkpoint")
        self.m_draining = registry.gauge(
            "tpu_scheduler_draining_gangs",
            "Gangs currently checkpointing before preemption")

    # ---- wiring -----------------------------------------------------------------

    def on_admitted(self, cb) -> None:
        """Register a re-enqueue callback: cb((namespace, name))."""
        self._enqueue_cbs.append(cb)

    def _enqueue(self, key: tuple) -> None:
        for cb in self._enqueue_cbs:
            try:
                cb(key)
            except Exception:
                log.exception("scheduler enqueue callback failed for %s", key)

    @property
    def active(self) -> bool:
        """True once a fleet is known — until then every admission passes
        through untouched."""
        return bool(self.policy.fleet.pools)

    async def _ensure_fleet(self) -> bool:
        """Discover — and for dynamic sources keep refreshing — the fleet.

        An explicit ``KFTPU_FLEET`` spec is immutable for the process's
        lifetime (env can't change under a running controller), so it is
        read once. The ConfigMap and ``auto`` (Node-label) sources are
        *dynamic*: operators grow/shrink them live, and the webhook's
        fast-fail ceiling re-reads the same ConfigMap on a short TTL —
        so both are re-read here on the same ``_CONFIGMAP_RETRY_SECONDS``
        throttle even after activation, or the admission ceiling and the
        scheduler's ledger would diverge until a controller restart. The
        throttle also bounds the auto path's cost while no TPU pool
        exists yet (no per-reconcile full-cluster Node list). A
        transiently EMPTY dynamic fleet is ignored: node pools come and
        go, and turning the scheduler transparent mid-flight would drop
        the queue; ``KFTPU_SCHEDULER=off`` is the deliberate off switch.
        On a shrink, pools already over capacity simply stop fitting new
        gangs and drain as holders release."""
        opts = self.options
        dynamic = opts.fleet_spec == "auto" or (
            not opts.fleet_spec and opts.fleet_configmap)
        if self.active and not dynamic:
            return True
        now = self._now()
        if now < self._fleet_next_try:
            return self.active
        self._fleet_next_try = now + _CONFIGMAP_RETRY_SECONDS
        fleet = None
        if opts.fleet_spec == "auto":
            if self._node_informer is not None:
                nodes = self._node_informer.items()
            else:
                try:
                    nodes = await self.kube.list("Node")
                except ApiError:
                    nodes = []
            fleet = Fleet.from_nodes(nodes)
        elif not opts.fleet_spec and opts.fleet_configmap:
            fleet = await load_fleet_from_configmap(
                self.kube, opts.fleet_configmap, opts.controller_namespace)
        if fleet is not None and fleet.pools \
                and fleet != self.policy.fleet:
            was_active = self.active
            # Re-seats live allocations onto the new pools (renamed pool
            # = same hardware under a new name must not be double-sold)
            # and bumps gen, so the next admission runs a full
            # arbitration pass over the new capacity.
            self.policy.rebind_fleet(fleet)
            log.info("TPU fleet scheduler %s: %d pool(s), %d chips",
                     "fleet updated" if was_active else "active",
                     len(fleet.pools), fleet.total_chips)
        return self.active

    # ---- request construction ---------------------------------------------------

    def _request_of(self, nb: dict, ms, now: float) -> GangRequest:
        ns = namespace_of(nb)
        annotations = annotations_of(nb)
        return GangRequest(
            key=(ns, name_of(nb)),
            namespace=ns or "",
            accelerator=ms.slice.accelerator.name,
            topology=ms.slice.topology_str,
            num_slices=ms.num_slices,
            chips=ms.num_chips,
            priority=parse_priority(annotations.get(PRIORITY_ANNOTATION)),
            weight=float(self.options.weights.get(ns, 1.0)),
            submitted_at=now,
        )

    @staticmethod
    def _last_active(nb: dict) -> float | None:
        """Culling's idle signal for preemption ranking. None — and
        therefore never idle — unless the culler has actually probed the
        server (LAST_ACTIVITY annotation present): on clusters running
        without culling nothing refreshes activity, and treating
        'no probe data' as 'idle since admission' would mark every busy
        gang preemptible ``idle_preempt_after`` seconds into its run.
        When probe data exists it is floored by the scheduler's own
        admitted-at stamp, so a gang that waited hours in the queue is
        not 'idle since before it ran'."""
        annotations = annotations_of(nb)
        last = parse_iso(
            annotations.get(nbapi.LAST_ACTIVITY_ANNOTATION) or "")
        if last is None:
            return None
        admitted = parse_iso(
            annotations.get(nbapi.SCHEDULER_ADMITTED_AT_ANNOTATION) or "")
        return max(last, admitted) if admitted is not None else last

    # ---- admission / release ----------------------------------------------------

    async def admission(self, nb: dict, ms, *,
                        running: bool = False) -> Admission | None:
        """Arbitrate one notebook's gang. Returns None while no fleet is
        known (transparent pass-through), otherwise the current admission
        state. ``running=True`` re-seats a gang whose StatefulSets are
        already live (controller restart) instead of queueing it."""
        if not await self._ensure_fleet():
            return None
        now = self._now()
        key = (namespace_of(nb), name_of(nb))
        if key in self._stop_pending:
            # This gang was preempted but its stop patch failed: the
            # ledger already gave its chips away, so retry the stop
            # rather than re-admit/reclaim a gang that must park.
            return await self._retry_stop(key, now)
        # Drains whose victims never reconcile (SDK wedged, pod gone)
        # must still hit their grace deadline — every admission pass
        # sweeps them. The CURRENT key is handled inline below with the
        # live CR this reconcile already holds.
        await self._sweep_drains(now, skip=key)
        if key in self._draining:
            return await self._drain_progress(key, nb, now)
        result = None
        with span("schedule", key=f"{key[0]}/{key[1]}"):
            if self.policy.is_admitted(key):
                self.policy.touch(key, self._last_active(nb))
                self._state[key] = "Admitted"
                ann = annotations_of(nb)
                if (nbapi.SCHEDULER_ADMITTED_AT_ANNOTATION not in ann
                        or nbapi.PREEMPTED_ANNOTATION in ann):
                    # The admit-time stamp patch failed (or a re-admitted
                    # victim still carries its stale Preempted verdict):
                    # without the stamp, culling clocks idleness from a
                    # pre-queue last-activity signal and stops the gang
                    # seconds after it finally started. Re-stamp with the
                    # ORIGINAL admission time until the patch lands.
                    alloc = self.policy.ledger.allocations[key]
                    await self._stamp_admitted(nb, alloc.admitted_at)
                if (migration.drain_requested_at(ann) is not None
                        and migration.drain_reason(ann).startswith("preempt")
                        and key not in self._draining):
                    # Controller restarted mid-drain: the in-memory drain
                    # (and its beneficiary) is gone and this gang was
                    # re-seated as a plain holder. Clear the stale marks
                    # so the SDK stops checkpointing for a preemption
                    # that no longer exists; if the pressure persists the
                    # next arbitration pass re-issues a fresh drain.
                    try:
                        await self.kube.patch(
                            "Notebook", key[1],
                            {"metadata": {"annotations":
                                          migration.clear_drain_patch()}},
                            key[0])
                    except ApiError:
                        pass
                return Admission("Admitted")
            self._preempted.pop(key, None)  # resubmission clears the verdict
            if nbapi.PREEMPTED_ANNOTATION in annotations_of(nb):
                # The DURABLE verdict must clear with the in-memory one:
                # a former victim the user re-queues and later stops is a
                # plain stop, and release() would otherwise resurrect the
                # stale annotation as "Preempted" after a controller
                # restart. Best-effort — release() also guards on the
                # live queue entry.
                try:
                    await self.kube.patch(
                        "Notebook", key[1],
                        {"metadata": {"annotations": {
                            nbapi.PREEMPTED_ANNOTATION: None}}}, key[0])
                except ApiError:
                    pass
            req = self._request_of(nb, ms, now)
            if running and self.policy.reclaim(req, now):
                self._state[key] = "Admitted"
                self._refresh_gauges()
                return Admission("Admitted")
            self.policy.submit(req)
            # Debounce: a long queue re-runs this gate every
            # queued_requeue_seconds per notebook; when nothing changed
            # since the last full pass (gen unchanged) and one ran
            # within the interval, the outcome is identical — serve the
            # queue snapshot instead of re-arbitrating O(queue) times
            # per interval. Aging/idle transitions are picked up by the
            # at-least-one-pass-per-interval that still runs.
            if (self.policy.gen == self._last_pass_gen
                    and now - self._last_pass_at
                    < self.options.queued_requeue_seconds):
                queue = self.policy.schedule_preview(now)
            else:
                result = self.policy.schedule(now)
                self._last_pass_gen = self.policy.gen
                self._last_pass_at = now
                queue = result.queue
        if result is not None:
            await self._apply(result, now, requester=nb)
        if self.policy.is_admitted(key):
            return Admission("Admitted")
        info = next((q for q in queue if q.key == key), None)
        position = info.position if info else 0
        reason = info.reason if info else ""
        chips = info.chips if info else ms.num_chips
        if self._state.get(key) != "Queued":
            self._state[key] = "Queued"
            await self._event(
                nb, "Normal", "Queued",
                f"Queued for TPU capacity (position {position}): {reason}")
        return Admission("Queued", position=position, reason=reason,
                         waiting_chips=chips)

    async def release(self, key: tuple,
                      nb: dict | None = None) -> Admission | None:
        """Drop a gang's hold (stop/delete). Frees its chips, runs an
        arbitration pass so waiting gangs can take them, and — for a
        stop caused by preemption — reports the ``Preempted`` state the
        victim's status should show. ``nb`` is the live CR for the stop
        path; None means the CR is GONE (delete), so the preemption
        verdict has nobody left to show it to and is dropped too.

        Discovers the fleet if needed (``_ensure_fleet``, not a bare
        ``active`` check): after a controller restart with a dynamic
        fleet source, a preempted victim's FIRST reconcile is this
        stopped path — returning early would wipe the annotation-backed
        Preempted verdict the end of this method restores."""
        if not await self._ensure_fleet():
            return None
        key = tuple(key)
        if nb is None:
            self._preempted.pop(key, None)
        self._stop_pending.pop(key, None)  # it IS stopped (or gone) now
        now = self._now()
        had_queue_entry = key in self.policy.pending
        alloc = self.policy.release(key)
        self._state.pop(key, None)
        if alloc is not None or had_queue_entry:
            with span("schedule", key=f"{key[0]}/{key[1]}", release=True):
                result = self.policy.schedule(now)
                self._last_pass_gen = self.policy.gen
                self._last_pass_at = now
            await self._apply(result, now)
        if key in self._draining:
            # Stopped (or deleted) mid-drain: the release above already
            # freed the chips, so the drain is moot — drop it. The
            # Preempted verdict (stamped at drain time) still reports.
            self._draining.pop(key, None)
            self._refresh_gauges()
        if key in self._preempted:
            return Admission("Preempted", reason=self._preempted[key])
        if nb is not None and alloc is None and not had_queue_entry:
            # Controller restarted since the preemption: the in-memory
            # verdict is gone, but the annotation stamped on the victim
            # survives — keep showing WHY it is stopped. Only a gang that
            # was PARKED when stopped qualifies: one that was queued or
            # admitted at stop time has been re-queued/running since the
            # verdict, so its leftover annotation is stale and this is a
            # plain user stop.
            reason = annotations_of(nb).get(nbapi.PREEMPTED_ANNOTATION)
            if reason:
                return Admission("Preempted", reason=reason)
        return None

    # ---- decision application ---------------------------------------------------

    async def _apply(self, result, now: float,
                     requester: dict | None = None) -> None:
        req_key = ((namespace_of(requester), name_of(requester))
                   if requester is not None else None)
        for p in result.preempted:
            with span("preempt", victim=f"{p.key[0]}/{p.key[1]}",
                      reason=p.reason):
                await self._preempt(p, now)
        for p in getattr(result, "drains", ()):
            with span("drain", victim=f"{p.key[0]}/{p.key[1]}",
                      reason=p.reason):
                await self._request_drain(p, now)
        for a in result.admitted:
            with span("admit", key=f"{a.key[0]}/{a.key[1]}"):
                self.m_wait.observe(a.waited)
                self._state[a.key] = "Admitted"
                nb = (requester if a.key == req_key
                      else await self._get_notebook(a.key))
                if nb is not None:
                    await self._stamp_admitted(nb, now)
                    hint = migration.restore_hint(annotations_of(nb))
                    if hint is not None:
                        # A parked-with-checkpoint gang coming back: the
                        # notebook controller stamps the hint into the
                        # pod env; announce the restore here so the
                        # lifecycle is auditable from Events alone.
                        with span("restore", key=f"{a.key[0]}/{a.key[1]}",
                                  step=hint[1]):
                            await self._event(
                                nb, "Normal", "Restoring",
                                f"Re-admitted; restoring from checkpoint "
                                f"{hint[0]}"
                                + (f" @ step {hint[1]}"
                                   if hint[1] is not None else ""))
                    await self._event(
                        nb, "Normal", "Admitted",
                        f"Admitted by the TPU fleet scheduler after "
                        f"{a.waited:.0f}s "
                        f"(slices: {_fmt_placements(a.placements)})")
                if a.key != req_key:
                    self._enqueue(a.key)
        self._refresh_gauges()

    async def _preempt(self, p, now: float) -> None:
        """Stop-annotate the victim: the notebook reconciler parks the
        whole gang (slice-atomic, replicas 0 everywhere) and its next
        reconcile releases the admission handle. Chips were already
        released in-ledger by the policy, so the beneficiary admits in
        this same pass. A failed stop patch is remembered and retried on
        the victim's next reconcile (``_retry_stop``) — the chips are
        gone from the ledger either way, so the victim MUST park or the
        fleet physically overcommits."""
        ns, name = p.key
        self._preempted[p.key] = p.reason
        self.m_preemptions.labels(reason=p.reason).inc()
        if not await self._stop_victim(p.key, p.reason, now):
            self._stop_pending[p.key] = p.reason
            log.warning("preemption stop patch failed for %s/%s; will "
                        "retry on its next reconcile", ns, name)
        else:
            nb = await self._get_notebook(p.key)
            if nb is not None:
                await self._event(
                    nb, "Warning", "Preempted",
                    f"Preempted ({p.reason}) to reclaim {p.chips} TPU "
                    f"chips for {p.for_key[0]}/{p.for_key[1]}; restart "
                    "to re-queue")
        self._enqueue(p.key)

    # ---- preempt-to-checkpoint (kubeflow_tpu/migration) ------------------------

    async def _request_drain(self, p, now: float) -> None:
        """Ask the victim to checkpoint instead of stopping it: stamp the
        drain annotations the in-pod SDK polls, start the grace clock,
        and keep its chips booked (policy marked the allocation draining)
        until :meth:`_finalize_drain` sees the ack or the deadline. The
        preemption verdict is recorded NOW so a victim the user stops
        mid-drain still reports why it parked."""
        ns, name = p.key
        self._preempted[p.key] = p.reason
        self._draining[p.key] = _Drain(
            reason=p.reason, for_key=p.for_key, chips=p.chips,
            requested_at=now,
            deadline=now + self.options.drain_grace_seconds)
        try:
            await self.kube.patch(
                "Notebook", name,
                {"metadata": {"annotations": migration.request_drain_patch(
                    f"preempt:{p.reason}", now)}}, ns)
        except ApiError:
            # The sweep re-patches a victim whose CR lacks the request
            # mark; if the apiserver stays down past the grace deadline
            # the fallback hard-stop takes over.
            log.warning("drain request patch failed for %s/%s; will "
                        "retry on the next scheduler pass", ns, name)
        nb = await self._get_notebook(p.key)
        if nb is not None:
            await self._event(
                nb, "Warning", "DrainRequested",
                f"Checkpoint requested ({p.reason}) to reclaim {p.chips} "
                f"TPU chips for {p.for_key[0]}/{p.for_key[1]}; parking "
                f"once the checkpoint commits (grace "
                f"{self.options.drain_grace_seconds:.0f}s)")
        self._enqueue(p.key)

    async def _drain_progress(self, key: tuple, nb: dict,
                              now: float) -> Admission:
        """The draining victim's own reconcile: ack → park with the
        checkpoint; deadline → today's hard stop; otherwise report
        Draining with a requeue that guarantees the deadline fires."""
        drain = self._draining[key]
        ann = annotations_of(nb)
        if migration.drain_requested_at(ann) is None:
            # The request patch never landed (or someone stripped it):
            # re-stamp with the ORIGINAL request time so the grace
            # deadline is unchanged.
            try:
                await self.kube.patch(
                    "Notebook", key[1],
                    {"metadata": {"annotations":
                                  migration.request_drain_patch(
                                      f"preempt:{drain.reason}",
                                      drain.requested_at)}}, key[0])
            except ApiError:
                pass
        elif migration.drain_acked(ann):
            return await self._finalize_drain(key, nb, checkpointed=True,
                                              now=now)
        if now >= drain.deadline:
            return await self._finalize_drain(key, nb, checkpointed=False,
                                              now=now)
        return Admission(
            "Draining", reason=drain.reason,
            requeue_after=max(0.1, drain.deadline - now + 0.05))

    async def _finalize_drain(self, key: tuple, nb: dict | None, *,
                              checkpointed: bool, now: float) -> Admission:
        """End one drain exactly once: count it, stop the victim (keeping
        the checkpoint marks — they are the restore hint), free its
        chips, and run the arbitration pass that admits the waiter."""
        drain = self._draining.pop(key, None)
        if drain is None:  # raced with release()/a concurrent finalize
            return Admission("Preempted",
                             reason=self._preempted.get(key, ""))
        self.m_preemptions.labels(reason=drain.reason).inc()
        if checkpointed:
            with span("checkpoint_ack", key=f"{key[0]}/{key[1]}",
                      waited=round(now - drain.requested_at, 3)):
                self.m_drain.observe(now - drain.requested_at)
        else:
            self.m_drain_fallback.inc()
        if not await self._stop_victim(
                key, drain.reason, now,
                extra=migration.clear_drain_patch(keep_reason=True)):
            # Same contract as an immediate preemption's failed stop:
            # chips are released below regardless, so the victim MUST
            # park — remember it and retry on its next reconcile.
            self._stop_pending[key] = drain.reason
        self.policy.release(key)
        self._state.pop(key, None)
        result = self.policy.schedule(now)
        self._last_pass_gen = self.policy.gen
        self._last_pass_at = now
        await self._apply(result, now)
        if nb is not None:
            if checkpointed:
                step = migration.checkpoint_step(annotations_of(nb))
                await self._event(
                    nb, "Normal", "Checkpointed",
                    "Checkpoint committed"
                    + (f" @ step {step}" if step is not None else "")
                    + f"; parking ({drain.reason} preemption)")
            else:
                await self._event(
                    nb, "Warning", "DrainDeadlineExceeded",
                    f"No checkpoint ack within "
                    f"{self.options.drain_grace_seconds:.0f}s; stopped "
                    f"without a checkpoint ({drain.reason} preemption)")
        return Admission("Preempted", reason=drain.reason)

    async def _sweep_drains(self, now: float, skip: tuple | None = None) \
            -> None:
        """Advance every in-flight drain that is not being handled inline
        by its own reconcile: finalize acks, fire expired deadlines, and
        re-patch victims whose request annotation never landed. Runs on
        every admission/release pass, so a waiter's safety-net requeue is
        enough to guarantee deadlines fire."""
        for key in list(self._draining):
            if key == skip or key not in self._draining:
                continue
            drain = self._draining[key]
            nb = await self._get_notebook(key)
            if nb is None:
                # CR gone mid-drain: nothing to stop; free the chips and
                # let the waiters arbitrate.
                self._draining.pop(key, None)
                if self.policy.release(key) is not None:
                    result = self.policy.schedule(now)
                    self._last_pass_gen = self.policy.gen
                    self._last_pass_at = now
                    await self._apply(result, now)
                continue
            ann = annotations_of(nb)
            if nbapi.STOP_ANNOTATION in ann:
                continue  # its own release path owns the cleanup
            if migration.drain_acked(ann):
                await self._finalize_drain(key, nb, checkpointed=True,
                                           now=now)
            elif now >= drain.deadline:
                await self._finalize_drain(key, nb, checkpointed=False,
                                           now=now)
            elif migration.drain_requested_at(ann) is None:
                try:
                    await self.kube.patch(
                        "Notebook", key[1],
                        {"metadata": {"annotations":
                                      migration.request_drain_patch(
                                          f"preempt:{drain.reason}",
                                          drain.requested_at)}}, key[0])
                except ApiError:
                    pass

    async def _stop_victim(self, key: tuple, reason: str, now: float,
                           extra: dict | None = None) -> bool:
        annotations = {
            nbapi.STOP_ANNOTATION: fmt_iso(now),
            nbapi.PREEMPTED_ANNOTATION: reason,
        }
        if extra:
            annotations.update(extra)
        try:
            await self.kube.patch(
                "Notebook", key[1],
                {"metadata": {"annotations": annotations}}, key[0])
            return True
        except ApiError:
            return False

    async def _retry_stop(self, key: tuple, now: float) -> Admission:
        reason = self._stop_pending[key]
        if not await self._stop_victim(
                key, reason, now,
                extra=migration.clear_drain_patch(keep_reason=True)):
            # Keep failing the reconcile until the patch lands: the
            # workqueue's error backoff is the retry loop. Returning
            # normally here would end retries after this attempt — the
            # manager is event-driven, so an un-parked victim would run
            # forever on chips the ledger already gave away.
            raise ApiError(
                f"preemption stop patch for {key[0]}/{key[1]} failed; "
                "retrying with backoff")
        self._stop_pending.pop(key, None)
        return Admission("Preempted", reason=reason)

    async def _stamp_admitted(self, nb: dict, now: float) -> None:
        """Persist the admitted-at timestamp: culling clocks idleness from
        it (a gang that queued for hours must not be culled seconds after
        it finally starts), and a controller restart re-reads it. Drain
        marks — including the park's drain-reason marker — clear here:
        an admitted gang is past its park, and a leftover reason would
        make a later plain stop present as a checkpointed park."""
        try:
            await self.kube.patch(
                "Notebook", name_of(nb),
                {"metadata": {"annotations": {
                    nbapi.SCHEDULER_ADMITTED_AT_ANNOTATION: fmt_iso(now),
                    nbapi.PREEMPTED_ANNOTATION: None,
                    **migration.clear_drain_patch(),
                }}}, namespace_of(nb))
        except ApiError:
            pass  # best-effort; the in-memory admitted_at still ranks

    async def _get_notebook(self, key: tuple) -> dict | None:
        ns, name = key
        if self._nb_informer is not None:
            nb = self._nb_informer.get(name, ns)
            if nb is not None:
                return nb
        try:
            return await self.kube.get_or_none("Notebook", name, ns)
        except ApiError:
            return None

    async def _event(self, nb: dict, type_: str, reason: str,
                     message: str) -> None:
        try:
            await self.recorder.event(nb, type_, reason, message)
        except Exception:
            pass  # events are best-effort

    def _refresh_gauges(self) -> None:
        self.m_queue_depth.set(len(self.policy.pending))
        self.m_draining.set(len(self._draining))
        ns_chips = self.policy.ledger.ns_chips
        for ns in self._gauge_ns - set(ns_chips):
            self.m_admitted_ns.labels(namespace=ns or "").set(0)
        for ns, chips in ns_chips.items():
            self.m_admitted_ns.labels(namespace=ns or "").set(chips)
        self._gauge_ns = set(ns_chips)
        by_pool = self.policy.ledger.admitted_chips_by_pool()
        for pool in self._gauge_pools - set(by_pool):
            self.m_admitted_pool.labels(pool=pool).set(0)
        for pool, chips in by_pool.items():
            self.m_admitted_pool.labels(pool=pool).set(chips)
        self._gauge_pools = set(by_pool)

    # ---- introspection ----------------------------------------------------------

    def debug_info(self) -> dict:
        now = self._now()
        info = self.policy.debug_info(now)
        info["active"] = self.active
        info["fleet_source"] = (
            "explicit" if self.options.fleet_spec
            and self.options.fleet_spec != "auto"
            else ("nodes" if self.options.fleet_spec == "auto"
                  else ("configmap" if self.options.fleet_configmap
                        else "none")))
        info["preempted"] = {
            f"{k[0]}/{k[1]}": reason for k, reason in self._preempted.items()
        }
        info["migration_enabled"] = self.options.enable_migration
        info["draining"] = {
            f"{k[0]}/{k[1]}": {
                "reason": d.reason,
                "for": f"{d.for_key[0]}/{d.for_key[1]}",
                "chips": d.chips,
                "deadline_in_sec": round(d.deadline - now, 3),
            }
            for k, d in self._draining.items()
        }
        return info


def _fmt_placements(placements: dict) -> str:
    return ", ".join(f"{pool}x{n}" for pool, n in sorted(placements.items()))
