"""Async runtime of the TPU fleet scheduler.

The single admission point between a Notebook CR and its slice
StatefulSets: the notebook controller's capacity stage calls
:meth:`TpuFleetScheduler.admission` before creating any slice, and
:meth:`TpuFleetScheduler.release` on stop/delete. The pure policy core
(:mod:`kubeflow_tpu.scheduler.policy`) makes every decision; this layer
adds what the cluster needs around it:

- fleet discovery (env spec, ConfigMap, or Node-label inference);
- preemption actuation — victims are stop-annotated (the notebook
  reconciler parks the whole gang, never a slice subset) and the
  preemption is recorded so their status can say why;
- transition side effects: ``Queued``/``Admitted``/``Preempted`` Events,
  the admitted-at annotation culling's idle clock needs, and re-enqueues
  so a freshly admitted notebook reconciles immediately;
- observability: ``schedule``/``admit``/``preempt`` tracing phases,
  Prometheus gauges/counters/histogram, and the ``/debug/scheduler``
  payload.

With no fleet configured the scheduler is a transparent no-op (every
admission passes through, zero API writes) — exactly today's behavior,
which is also what the ``KFTPU_SCHEDULER=off`` kill switch restores.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field, replace

from kubeflow_tpu.api import keys
from kubeflow_tpu.api import inferenceservice as isvcapi
from kubeflow_tpu.api import notebook as nbapi
from kubeflow_tpu.runtime.errors import ApiError, NotFound
from kubeflow_tpu.runtime.events import EventRecorder
from kubeflow_tpu.runtime.metrics import Registry, global_registry
from kubeflow_tpu.runtime.objects import (
    annotations_of,
    deep_get,
    fmt_iso,
    name_of,
    namespace_of,
    parse_iso,
)
from kubeflow_tpu.runtime import slo
from kubeflow_tpu.runtime.tracing import current_trace_id, span
from kubeflow_tpu.migration import protocol as migration
from kubeflow_tpu.scheduler import elastic
from kubeflow_tpu.scheduler.fleet import Allocation, Fleet
from kubeflow_tpu.scheduler.policy import (
    GangRequest,
    PolicyConfig,
    PolicyQueue,
    Preemption,
)
from kubeflow_tpu.tpu.topology import TopologyError, TpuSlice

log = logging.getLogger(__name__)

# Priority classes from a CR annotation; plain integers are accepted too.
PRIORITY_ANNOTATION = nbapi.PRIORITY_ANNOTATION
PRIORITY_CLASSES = {"low": -100, "normal": 0, "high": 100, "critical": 200}

# Warm-pool slot reservations (ISSUE 14) sit below every user priority
# class: the reserve exists to be cannibalized, and the tier -1 victim
# ordering in policy._find_victims makes the intent structural, not just
# a number.
WARM_POOL_PRIORITY = -1000

FLEET_CONFIGMAP_KEY = "fleet"
_CONFIGMAP_RETRY_SECONDS = 30.0


async def load_fleet_from_configmap(kube, name: str,
                                    namespace: str) -> Fleet | None:
    """The ONE reader of the fleet ConfigMap — shared by the scheduler's
    ``_ensure_fleet`` and the webhook's can-never-fit ceiling
    (webhooks/notebook.py), so the spec key and the bad-spec tolerance
    cannot drift apart between the two admission layers. Returns None
    when the ConfigMap/key is absent or the spec is malformed (a broken
    spec must not block admissions or wedge the scheduler); callers own
    their caching/retry policy."""
    cm = await kube.get_or_none("ConfigMap", name, namespace)
    spec = ((cm or {}).get("data") or {}).get(FLEET_CONFIGMAP_KEY) or ""
    if not spec.strip():
        return None
    try:
        return Fleet.parse(spec)
    except Exception:
        log.exception("bad fleet spec in ConfigMap %s/%s", namespace, name)
        return None


def parse_priority(value: str | None) -> int:
    if not value:
        return 0
    v = value.strip().lower()
    if v in PRIORITY_CLASSES:
        return PRIORITY_CLASSES[v]
    try:
        return int(v)
    except ValueError:
        return 0


@dataclass(frozen=True)
class Admission:
    """What the capacity stage gets back."""

    state: str                 # "Admitted" | "Queued" | "Preempted" | "Draining"
    position: int = 0
    reason: str = ""
    waiting_chips: int = 0
    # Draining only: how soon the controller must reconcile again so the
    # grace deadline fires even if the SDK never acks.
    requeue_after: float = 0.0
    # Queued only, elastic: why this gang is BACK in the queue
    # ("spot-reclaim" after its capacity was revoked, "defrag" after a
    # migration park) — JWA keys its message off it.
    reclaimed: str = ""
    # Queued only, elastic: a pool-scale-up intent is pending for this
    # gang's shape (chips asked for, and how long the ask has waited).
    scale_up_chips: int = 0
    scale_up_pending_sec: float = -1.0

    @property
    def admitted(self) -> bool:
        return self.state == "Admitted"


@dataclass
class _Drain:
    """In-memory side of one in-flight drain (the durable side lives in
    the victim's annotations — migration/protocol.py)."""

    reason: str                # "idle" | "priority" | "spot-reclaim" | "defrag"
    for_key: tuple             # beneficiary waiting on the chips
    chips: int
    requested_at: float
    deadline: float
    # The drain-reason annotation value (the protocol's finalizer
    # contract): "preempt:<reason>" for scheduler preemption,
    # "spot-reclaim"/"defrag" for the elastic paths.
    annotation: str = ""
    # Elastic drains: once parked, un-park and re-queue the victim with
    # its aging credit instead of waiting for a user restart.
    requeue: bool = False


@dataclass
class _CommitWait:
    """Post-park commit watch (checkpoint fabric, ISSUE 16): the drain
    acked at snapshot and the chips are already free, but the restore
    guarantee is only hard-released when the background upload durably
    commits — or the commit grace expires and the park is marked
    commit-dirty (the drain then counts as a fallback, not a clean
    drain)."""

    reason: str
    requested_at: float        # drain request — the commit SLI's t0
    deadline: float            # requested_at-anchored commit grace


@dataclass
class SchedulerOptions:
    """Env contract (cmd/envconfig.py scheduler_options)."""

    # "" → no explicit fleet; "auto" → infer from Node labels; otherwise a
    # Fleet.parse spec ("pool-a=v5e:4x4:2,...").
    fleet_spec: str = ""
    # ConfigMap (controller namespace) with the same spec under
    # data["fleet"]; tried when fleet_spec is empty. None disables.
    fleet_configmap: str | None = None
    controller_namespace: str = "kubeflow-tpu"
    weights: dict = field(default_factory=dict)   # namespace → weight
    aging_seconds: float = 300.0
    aging_max_boost: int = 4
    starvation_reserve_seconds: float = 900.0
    enable_preemption: bool = True
    idle_preempt_after_seconds: float = 1800.0
    # Requeue cadence for queued notebooks — a safety net; admissions
    # re-enqueue the winner immediately.
    queued_requeue_seconds: float = 10.0
    # Preempt-to-checkpoint (kubeflow_tpu/migration): preemption requests
    # a drain and only frees the ledger once the victim acks a committed
    # checkpoint (or the grace deadline fires — chips are never held
    # hostage). The DATACLASS default is off so bare construction keeps
    # the pre-migration immediate-stop semantics byte-for-byte; the
    # production env wiring (cmd/envconfig.py, KFTPU_MIGRATION, default
    # on) is what turns it on.
    enable_migration: bool = False
    drain_grace_seconds: float = migration.DEFAULT_DRAIN_GRACE_SECONDS
    # Checkpoint fabric (ISSUE 16): how long after the snapshot ack the
    # background upload may run before the park is marked commit-dirty
    # and the drain counted as a fallback (KFTPU_COMMIT_GRACE; defaults
    # to the drain grace via cmd/envconfig.py).
    commit_grace_seconds: float = migration.DEFAULT_DRAIN_GRACE_SECONDS
    # Elastic fleet (kubeflow_tpu/scheduler/elastic.py): scale-up
    # intents, flex (host-borrowing) placement, spot reclaim, defrag.
    # The DATACLASS default is off — bare construction keeps PR 5–7
    # semantics byte-for-byte; production gets it from KFTPU_ELASTIC
    # (default on) via cmd/envconfig.py.
    enable_elastic: bool = False
    scale_up_ttl_seconds: float = elastic.DEFAULT_SCALE_UP_TTL_SECONDS
    # Defrag rides under enable_elastic; KFTPU_DEFRAG=off clears it.
    enable_defrag: bool = True
    defrag_interval_seconds: float = \
        elastic.DEFAULT_DEFRAG_INTERVAL_SECONDS
    defrag_idle_seconds: float = elastic.DEFAULT_DEFRAG_IDLE_SECONDS
    defrag_max_moves: int = elastic.DEFAULT_DEFRAG_MAX_MOVES
    # Dynamic fleet sources (ConfigMap / node inference) re-read on this
    # throttle; also paces how quickly a granted scale-up is noticed.
    fleet_refresh_seconds: float = _CONFIGMAP_RETRY_SECONDS


class TpuFleetScheduler:
    def __init__(
        self,
        kube,
        options: SchedulerOptions | None = None,
        *,
        fleet: Fleet | None = None,
        registry: Registry | None = None,
    ):
        self.kube = kube
        self.options = options or SchedulerOptions()
        self.recorder = EventRecorder(kube, "tpu-fleet-scheduler",
                                      registry=registry)
        if fleet is None and self.options.fleet_spec and \
                self.options.fleet_spec != "auto":
            fleet = Fleet.parse(self.options.fleet_spec)  # fail fast
        self.policy = PolicyQueue(
            fleet=fleet or Fleet(),
            config=PolicyConfig(
                aging_seconds=self.options.aging_seconds,
                aging_max_boost=self.options.aging_max_boost,
                starvation_reserve_seconds=(
                    self.options.starvation_reserve_seconds),
                enable_preemption=self.options.enable_preemption,
                idle_preempt_after_seconds=(
                    self.options.idle_preempt_after_seconds),
                deferred_preemption=self.options.enable_migration,
            ),
        )
        self._now = time.time
        self._node_informer = None          # set by setup wiring
        self._nb_informer = None
        self._ring = None                   # set by attach_ring (sharded)
        self._enqueue_cbs: list = []
        # Serving workload class (kubeflow_tpu/serving): replica gang
        # keys admitted through serving_admission(). Their side effects
        # differ from notebooks' — no CR annotation stamps (the key
        # names no Notebook), no drain protocol (the engine's parked
        # checkpoint is the state), and re-enqueues route to the
        # serving controller's callbacks, never the notebook workqueue
        # (a notebook reconcile of a nonexistent key would RELEASE the
        # serving allocation). Empty — and every path below byte-
        # identical to PR 5–8 — until a serving controller registers.
        self._serving_keys: set = set()
        self._serving_cbs: list = []
        # Warm pod pools (ISSUE 14, controllers/warmpool.py): slot
        # reservation keys admitted through warm_reserve(). Their chips
        # are a low-priority reclaimable reserve — the FIRST preemption
        # victims, released instantly (nothing to checkpoint), with the
        # teardown routed to the pool manager's async callbacks instead
        # of any Notebook CR patch (no CR exists under these keys).
        self._warmpool_keys: set = set()
        self._warm_cbs: list = []
        # key → "Queued"|"Admitted" (last surfaced state, for transition
        # events); key → preemption reason for stopped victims; key →
        # reason for victims whose stop patch FAILED and must be retried
        # on their next reconcile (the ledger already re-assigned their
        # chips — without the retry the victim would run forever).
        self._state: dict[tuple, str] = {}
        self._preempted: dict[tuple, str] = {}
        self._stop_pending: dict[tuple, str] = {}
        # key → in-flight drain (preempt-to-checkpoint): the victim still
        # holds its chips while it checkpoints; finalized on ack or when
        # the grace deadline fires.
        self._draining: dict[tuple, _Drain] = {}
        self._commit_waits: dict[tuple, _CommitWait] = {}
        self._fleet_next_try = 0.0
        # Debounce for full arbitration passes (see Admission below).
        self._last_pass_gen = -1
        self._last_pass_at = float("-inf")
        self._gauge_ns: set = set()
        self._gauge_pools: set = set()
        # ---- elastic fleet state (None/"empty" with elastic off) ----
        # Pending scale-up intents (pure book; the CR mirror + metrics
        # live here in the runtime).
        self._intent_book = (
            elastic.IntentBook(self.options.scale_up_ttl_seconds)
            if self.options.enable_elastic else None)
        self._elastic_cfg = elastic.ElasticConfig(
            scale_up_ttl_seconds=self.options.scale_up_ttl_seconds,
            enable_defrag=self.options.enable_defrag,
            defrag_interval_seconds=self.options.defrag_interval_seconds,
            defrag_idle_seconds=self.options.defrag_idle_seconds,
            defrag_max_moves=self.options.defrag_max_moves,
        )
        self._last_defrag_at = float("-inf")
        self._defrag_moves = 0
        # Debounce for the elastic post-pass (intents/eviction are
        # O(queue) scans — same rationale as the arbitration debounce).
        self._last_elastic_gen = -1
        self._last_elastic_at = float("-inf")
        # Serializes the elastic post-pass: IntentBook.sync computes an
        # IntentSync delta and the CR mirror then applies it over many
        # await round trips — two reconcile workers interleaving there
        # could apply STALE deltas (one task creating a ProvisioningRequest
        # the other's sync just withdrew → an orphan CR only the throttled
        # janitor ever collects). The await-race pass tracks acquisition
        # through the call graph.
        self._elastic_lock = asyncio.Lock()
        # pool name → {"since": t, "nodes": set}: in-progress spot
        # reclaims. While an entry exists the pool is marked unavailable
        # in the ledger (sells nothing); the entry clears when the
        # signaling nodes recover/disappear AND every resident gang has
        # drained out.
        self._spot_reclaims: dict[str, dict] = {}
        # key → (reason, park stop-stamp): elastic drains that
        # auto-requeue after the park — release() un-parks them, and the
        # recorded stamp (a nonce'd stop value) lets it tell the
        # scheduler's own park from a user's racing stop. Alongside:
        # key → reason, the surviving "why am I queued again" marker JWA
        # reads until re-admission.
        self._auto_resume: dict[tuple, tuple] = {}
        self._reclaim_verdict: dict[tuple, str] = {}
        # key → submitted_at credit carried across a reclaim/defrag
        # re-queue (seniority from the gang's original admission — a
        # reclaimed gang must not age from zero behind newcomers).
        self._requeue_credit: dict[tuple, float] = {}
        registry = registry or global_registry
        self.m_queue_depth = registry.gauge(
            "tpu_scheduler_queue_depth",
            "Gangs waiting for TPU fleet admission")
        self.m_admitted_ns = registry.gauge(
            "tpu_scheduler_admitted_chips",
            "TPU chips admitted by the fleet scheduler", ["namespace"])
        self.m_admitted_pool = registry.gauge(
            "tpu_scheduler_pool_admitted_chips",
            "TPU chips admitted per node pool", ["pool"])
        self.m_preemptions = registry.counter(
            "tpu_scheduler_preemptions_total",
            "Gangs preempted to reclaim chips", ["reason"])
        self.m_wait = registry.histogram(
            "tpu_scheduler_admission_wait_seconds",
            "Queue wait from submission to admission")
        self.m_drain = registry.histogram(
            "tpu_scheduler_drain_seconds",
            "Drain request to checkpoint-ack round trip")
        self.m_drain_fallback = registry.counter(
            "tpu_scheduler_drain_fallback_total",
            "Drains that hit the grace deadline and hard-stopped "
            "without a checkpoint")
        self.m_draining = registry.gauge(
            "tpu_scheduler_draining_gangs",
            "Gangs currently checkpointing before preemption")
        self.m_scale_up = registry.gauge(
            "tpu_scheduler_scale_up_intents",
            "Pool scale-up intents currently pending")
        self.m_scale_up_events = registry.counter(
            "tpu_scheduler_scale_up_events_total",
            "Scale-up intent lifecycle events",
            ["event"])  # created | renewed | granted | moot | denied
        self.m_spot_reclaims = registry.counter(
            "tpu_scheduler_spot_reclaims_total",
            "Gangs drained off revoked spot capacity")
        self.m_defrag = registry.counter(
            "tpu_scheduler_defrag_moves_total",
            "Gangs migrated off pack-breaking pools by the defragmenter")
        self.m_borrowed = registry.gauge(
            "tpu_scheduler_borrowed_hosts",
            "Hosts borrowed from foreign-shape pools (flex placement)",
            ["pool"])
        self._gauge_borrow_pools: set = set()

    # ---- wiring -----------------------------------------------------------------

    def on_admitted(self, cb) -> None:
        """Register a re-enqueue callback: cb((namespace, name))."""
        self._enqueue_cbs.append(cb)

    def on_serving_admitted(self, cb) -> None:
        """Register the serving controller's re-enqueue callback:
        cb(replica_key) — called with the (namespace, "<svc>#r<i>")
        replica key whenever a serving replica's admission state may
        have changed (admitted, or its capacity reclaimed)."""
        self._serving_cbs.append(cb)

    def attach_ring(self, ring) -> None:
        """Arbiter election for sharded active-active deployments
        (runtime/sharding.py): the chip ledger stays GLOBALLY consistent
        by running arbitration only on the replica holding the arbiter
        shard (shard 0). A scheduler attached to a non-arbiter ring is
        dormant — ``_ensure_fleet`` refuses to activate, so its whole
        surface is transparent pass-through and none of its background
        sweeps (drains, spot reclaims, elastic intents) can fight the
        real arbiter's. In-process harnesses (bench, chaos) give every
        replica's controllers the ARBITER's scheduler instance — the
        per-shard workqueues feeding one elected arbiter; on arbiter
        failover a fresh scheduler rebuilds its ledger from the API via
        the ``running=True`` re-seat path, exactly the controller-restart
        semantics the chaos soak already exercises."""
        self._ring = ring

    @property
    def arbiter(self) -> bool:
        """True when this replica may arbitrate (unsharded, or holding
        the arbiter shard)."""
        return self._ring is None or self._ring.is_arbiter

    def _enqueue(self, key: tuple) -> None:
        cbs = (self._serving_cbs if key in self._serving_keys
               else self._enqueue_cbs)
        for cb in cbs:
            try:
                cb(key)
            except Exception:
                log.exception("scheduler enqueue callback failed for %s", key)

    @property
    def active(self) -> bool:
        """True once a fleet is known — until then every admission passes
        through untouched."""
        return bool(self.policy.fleet.pools)

    async def _ensure_fleet(self) -> bool:
        """Discover — and for dynamic sources keep refreshing — the fleet.

        An explicit ``KFTPU_FLEET`` spec is immutable for the process's
        lifetime (env can't change under a running controller), so it is
        read once. The ConfigMap and ``auto`` (Node-label) sources are
        *dynamic*: operators grow/shrink them live, and the webhook's
        fast-fail ceiling re-reads the same ConfigMap on a short TTL —
        so both are re-read here on the same ``_CONFIGMAP_RETRY_SECONDS``
        throttle even after activation, or the admission ceiling and the
        scheduler's ledger would diverge until a controller restart. The
        throttle also bounds the auto path's cost while no TPU pool
        exists yet (no per-reconcile full-cluster Node list). A
        transiently EMPTY dynamic fleet is ignored: node pools come and
        go, and turning the scheduler transparent mid-flight would drop
        the queue; ``KFTPU_SCHEDULER=off`` is the deliberate off switch.
        On a shrink, pools already over capacity simply stop fitting new
        gangs and drain as holders release."""
        if not self.arbiter:
            # Dormant standby: no fleet, so every admission passes
            # through untouched and no sweep mutates shared state. The
            # moment the ring hands this replica the arbiter shard, the
            # next admission activates it here.
            return False
        opts = self.options
        dynamic = opts.fleet_spec == "auto" or (
            not opts.fleet_spec and opts.fleet_configmap)
        if self.active and not dynamic:
            return True
        now = self._now()
        if now < self._fleet_next_try:
            return self.active
        refresh = self.options.fleet_refresh_seconds
        if self._intent_book is not None and self._intent_book.intents:
            # A scale-up ask is out: poll the fleet source faster so
            # granted capacity admits promptly, not a full throttle
            # interval later.
            refresh = min(refresh, max(refresh / 6.0, 1.0))
        self._fleet_next_try = now + refresh
        fleet = None
        if opts.fleet_spec == "auto":
            if self._node_informer is not None:
                nodes = self._node_informer.items()
            else:
                try:
                    nodes = await self.kube.list("Node")
                except ApiError:
                    nodes = []
            fleet = Fleet.from_nodes(nodes)
        elif not opts.fleet_spec and opts.fleet_configmap:
            fleet = await load_fleet_from_configmap(
                self.kube, opts.fleet_configmap, opts.controller_namespace)
        if fleet is not None and fleet.pools \
                and fleet != self.policy.fleet:
            was_active = self.active
            # Re-seats live allocations onto the new pools (renamed pool
            # = same hardware under a new name must not be double-sold)
            # and bumps gen, so the next admission runs a full
            # arbitration pass over the new capacity.
            self.policy.rebind_fleet(fleet)
            log.info("TPU fleet scheduler %s: %d pool(s), %d chips",
                     "fleet updated" if was_active else "active",
                     len(fleet.pools), fleet.total_chips)
            # Every known notebook re-arbitrates NOW: gangs whose last
            # reconcile ran during the pre-activation pass-through
            # window (fresh restart, dynamic source still loading) are
            # in neither book and may hold chips the new ledger is
            # about to sell — waiting for their next organic event
            # leaves that double-booking window open indefinitely.
            if self._nb_informer is not None:
                for nb in self._nb_informer.items():
                    self._enqueue((namespace_of(nb), name_of(nb)))
            # Same for reclaim signals: a revocation taint dispatched by
            # the Node informer's initial sync BEFORE the fleet loaded
            # mapped onto no pool and was dropped — and a healthy watch
            # never re-delivers it. Re-scan the cached nodes against the
            # fleet that now exists.
            if self._intent_book is not None \
                    and self._node_informer is not None:
                for node in self._node_informer.items():
                    self.note_node_event(node)
        return self.active

    # ---- request construction ---------------------------------------------------

    def _request_of(self, nb: dict, ms, now: float) -> GangRequest:
        ns = namespace_of(nb)
        annotations = annotations_of(nb)
        return GangRequest(
            key=(ns, name_of(nb)),
            namespace=ns or "",
            accelerator=ms.slice.accelerator.name,
            topology=ms.slice.topology_str,
            num_slices=ms.num_slices,
            chips=ms.num_chips,
            priority=parse_priority(annotations.get(PRIORITY_ANNOTATION)),
            weight=float(self.options.weights.get(ns, 1.0)),
            submitted_at=now,
            # A Notebook labeled workload-class=serving (a serving pod
            # deployed through the notebook CR) gets the same victim
            # protection as a real InferenceService replica: no Jupyter
            # activity probe means the idle heuristic would misread it.
            workload=("serving" if isvcapi.is_serving_class(nb)
                      else "notebook"),
        )

    @staticmethod
    def _last_active(nb: dict) -> float | None:
        """Culling's idle signal for preemption ranking. None — and
        therefore never idle — unless the culler has actually probed the
        server (LAST_ACTIVITY annotation present): on clusters running
        without culling nothing refreshes activity, and treating
        'no probe data' as 'idle since admission' would mark every busy
        gang preemptible ``idle_preempt_after`` seconds into its run.
        When probe data exists it is floored by the scheduler's own
        admitted-at stamp, so a gang that waited hours in the queue is
        not 'idle since before it ran'."""
        annotations = annotations_of(nb)
        last = parse_iso(
            annotations.get(nbapi.LAST_ACTIVITY_ANNOTATION) or "")
        if last is None:
            return None
        admitted = parse_iso(
            annotations.get(nbapi.SCHEDULER_ADMITTED_AT_ANNOTATION) or "")
        return max(last, admitted) if admitted is not None else last

    # ---- admission / release ----------------------------------------------------

    async def admission(self, nb: dict, ms, *,
                        running: bool = False) -> Admission | None:
        """Arbitrate one notebook's gang. Returns None while no fleet is
        known (transparent pass-through), otherwise the current admission
        state. ``running=True`` re-seats a gang whose StatefulSets are
        already live (controller restart) instead of queueing it."""
        if not await self._ensure_fleet():
            return None
        now = self._now()
        key = (namespace_of(nb), name_of(nb))
        if key in self._stop_pending:
            # This gang was preempted but its stop patch failed: the
            # ledger already gave its chips away, so retry the stop
            # rather than re-admit/reclaim a gang that must park.
            return await self._retry_stop(key, now)
        # Drains whose victims never reconcile (SDK wedged, pod gone)
        # must still hit their grace deadline — every admission pass
        # sweeps them. The CURRENT key is handled inline below with the
        # live CR this reconcile already holds.
        await self._sweep_drains(now, skip=key)
        # Spot revocations signaled since the last pass start their
        # drains here — including the CURRENT key's (no skip: the
        # drain-progress branch right below then handles it inline).
        await self._sweep_spot_reclaims(now)
        if key in self._draining:
            return await self._drain_progress(key, nb, now)
        result = None
        with span("schedule", key=f"{key[0]}/{key[1]}"):
            if self.policy.is_admitted(key):
                self.policy.touch(key, self._last_active(nb))
                self._state[key] = "Admitted"
                ann = annotations_of(nb)
                if (nbapi.SCHEDULER_ADMITTED_AT_ANNOTATION not in ann
                        or nbapi.PREEMPTED_ANNOTATION in ann):
                    # The admit-time stamp patch failed (or a re-admitted
                    # victim still carries its stale Preempted verdict):
                    # without the stamp, culling clocks idleness from a
                    # pre-queue last-activity signal and stops the gang
                    # seconds after it finally started. Re-stamp with the
                    # ORIGINAL admission time until the patch lands.
                    alloc = self.policy.ledger.allocations[key]
                    await self._stamp_admitted(nb, alloc.admitted_at)
                self._requeue_credit.pop(key, None)
                self._reclaim_verdict.pop(key, None)
                reason_ann = migration.drain_reason(ann)
                if (migration.drain_requested_at(ann) is not None
                        and (reason_ann.startswith("preempt")
                             or reason_ann in (
                                 elastic.SPOT_RECLAIM_REASON,
                                 elastic.DEFRAG_REASON))
                        and key not in self._draining):
                    # Controller restarted mid-drain: the in-memory drain
                    # (and its beneficiary) is gone and this gang was
                    # re-seated as a plain holder. Clear the stale marks
                    # so the SDK stops checkpointing for a preemption
                    # that no longer exists; if the pressure persists the
                    # next arbitration pass re-issues a fresh drain.
                    try:
                        await self.kube.patch(
                            "Notebook", key[1],
                            {"metadata": {"annotations":
                                          migration.clear_drain_patch()}},
                            key[0])
                    except ApiError as exc:
                        log.debug("stale drain-mark clear for %s/%s "
                                  "failed (retried next pass): %s",
                                  key[0], key[1], exc)
                return Admission("Admitted")
            self._preempted.pop(key, None)  # resubmission clears the verdict
            if nbapi.PREEMPTED_ANNOTATION in annotations_of(nb):
                # The DURABLE verdict must clear with the in-memory one:
                # a former victim the user re-queues and later stops is a
                # plain stop, and release() would otherwise resurrect the
                # stale annotation as "Preempted" after a controller
                # restart. Best-effort — release() also guards on the
                # live queue entry.
                try:
                    await self.kube.patch(
                        "Notebook", key[1],
                        {"metadata": {"annotations": {
                            nbapi.PREEMPTED_ANNOTATION: None}}}, key[0])
                except ApiError as exc:
                    log.debug("stale Preempted clear for %s/%s failed "
                              "(release() re-guards on the live queue "
                              "entry): %s", key[0], key[1], exc)
            req = self._request_of(nb, ms, now)
            credit = self._requeue_credit.get(key)
            if credit is not None:
                # Re-queued reclaim/defrag victim: seniority from its
                # original admission — it must not age from zero behind
                # gangs that arrived while it was running.
                req = replace(req, submitted_at=min(credit, now))
            flex_hint = annotations_of(nb).get(
                nbapi.FLEX_POOL_ANNOTATION)
            if running and self.policy.reclaim(
                    req, now, borrow_first=bool(flex_hint),
                    prefer_pool=flex_hint):
                self._state[key] = "Admitted"
                self._refresh_gauges()
                return Admission("Admitted")
            self.policy.submit(req)
            # Debounce: a long queue re-runs this gate every
            # queued_requeue_seconds per notebook; when nothing changed
            # since the last full pass (gen unchanged) and one ran
            # within the interval, the outcome is identical — serve the
            # queue snapshot instead of re-arbitrating O(queue) times
            # per interval. Aging/idle transitions are picked up by the
            # at-least-one-pass-per-interval that still runs.
            if (self.policy.gen == self._last_pass_gen
                    and now - self._last_pass_at
                    < self.options.queued_requeue_seconds):
                queue = self.policy.schedule_preview(now)
            else:
                result = self._arbitrate(now)
                self._last_pass_gen = self.policy.gen
                self._last_pass_at = now
                queue = result.queue
        if result is not None:
            await self._apply(result, now, requester=nb)
        await self._elastic_post(now)
        if self.policy.is_admitted(key):
            return Admission("Admitted")
        info = next((q for q in queue if q.key == key), None)
        position = info.position if info else 0
        reason = info.reason if info else ""
        chips = info.chips if info else ms.num_chips
        if self._state.get(key) != "Queued":
            self._state[key] = "Queued"
            await self._event(
                nb, "Normal", "Queued",
                f"Queued for TPU capacity (position {position}): {reason}")
        intent = (self._intent_book.for_shape(
            ms.slice.accelerator.name, ms.slice.topology_str)
            if self._intent_book is not None else None)
        if intent is not None and key not in intent.for_keys:
            intent = None
        return Admission(
            "Queued", position=position, reason=reason,
            waiting_chips=chips,
            reclaimed=self._reclaim_verdict.get(key, ""),
            scale_up_chips=intent.chips if intent is not None else 0,
            scale_up_pending_sec=(
                round(intent.pending_seconds(now), 3)
                if intent is not None else -1.0))

    async def release(self, key: tuple,
                      nb: dict | None = None) -> Admission | None:
        """Drop a gang's hold (stop/delete). Frees its chips, runs an
        arbitration pass so waiting gangs can take them, and — for a
        stop caused by preemption — reports the ``Preempted`` state the
        victim's status should show. ``nb`` is the live CR for the stop
        path; None means the CR is GONE (delete), so the preemption
        verdict has nobody left to show it to and is dropped too.

        Discovers the fleet if needed (``_ensure_fleet``, not a bare
        ``active`` check): after a controller restart with a dynamic
        fleet source, a preempted victim's FIRST reconcile is this
        stopped path — returning early would wipe the annotation-backed
        Preempted verdict the end of this method restores."""
        if not await self._ensure_fleet():
            return None
        key = tuple(key)
        if nb is None:
            self._preempted.pop(key, None)
            self._auto_resume.pop(key, None)
            self._reclaim_verdict.pop(key, None)
            self._requeue_credit.pop(key, None)
        self._stop_pending.pop(key, None)  # it IS stopped (or gone) now
        now = self._now()
        had_queue_entry = key in self.policy.pending
        alloc = self.policy.release(key)
        self._state.pop(key, None)
        if alloc is not None or had_queue_entry:
            with span("schedule", key=f"{key[0]}/{key[1]}", release=True):
                result = self._arbitrate(now)
                self._last_pass_gen = self.policy.gen
                self._last_pass_at = now
            await self._apply(result, now)
        await self._elastic_post(now)
        if nb is not None and key in self._auto_resume:
            # An elastic (spot-reclaim/defrag) park: the gang is released
            # and its pods are parking under the stop annotation this
            # reconcile already read — un-park it now so the NEXT
            # reconcile re-queues it (with its aging credit) instead of
            # waiting for a user restart.
            reason, stamp = self._auto_resume[key]
            live_stop = annotations_of(nb).get(nbapi.STOP_ANNOTATION)
            if live_stop != stamp:
                # The stop on the CR is not OURS: the user (or another
                # actor) stopped the gang between the park and this
                # release — an explicit stop the auto-resume must not
                # silently revert. The gang stays parked, and the
                # DURABLE elastic verdict clears too: a controller
                # restart would otherwise read it back and un-park the
                # gang against the user's decision.
                self._auto_resume.pop(key, None)
                self._reclaim_verdict.pop(key, None)
                self._requeue_credit.pop(key, None)
                try:
                    await self.kube.patch(
                        "Notebook", key[1],
                        {"metadata": {"annotations": {
                            nbapi.PREEMPTED_ANNOTATION: None}}}, key[0])
                except ApiError as exc:
                    log.debug("durable Preempted clear for %s/%s after "
                              "a user stop failed (stale verdict may "
                              "survive one restart): %s",
                              key[0], key[1], exc)
            else:
                try:
                    await self.kube.patch(
                        "Notebook", key[1],
                        {"metadata": {"annotations": {
                            nbapi.STOP_ANNOTATION: None,
                            nbapi.PREEMPTED_ANNOTATION: None,
                        }}}, key[0])
                    # kftpu: ignore[await-race] release() runs only from this key's own reconcile (per-key workqueue serialization); the pop races no one
                    self._auto_resume.pop(key, None)
                    self._enqueue(key)
                except ApiError:
                    # Keep the entry and re-raise into workqueue backoff
                    # (the _retry_stop contract): nothing else ever
                    # reconciles a parked gang, so one transient
                    # apiserver error must not silently turn "re-queued
                    # with aging credit" into a permanent park.
                    raise ApiError(
                        f"elastic re-queue un-park for {key[0]}/{key[1]} "
                        f"({reason}) failed; retrying with backoff")
        if key in self._draining:
            # Stopped (or deleted) mid-drain: the release above already
            # freed the chips, so the drain is moot — drop it. The
            # Preempted verdict (stamped at drain time) still reports.
            self._draining.pop(key, None)
            self._refresh_gauges()
        if key in self._preempted:
            return Admission("Preempted", reason=self._preempted[key])
        if nb is not None and alloc is None and not had_queue_entry:
            # Controller restarted since the preemption: the in-memory
            # verdict is gone, but the annotation stamped on the victim
            # survives — keep showing WHY it is stopped. Only a gang that
            # was PARKED when stopped qualifies: one that was queued or
            # admitted at stop time has been re-queued/running since the
            # verdict, so its leftover annotation is stale and this is a
            # plain user stop.
            reason = annotations_of(nb).get(nbapi.PREEMPTED_ANNOTATION)
            if reason in (elastic.SPOT_RECLAIM_REASON,
                          elastic.DEFRAG_REASON):
                # An elastic park interrupted by a restart: the
                # auto-requeue lived only in memory, but the durable
                # verdict says this stop was a reclaim/defrag — finish
                # the migration now instead of leaving the gang parked
                # forever. (The aging credit is lost with the process;
                # the re-queue itself must not be.)
                self._reclaim_verdict[key] = reason
                try:
                    await self.kube.patch(
                        "Notebook", key[1],
                        {"metadata": {"annotations": {
                            nbapi.STOP_ANNOTATION: None,
                            nbapi.PREEMPTED_ANNOTATION: None,
                        }}}, key[0])
                    self._enqueue(key)
                except ApiError:
                    raise ApiError(
                        f"elastic re-queue un-park for "
                        f"{key[0]}/{key[1]} ({reason}) failed after "
                        "restart; retrying with backoff")
                return Admission("Preempted", reason=reason)
            if reason:
                return Admission("Preempted", reason=reason)
        return None

    # ---- serving workload class (kubeflow_tpu/serving) --------------------------

    async def serving_admission(self, key: tuple, ms, *, namespace: str,
                                priority: int = 100, running: bool = False,
                                flex_pool: str | None = None,
                                ) -> Admission | None:
        """Arbitrate one serving replica's gang against the SAME ledger
        and policy queue as every notebook — one chip ledger, one fair
        order, one preemption path (a queued serving replica drains idle
        notebooks through the existing protocol; it is never a victim
        itself — Allocation.workload). Returns None while no fleet is
        known (transparent pass-through, like notebook admission);
        ``running=True`` re-seats a replica whose StatefulSet is already
        live (controller restart) instead of queueing it."""
        if not await self._ensure_fleet():
            return None
        key = tuple(key)
        self._serving_keys.add(key)
        now = self._now()
        await self._sweep_drains(now)
        await self._sweep_spot_reclaims(now)
        result = None
        with span("schedule", key=f"{key[0]}/{key[1]}", workload="serving"):
            if self.policy.is_admitted(key):
                self._state[key] = "Admitted"
                return Admission("Admitted")
            req = GangRequest(
                key=key, namespace=namespace or "",
                accelerator=ms.slice.accelerator.name,
                topology=ms.slice.topology_str,
                num_slices=ms.num_slices, chips=ms.num_chips,
                priority=priority,
                weight=float(self.options.weights.get(namespace, 1.0)),
                submitted_at=now, workload="serving")
            # ``flex_pool`` is the controller's durable borrow marker
            # (stamped per replica on the CR): a flex-placed replica
            # must re-seat as a BORROW across a restart — seating it
            # natively would resell the foreign host under its running
            # pods and flip their node selectors (same contract as the
            # notebook FLEX_POOL_ANNOTATION).
            if running and self.policy.reclaim(
                    req, now, borrow_first=bool(flex_pool),
                    prefer_pool=flex_pool):
                alloc = self.policy.ledger.allocations.get(key)
                if alloc is not None and (
                        alloc.forced
                        or set(alloc.placements)
                        & self.policy.ledger.unavailable):
                    # reclaim() never refuses — but a serving replica
                    # re-seated as overcommit, or back onto a revoked
                    # spot pool, must QUEUE instead: it restores from
                    # its checkpoint wherever capacity really exists,
                    # and pinning it to a dying pool would loop the
                    # spot sweep (release → force-re-admit → release)
                    # forever. Notebooks keep force-reclaim semantics —
                    # their pods hold un-checkpointed state.
                    self.policy.release(key)
                else:
                    self._state[key] = "Admitted"
                    self._refresh_gauges()
                    return Admission("Admitted")
            self.policy.submit(req)
            # Same debounce as notebook admission: identical queue state
            # within the interval serves the snapshot instead of paying
            # another O(queue) arbitration pass.
            if (self.policy.gen == self._last_pass_gen
                    and now - self._last_pass_at
                    < self.options.queued_requeue_seconds):
                queue = self.policy.schedule_preview(now)
            else:
                result = self._arbitrate(now)
                self._last_pass_gen = self.policy.gen
                self._last_pass_at = now
                queue = result.queue
        if result is not None:
            await self._apply(result, now)
        await self._elastic_post(now)
        if self.policy.is_admitted(key):
            return Admission("Admitted")
        info = next((q for q in queue if q.key == key), None)
        self._state[key] = "Queued"
        return Admission(
            "Queued",
            position=info.position if info else 0,
            reason=info.reason if info else "",
            waiting_chips=info.chips if info else ms.num_chips)

    async def serving_release(self, key: tuple) -> None:
        """Give a serving replica's chips back (scale-down, park-to-zero,
        or service deletion) and run the arbitration pass that hands
        them to whoever queues. No preemption verdict bookkeeping — a
        serving replica's lifecycle lives in its controller's status."""
        key = tuple(key)
        if not self.active:
            self._serving_keys.discard(key)
            return
        now = self._now()
        had_queue_entry = key in self.policy.pending
        alloc = self.policy.release(key)
        self._state.pop(key, None)
        if alloc is not None or had_queue_entry:
            with span("schedule", key=f"{key[0]}/{key[1]}", release=True,
                      workload="serving"):
                result = self._arbitrate(now)
                self._last_pass_gen = self.policy.gen
                self._last_pass_at = now
            await self._apply(result, now)
        await self._elastic_post(now)
        self._refresh_gauges()
        self._serving_keys.discard(key)

    # ---- warm pod pools (ISSUE 14, controllers/warmpool.py) ----------------------

    def on_warm_reclaimed(self, cb) -> None:
        """Register the warm-pool manager's teardown callback:
        ``await cb(slot_key)`` whenever a slot's reservation is
        cannibalized (arbitration preemption or spot reclaim)."""
        self._warm_cbs.append(cb)

    async def warm_reserve(self, key: tuple, *, namespace: str,
                           accelerator: str, topology: str) -> bool:
        """Book ONE warm slot's chips in the ledger as a low-priority
        reclaimable reservation. Never queues — pool replenishment is
        opportunistic: no free capacity means no warm pod (the pool
        rebuilds when pressure clears). Idempotent per key. Returns
        False when the slot cannot be backed right now; True also while
        no fleet is known (pass-through, like every admission)."""
        if not await self._ensure_fleet():
            return True
        key = tuple(key)
        if self.policy.is_admitted(key):
            self._warmpool_keys.add(key)
            return True
        try:
            shape = TpuSlice.parse(accelerator, topology)
        except TopologyError:
            return False
        plan = self.policy.ledger.fit(accelerator, topology, 1)
        if plan is None:
            return False
        self.policy.ledger.admit(Allocation(
            key=key, namespace=namespace or "",
            accelerator=accelerator, topology=topology,
            num_slices=1, chips=shape.num_chips, placements=plan,
            priority=WARM_POOL_PRIORITY, admitted_at=self._now(),
            # Epoch-old activity: among warm slots themselves, the
            # victim sort's idle ranking is moot (tier -1 already
            # outranks everything); this just keeps debug rows honest —
            # a warm slot is never "active".
            last_active_at=0.0,
            workload="warmpool",
        ))
        self.policy.gen += 1
        self._warmpool_keys.add(key)
        self._refresh_gauges()
        return True

    async def warm_release(self, key: tuple) -> None:
        """Give a warm slot's chips back (claim consumed the slot, spec
        shrink, pool teardown) and let waiters arbitrate for them."""
        key = tuple(key)
        self._warmpool_keys.discard(key)
        if not self.active:
            return
        if self.policy.release(key) is not None:
            now = self._now()
            with span("schedule", key=f"{key[0]}/{key[1]}", release=True,
                      workload="warmpool"):
                result = self._arbitrate(now)
                self._last_pass_gen = self.policy.gen
                self._last_pass_at = now
            await self._apply(result, now)
            self._refresh_gauges()

    async def _notify_warm_reclaimed(self, key: tuple) -> None:
        for cb in self._warm_cbs:
            try:
                await cb(key)
            except Exception:
                log.exception("warm-pool reclaim callback failed for %s",
                              key)

    # ---- decision application ---------------------------------------------------

    async def _apply(self, result, now: float,
                     requester: dict | None = None) -> None:
        req_key = ((namespace_of(requester), name_of(requester))
                   if requester is not None else None)
        for p in result.preempted:
            with span("preempt", victim=f"{p.key[0]}/{p.key[1]}",
                      reason=p.reason):
                await self._preempt(p, now)
        for p in getattr(result, "drains", ()):
            with span("drain", victim=f"{p.key[0]}/{p.key[1]}",
                      reason=p.reason):
                await self._request_drain(p, now)
        for a in result.admitted:
            with span("admit", key=f"{a.key[0]}/{a.key[1]}"):
                self.m_wait.observe(a.waited)
                # Time-to-admission SLI (runtime/slo.py): the same wait
                # the histogram records, scored against the objective.
                slo.observe("scheduler_time_to_admission", a.waited,
                            key=a.key, trace_id=current_trace_id())
                self._state[a.key] = "Admitted"
                self._requeue_credit.pop(a.key, None)
                self._reclaim_verdict.pop(a.key, None)
                # Serving replicas: no Notebook CR exists under this key
                # — skip the annotation/Event side effects; the enqueue
                # below routes to the serving controller, which owns its
                # own status surface.
                nb = (None if a.key in self._serving_keys
                      else requester if a.key == req_key
                      else await self._get_notebook(a.key))
                if nb is not None:
                    await self._stamp_admitted(nb, now)
                    hint = migration.restore_hint(annotations_of(nb))
                    if hint is not None:
                        # A parked-with-checkpoint gang coming back: the
                        # notebook controller stamps the hint into the
                        # pod env; announce the restore here so the
                        # lifecycle is auditable from Events alone.
                        with span("restore", key=f"{a.key[0]}/{a.key[1]}",
                                  step=hint[1]):
                            await self._event(
                                nb, "Normal", "Restoring",
                                f"Re-admitted; restoring from checkpoint "
                                f"{hint[0]}"
                                + (f" @ step {hint[1]}"
                                   if hint[1] is not None else ""))
                    await self._event(
                        nb, "Normal", "Admitted",
                        f"Admitted by the TPU fleet scheduler after "
                        f"{a.waited:.0f}s "
                        f"(slices: {_fmt_placements(a.placements)})")
                if a.key != req_key:
                    self._enqueue(a.key)
        self._refresh_gauges()

    async def _preempt(self, p, now: float) -> None:
        """Stop-annotate the victim: the notebook reconciler parks the
        whole gang (slice-atomic, replicas 0 everywhere) and its next
        reconcile releases the admission handle. Chips were already
        released in-ledger by the policy, so the beneficiary admits in
        this same pass. A failed stop patch is remembered and retried on
        the victim's next reconcile (``_retry_stop``) — the chips are
        gone from the ledger either way, so the victim MUST park or the
        fleet physically overcommits."""
        if p.key in self._warmpool_keys:
            # A cannibalized warm-pool reservation: no CR to stop and
            # nothing to checkpoint — the chips are already free; hand
            # the slot to the pool manager for (deferred) pod teardown.
            self._warmpool_keys.discard(p.key)
            self.m_preemptions.labels(reason=p.reason).inc()
            await self._notify_warm_reclaimed(p.key)
            return
        ns, name = p.key
        self._preempted[p.key] = p.reason
        self.m_preemptions.labels(reason=p.reason).inc()
        if not await self._stop_victim(p.key, p.reason, now):
            self._stop_pending[p.key] = p.reason
            log.warning("preemption stop patch failed for %s/%s; will "
                        "retry on its next reconcile", ns, name)
        else:
            nb = await self._get_notebook(p.key)
            if nb is not None:
                await self._event(
                    nb, "Warning", "Preempted",
                    f"Preempted ({p.reason}) to reclaim {p.chips} TPU "
                    f"chips for {p.for_key[0]}/{p.for_key[1]}; restart "
                    "to re-queue")
        self._enqueue(p.key)

    # ---- preempt-to-checkpoint (kubeflow_tpu/migration) ------------------------

    async def _request_drain(self, p, now: float, *,
                             requeue: bool = False,
                             annotation: str | None = None,
                             message: str | None = None) -> None:
        """Ask the victim to checkpoint instead of stopping it: stamp the
        drain annotations the in-pod SDK polls, start the grace clock,
        and keep its chips booked (policy marked the allocation draining)
        until :meth:`_finalize_drain` sees the ack or the deadline. The
        preemption verdict is recorded NOW so a victim the user stops
        mid-drain still reports why it parked.

        The elastic paths ride the SAME protocol — ``annotation`` is
        their drain-reason ("spot-reclaim"/"defrag" instead of
        "preempt:<reason>") and ``requeue`` makes the eventual park
        un-park and re-queue the victim instead of waiting for a user
        restart."""
        ns, name = p.key
        annotation = annotation or f"preempt:{p.reason}"
        self._preempted[p.key] = p.reason
        self._draining[p.key] = _Drain(
            reason=p.reason, for_key=p.for_key, chips=p.chips,
            requested_at=now,
            deadline=now + self.options.drain_grace_seconds,
            annotation=annotation, requeue=requeue)
        try:
            await self.kube.patch(
                "Notebook", name,
                {"metadata": {"annotations": migration.request_drain_patch(
                    annotation, now)}}, ns)
        except ApiError:
            # The sweep re-patches a victim whose CR lacks the request
            # mark; if the apiserver stays down past the grace deadline
            # the fallback hard-stop takes over.
            log.warning("drain request patch failed for %s/%s; will "
                        "retry on the next scheduler pass", ns, name)
        nb = await self._get_notebook(p.key)
        if nb is not None:
            await self._event(
                nb, "Warning", "DrainRequested",
                message or (
                    f"Checkpoint requested ({p.reason}) to reclaim "
                    f"{p.chips} TPU chips for "
                    f"{p.for_key[0]}/{p.for_key[1]}; parking once the "
                    f"checkpoint commits (grace "
                    f"{self.options.drain_grace_seconds:.0f}s)"))
        self._enqueue(p.key)

    async def _drain_progress(self, key: tuple, nb: dict,
                              now: float) -> Admission:
        """The draining victim's own reconcile: ack → park with the
        checkpoint; deadline → today's hard stop; otherwise report
        Draining with a requeue that guarantees the deadline fires."""
        drain = self._draining[key]
        ann = annotations_of(nb)
        if migration.drain_requested_at(ann) is None:
            # The request patch never landed (or someone stripped it):
            # re-stamp with the ORIGINAL request time so the grace
            # deadline is unchanged.
            try:
                await self.kube.patch(
                    "Notebook", key[1],
                    {"metadata": {"annotations":
                                  migration.request_drain_patch(
                                      drain.annotation
                                      or f"preempt:{drain.reason}",
                                      drain.requested_at)}}, key[0])
            except ApiError as exc:
                log.debug("drain-request re-stamp for %s/%s failed "
                          "(grace fallback still fires): %s",
                          key[0], key[1], exc)
        elif migration.drain_acked(ann):
            return await self._finalize_drain(key, nb, checkpointed=True,
                                              now=now)
        if now >= drain.deadline:
            return await self._finalize_drain(key, nb, checkpointed=False,
                                              now=now)
        return Admission(
            "Draining", reason=drain.reason,
            requeue_after=max(0.1, drain.deadline - now + 0.05))

    async def _finalize_drain(self, key: tuple, nb: dict | None, *,
                              checkpointed: bool, now: float) -> Admission:
        """End one drain exactly once: count it, stop the victim (keeping
        the checkpoint marks — they are the restore hint), free its
        chips, and run the arbitration pass that admits the waiter."""
        drain = self._draining.pop(key, None)
        if drain is None:  # raced with release()/a concurrent finalize
            return Admission("Preempted",
                             reason=self._preempted.get(key, ""))
        self.m_preemptions.labels(reason=drain.reason).inc()
        if drain.reason == elastic.SPOT_RECLAIM_REASON:
            self.m_spot_reclaims.inc()
        if checkpointed:
            with span("checkpoint_ack", key=f"{key[0]}/{key[1]}",
                      waited=round(now - drain.requested_at, 3)):
                self.m_drain.observe(now - drain.requested_at)
            # Snapshot-then-ack (checkpoint fabric): the ack frees the
            # chips, but the durable upload may still be in flight —
            # watch for the commit mark until the commit grace expires,
            # at which point the park is marked dirty and the drain
            # counted as a fallback after all (satellite: an acked drain
            # whose upload never landed is NOT a clean drain).
            ann_now = annotations_of(nb) if nb is not None else {}
            if migration.checkpoint_committed(ann_now):
                slo.observe("checkpoint_commit", now - drain.requested_at,
                            key=key, trace_id=current_trace_id())
            else:
                self._commit_waits[key] = _CommitWait(
                    reason=drain.reason,
                    requested_at=drain.requested_at,
                    deadline=now + self.options.commit_grace_seconds)
        else:
            self.m_drain_fallback.inc()
        # Drain-roundtrip SLI: ack-less grace fallbacks count as bad
        # events at the full elapsed time — a fleet whose drains always
        # hard-stop is failing its migration promise even though chips
        # were never held hostage.
        slo.observe("drain_roundtrip", now - drain.requested_at,
                    key=key, trace_id=current_trace_id())
        park_stamp = None
        if drain.requeue:
            # Elastic park: once the victim's release path observes the
            # stop, un-park it so it re-queues with its aging credit —
            # the reclaim/defrag took its CAPACITY, not its place in
            # line. The park's stop stamp carries a unique nonce (no
            # consumer parses the value; presence is the contract) so
            # the un-park can tell OUR park from a user's own stop even
            # within the same fmt_iso second.
            self._park_seq = getattr(self, "_park_seq", 0) + 1
            park_stamp = f"{fmt_iso(now)}+park{self._park_seq}"
            alloc = self.policy.ledger.allocations.get(key)
            self._auto_resume[key] = (drain.reason, park_stamp)
            self._reclaim_verdict[key] = drain.reason
            self._requeue_credit[key] = (
                alloc.admitted_at if alloc is not None else now)
        if not await self._stop_victim(
                key, drain.reason, now, stop_value=park_stamp,
                extra=migration.clear_drain_patch(keep_reason=True)):
            # Same contract as an immediate preemption's failed stop:
            # chips are released below regardless, so the victim MUST
            # park — remember it and retry on its next reconcile.
            self._stop_pending[key] = drain.reason
        self.policy.release(key)
        self._state.pop(key, None)
        result = self._arbitrate(now)
        self._last_pass_gen = self.policy.gen
        self._last_pass_at = now
        await self._apply(result, now)
        if nb is not None:
            if checkpointed:
                step = migration.checkpoint_step(annotations_of(nb))
                await self._event(
                    nb, "Normal", "Checkpointed",
                    "Checkpoint committed"
                    + (f" @ step {step}" if step is not None else "")
                    + f"; parking ({drain.reason} preemption)")
            else:
                await self._event(
                    nb, "Warning", "DrainDeadlineExceeded",
                    f"No checkpoint ack within "
                    f"{self.options.drain_grace_seconds:.0f}s; stopped "
                    f"without a checkpoint ({drain.reason} preemption)")
        return Admission("Preempted", reason=drain.reason)

    async def _sweep_commits(self, now: float) -> None:
        """Advance every post-park commit watch: a commit mark closes it
        with a good ``checkpoint_commit`` SLI event; an expired commit
        grace marks the park commit-dirty, counts the drain as a
        fallback, and records the full elapsed time as a bad event.
        Runs with the drain sweep on every admission/release pass."""
        for key, wait in list(self._commit_waits.items()):
            nb = await self._get_notebook(key)
            if self._commit_waits.get(key) is not wait:
                continue  # resolved by a concurrent sweep in the await
            ann = annotations_of(nb) if nb is not None else {}
            if nb is not None and migration.checkpoint_committed(ann):
                # kftpu: ignore[await-race] re-validated after the await: the identity check above skips watches a concurrent sweep already resolved
                self._commit_waits.pop(key, None)
                with span("checkpoint_commit", key=f"{key[0]}/{key[1]}",
                          waited=round(now - wait.requested_at, 3)):
                    slo.observe("checkpoint_commit",
                                now - wait.requested_at,
                                key=key, trace_id=current_trace_id())
                continue
            if now < wait.deadline:
                continue
            self._commit_waits.pop(key, None)
            self.m_drain_fallback.inc()
            # A commit that never landed is a bad event by definition —
            # a short KFTPU_COMMIT_GRACE must not let the timeout slip
            # under the SLI objective and count as a fast commit.
            slo.observe("checkpoint_commit",
                        max(now - wait.requested_at,
                            slo.objective_for("checkpoint_commit")[0]
                            + 0.001),
                        key=key, trace_id=current_trace_id())
            if nb is None:
                continue
            try:
                await self.kube.patch(
                    "Notebook", key[1],
                    {"metadata": {"annotations":
                                  migration.mark_commit_dirty_patch(now)}},
                    key[0])
            except ApiError as exc:
                log.warning("commit-dirty patch for %s/%s failed: %s",
                            key[0], key[1], exc)
            await self._event(
                nb, "Warning", "CheckpointCommitTimeout",
                f"Checkpoint upload did not commit within "
                f"{self.options.commit_grace_seconds:.0f}s of the drain "
                f"request; parked checkpoint marked dirty "
                f"({wait.reason})")

    async def _sweep_drains(self, now: float, skip: tuple | None = None) \
            -> None:
        """Advance every in-flight drain that is not being handled inline
        by its own reconcile: finalize acks, fire expired deadlines, and
        re-patch victims whose request annotation never landed. Runs on
        every admission/release pass, so a waiter's safety-net requeue is
        enough to guarantee deadlines fire."""
        await self._sweep_commits(now)
        for key in list(self._draining):
            if key == skip or key not in self._draining:
                continue
            drain = self._draining[key]
            nb = await self._get_notebook(key)
            if nb is None:
                # CR gone mid-drain: nothing to stop; free the chips and
                # let the waiters arbitrate.
                # kftpu: ignore[await-race] re-validated after every await: the loop re-checks `key in self._draining` per iteration and every pop carries a default
                self._draining.pop(key, None)
                self._auto_resume.pop(key, None)
                if self.policy.release(key) is not None:
                    result = self._arbitrate(now)
                    self._last_pass_gen = self.policy.gen
                    self._last_pass_at = now
                    await self._apply(result, now)
                continue
            ann = annotations_of(nb)
            if nbapi.STOP_ANNOTATION in ann:
                continue  # its own release path owns the cleanup
            if migration.drain_acked(ann):
                await self._finalize_drain(key, nb, checkpointed=True,
                                           now=now)
            elif now >= drain.deadline:
                await self._finalize_drain(key, nb, checkpointed=False,
                                           now=now)
            elif migration.drain_requested_at(ann) is None:
                try:
                    await self.kube.patch(
                        "Notebook", key[1],
                        {"metadata": {"annotations":
                                      migration.request_drain_patch(
                                          drain.annotation
                                          or f"preempt:{drain.reason}",
                                          drain.requested_at)}}, key[0])
                except ApiError as exc:
                    log.debug("drain-request sweep re-stamp for %s/%s "
                              "failed (grace fallback still fires): %s",
                              key[0], key[1], exc)

    # ---- elastic fleet (kubeflow_tpu/scheduler/elastic.py) ----------------------

    @property
    def elastic_active(self) -> bool:
        return self._intent_book is not None and self.active

    def _arbitrate(self, now: float):
        """One full arbitration pass: (elastic) flex overflow first —
        a waiter a free borrowed host can seat must not cost a running
        gang a preemption drain — then the native schedule, then a
        second overflow for gangs whose options the schedule pass just
        changed. Flex admissions ride the result's ``admitted`` list so
        every downstream side effect (stamp, events, re-enqueue) is
        identical to a native admission."""
        flex_pre = (elastic.overflow_pass(self.policy, now)
                    if self.elastic_active else [])
        result = self.policy.schedule(now)
        if flex_pre:
            result.admitted.extend(flex_pre)
        if self.elastic_active:
            flex = elastic.overflow_pass(self.policy, now)
            if flex:
                result.admitted.extend(flex)
        return result

    async def _elastic_post(self, now: float) -> None:
        """The elastic bookkeeping that follows an arbitration pass:
        sync scale-up intents against the queue's shortfalls (create /
        renew / withdraw, mirrored to ProvisioningRequest CRs) and run
        the interval-gated defrag planner. Cheap no-op with elastic off
        or no fleet."""
        if not self.elastic_active:
            return
        # Same debounce as the arbitration pass: shortfall computation
        # and idle-borrower scans are O(queue)/O(allocations) — a long
        # queue's safety-net requeues must not each pay them when
        # nothing changed. TTL renewals and the defrag interval still
        # tick through the one pass per interval this allows.
        if (self.policy.gen == self._last_elastic_gen
                and now - self._last_elastic_at
                < self.options.queued_requeue_seconds):
            return
        async with self._elastic_lock:
            # Re-check under the lock: the pass that held it ahead of us
            # may have just done this generation's work.
            if (self.policy.gen == self._last_elastic_gen
                    and now - self._last_elastic_at
                    < self.options.queued_requeue_seconds):
                return
            # kftpu: ignore[await-race] double-checked locking: the debounce pair is re-read under _elastic_lock right above before this write
            self._last_elastic_gen = self.policy.gen
            # kftpu: ignore[await-race] written with its pair under _elastic_lock after the re-check above
            self._last_elastic_at = now
            await self._sync_intents(now)
            await self._maybe_defrag(now)
            await self._evict_idle_borrowers(now)

    async def _evict_idle_borrowers(self, now: float) -> None:
        """Idle preemption at host granularity: a queued flexible gang
        with no free host to borrow drains the idlest borrower squatting
        on usable hosts (reason ``idle`` — the victim parks like any
        idle-preemption victim; no auto-requeue). One eviction per pass;
        requires migration (the drain path) — with it off, the idle
        culler remains the squatter remedy."""
        if not self.options.enable_preemption \
                or not self.options.enable_migration:
            return
        for req in self.policy._ordered_pending(now):
            victim = elastic.plan_idle_borrower_eviction(
                self.policy, req, now,
                idle_after=self.options.idle_preempt_after_seconds)
            if victim is None or victim.key in self._draining:
                continue
            victim.draining = True
            self.policy.gen += 1
            with span("drain", victim=f"{victim.key[0]}/{victim.key[1]}",
                      reason="idle", flex=True):
                await self._request_drain(
                    Preemption(key=victim.key, reason="idle",
                               for_key=req.key, chips=victim.chips),
                    now)
            return

    async def _sync_intents(self, now: float) -> None:
        book = self._intent_book
        shortfalls = elastic.compute_shortfalls(self.policy, now)
        events = book.sync(shortfalls, self.policy.fleet, now)
        ns = self.options.controller_namespace
        for intent in events.created:
            with span("scale_up", event="created", name=intent.name,
                      slices=intent.slices, chips=intent.chips):
                self.m_scale_up_events.labels(event="created").inc()
                log.info("scale-up intent %s: %d slice(s) / %d chips for "
                         "%s", intent.name, intent.slices, intent.chips,
                         [f"{k[0]}/{k[1]}" for k in intent.for_keys])
                try:
                    await self.kube.create(
                        "ProvisioningRequest",
                        intent.to_provisioning_request(ns), ns)
                except ApiError as exc:
                    # best-effort mirror; the book is the truth
                    log.debug("scale-up intent CR create %s failed: %s",
                              intent.name, exc)
                for key in intent.for_keys:
                    nb = await self._get_notebook(key)
                    if nb is not None:
                        await self._event(
                            nb, "Normal", "ScaleUpRequested",
                            f"No pool can host this gang even if fully "
                            f"drained; asked for {intent.slices} more "
                            f"{intent.accelerator}:{intent.topology} "
                            f"slice(s) ({intent.chips} chips) via "
                            f"ProvisioningRequest {intent.name}")
        for intent in events.renewed:
            with span("scale_up", event="renewed", name=intent.name,
                      renewals=intent.renewals):
                self.m_scale_up_events.labels(event="renewed").inc()
                log.warning(
                    "scale-up intent %s unanswered for %.0fs (renewal "
                    "#%d) — is the pool autoscaler watching?",
                    intent.name, intent.pending_seconds(now),
                    intent.renewals)
                if intent.denied:
                    # "Re-asserts on its TTL" is a promise: replace the
                    # Failed CR with a fresh ask and re-arm denial
                    # detection — otherwise the denial is terminal and
                    # the autoscaler never hears from us again.
                    intent.denied = False
                    try:
                        await self.kube.delete("ProvisioningRequest",
                                               intent.name, ns)
                    except (NotFound, ApiError) as exc:
                        log.debug("denied-intent CR delete %s failed "
                                  "(recreate below may 409): %s",
                                  intent.name, exc)
                    try:
                        await self.kube.create(
                            "ProvisioningRequest",
                            intent.to_provisioning_request(ns), ns)
                    except ApiError as exc:
                        log.debug("denied-intent CR recreate %s failed "
                                  "(re-asserted on the next TTL): %s",
                                  intent.name, exc)
        for intent in events.updated:
            # Keep the CR mirror honest about the current ask size.
            try:
                await self.kube.patch(
                    "ProvisioningRequest", intent.name,
                    {"spec": intent.to_provisioning_request(ns)["spec"]},
                    ns)
            except (NotFound, ApiError) as exc:
                # denial probe / TTL renewal recreate it
                log.debug("scale-up intent CR resize %s failed: %s",
                          intent.name, exc)
        for intent, reason in events.withdrawn:
            with span("scale_up", event=reason, name=intent.name):
                self.m_scale_up_events.labels(event=reason).inc()
                try:
                    await self.kube.delete("ProvisioningRequest",
                                           intent.name, ns)
                except (NotFound, ApiError) as exc:
                    log.debug("withdrawn-intent CR delete %s failed "
                              "(janitor sweeps strays): %s",
                              intent.name, exc)
        if book.intents:
            await self._probe_intent_denials(now)
        elif now >= getattr(self, "_intent_gc_next", 0.0):
            # Stray-intent janitor: the book is in-memory, so a restart
            # can orphan pool-scale-up CRs whose demand died with the
            # old process. With no live intents, sweep ours away
            # (throttled — this is a LIST).
            self._intent_gc_next = now + max(
                5.0, self.options.fleet_refresh_seconds)
            try:
                prs = await self.kube.list("ProvisioningRequest", ns)
            except ApiError:
                prs = []
            for pr in prs:
                labels = ((pr.get("metadata") or {}).get("labels")) or {}
                # OUR intents only — a notebook named pool-scale-up-*
                # has a capacity PR with a matching prefix but no
                # scale-up label; it must not be janitored.
                if keys.TPU_SCALE_UP_ACCELERATOR not in labels:
                    continue
                try:
                    await self.kube.delete("ProvisioningRequest",
                                           name_of(pr), ns)
                except (NotFound, ApiError) as exc:
                    log.debug("stray-intent janitor delete %s failed "
                              "(retried next sweep): %s",
                              name_of(pr), exc)
        self.m_scale_up.set(len(book.intents))

    async def _probe_intent_denials(self, now: float) -> None:
        """Surface a denial: the autoscaler (or an operator) stamped
        Failed=True on an intent's ProvisioningRequest. The intent stays
        in the book — the demand is real — but is marked, evented once,
        and re-asserted on its TTL. Throttled with the fleet refresh so
        pending intents don't add a GET per reconcile."""
        if now < getattr(self, "_denial_next_probe", 0.0):
            return
        self._denial_next_probe = now + max(
            1.0, min(self.options.fleet_refresh_seconds, 5.0))
        ns = self.options.controller_namespace
        for intent in list(self._intent_book.intents.values()):
            if intent.denied:
                continue
            try:
                pr = await self.kube.get_or_none(
                    "ProvisioningRequest", intent.name, ns)
            except ApiError as exc:
                log.debug("denial probe for intent %s failed (retried "
                          "on the next probe throttle): %s",
                          intent.name, exc)
                continue
            conditions = deep_get(pr or {}, "status", "conditions",
                                  default=[]) or []
            failed = next((c for c in conditions
                           if c.get("type") == "Failed"
                           and c.get("status") == "True"), None)
            if failed is None:
                continue
            intent.denied = True
            self.m_scale_up_events.labels(event="denied").inc()
            log.warning("scale-up intent %s denied: %s %s", intent.name,
                        failed.get("reason", ""),
                        failed.get("message", ""))
            for key in intent.for_keys:
                nb = await self._get_notebook(key)
                if nb is not None:
                    await self._event(
                        nb, "Warning", "ScaleUpDenied",
                        f"Pool scale-up {intent.name} was denied "
                        f"({failed.get('reason', '')}); the gang keeps "
                        "waiting and the ask re-asserts on its TTL")

    def flex_node_selectors(self, key: tuple) -> dict | None:
        """Node selectors for a flex (borrowed-host) gang: the HOST
        pool's GKE shape labels, not the gang's own — its own shape has
        no schedulable nodes (that is why it borrowed), so pods carrying
        the native selector would sit Pending while the ledger books the
        borrow. The chip request stays the gang's own (sub-host
        allocation: its chips ≤ the foreign pool's chips per host — the
        flex_plan admission precondition). None for native placements,
        so the common path is untouched."""
        alloc = self.policy.ledger.allocations.get(tuple(key))
        if alloc is None or not alloc.borrowed:
            return None
        pool = self.policy.fleet.by_name(next(iter(alloc.borrow)))
        if pool is None:
            return None
        # Shape labels alone are ambiguous across same-shape pools (the
        # pods could land on a spot pool the ledger didn't book) — pin
        # the exact pool with the nodepool label. Operators name fleet
        # pools after their nodepools; `Fleet.from_nodes` keeps the
        # label value except for shape-disambiguated mixed pools.
        from kubeflow_tpu.scheduler.fleet import GKE_NODEPOOL_LABEL

        return {**pool.slice_shape.node_selectors(),
                GKE_NODEPOOL_LABEL: pool.name}

    def note_node_event(self, node: dict) -> None:
        """Node-informer hook (sync): a reclaim taint on a spot pool's
        node starts that pool's reclaim; the taint clearing withdraws
        that node's signal. Non-spot pools ignore the signal — their
        teardown path is maintenance (the notebook controller's taint
        mirror), not capacity revocation."""
        if self._intent_book is None:
            return
        pool = elastic.pool_of_node(self.policy.fleet, node)
        if pool is None or not pool.spot:
            return
        signal = elastic.node_reclaim_signal(node)
        if signal is not None:
            self.note_spot_reclaim(pool.name, node=name_of(node),
                                   signal=signal)
        else:
            self._clear_node_signal(pool.name, name_of(node))

    def note_node_gone(self, node: dict) -> None:
        """A signaling node was deleted: its revocation is complete.
        The pool re-opens once every signaling node is gone AND the
        residents drained — with a dynamic fleet source the pool itself
        shrinks shortly after."""
        if self._intent_book is None:
            return
        pool = elastic.pool_of_node(self.policy.fleet, node)
        if pool is not None:
            self._clear_node_signal(pool.name, name_of(node))

    def _clear_node_signal(self, pool_name: str, node_name: str) -> None:
        episode = self._spot_reclaims.get(pool_name)
        if episode is None or node_name not in episode["nodes"]:
            return
        episode["nodes"].discard(node_name)
        if not episode["nodes"]:
            log.info("spot pool %s: revocation signal cleared", pool_name)

    def note_spot_reclaim(self, pool_name: str, *, node: str = "manual",
                          signal: str = "reclaim") -> None:
        """Begin (or extend) one spot pool's reclaim — idempotent per
        signaling node. While in progress the pool is UNAVAILABLE (the
        ledger sells none of its capacity, so drained gangs cannot
        bounce straight back onto dying nodes). The actual drains start
        on the next scheduler pass (:meth:`_sweep_spot_reclaims`); every
        resident gang is enqueued so those passes happen now, not at the
        next periodic requeue."""
        pool = self.policy.fleet.by_name(pool_name)
        if pool is None or not pool.spot:
            log.info("ignoring reclaim signal for non-spot pool %r",
                     pool_name)
            return
        episode = self._spot_reclaims.get(pool_name)
        if episode is None:
            episode = {"since": self._now(), "nodes": set()}
            self._spot_reclaims[pool_name] = episode
            self.policy.ledger.unavailable.add(pool_name)
            self.policy.gen += 1
            log.warning("spot pool %s: revocation signal (%s); draining "
                        "resident gangs through checkpoint", pool_name,
                        signal)
        episode["nodes"].add(node)
        for alloc in elastic.reclaimable(self.policy.ledger, pool_name):
            self._enqueue(alloc.key)

    async def _sweep_spot_reclaims(self, now: float) -> None:
        """Start a checkpoint drain for every gang still holding revoked
        spot capacity. Routed through :meth:`_request_drain` — NEVER a
        bare stop — so a revocation is a migration: checkpoint → park →
        re-queue at original priority with aging credit; the drain-grace
        hard stop remains the fallback for ack-less victims."""
        if not self._spot_reclaims:
            return
        for pool_name in list(self._spot_reclaims):
            # Re-validate after the drains awaited below: a concurrent
            # sweep (admission and serving_admission both run this) can
            # finish an episode and pop it while this task is awaiting a
            # drain request — the stale snapshot key would KeyError and
            # fail the whole reconcile into backoff.
            episode = self._spot_reclaims.get(pool_name)
            if episode is None:
                continue
            victims = elastic.reclaimable(self.policy.ledger, pool_name)
            drains_out = not any(d.for_key == ("pool", pool_name)
                                 for d in self._draining.values())
            if self.policy.fleet.by_name(pool_name) is None or (
                    not victims and drains_out
                    and not episode["nodes"]):
                # Episode over: the pool left the fleet, or the
                # revocation signal cleared with every resident drained.
                # Re-open what remains of the pool.
                # kftpu: ignore[await-race] re-validated after every await: the loop re-reads the episode via .get() per iteration (regression test test_concurrent_spot_sweep_survives_episode_removal) and the pop carries a default
                self._spot_reclaims.pop(pool_name, None)
                if pool_name in self.policy.ledger.unavailable:
                    self.policy.ledger.unavailable.discard(pool_name)
                    self.policy.gen += 1
                continue
            if not victims:
                continue  # drained; waiting for the signal to clear
            for alloc in victims:
                if alloc.key in self._draining:
                    continue
                if alloc.workload == "warmpool" \
                        or alloc.key in self._warmpool_keys:
                    # Warm slots on revoked spot capacity: release the
                    # reservation and tear the pod down — a warm pod
                    # must not sit on a dying node, and it holds no
                    # state worth the drain protocol.
                    with span("reclaim", pool=pool_name,
                              victim=f"{alloc.key[0]}/{alloc.key[1]}",
                              workload="warmpool"):
                        self.policy.release(alloc.key)
                        # kftpu: ignore[await-race] discard is idempotent and membership is re-derived per victim from the fresh ledger snapshot
                        self._warmpool_keys.discard(alloc.key)
                        await self._notify_warm_reclaimed(alloc.key)
                    continue
                if alloc.key in self._serving_keys \
                        or isvcapi.parse_replica_key(alloc.key) is not None:
                    # InferenceService REPLICAS (their key carries the
                    # impossible-CR-name "#r" marker, so this never
                    # matches a real Notebook) don't speak the notebook
                    # drain protocol — their durable state is the parked
                    # checkpoint the engine keeps, so a revocation just
                    # releases the booking; the serving controller's
                    # next pass re-admits the replica off the revoked
                    # pool (the ledger already marks it unavailable).
                    # A serving-class NOTEBOOK (workload="serving" but a
                    # real CR) deliberately falls through to the normal
                    # checkpoint drain below — it has state to save and
                    # a CR that speaks the protocol.
                    with span("reclaim", pool=pool_name,
                              victim=f"{alloc.key[0]}/{alloc.key[1]}",
                              workload="serving"):
                        self.m_spot_reclaims.inc()
                        self.policy.release(alloc.key)
                        self._state.pop(alloc.key, None)
                        self._enqueue(alloc.key)
                    continue
                # Chips stay booked while the victim checkpoints, but
                # marked draining: the victim search credits them as
                # incoming-free and never double-picks the gang.
                alloc.draining = True
                self.policy.gen += 1
                with span("reclaim", pool=pool_name,
                          victim=f"{alloc.key[0]}/{alloc.key[1]}"):
                    await self._request_drain(
                        Preemption(key=alloc.key,
                                   reason=elastic.SPOT_RECLAIM_REASON,
                                   for_key=("pool", pool_name),
                                   chips=alloc.chips),
                        now, requeue=True,
                        annotation=elastic.SPOT_RECLAIM_REASON,
                        message=(
                            f"Spot capacity on pool {pool_name} is being "
                            f"revoked; checkpointing now — the gang "
                            f"re-queues at its original priority (grace "
                            f"{self.options.drain_grace_seconds:.0f}s)"))

    async def _maybe_defrag(self, now: float) -> None:
        """Interval-gated defrag pass: migrate idle borrowers off
        pack-breaking pools so a waiting native gang's slices come
        free. Disabled by ``KFTPU_DEFRAG=off``; rate-limited by the
        interval and the per-pass move cap."""
        cfg = self._elastic_cfg
        if not cfg.enable_defrag:
            return
        if now - self._last_defrag_at < cfg.defrag_interval_seconds:
            return
        self._last_defrag_at = now
        moves = elastic.plan_defrag(self.policy, cfg, now)
        for move in moves:
            if move.key in self._draining:
                continue
            alloc = self.policy.ledger.allocations.get(move.key)
            if alloc is None:
                continue
            alloc.draining = True
            self.policy.gen += 1
            with span("defrag", victim=f"{move.key[0]}/{move.key[1]}",
                      source=move.source_pool,
                      waiter=f"{move.for_key[0]}/{move.for_key[1]}"):
                self.m_defrag.inc()
                self._defrag_moves += 1
                await self._request_drain(
                    Preemption(key=move.key,
                               reason=elastic.DEFRAG_REASON,
                               for_key=move.for_key, chips=move.chips),
                    now, requeue=True,
                    annotation=elastic.DEFRAG_REASON,
                    message=(
                        f"Migrating to a pack pool: this notebook's "
                        f"borrowed host on {move.source_pool} blocks a "
                        f"whole slice "
                        f"{move.for_key[0]}/{move.for_key[1]} is waiting "
                        f"for; checkpointing, then re-queueing onto a "
                        f"pool of its own shape"))

    async def _stop_victim(self, key: tuple, reason: str, now: float,
                           extra: dict | None = None,
                           stop_value: str | None = None) -> bool:
        annotations = {
            nbapi.STOP_ANNOTATION: stop_value or fmt_iso(now),
            nbapi.PREEMPTED_ANNOTATION: reason,
        }
        if extra:
            annotations.update(extra)
        try:
            await self.kube.patch(
                "Notebook", key[1],
                {"metadata": {"annotations": annotations}}, key[0])
            return True
        except ApiError:
            return False

    async def _retry_stop(self, key: tuple, now: float) -> Admission:
        reason = self._stop_pending[key]
        # A retried elastic park re-stamps the SAME recorded nonce, so
        # the un-park's user-stop guard still recognizes it as ours.
        recorded = self._auto_resume.get(key)
        if not await self._stop_victim(
                key, reason, now,
                stop_value=recorded[1] if recorded else None,
                extra=migration.clear_drain_patch(keep_reason=True)):
            # Keep failing the reconcile until the patch lands: the
            # workqueue's error backoff is the retry loop. Returning
            # normally here would end retries after this attempt — the
            # manager is event-driven, so an un-parked victim would run
            # forever on chips the ledger already gave away.
            raise ApiError(
                f"preemption stop patch for {key[0]}/{key[1]} failed; "
                "retrying with backoff")
        # kftpu: ignore[await-race] _retry_stop runs only from this key's own reconcile (per-key workqueue serialization); the pop races no one
        self._stop_pending.pop(key, None)
        return Admission("Preempted", reason=reason)

    async def _stamp_admitted(self, nb: dict, now: float) -> None:
        """Persist the admitted-at timestamp: culling clocks idleness from
        it (a gang that queued for hours must not be culled seconds after
        it finally starts), and a controller restart re-reads it. Drain
        marks — including the park's drain-reason marker — clear here:
        an admitted gang is past its park, and a leftover reason would
        make a later plain stop present as a checkpointed park."""
        key = (namespace_of(nb), name_of(nb))
        alloc = self.policy.ledger.allocations.get(key)
        flex_pool = (next(iter(alloc.borrow))
                     if alloc is not None and alloc.borrowed else None)
        try:
            await self.kube.patch(
                "Notebook", name_of(nb),
                {"metadata": {"annotations": {
                    nbapi.SCHEDULER_ADMITTED_AT_ANNOTATION: fmt_iso(now),
                    nbapi.PREEMPTED_ANNOTATION: None,
                    # Durable borrow marker: a restart must re-seat a
                    # flex gang as a BORROW, not natively.
                    nbapi.FLEX_POOL_ANNOTATION: flex_pool,
                    **migration.clear_drain_patch(),
                }}}, namespace_of(nb))
        except ApiError as exc:
            # best-effort; the in-memory admitted_at still ranks, and
            # the holder's next reconcile self-heals the stamp
            log.debug("admitted-at stamp for %s failed: %s", key, exc)

    async def _get_notebook(self, key: tuple) -> dict | None:
        ns, name = key
        if self._nb_informer is not None:
            nb = self._nb_informer.get(name, ns)
            if nb is not None:
                return nb
        try:
            return await self.kube.get_or_none("Notebook", name, ns)
        except ApiError:
            return None

    async def _event(self, nb: dict, type_: str, reason: str,
                     message: str) -> None:
        try:
            await self.recorder.event(nb, type_, reason, message)
        except Exception:
            # Events are best-effort BY CONTRACT; the recorder only
            # counts API-level swallows, so count this one ourselves.
            self.recorder.count_drop()

    def _refresh_gauges(self) -> None:
        self.m_queue_depth.set(len(self.policy.pending))
        self.m_draining.set(len(self._draining))
        ns_chips = self.policy.ledger.ns_chips
        for ns in self._gauge_ns - set(ns_chips):
            self.m_admitted_ns.labels(namespace=ns or "").set(0)
        for ns, chips in ns_chips.items():
            self.m_admitted_ns.labels(namespace=ns or "").set(chips)
        self._gauge_ns = set(ns_chips)
        by_pool = self.policy.ledger.admitted_chips_by_pool()
        for pool in self._gauge_pools - set(by_pool):
            self.m_admitted_pool.labels(pool=pool).set(0)
        for pool, chips in by_pool.items():
            self.m_admitted_pool.labels(pool=pool).set(chips)
        self._gauge_pools = set(by_pool)
        borrowed = self.policy.ledger.borrowed
        for pool in self._gauge_borrow_pools - set(borrowed):
            self.m_borrowed.labels(pool=pool).set(0)
        for pool, hosts in borrowed.items():
            self.m_borrowed.labels(pool=pool).set(hosts)
        self._gauge_borrow_pools = set(borrowed)

    def note_telemetry(self, key: tuple, family: str, mfu) -> None:
        """Feed the efficiency ledger one telemetry window (the notebook
        controller dedups on the annotation's publish seq before calling
        this). Shape is derived from the gang's own allocation
        (accelerator:topology) so the family prior keys match the shapes
        explain/queue reports; keys the ledger doesn't hold are ignored
        — telemetry from a gang mid-release carries no signal."""
        key = tuple(key)
        alloc = self.policy.ledger.allocations.get(key)
        if alloc is None:
            return
        shape = f"{alloc.accelerator}:{alloc.topology}"
        self.policy.note_efficiency(key, family, shape, mfu)

    # ---- introspection ----------------------------------------------------------

    def debug_info(self) -> dict:
        now = self._now()
        info = self.policy.debug_info(now)
        info["active"] = self.active
        info["arbiter"] = self.arbiter
        info["fleet_source"] = (
            "explicit" if self.options.fleet_spec
            and self.options.fleet_spec != "auto"
            else ("nodes" if self.options.fleet_spec == "auto"
                  else ("configmap" if self.options.fleet_configmap
                        else "none")))
        info["preempted"] = {
            f"{k[0]}/{k[1]}": reason for k, reason in self._preempted.items()
        }
        info["migration_enabled"] = self.options.enable_migration
        info["elastic"] = {
            "enabled": self._intent_book is not None,
            "defrag_enabled": (self._intent_book is not None
                               and self._elastic_cfg.enable_defrag),
            "scale_up_intents": (
                self._intent_book.debug_rows(now)
                if self._intent_book is not None else []),
            "spot_reclaims_in_progress": {
                pool: {
                    "for_sec": round(now - episode["since"], 3),
                    "signaling_nodes": sorted(episode["nodes"]),
                }
                for pool, episode in sorted(self._spot_reclaims.items())
            },
            "defrag_moves_total": self._defrag_moves,
            "requeued": {
                f"{k[0]}/{k[1]}": reason
                for k, reason in sorted(self._reclaim_verdict.items())
            },
        }
        info["draining"] = {
            f"{k[0]}/{k[1]}": {
                "reason": d.reason,
                "for": f"{d.for_key[0]}/{d.for_key[1]}",
                "chips": d.chips,
                "deadline_in_sec": round(d.deadline - now, 3),
            }
            for k, d in self._draining.items()
        }
        return info

    def explain(self, key: tuple) -> dict:
        """Scheduler explainability (/debug/scheduler/explain): the pure
        policy explanation (queue position, rank breakdown, blocking
        shape, feasible-if-drained candidates, starvation-door state)
        plus the runtime-only context — the in-flight drain, the pending
        scale-up intent's age, the elastic re-queue verdict, and any
        failed-stop retry. Read-only."""
        key = tuple(key)
        now = self._now()
        if not self.active:
            return {"state": "Inactive",
                    "reason": "no fleet configured — every admission "
                              "passes through"}
        out = self.policy.explain(key, now)
        out["key"] = f"{key[0]}/{key[1]}"
        drain = self._draining.get(key)
        if drain is not None:
            out["drain"] = {
                "reason": drain.reason,
                "for": f"{drain.for_key[0]}/{drain.for_key[1]}",
                "requested_at": drain.requested_at,
                "deadline_in_sec": round(drain.deadline - now, 3),
                "auto_requeue": drain.requeue,
            }
        if key in self._stop_pending:
            out["stop_pending"] = self._stop_pending[key]
        if key in self._preempted:
            out["preempted_reason"] = self._preempted[key]
        if key in self._reclaim_verdict:
            out["reclaimed"] = self._reclaim_verdict[key]
        if key in self._requeue_credit:
            out["requeue_credit_seconds"] = round(
                now - self._requeue_credit[key], 3)
        if self._intent_book is not None and out.get("blocking_shape"):
            acc, _, topo = out["blocking_shape"].partition(":")
            intent = self._intent_book.for_shape(acc, topo)
            if intent is not None:
                out["scale_up_intent"] = {
                    "name": intent.name,
                    "chips": intent.chips,
                    "slices": intent.slices,
                    "pending_seconds": round(
                        intent.pending_seconds(now), 3),
                    "renewals": intent.renewals,
                    "denied": intent.denied,
                    "for_this_gang": key in intent.for_keys,
                }
        return out


def _fmt_placements(placements: dict) -> str:
    return ", ".join(f"{pool}x{n}" for pool, n in sorted(placements.items()))
