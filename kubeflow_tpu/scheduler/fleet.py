"""Fleet model + chip ledger for the TPU fleet scheduler.

A **fleet** is the cluster's TPU inventory as node pools. Each pool hosts
slices of exactly one shape — GKE TPU node pools are created per
``(accelerator, topology)`` and their nodes carry the matching
``cloud.google.com/gke-tpu-*`` labels, so a slice of shape S can only ever
land on a pool of shape S. The schedulable unit is therefore a **slice of
the pool's shape**, and a pool's capacity is counted in slices.

The **ledger** tracks which gang (one Notebook's full MultiSlice) holds
which slices, with two hard invariants the property tests in
``tests/test_scheduler.py`` drive:

- *capacity*: admitted slices per pool never exceed the pool's capacity;
- *gang atomicity*: an allocation is always the request's whole slice set
  — there is no code path that records a partial gang.

Everything here is pure (no Kubernetes imports, no clock, no I/O) so the
policy core above it stays deterministic and property-testable.

Fleet sources, in the order the runtime tries them:

- ``KFTPU_FLEET`` env: ``pool-a=v5e:4x4:2,pool-b=v5p:2x2x1:4``
  (``<name>=<accelerator>:<topology>:<num-slices>[:spot]`` — the
  optional 4th field marks a reclaimable spot/preemptible pool);
- a ConfigMap with the same format under ``data["fleet"]``
  (``KFTPU_FLEET_CONFIGMAP``, loaded by the runtime);
- ``KFTPU_FLEET=auto``: inferred from Node objects' GKE TPU labels
  (``from_nodes``) — one pool per ``cloud.google.com/gke-nodepool``;
  nodes carrying ``cloud.google.com/gke-spot=true`` mark their pool
  spot.

Elastic extension (kubeflow_tpu/scheduler/elastic.py): with
``KFTPU_ELASTIC`` on, a single-host gang that fits no pool of its own
shape may *borrow* a host from a same-accelerator pool of a larger
shape. Borrowed hosts are tracked host-granular (``ChipLedger.
borrowed``); each pool's borrowed hosts break ``ceil(borrowed /
hosts_per_slice)`` whole slices out of its native capacity — that is
the fragmentation the defragmenter exists to undo. With no borrows the
accounting below is bit-identical to the pre-elastic ledger.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

from kubeflow_tpu.tpu.topology import (
    ACCELERATORS,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    TopologyError,
    TpuSlice,
)

GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"
# GKE's well-known spot/preemptible marker on Nodes.
GKE_SPOT_LABEL = "cloud.google.com/gke-spot"

# Pool names feed metric labels, debug rows, and (auto mode) come from
# nodepool names — hold them to the same DNS-1123-ish contract so a typo
# like "pool a" or an empty name fails at parse time, not as a confusing
# ledger key later.
_POOL_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9._]*[a-z0-9])?$",
                           re.IGNORECASE)

# gke_accelerator label value → our short accelerator name ("v5e", ...).
_GKE_TO_NAME = {acc.gke_accelerator: acc.name for acc in ACCELERATORS.values()}


class FleetConfigError(ValueError):
    """Malformed fleet specification."""


class LedgerError(RuntimeError):
    """A ledger invariant would be violated (admitted > capacity, double
    admission, partial release). Raised, never swallowed — the policy layer
    must make these impossible; the bench counts raises (must be zero)."""


@dataclass(frozen=True)
class NodePool:
    """One TPU node pool: ``num_slices`` slices of one shape. ``spot``
    marks reclaimable (preemptible) capacity: the elastic runtime drains
    its gangs through the checkpoint protocol when a revocation signal
    lands, instead of letting the node teardown kill work in flight."""

    name: str
    accelerator: str       # short name: v4 | v5e | v5p | v6e
    topology: str          # slice chip grid, e.g. "4x4"
    num_slices: int
    spot: bool = False

    def __post_init__(self):
        if not self.name or not _POOL_NAME_RE.match(self.name):
            raise FleetConfigError(
                f"bad pool name {self.name!r}: pool names must be "
                "non-empty and use only letters, digits, '-', '_', '.' "
                "(they become ledger keys, metric labels and nodepool "
                "references)")
        if self.num_slices < 1:
            raise FleetConfigError(
                f"pool {self.name}: num_slices must be >= 1, "
                f"got {self.num_slices}")
        # Validates accelerator/topology; raises TopologyError on garbage.
        TpuSlice.parse(self.accelerator, self.topology)

    @property
    def slice_shape(self) -> TpuSlice:
        return TpuSlice.parse(self.accelerator, self.topology)

    @property
    def chips_per_slice(self) -> int:
        return self.slice_shape.num_chips

    @property
    def hosts_per_slice(self) -> int:
        return self.slice_shape.num_hosts

    @property
    def chips_per_host(self) -> int:
        return self.slice_shape.chips_per_host

    @property
    def total_chips(self) -> int:
        return self.num_slices * self.chips_per_slice

    @property
    def shape_key(self) -> tuple[str, str]:
        return (self.accelerator.lower(), self.topology.lower())


@dataclass(frozen=True)
class Fleet:
    """Immutable pool inventory, keyed by pool name."""

    pools: tuple[NodePool, ...] = ()

    @classmethod
    def parse(cls, spec: str) -> "Fleet":
        """``pool-a=v5e:4x4:2,pool-b=v5p:2x2x1:4:spot`` → Fleet. Empty/
        None spec → empty fleet (scheduler passes everything through).
        The optional 4th field marks a spot (reclaimable) pool.

        Duplicate pool names are a hard error, not last-wins: the ledger
        resolves placements by name, so two entries under one name would
        silently sell one pool's capacity twice. The error names both
        entry positions so the operator can find the clash in a long
        spec."""
        pools: list[NodePool] = []
        seen: dict[str, int] = {}   # pool name → 1-based entry position
        position = 0
        for raw in (spec or "").replace("\n", ",").split(","):
            entry = raw.strip()
            if not entry:
                continue
            position += 1
            name, sep, shape = entry.partition("=")
            parts = shape.split(":")
            if not sep or len(parts) not in (3, 4):
                raise FleetConfigError(
                    f"bad fleet entry {entry!r}: want "
                    "<name>=<accelerator>:<topology>:<num-slices>[:spot]")
            acc, topo, n = (p.strip() for p in parts[:3])
            spot = False
            if len(parts) == 4:
                flag = parts[3].strip().lower()
                if flag == "spot":
                    spot = True
                elif flag not in ("", "reserved", "on-demand"):
                    raise FleetConfigError(
                        f"bad fleet entry {entry!r}: unknown pool flag "
                        f"{parts[3].strip()!r} — the 4th field is 'spot' "
                        "(reclaimable capacity) or omitted")
            try:
                num = int(n)
            except ValueError:
                raise FleetConfigError(
                    f"bad fleet entry {entry!r}: slice count {n!r} is not "
                    "an integer") from None
            name = name.strip()
            if name in seen:
                raise FleetConfigError(
                    f"duplicate pool name {name!r} (entries {seen[name]} "
                    f"and {position}): each pool must appear exactly once "
                    "— merge the slice counts into one entry or rename "
                    "one of the pools")
            seen[name] = position
            try:
                pools.append(NodePool(name, acc.lower(), topo.lower(), num,
                                      spot=spot))
            except TopologyError as e:
                raise FleetConfigError(f"bad fleet entry {entry!r}: {e}") \
                    from None
        return cls(pools=tuple(sorted(pools, key=lambda p: p.name)))

    @classmethod
    def from_nodes(cls, nodes: list[dict]) -> "Fleet":
        """Infer pools from Node objects' GKE TPU labels: hosts sharing a
        ``gke-nodepool`` label and a TPU shape form one pool; its slice
        count is ``hosts // hosts_per_slice`` (partial slices can never
        schedule a gang, so they don't count)."""
        hosts: dict[tuple[str, str, str], int] = {}
        spot_pools: set[str] = set()
        for node in nodes:
            labels = ((node.get("metadata") or {}).get("labels")) or {}
            gke_acc = labels.get(GKE_TPU_ACCELERATOR_LABEL)
            topo = labels.get(GKE_TPU_TOPOLOGY_LABEL)
            acc = _GKE_TO_NAME.get(gke_acc or "")
            if not acc or not topo:
                continue
            pool = labels.get(GKE_NODEPOOL_LABEL) or f"{acc}-{topo}"
            hosts[(pool, acc, topo.lower())] = \
                hosts.get((pool, acc, topo.lower()), 0) + 1
            if labels.get(GKE_SPOT_LABEL) == "true":
                # ANY spot node marks the pool spot: treating a mixed
                # pool as reclaimable errs toward draining through the
                # checkpoint protocol — the safe direction.
                spot_pools.add(pool)
        # A nodepool label carrying two TPU shapes (mid-migration label
        # drift) must not yield two same-named pools: the ledger resolves
        # placements by name, and the collision would make every admit of
        # the second shape a LedgerError. Disambiguate with the shape —
        # but count only shapes that survive the whole-slice/parse
        # filters: a stray partial-slice or unparsable shape must not
        # rename the real pool (the rename would look like a fleet change
        # and rebind-churn every allocation booked on it).
        survivors = []
        name_shapes: dict[str, int] = {}
        for (pool, acc, topo), count in sorted(hosts.items()):
            try:
                per_slice = TpuSlice.parse(acc, topo).num_hosts
            except TopologyError:
                continue
            num_slices = count // per_slice
            if num_slices >= 1:
                survivors.append((pool, acc, topo, num_slices))
                name_shapes[pool] = name_shapes.get(pool, 0) + 1
        pools = []
        for pool, acc, topo, num_slices in survivors:
            name = (f"{pool}-{acc}-{topo}" if name_shapes[pool] > 1
                    else pool)
            try:
                pools.append(NodePool(name, acc, topo, num_slices,
                                      spot=pool in spot_pools))
            except FleetConfigError:
                # A garbage nodepool label must not wedge fleet
                # inference for the healthy pools.
                continue
        return cls(pools=tuple(pools))

    def by_name(self, name: str) -> NodePool | None:
        for p in self.pools:
            if p.name == name:
                return p
        return None

    def matching(self, accelerator: str, topology: str) -> list[NodePool]:
        """Pools that can host slices of this shape, name-sorted (the
        deterministic allocation order)."""
        key = (accelerator.lower(), topology.lower())
        return [p for p in self.pools if p.shape_key == key]

    def total_slices(self, accelerator: str, topology: str) -> int:
        """Whole-fleet ceiling for one shape — the webhook's can-never-fit
        check compares a gang's num_slices against this."""
        return sum(p.num_slices for p in self.matching(accelerator, topology))

    @property
    def total_chips(self) -> int:
        return sum(p.total_chips for p in self.pools)


@dataclass
class Allocation:
    """One admitted gang: the notebook's FULL slice set, spread over
    matching pools. ``placements`` maps pool name → slices taken there;
    its values always sum to the request's num_slices (gang atomicity —
    checked at admit time and by ``ChipLedger.assert_consistent``).

    Elastic flex placement (``borrow``): a single-host gang seated on a
    same-accelerator pool of a DIFFERENT shape occupies whole hosts, not
    slices — ``borrow`` maps pool name → hosts and ``placements`` is
    empty. Gang atomicity then means the borrow hosts sum to the gang's
    host count."""

    key: tuple              # (namespace, name)
    namespace: str
    accelerator: str
    topology: str
    num_slices: int
    chips: int
    placements: dict[str, int]
    priority: int = 0
    admitted_at: float = 0.0
    # Culling's last-activity signal (idle-preemption ranking); None means
    # "no probe data yet" and is never treated as idle.
    last_active_at: float | None = None
    # True for a gang force-admitted by reclaim() over a fleet that no
    # longer has room for it (controller restart after the fleet shrank):
    # its pods exist, so the ledger records it as a deliberate overcommit
    # and assert_consistent exempts its pools from the capacity check.
    forced: bool = False
    # Deferred preemption (kubeflow_tpu/migration): a drain was requested
    # for this gang — it still holds its chips while it checkpoints, but
    # the victim search treats its capacity as incoming-free (no second
    # gang is drained for slices already on their way out) and never
    # re-picks it as a victim.
    draining: bool = False
    # Elastic flex placement: pool → borrowed hosts (see class docstring).
    # None/empty for every native (slice-granular) allocation.
    borrow: dict[str, int] | None = None
    # Workload class ("notebook" | "serving", kubeflow_tpu/serving):
    # serving replicas are never preemption victims — no notebook
    # activity signal exists for them, so the idle heuristic would
    # misread a loaded service as idle, and their capacity is the
    # serving autoscaler's to give back. Default keeps the pre-serving
    # ledger bit-identical.
    workload: str = "notebook"

    @property
    def borrowed(self) -> bool:
        return bool(self.borrow)


@dataclass
class ChipLedger:
    """Admitted-vs-free accounting over a Fleet. All mutation goes through
    ``admit``/``release``; both enforce the invariants and raise
    LedgerError (counted in ``violations``) rather than record a bad
    state."""

    fleet: Fleet
    used: dict[str, int] = field(default_factory=dict)        # pool → slices
    allocations: dict[tuple, Allocation] = field(default_factory=dict)
    ns_chips: dict[str, int] = field(default_factory=dict)    # ns → chips
    # Elastic flex placement: pool → hosts borrowed by foreign-shape
    # single-host gangs. Empty (and the accounting below bit-identical
    # to pre-elastic) unless the elastic pass admits borrows.
    borrowed: dict[str, int] = field(default_factory=dict)
    # Pools that must sell NOTHING right now: a spot pool mid-reclaim
    # (its nodes carry a revocation signal) offers zero free slices and
    # zero borrowable hosts until the signal clears or the fleet source
    # drops the pool. Existing holders keep their booking — the drain
    # protocol vacates them. Empty unless the elastic runtime marks it.
    unavailable: set = field(default_factory=set)
    violations: int = 0

    def broken_slices(self, pool: NodePool) -> int:
        """Whole native slices a pool's borrowed hosts put out of
        service. Borrowers are packed onto the fewest slices, so the
        breakage is the ceiling, not one slice per borrower."""
        hosts = self.borrowed.get(pool.name, 0)
        return math.ceil(hosts / pool.hosts_per_slice) if hosts else 0

    def free_slices(self, pool: NodePool) -> int:
        if pool.name in self.unavailable:
            return 0
        return pool.num_slices - self.used.get(pool.name, 0) \
            - self.broken_slices(pool)

    def free_hosts(self, pool: NodePool) -> int:
        """Hosts available for elastic borrowing: everything not under a
        native slice allocation and not already borrowed."""
        if pool.name in self.unavailable:
            return 0
        native_hosts = self.used.get(pool.name, 0) * pool.hosts_per_slice
        return pool.num_slices * pool.hosts_per_slice - native_hosts \
            - self.borrowed.get(pool.name, 0)

    def fit(self, accelerator: str, topology: str,
            num_slices: int) -> dict[str, int] | None:
        """All-or-nothing placement plan for a gang: spread num_slices
        over matching pools in name order, or None if the whole gang
        cannot fit right now. Never returns a partial plan."""
        plan: dict[str, int] = {}
        remaining = num_slices
        for pool in self.fleet.matching(accelerator, topology):
            if remaining == 0:
                break
            take = min(self.free_slices(pool), remaining)
            if take > 0:
                plan[pool.name] = take
                remaining -= take
        return plan if remaining == 0 else None

    def borrow_fit(self, accelerator: str, topology: str,
                   *, avoid_new_break_shapes: frozenset = frozenset(),
                   prefer: str | None = None) -> dict | None:
        """Host-borrow plan (``{pool: 1}``) for ONE single-host slice of
        this shape — the elastic flex unit. Same-accelerator pools of a
        DIFFERENT shape with a free host and enough chips per host;
        prefers a pool where the borrow breaks no NEW slice (pack
        borrowers together), then name order. Pools whose native shape
        is in ``avoid_new_break_shapes`` accept no new breakage. None
        for multi-host or multi-slice shapes — a foreign pool can host a
        whole single-host slice, never a split ICI mesh."""
        try:
            shape = TpuSlice.parse(accelerator, topology)
        except TopologyError:
            return None
        if shape.num_hosts != 1:
            return None
        candidates = []
        for pool in self.fleet.pools:
            if pool.shape_key == (accelerator.lower(), topology.lower()):
                continue
            if pool.accelerator.lower() != accelerator.lower():
                continue
            if pool.chips_per_host < shape.chips_per_host:
                continue
            if self.free_hosts(pool) < 1:
                continue
            borrowed = self.borrowed.get(pool.name, 0)
            breaks = math.ceil((borrowed + 1) / pool.hosts_per_slice) \
                > math.ceil(borrowed / pool.hosts_per_slice)
            if breaks and pool.shape_key in avoid_new_break_shapes:
                continue
            # ``prefer`` (a restart's durable flex-pool hint) outranks
            # the no-new-break preference: the pods are already THERE.
            candidates.append((pool.name != prefer, breaks, pool.name))
        if not candidates:
            return None
        candidates.sort()
        return {candidates[0][-1]: 1}

    def admit(self, alloc: Allocation, *, force: bool = False) -> None:
        """Record one whole gang. ``force=True`` is the reclaim path
        (controller restart over a fleet that no longer has room): the
        per-pool capacity check — and ONLY it — is skipped, because the
        gang's pods already run; gang atomicity and no-double-admit
        still hold. The allocation is marked ``forced`` so
        ``assert_consistent`` treats the resulting over-capacity pools
        as overcommit, not as ledger drift; it drains on release."""
        if alloc.key in self.allocations:
            self.violations += 1
            raise LedgerError(f"{alloc.key} is already admitted")
        if alloc.borrowed:
            self._admit_borrow(alloc)
            return
        if sum(alloc.placements.values()) != alloc.num_slices:
            self.violations += 1
            raise LedgerError(
                f"{alloc.key}: partial gang ({alloc.placements} vs "
                f"{alloc.num_slices} slices) — gangs admit all-or-nothing")
        if force:
            alloc.forced = True
        else:
            for pool_name, n in alloc.placements.items():
                pool = self.fleet.by_name(pool_name)
                if pool is None or pool.shape_key != (
                        alloc.accelerator.lower(), alloc.topology.lower()):
                    self.violations += 1
                    raise LedgerError(
                        f"{alloc.key}: placement on unknown/mismatched "
                        f"pool {pool_name!r}")
                if self.used.get(pool_name, 0) + n > \
                        pool.num_slices - self.broken_slices(pool):
                    self.violations += 1
                    raise LedgerError(
                        f"{alloc.key}: pool {pool_name} over capacity "
                        f"({self.used.get(pool_name, 0)}+{n} > "
                        f"{pool.num_slices} slices, "
                        f"{self.broken_slices(pool)} broken by borrows)")
        for pool_name, n in alloc.placements.items():
            self.used[pool_name] = self.used.get(pool_name, 0) + n
        self.allocations[alloc.key] = alloc
        self.ns_chips[alloc.namespace] = \
            self.ns_chips.get(alloc.namespace, 0) + alloc.chips

    def _admit_borrow(self, alloc: Allocation) -> None:
        """Record an elastic flex (host-borrowing) gang. The invariants
        mirror the native path at host granularity: the borrow set must
        cover the gang's whole host count (atomicity), land on known
        same-accelerator pools, and fit the pools' free hosts."""
        shape = TpuSlice.parse(alloc.accelerator, alloc.topology)
        want_hosts = shape.num_hosts * alloc.num_slices
        if sum(alloc.borrow.values()) != want_hosts:
            self.violations += 1
            raise LedgerError(
                f"{alloc.key}: partial borrow ({alloc.borrow} vs "
                f"{want_hosts} host(s)) — gangs admit all-or-nothing")
        for pool_name, hosts in alloc.borrow.items():
            pool = self.fleet.by_name(pool_name)
            if pool is None \
                    or pool.accelerator.lower() != alloc.accelerator.lower():
                self.violations += 1
                raise LedgerError(
                    f"{alloc.key}: borrow on unknown/mismatched pool "
                    f"{pool_name!r}")
            if hosts > self.free_hosts(pool):
                self.violations += 1
                raise LedgerError(
                    f"{alloc.key}: pool {pool_name} has "
                    f"{self.free_hosts(pool)} free host(s), borrow wants "
                    f"{hosts}")
        for pool_name, hosts in alloc.borrow.items():
            self.borrowed[pool_name] = \
                self.borrowed.get(pool_name, 0) + hosts
        self.allocations[alloc.key] = alloc
        self.ns_chips[alloc.namespace] = \
            self.ns_chips.get(alloc.namespace, 0) + alloc.chips

    def release(self, key: tuple) -> Allocation | None:
        alloc = self.allocations.pop(key, None)
        if alloc is None:
            return None
        for pool_name, hosts in (alloc.borrow or {}).items():
            left = self.borrowed.get(pool_name, 0) - hosts
            if left < 0:
                self.violations += 1
                raise LedgerError(
                    f"{key}: releasing more borrowed hosts than admitted "
                    f"on {pool_name}")
            if left:
                self.borrowed[pool_name] = left
            else:
                self.borrowed.pop(pool_name, None)
        for pool_name, n in alloc.placements.items():
            left = self.used.get(pool_name, 0) - n
            if left < 0:
                self.violations += 1
                raise LedgerError(
                    f"{key}: releasing more slices than admitted on "
                    f"{pool_name}")
            if left:
                self.used[pool_name] = left
            else:
                self.used.pop(pool_name, None)
        left_chips = self.ns_chips.get(alloc.namespace, 0) - alloc.chips
        if left_chips:
            self.ns_chips[alloc.namespace] = left_chips
        else:
            self.ns_chips.pop(alloc.namespace, None)
        return alloc

    def admitted_chips_by_pool(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for pool in self.fleet.pools:
            chips = self.used.get(pool.name, 0) * pool.chips_per_slice \
                + self.borrowed.get(pool.name, 0) * pool.chips_per_host
            if chips:
                out[pool.name] = chips
        return out

    def assert_consistent(self) -> None:
        """Recompute used/borrowed/ns_chips from the allocations and
        compare — the property test calls this after every step."""
        used: dict[str, int] = {}
        borrowed: dict[str, int] = {}
        ns: dict[str, int] = {}
        for alloc in self.allocations.values():
            if alloc.borrowed:
                for pool_name, hosts in alloc.borrow.items():
                    borrowed[pool_name] = borrowed.get(pool_name, 0) + hosts
                ns[alloc.namespace] = \
                    ns.get(alloc.namespace, 0) + alloc.chips
                continue
            if sum(alloc.placements.values()) != alloc.num_slices:
                raise LedgerError(f"{alloc.key}: partial gang recorded")
            for pool_name, n in alloc.placements.items():
                used[pool_name] = used.get(pool_name, 0) + n
            ns[alloc.namespace] = ns.get(alloc.namespace, 0) + alloc.chips
        if used != self.used or ns != self.ns_chips \
                or borrowed != self.borrowed:
            raise LedgerError(
                f"ledger drift: used {self.used} vs {used}, borrowed "
                f"{self.borrowed} vs {borrowed}, "
                f"ns_chips {self.ns_chips} vs {ns}")
        # Pools carrying a force-admitted (reclaimed-with-overcommit)
        # gang are legitimately over capacity until it releases.
        forced_pools = {
            pool_name
            for alloc in self.allocations.values() if alloc.forced
            for pool_name in alloc.placements
        }
        for pool in self.fleet.pools:
            if pool.name in forced_pools:
                continue
            if used.get(pool.name, 0) + self.broken_slices(pool) \
                    > pool.num_slices:
                raise LedgerError(
                    f"pool {pool.name} over capacity: "
                    f"{used.get(pool.name, 0)} native + "
                    f"{self.broken_slices(pool)} borrow-broken > "
                    f"{pool.num_slices}")
