"""Fleet model + chip ledger for the TPU fleet scheduler.

A **fleet** is the cluster's TPU inventory as node pools. Each pool hosts
slices of exactly one shape — GKE TPU node pools are created per
``(accelerator, topology)`` and their nodes carry the matching
``cloud.google.com/gke-tpu-*`` labels, so a slice of shape S can only ever
land on a pool of shape S. The schedulable unit is therefore a **slice of
the pool's shape**, and a pool's capacity is counted in slices.

The **ledger** tracks which gang (one Notebook's full MultiSlice) holds
which slices, with two hard invariants the property tests in
``tests/test_scheduler.py`` drive:

- *capacity*: admitted slices per pool never exceed the pool's capacity;
- *gang atomicity*: an allocation is always the request's whole slice set
  — there is no code path that records a partial gang.

Everything here is pure (no Kubernetes imports, no clock, no I/O) so the
policy core above it stays deterministic and property-testable.

Fleet sources, in the order the runtime tries them:

- ``KFTPU_FLEET`` env: ``pool-a=v5e:4x4:2,pool-b=v5p:2x2x1:4``
  (``<name>=<accelerator>:<topology>:<num-slices>``);
- a ConfigMap with the same format under ``data["fleet"]``
  (``KFTPU_FLEET_CONFIGMAP``, loaded by the runtime);
- ``KFTPU_FLEET=auto``: inferred from Node objects' GKE TPU labels
  (``from_nodes``) — one pool per ``cloud.google.com/gke-nodepool``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeflow_tpu.tpu.topology import (
    ACCELERATORS,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    TopologyError,
    TpuSlice,
)

GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"

# gke_accelerator label value → our short accelerator name ("v5e", ...).
_GKE_TO_NAME = {acc.gke_accelerator: acc.name for acc in ACCELERATORS.values()}


class FleetConfigError(ValueError):
    """Malformed fleet specification."""


class LedgerError(RuntimeError):
    """A ledger invariant would be violated (admitted > capacity, double
    admission, partial release). Raised, never swallowed — the policy layer
    must make these impossible; the bench counts raises (must be zero)."""


@dataclass(frozen=True)
class NodePool:
    """One TPU node pool: ``num_slices`` slices of one shape."""

    name: str
    accelerator: str       # short name: v4 | v5e | v5p | v6e
    topology: str          # slice chip grid, e.g. "4x4"
    num_slices: int

    def __post_init__(self):
        if self.num_slices < 1:
            raise FleetConfigError(
                f"pool {self.name}: num_slices must be >= 1, "
                f"got {self.num_slices}")
        # Validates accelerator/topology; raises TopologyError on garbage.
        TpuSlice.parse(self.accelerator, self.topology)

    @property
    def slice_shape(self) -> TpuSlice:
        return TpuSlice.parse(self.accelerator, self.topology)

    @property
    def chips_per_slice(self) -> int:
        return self.slice_shape.num_chips

    @property
    def total_chips(self) -> int:
        return self.num_slices * self.chips_per_slice

    @property
    def shape_key(self) -> tuple[str, str]:
        return (self.accelerator.lower(), self.topology.lower())


@dataclass(frozen=True)
class Fleet:
    """Immutable pool inventory, keyed by pool name."""

    pools: tuple[NodePool, ...] = ()

    @classmethod
    def parse(cls, spec: str) -> "Fleet":
        """``pool-a=v5e:4x4:2,pool-b=v5p:2x2x1:4`` → Fleet. Empty/None
        spec → empty fleet (scheduler passes everything through)."""
        pools: list[NodePool] = []
        seen: set[str] = set()
        for raw in (spec or "").replace("\n", ",").split(","):
            entry = raw.strip()
            if not entry:
                continue
            name, sep, shape = entry.partition("=")
            parts = shape.split(":")
            if not sep or len(parts) != 3:
                raise FleetConfigError(
                    f"bad fleet entry {entry!r}: want "
                    "<name>=<accelerator>:<topology>:<num-slices>")
            acc, topo, n = (p.strip() for p in parts)
            try:
                num = int(n)
            except ValueError:
                raise FleetConfigError(
                    f"bad fleet entry {entry!r}: slice count {n!r} is not "
                    "an integer") from None
            name = name.strip()
            if name in seen:
                raise FleetConfigError(f"duplicate pool name {name!r}")
            seen.add(name)
            try:
                pools.append(NodePool(name, acc.lower(), topo.lower(), num))
            except TopologyError as e:
                raise FleetConfigError(f"bad fleet entry {entry!r}: {e}") \
                    from None
        return cls(pools=tuple(sorted(pools, key=lambda p: p.name)))

    @classmethod
    def from_nodes(cls, nodes: list[dict]) -> "Fleet":
        """Infer pools from Node objects' GKE TPU labels: hosts sharing a
        ``gke-nodepool`` label and a TPU shape form one pool; its slice
        count is ``hosts // hosts_per_slice`` (partial slices can never
        schedule a gang, so they don't count)."""
        hosts: dict[tuple[str, str, str], int] = {}
        for node in nodes:
            labels = ((node.get("metadata") or {}).get("labels")) or {}
            gke_acc = labels.get(GKE_TPU_ACCELERATOR_LABEL)
            topo = labels.get(GKE_TPU_TOPOLOGY_LABEL)
            acc = _GKE_TO_NAME.get(gke_acc or "")
            if not acc or not topo:
                continue
            pool = labels.get(GKE_NODEPOOL_LABEL) or f"{acc}-{topo}"
            hosts[(pool, acc, topo.lower())] = \
                hosts.get((pool, acc, topo.lower()), 0) + 1
        # A nodepool label carrying two TPU shapes (mid-migration label
        # drift) must not yield two same-named pools: the ledger resolves
        # placements by name, and the collision would make every admit of
        # the second shape a LedgerError. Disambiguate with the shape —
        # but count only shapes that survive the whole-slice/parse
        # filters: a stray partial-slice or unparsable shape must not
        # rename the real pool (the rename would look like a fleet change
        # and rebind-churn every allocation booked on it).
        survivors = []
        name_shapes: dict[str, int] = {}
        for (pool, acc, topo), count in sorted(hosts.items()):
            try:
                per_slice = TpuSlice.parse(acc, topo).num_hosts
            except TopologyError:
                continue
            num_slices = count // per_slice
            if num_slices >= 1:
                survivors.append((pool, acc, topo, num_slices))
                name_shapes[pool] = name_shapes.get(pool, 0) + 1
        pools = []
        for pool, acc, topo, num_slices in survivors:
            name = (f"{pool}-{acc}-{topo}" if name_shapes[pool] > 1
                    else pool)
            pools.append(NodePool(name, acc, topo, num_slices))
        return cls(pools=tuple(pools))

    def by_name(self, name: str) -> NodePool | None:
        for p in self.pools:
            if p.name == name:
                return p
        return None

    def matching(self, accelerator: str, topology: str) -> list[NodePool]:
        """Pools that can host slices of this shape, name-sorted (the
        deterministic allocation order)."""
        key = (accelerator.lower(), topology.lower())
        return [p for p in self.pools if p.shape_key == key]

    def total_slices(self, accelerator: str, topology: str) -> int:
        """Whole-fleet ceiling for one shape — the webhook's can-never-fit
        check compares a gang's num_slices against this."""
        return sum(p.num_slices for p in self.matching(accelerator, topology))

    @property
    def total_chips(self) -> int:
        return sum(p.total_chips for p in self.pools)


@dataclass
class Allocation:
    """One admitted gang: the notebook's FULL slice set, spread over
    matching pools. ``placements`` maps pool name → slices taken there;
    its values always sum to the request's num_slices (gang atomicity —
    checked at admit time and by ``ChipLedger.assert_consistent``)."""

    key: tuple              # (namespace, name)
    namespace: str
    accelerator: str
    topology: str
    num_slices: int
    chips: int
    placements: dict[str, int]
    priority: int = 0
    admitted_at: float = 0.0
    # Culling's last-activity signal (idle-preemption ranking); None means
    # "no probe data yet" and is never treated as idle.
    last_active_at: float | None = None
    # True for a gang force-admitted by reclaim() over a fleet that no
    # longer has room for it (controller restart after the fleet shrank):
    # its pods exist, so the ledger records it as a deliberate overcommit
    # and assert_consistent exempts its pools from the capacity check.
    forced: bool = False
    # Deferred preemption (kubeflow_tpu/migration): a drain was requested
    # for this gang — it still holds its chips while it checkpoints, but
    # the victim search treats its capacity as incoming-free (no second
    # gang is drained for slices already on their way out) and never
    # re-picks it as a victim.
    draining: bool = False


@dataclass
class ChipLedger:
    """Admitted-vs-free accounting over a Fleet. All mutation goes through
    ``admit``/``release``; both enforce the invariants and raise
    LedgerError (counted in ``violations``) rather than record a bad
    state."""

    fleet: Fleet
    used: dict[str, int] = field(default_factory=dict)        # pool → slices
    allocations: dict[tuple, Allocation] = field(default_factory=dict)
    ns_chips: dict[str, int] = field(default_factory=dict)    # ns → chips
    violations: int = 0

    def free_slices(self, pool: NodePool) -> int:
        return pool.num_slices - self.used.get(pool.name, 0)

    def fit(self, accelerator: str, topology: str,
            num_slices: int) -> dict[str, int] | None:
        """All-or-nothing placement plan for a gang: spread num_slices
        over matching pools in name order, or None if the whole gang
        cannot fit right now. Never returns a partial plan."""
        plan: dict[str, int] = {}
        remaining = num_slices
        for pool in self.fleet.matching(accelerator, topology):
            if remaining == 0:
                break
            take = min(self.free_slices(pool), remaining)
            if take > 0:
                plan[pool.name] = take
                remaining -= take
        return plan if remaining == 0 else None

    def admit(self, alloc: Allocation, *, force: bool = False) -> None:
        """Record one whole gang. ``force=True`` is the reclaim path
        (controller restart over a fleet that no longer has room): the
        per-pool capacity check — and ONLY it — is skipped, because the
        gang's pods already run; gang atomicity and no-double-admit
        still hold. The allocation is marked ``forced`` so
        ``assert_consistent`` treats the resulting over-capacity pools
        as overcommit, not as ledger drift; it drains on release."""
        if alloc.key in self.allocations:
            self.violations += 1
            raise LedgerError(f"{alloc.key} is already admitted")
        if sum(alloc.placements.values()) != alloc.num_slices:
            self.violations += 1
            raise LedgerError(
                f"{alloc.key}: partial gang ({alloc.placements} vs "
                f"{alloc.num_slices} slices) — gangs admit all-or-nothing")
        if force:
            alloc.forced = True
        else:
            for pool_name, n in alloc.placements.items():
                pool = self.fleet.by_name(pool_name)
                if pool is None or pool.shape_key != (
                        alloc.accelerator.lower(), alloc.topology.lower()):
                    self.violations += 1
                    raise LedgerError(
                        f"{alloc.key}: placement on unknown/mismatched "
                        f"pool {pool_name!r}")
                if self.used.get(pool_name, 0) + n > pool.num_slices:
                    self.violations += 1
                    raise LedgerError(
                        f"{alloc.key}: pool {pool_name} over capacity "
                        f"({self.used.get(pool_name, 0)}+{n} > "
                        f"{pool.num_slices} slices)")
        for pool_name, n in alloc.placements.items():
            self.used[pool_name] = self.used.get(pool_name, 0) + n
        self.allocations[alloc.key] = alloc
        self.ns_chips[alloc.namespace] = \
            self.ns_chips.get(alloc.namespace, 0) + alloc.chips

    def release(self, key: tuple) -> Allocation | None:
        alloc = self.allocations.pop(key, None)
        if alloc is None:
            return None
        for pool_name, n in alloc.placements.items():
            left = self.used.get(pool_name, 0) - n
            if left < 0:
                self.violations += 1
                raise LedgerError(
                    f"{key}: releasing more slices than admitted on "
                    f"{pool_name}")
            if left:
                self.used[pool_name] = left
            else:
                self.used.pop(pool_name, None)
        left_chips = self.ns_chips.get(alloc.namespace, 0) - alloc.chips
        if left_chips:
            self.ns_chips[alloc.namespace] = left_chips
        else:
            self.ns_chips.pop(alloc.namespace, None)
        return alloc

    def admitted_chips_by_pool(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for pool in self.fleet.pools:
            used = self.used.get(pool.name, 0)
            if used:
                out[pool.name] = used * pool.chips_per_slice
        return out

    def assert_consistent(self) -> None:
        """Recompute used/ns_chips from the allocations and compare — the
        property test calls this after every step."""
        used: dict[str, int] = {}
        ns: dict[str, int] = {}
        for alloc in self.allocations.values():
            if sum(alloc.placements.values()) != alloc.num_slices:
                raise LedgerError(f"{alloc.key}: partial gang recorded")
            for pool_name, n in alloc.placements.items():
                used[pool_name] = used.get(pool_name, 0) + n
            ns[alloc.namespace] = ns.get(alloc.namespace, 0) + alloc.chips
        if used != self.used or ns != self.ns_chips:
            raise LedgerError(
                f"ledger drift: used {self.used} vs {used}, "
                f"ns_chips {self.ns_chips} vs {ns}")
        # Pools carrying a force-admitted (reclaimed-with-overcommit)
        # gang are legitimately over capacity until it releases.
        forced_pools = {
            pool_name
            for alloc in self.allocations.values() if alloc.forced
            for pool_name in alloc.placements
        }
        for pool in self.fleet.pools:
            if pool.name in forced_pools:
                continue
            if used.get(pool.name, 0) > pool.num_slices:
                raise LedgerError(
                    f"pool {pool.name} over capacity: "
                    f"{used[pool.name]} > {pool.num_slices}")
