"""Pure policy core of the TPU fleet scheduler.

Kueue-style arbitration as a deterministic, clock-free state machine —
every decision is a function of (queue state, ledger state, the ``now``
the caller passes in), so tier-1 can property-test randomized
arrival/completion sequences without FakeKube or an event loop.

Policy, in admission order:

- **Gang admission**: a request is one Notebook's full MultiSlice; it is
  admitted with all of its slices placed or not at all (``ChipLedger.fit``
  never returns a partial plan).
- **Priority classes**: higher ``priority`` schedules first.
- **Weighted fair share** (DRF on chips — chips are the single dominant
  resource, so dominant-resource fairness reduces to admitted chips
  divided by namespace weight): among equal priority, the namespace with
  the smallest share goes first.
- **Aging** (bounded starvation): every ``aging_seconds`` of queue wait
  adds one effective priority step (capped at ``aging_max_boost``), and a
  request starved past ``starvation_reserve_seconds`` blocks backfill —
  smaller gangs stop jumping over it, so the capacity it needs eventually
  drains free.
- **Preemption**: when a request cannot fit, reclaim whole gangs (never a
  slice subset — mid-gang preemption would leave a broken ICI mesh and a
  half-accounted ledger) from *idle* holders (culling's last-activity
  signal, any priority) or *strictly lower-priority* holders. Victims'
  chips are released in-ledger immediately so the waiting gang admits in
  the same pass; the runtime stop-annotates the victim CRs.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from kubeflow_tpu.scheduler.fleet import Allocation, ChipLedger, Fleet
from kubeflow_tpu.telemetry.ledger import EfficiencyLedger


def _eff_key(key: tuple) -> str:
    """Gang key as the efficiency ledger's string key (its rows appear in
    JSON debug payloads, where tuple keys can't)."""
    return "/".join(str(p) for p in key)


@dataclass(frozen=True)
class GangRequest:
    """One notebook's whole MultiSlice, as the queue sees it."""

    key: tuple                 # (namespace, name)
    namespace: str
    accelerator: str
    topology: str
    num_slices: int
    chips: int                 # total chips across the gang
    priority: int = 0
    weight: float = 1.0        # namespace weight (fair-share divisor)
    submitted_at: float = 0.0
    seq: int = 0               # arrival order; the final deterministic tie-break
    # Workload class ("notebook" | "serving", kubeflow_tpu/serving): a
    # serving replica admits exactly like a notebook gang but is NEVER a
    # preemption victim — it has no notebook activity signal (no Jupyter
    # kernels), so the idle heuristic would misread a service under load
    # as idle forever, and its capacity is managed by its own autoscaler
    # (scale-down releases chips; killing one replica would just make
    # the service re-request it). Default keeps PR 5–8 behavior
    # bit-identical.
    workload: str = "notebook"


@dataclass(frozen=True)
class Preemption:
    key: tuple                 # victim (namespace, name)
    reason: str                # "idle" | "priority"
    for_key: tuple             # the queued gang the chips were reclaimed for
    chips: int


@dataclass(frozen=True)
class Admitted:
    key: tuple
    placements: dict
    waited: float              # now - submitted_at (time-to-admission)


@dataclass(frozen=True)
class QueuedInfo:
    key: tuple
    position: int              # 1-based rank in the current queue order
    chips: int
    reason: str


@dataclass(frozen=True)
class ScheduleResult:
    admitted: list
    preempted: list
    queue: list                # QueuedInfo for everything still waiting
    # Deferred preemption only: victims that must be ASKED to checkpoint
    # (Preemption records; their chips stay booked until the runtime
    # observes the ack or the grace deadline and calls release()).
    drains: list = field(default_factory=list)


@dataclass(frozen=True)
class PolicyConfig:
    aging_seconds: float = 300.0
    aging_max_boost: int = 4
    starvation_reserve_seconds: float = 900.0
    enable_preemption: bool = True
    # A holder whose culling last-activity is older than this is fair
    # game for any queued gang that needs its chips.
    idle_preempt_after_seconds: float = 1800.0
    # Preempt-to-checkpoint (kubeflow_tpu/migration): victims are DRAIN
    # requests, not in-pass releases — chips stay booked (alloc.draining)
    # until the runtime sees the checkpoint ack or the grace deadline and
    # releases them. False keeps the immediate-stop semantics.
    deferred_preemption: bool = False


@dataclass
class PolicyQueue:
    """The scheduler's brain: a pending queue over a chip ledger."""

    fleet: Fleet
    config: PolicyConfig = field(default_factory=PolicyConfig)
    ledger: ChipLedger = None  # type: ignore[assignment]
    pending: dict = field(default_factory=dict)   # key → GangRequest
    # Per-family x shape MFU history (ISSUE 18): fed from the telemetry
    # annotation by the runtime, consumed ONLY as a tie-break inside the
    # idle victim tier and by explain/debug_info.
    efficiency: EfficiencyLedger = field(default_factory=EfficiencyLedger)
    # Bumped on every state change (submit/release/touch/admission/
    # preemption/reclaim): the runtime skips redundant full arbitration
    # passes — each queued notebook's safety-net requeue would otherwise
    # run a global O(queue) pass — when gen is unchanged.
    gen: int = 0
    _seq: int = 0

    def __post_init__(self):
        if self.ledger is None:
            self.ledger = ChipLedger(self.fleet)

    @property
    def overcommitted(self) -> int:
        """Gangs reclaim() had to force-place over a too-small fleet
        (controller restart after a shrink, or their shape left the
        fleet) — surfaced in debug_info. Counted live from the ledger so
        a rebind_fleet() re-seat of a still-overcommitted gang never
        double-counts it, and the number drains as holders release."""
        return sum(1 for a in self.ledger.allocations.values() if a.forced)

    # ---- queue mutation ---------------------------------------------------------

    def submit(self, req: GangRequest) -> GangRequest:
        """Enqueue (or refresh) a gang. An existing pending entry keeps its
        original submitted_at/seq — a spec refresh must not reset aging —
        unless its shape changed, in which case demand is re-declared.
        Submitting an already-admitted key is a no-op (the holder's
        reconcile calls this idempotently)."""
        if req.key in self.ledger.allocations:
            return req
        prior = self.pending.get(req.key)
        if prior is not None and (
                prior.accelerator.lower(), prior.topology.lower(),
                prior.num_slices,
        ) == (req.accelerator.lower(), req.topology.lower(),
              req.num_slices):
            req = replace(req, submitted_at=prior.submitted_at,
                          seq=prior.seq)
        else:
            # New demand — or a shape EDIT while queued, which re-declares
            # it: aging/starvation credit earned as a small gang must not
            # transfer to an arbitrarily larger one (a tenant could wedge
            # the shape's starvation door without ever waiting as that
            # demand).
            self._seq += 1
            req = replace(req, seq=self._seq)
        if self.pending.get(req.key) != req:
            self.gen += 1
        self.pending[req.key] = req
        return req

    def release(self, key: tuple) -> Allocation | None:
        """Drop a gang entirely: its queue entry (stopped while waiting)
        and/or its allocation (stopped/deleted while running)."""
        dropped = self.pending.pop(key, None)
        alloc = self.ledger.release(key)
        self.efficiency.forget(_eff_key(key))
        if dropped is not None or alloc is not None:
            self.gen += 1
        return alloc

    def note_efficiency(self, key: tuple, family: str, shape: str,
                        mfu) -> None:
        """Feed one telemetry window (deduplicated by annotation seq at
        the caller). Deliberately no ``gen`` bump: efficiency only
        reorders victims *within* the idle tier, so it never makes a new
        admission possible and must not trigger re-arbitration churn."""
        self.efficiency.note(_eff_key(key), family, shape, mfu)

    def touch(self, key: tuple, last_active_at: float | None) -> None:
        """Refresh a holder's idle signal (culling's last-activity)."""
        alloc = self.ledger.allocations.get(key)
        if alloc is not None and last_active_at is not None \
                and alloc.last_active_at != last_active_at:
            alloc.last_active_at = last_active_at
            self.gen += 1

    def is_admitted(self, key: tuple) -> bool:
        return key in self.ledger.allocations

    def is_draining(self, key: tuple) -> bool:
        alloc = self.ledger.allocations.get(key)
        return alloc is not None and alloc.draining

    def reclaim(self, req: GangRequest, now: float, *,
                borrow_first: bool = False,
                prefer_pool: str | None = None) -> bool:
        """Re-seat an ALREADY-RUNNING gang after a controller restart
        (scheduler state is in-memory). Uses a normal fit when capacity
        allows; otherwise force-places on matching pools — the pods exist,
        so refusing would stop-annotate healthy workloads on every
        controller restart. Forced placements may transiently exceed a
        shrunken fleet's capacity; that is recorded as an overcommit, not
        a ledger violation, and drains as holders release.

        ``borrow_first`` (with ``prefer_pool``, the durable flex-pool
        annotation): the gang was flex-placed before the restart, so its
        pods run on a FOREIGN pool's host — restore the borrow even when
        a native fit now exists, or the host pool's capacity is resold
        under the running pods and the gang's node selectors flip."""
        if req.key in self.ledger.allocations:
            return True
        self.pending.pop(req.key, None)
        # Borrow re-seat (one shared block, two triggers): with the
        # durable flex hint, BEFORE the native fit — the gang's pods run
        # on a foreign pool's host, and seating it natively would
        # un-break that pool's slice, resell the occupied host, and flip
        # the gang's node selectors; without the hint, only as the
        # fallback when no native fit exists (an ex-native single-host
        # gang whose slice was resold is better borrowed than
        # force-overcommitted).
        borrow = (self.ledger.borrow_fit(req.accelerator, req.topology,
                                         prefer=prefer_pool)
                  if borrow_first else None)
        plan = (None if borrow is not None else
                self.ledger.fit(req.accelerator, req.topology,
                                req.num_slices))
        if borrow is None and plan is None:
            borrow = self.ledger.borrow_fit(req.accelerator, req.topology)
        if borrow is not None:
            self.ledger.admit(Allocation(
                key=req.key, namespace=req.namespace,
                accelerator=req.accelerator, topology=req.topology,
                num_slices=req.num_slices, chips=req.chips,
                placements={}, borrow=borrow,
                priority=req.priority, admitted_at=now,
                workload=req.workload,
            ))
            self.gen += 1
            return True
        overcommit = plan is None
        if overcommit:
            pools = self.fleet.matching(req.accelerator, req.topology)
            if not pools:
                # The shape left the fleet entirely but the gang's pods
                # still run: seat it on a shape pseudo-pool as pure
                # overcommit rather than queueing a live workload —
                # 'Queued' would suppress its child reconcile and tell
                # the UI nothing runs while pods serve traffic. It takes
                # no real pool's capacity and drains on release.
                plan = {f"{req.accelerator}:{req.topology}":
                        req.num_slices}
            else:
                plan = {}
                remaining = req.num_slices
                for pool in pools:
                    take = min(max(self.ledger.free_slices(pool), 0),
                               remaining)
                    if take:
                        plan[pool.name] = take
                        remaining -= take
                if remaining:
                    plan[pools[0].name] = \
                        plan.get(pools[0].name, 0) + remaining
        alloc = Allocation(
            key=req.key, namespace=req.namespace,
            accelerator=req.accelerator, topology=req.topology,
            num_slices=req.num_slices, chips=req.chips,
            placements=plan, priority=req.priority, admitted_at=now,
            workload=req.workload,
        )
        self.ledger.admit(alloc, force=overcommit)
        self.gen += 1
        return True

    def rebind_fleet(self, fleet: Fleet) -> None:
        """Swap the fleet under live allocations (dynamic fleet sources:
        ConfigMap edits, node-label inference). Allocations whose
        placements reference pools that left the fleet — or whose named
        pool now hosts a different shape — are released and re-seated
        via :meth:`reclaim`: a renamed pool (same hardware, new name)
        re-books onto the new name so its capacity is not double-sold to
        new gangs, and a shape that vanished falls back to the reclaim
        pseudo-pool overcommit. Everything else keeps its booking."""
        self.fleet = fleet
        self.ledger.fleet = fleet
        stale = []
        for alloc in self.ledger.allocations.values():
            ok = not alloc.forced
            for pool_name in alloc.placements:
                pool = fleet.by_name(pool_name)
                if pool is None or pool.shape_key != (
                        alloc.accelerator.lower(),
                        alloc.topology.lower()):
                    ok = False
                    break
            # A borrower's pool must still exist with the same
            # accelerator (its shape differs from the pool's by design);
            # gone → re-seat like any stale placement.
            for pool_name in (alloc.borrow or {}):
                pool = fleet.by_name(pool_name)
                if pool is None or pool.accelerator.lower() != \
                        alloc.accelerator.lower():
                    ok = False
                    break
            if not ok:
                stale.append(alloc)
        for alloc in stale:   # release all first: re-seating must see
            self.ledger.release(alloc.key)        # the full free space
        for alloc in stale:
            self.reclaim(
                GangRequest(
                    key=alloc.key, namespace=alloc.namespace,
                    accelerator=alloc.accelerator,
                    topology=alloc.topology,
                    num_slices=alloc.num_slices, chips=alloc.chips,
                    priority=alloc.priority, workload=alloc.workload),
                now=alloc.admitted_at,   # keep the original admission time
                # An ex-borrower re-seats as a borrow (its pods live on
                # a foreign pool's host, likely the renamed survivor).
                borrow_first=alloc.borrowed)
            reseated = self.ledger.allocations.get(alloc.key)
            if reseated is not None:
                reseated.last_active_at = alloc.last_active_at
                # A drain in flight survives the fleet swap: the victim
                # is still checkpointing and must not become a candidate
                # for a second preemption.
                reseated.draining = alloc.draining
        # A shrink that KEEPS a pool's name/shape can leave its live
        # gangs over the new capacity. That is deliberate drain-down
        # overcommit, not ledger drift — mark those gangs forced so
        # assert_consistent exempts the pool and debug_info reports the
        # overcommit (it clears when they release or a later rebind
        # re-seats them within capacity).
        for pool in fleet.pools:
            used = sum(a.placements.get(pool.name, 0)
                       for a in self.ledger.allocations.values())
            if used > pool.num_slices:
                for a in self.ledger.allocations.values():
                    if a.placements.get(pool.name):
                        a.forced = True
        self.gen += 1

    # ---- scheduling pass --------------------------------------------------------

    def _effective_priority(self, req: GangRequest, now: float) -> int:
        cfg = self.config
        if cfg.aging_seconds <= 0:
            return req.priority
        boost = int(max(0.0, now - req.submitted_at) // cfg.aging_seconds)
        return req.priority + min(boost, cfg.aging_max_boost)

    def _share(self, req: GangRequest) -> float:
        """Weighted fair-share term (admitted chips / namespace weight)
        — one definition for ranking AND the explain mirror."""
        return self.ledger.ns_chips.get(req.namespace, 0) \
            / max(req.weight, 1e-9)

    def _starved(self, req: GangRequest, now: float) -> bool:
        """Does this gang hold the starvation door for its shape? One
        predicate shared by schedule()'s backfill block and explain() —
        the explanation must mirror what admission actually enforces
        (incl. the never-fits ceiling exemption)."""
        return (now - req.submitted_at
                >= self.config.starvation_reserve_seconds
                and self.fleet.total_slices(req.accelerator, req.topology)
                >= req.num_slices)

    def _rank_key(self, req: GangRequest, now: float):
        return (-self._effective_priority(req, now), self._share(req),
                req.seq)

    def _ordered_pending(self, now: float) -> list:
        return sorted(self.pending.values(),
                      key=lambda r: self._rank_key(r, now))

    def _find_victims(self, req: GangRequest, now: float) -> list | None:
        """Whole-gang victims whose release lets ``req`` fit, or None.
        Idle holders (culling signal) are preemptible by anyone; busy
        holders only by strictly higher BASE priority — aging boosts
        where a gang sorts in the queue, never whom it may kill (an
        equal-priority gang that waited long enough must not stop-
        annotate a busy peer). Within the idle tier, gangs the
        efficiency ledger flags persistently-low-MFU rank first (ISSUE
        18's placement signal — strictly a tie-break inside tier 0:
        serving/busy/priority protections all sort ahead of it); then
        most-idle, lowest priority, youngest admission (LIFO), so the
        decision is deterministic and the cheapest work dies first."""
        cfg = self.config
        shape = (req.accelerator.lower(), req.topology.lower())
        matching = {p.name
                    for p in self.fleet.matching(req.accelerator,
                                                 req.topology)}
        candidates = []
        # Capacity already on its way out (deferred preemption: gangs
        # asked to checkpoint but still holding chips) counts as incoming
        # free space — selecting a second victim for slices a first one
        # is already vacating would double-kill for one waiter.
        draining_by_pool: dict[str, int] = {}
        for alloc in self.ledger.allocations.values():
            if alloc.draining:
                if (alloc.accelerator.lower(),
                        alloc.topology.lower()) == shape:
                    for pool, n in alloc.placements.items():
                        if pool in matching:
                            draining_by_pool[pool] = \
                                draining_by_pool.get(pool, 0) + n
                continue  # never re-pick a draining gang as a victim
            if alloc.workload == "warmpool":
                # Warm-pool chips are a RESERVE by contract (ISSUE 14):
                # the reservation exists precisely so the scheduler can
                # cannibalize it under pressure — tier -1 makes every
                # warm slot a victim before any idle or lower-priority
                # REAL gang, and schedule() releases it instantly (a
                # warm pod holds no state worth a checkpoint drain).
                if (alloc.accelerator.lower(),
                        alloc.topology.lower()) != shape:
                    continue
                warm_reclaimable = sum(
                    n for pool, n in alloc.placements.items()
                    if pool in matching)
                if warm_reclaimable == 0:
                    continue
                candidates.append((-1, 0, 0.0, alloc.priority,
                                   -alloc.admitted_at, alloc.key,
                                   "warm-pool", warm_reclaimable, alloc))
                continue
            if alloc.workload != "notebook":
                # Workload-class guard (kubeflow_tpu/serving): a serving
                # replica has no activity probe — "no kernels" must not
                # read as idle — and stopping one would not free capacity
                # for long (its autoscaler would re-bid immediately).
                # Serving capacity comes back through scale-down /
                # scale-to-zero, never through preemption.
                continue
            if (alloc.accelerator.lower(), alloc.topology.lower()) != shape:
                continue  # frees no capacity this gang can use
            # Only slices booked on REAL matching pools come back on
            # release: a gang force-seated on a shape pseudo-pool
            # (reclaim after the shape left the fleet) would be stopped
            # for zero benefit — the waiter still couldn't fit.
            reclaimable = sum(n for pool, n in alloc.placements.items()
                              if pool in matching)
            if reclaimable == 0:
                continue
            # Floored by the in-memory admitted_at: the durable
            # admitted-at annotation usually floors the culling signal
            # already, but its stamp patch is best-effort — if it failed,
            # a long-queued gang would look 'idle since before it ran'
            # seconds after admission.
            last = (None if alloc.last_active_at is None
                    else max(alloc.last_active_at, alloc.admitted_at))
            idle = (last is not None
                    and now - last >= cfg.idle_preempt_after_seconds)
            if idle:
                # Efficiency tie-break INSIDE tier 0 only: a persistently
                # low-MFU idle gang is the preferred reclaim, but the
                # signal can never promote a candidate across tiers.
                eff = 0 if self.efficiency.persistently_low(
                    _eff_key(alloc.key)) else 1
                candidates.append((0, eff, -(now - last),
                                   alloc.priority, -alloc.admitted_at,
                                   alloc.key, "idle", reclaimable, alloc))
            elif alloc.priority < req.priority:
                candidates.append((1, 0, 0.0, alloc.priority,
                                   -alloc.admitted_at, alloc.key,
                                   "priority", reclaimable, alloc))
        candidates.sort(key=lambda c: c[:6])
        # Per-pool simulation, not one aggregate sum: an overcommitted
        # pool's NEGATIVE free space (restart reclaim / fleet shrink)
        # must neither mask reclaimable capacity on healthy pools (the
        # deficit would hide a sufficient victim and wrongly refuse
        # preemption) nor count a victim's slices as usable when they
        # only drain that pool's deficit (over-selecting healthy gangs).
        # An unavailable pool (spot mid-reclaim) can never satisfy the
        # waiter: -inf keeps it unusable no matter how many of its
        # holders a victim search would free.
        free_by_pool = {
            p.name: (float("-inf")
                     if p.name in self.ledger.unavailable
                     else self.ledger.free_slices(p))
            for p in self.fleet.matching(req.accelerator, req.topology)}
        for pool, n in draining_by_pool.items():
            free_by_pool[pool] = free_by_pool.get(pool, 0) + n

        def usable() -> int:
            return sum(max(f, 0) for f in free_by_pool.values())

        victims = []
        for *_rank, _key, reason, _reclaimable, alloc in candidates:
            if usable() >= req.num_slices:
                break
            victims.append((alloc, reason))
            for pool, n in alloc.placements.items():
                if pool in free_by_pool:
                    free_by_pool[pool] += n
        # An EMPTY list is meaningful in deferred mode: enough capacity is
        # already draining, so no new victim is needed — the caller keeps
        # the requester queued without emitting further drains. None still
        # means preemption cannot help at all.
        return victims if usable() >= req.num_slices else None

    def schedule(self, now: float) -> ScheduleResult:
        """One deterministic arbitration pass. Mutates the ledger (admits,
        preempts) and returns everything the runtime must act on."""
        admitted: list[Admitted] = []
        preempted: list[Preemption] = []
        drains: list[Preemption] = []
        # Shapes whose warm-pool reserve was released THIS pass for a
        # requester still waiting on real drains: held for the whole
        # pass (across re-rank iterations), or a lower-ranked same-shape
        # gang would backfill onto the freed reserve and leave the
        # requester short — forcing a second real-gang drain later.
        warm_held: set = set()
        progressed = True
        while progressed and self.pending:
            progressed = False
            # Shapes a starved gang has reserved this scan: backfill of
            # the SAME shape must not jump it, but gangs for disjoint
            # pools take nothing it is waiting for and admit freely.
            blocked: set = set(warm_held)
            for req in self._ordered_pending(now):
                shape = (req.accelerator.lower(), req.topology.lower())
                if shape in blocked:
                    continue
                plan = self.ledger.fit(req.accelerator, req.topology,
                                       req.num_slices)
                if plan is None and self.config.enable_preemption:
                    victims = self._find_victims(req, now)
                    if victims is not None:
                        # Warm-pool reservations release INSTANTLY even
                        # in deferred mode: a warm pod has nothing to
                        # checkpoint, and the whole point of the reserve
                        # is that a real gang takes its chips in the
                        # same pass (ISSUE 14).
                        instant = [(a, r) for a, r in victims
                                   if a.workload == "warmpool"]
                        rest = [(a, r) for a, r in victims
                                if a.workload != "warmpool"]
                        for alloc, reason in instant:
                            self.ledger.release(alloc.key)
                            preempted.append(Preemption(
                                key=alloc.key, reason=reason,
                                for_key=req.key, chips=alloc.chips))
                    else:
                        instant, rest = [], []
                    if victims is not None and self.config.deferred_preemption:
                        # Drain, don't kill: mark the victims draining
                        # (chips stay booked — the fleet must not admit
                        # anyone onto slices that still hold un-saved
                        # state) and hand them to the runtime to ask for
                        # a checkpoint. The requester stays queued until
                        # the runtime observes the ack (or the grace
                        # deadline) and releases the victims for real.
                        # An empty list = enough capacity already
                        # draining for this shape; just keep waiting.
                        for alloc, reason in rest:
                            alloc.draining = True
                            drains.append(Preemption(
                                key=alloc.key, reason=reason,
                                for_key=req.key, chips=alloc.chips))
                        if instant and not rest:
                            # The reserve alone covered the ask — admit
                            # in this pass, like immediate preemption.
                            plan = self.ledger.fit(
                                req.accelerator, req.topology,
                                req.num_slices)
                        elif instant:
                            # Warm chips freed NOW for a requester that
                            # must still wait on real drains: hold the
                            # shape's door for the rest of this PASS
                            # (warm_held survives re-rank iterations).
                            # Future passes before the drains finalize
                            # keep a bounded window; _find_victims picks
                            # warm slots first in any follow-up search,
                            # so a real gang is still never preferred.
                            blocked.add(shape)
                            warm_held.add(shape)
                    elif victims:
                        for alloc, reason in rest:
                            self.ledger.release(alloc.key)
                            preempted.append(Preemption(
                                key=alloc.key, reason=reason,
                                for_key=req.key, chips=alloc.chips))
                        plan = self.ledger.fit(req.accelerator,
                                               req.topology, req.num_slices)
                if plan is not None:
                    self.ledger.admit(Allocation(
                        key=req.key, namespace=req.namespace,
                        accelerator=req.accelerator, topology=req.topology,
                        num_slices=req.num_slices, chips=req.chips,
                        placements=plan, priority=req.priority,
                        admitted_at=now, workload=req.workload,
                    ))
                    del self.pending[req.key]
                    admitted.append(Admitted(
                        key=req.key, placements=plan,
                        waited=max(0.0, now - req.submitted_at)))
                    progressed = True
                    break  # shares changed; re-rank from scratch
                if self._starved(req, now):
                    # Starved: hold the door on this SHAPE — no backfill
                    # jumps it, so the capacity it needs can drain free.
                    # Only for gangs the fleet CAN eventually host: a
                    # never-fits gang (over the shape ceiling — created
                    # before the fleet shrank, or past the CREATE-only
                    # webhook check) would otherwise wedge its shape
                    # forever; it stays queued with the ceiling in its
                    # reason instead.
                    blocked.add(shape)
        if admitted or preempted or drains:
            self.gen += 1
        return ScheduleResult(admitted=admitted, preempted=preempted,
                              drains=drains,
                              queue=self.schedule_preview(now))

    def _queue_reason(self, req: GangRequest) -> str:
        total = self.fleet.total_slices(req.accelerator, req.topology)
        if total == 0:
            return (f"no pool hosts {req.accelerator}:{req.topology} slices")
        if total < req.num_slices:
            return (f"gang needs {req.num_slices} "
                    f"{req.accelerator}:{req.topology} slice(s); the fleet "
                    f"ceiling is {total}")
        shape = (req.accelerator.lower(), req.topology.lower())
        draining = sum(
            1 for a in self.ledger.allocations.values()
            if a.draining and (a.accelerator.lower(),
                               a.topology.lower()) == shape)
        if draining:
            return (f"waiting for {draining} draining gang(s) to "
                    f"checkpoint ({req.chips} chips)")
        return (f"waiting for {req.chips} chips "
                f"({req.num_slices}x {req.accelerator}:{req.topology})")

    # ---- introspection ----------------------------------------------------------

    def debug_info(self, now: float) -> dict:
        # Per-pool chip attribution for the /debug/scheduler rows:
        # draining chips are still booked (the victim is checkpointing)
        # but on their way out — operators watching a reclaim want to
        # see them apart from plain used.
        draining_by_pool: dict[str, int] = {}
        for a in self.ledger.allocations.values():
            if not a.draining:
                continue
            for pool_name, n in a.placements.items():
                pool = self.fleet.by_name(pool_name)
                if pool is not None:
                    draining_by_pool[pool_name] = \
                        draining_by_pool.get(pool_name, 0) \
                        + n * pool.chips_per_slice
            for pool_name, hosts in (a.borrow or {}).items():
                pool = self.fleet.by_name(pool_name)
                if pool is not None:
                    draining_by_pool[pool_name] = \
                        draining_by_pool.get(pool_name, 0) \
                        + hosts * pool.chips_per_host
        return {
            "pools": [
                {
                    "name": p.name, "accelerator": p.accelerator,
                    "topology": p.topology, "slices": p.num_slices,
                    "free_slices": self.ledger.free_slices(p),
                    "chips": p.total_chips,
                    "used_chips":
                        self.ledger.used.get(p.name, 0)
                        * p.chips_per_slice
                        + self.ledger.borrowed.get(p.name, 0)
                        * p.chips_per_host,
                    "draining_chips": draining_by_pool.get(p.name, 0),
                    "free_chips": self.ledger.free_slices(p)
                    * p.chips_per_slice,
                    "borrowed_hosts": self.ledger.borrowed.get(p.name, 0),
                    "spot": p.spot,
                }
                for p in self.fleet.pools
            ],
            "admitted": [
                {
                    "key": list(a.key), "chips": a.chips,
                    "slices": a.num_slices, "priority": a.priority,
                    "placements": a.placements,
                    "borrow": a.borrow or {},
                    "admitted_at": a.admitted_at,
                    "last_active_at": a.last_active_at,
                    "draining": a.draining,
                    "workload": a.workload,
                }
                for a in sorted(self.ledger.allocations.values(),
                                key=lambda a: a.key)
            ],
            "queue": [
                {
                    "key": list(q.key), "position": q.position,
                    "chips": q.chips, "reason": q.reason,
                }
                for q in self.schedule_preview(now)
            ],
            "ns_chips": dict(sorted(self.ledger.ns_chips.items())),
            "violations": self.ledger.violations,
            "overcommitted": self.overcommitted,
            "efficiency": self.efficiency.debug_info(),
        }

    def schedule_preview(self, now: float) -> list:
        """Queue snapshot without mutating anything (for /debug)."""
        return [
            QueuedInfo(key=req.key, position=i + 1, chips=req.chips,
                       reason=self._queue_reason(req))
            for i, req in enumerate(self._ordered_pending(now))
        ]

    def explain(self, key: tuple, now: float) -> dict:
        """The machine answer to "why is this gang where it is" —
        read-only (``fit`` plans, ``_find_victims`` simulates; neither
        mutates the ledger). Three shapes: an Admitted/Draining holder,
        a Queued gang with its full rank breakdown, or Unknown."""
        key = tuple(key)
        alloc = self.ledger.allocations.get(key)
        if alloc is not None:
            eff = self.efficiency.explain(_eff_key(key))
            out = {
                "state": "Draining" if alloc.draining else "Admitted",
                "chips": alloc.chips,
                "slices": alloc.num_slices,
                "priority": alloc.priority,
                "placements": dict(alloc.placements),
                "borrow": dict(alloc.borrow or {}),
                "admitted_at": alloc.admitted_at,
                "forced_overcommit": alloc.forced,
                "workload": alloc.workload,
                "efficiency": eff,
            }
            if eff and eff.get("expected_mfu") is not None:
                out["efficiency"]["note"] = (
                    f"family {eff['family']} historically achieves "
                    f"{eff['expected_mfu']:.1%} MFU on {eff['shape']} "
                    f"({eff['family_samples']} window(s))")
            return out
        req = self.pending.get(key)
        if req is None:
            return {"state": "Unknown",
                    "reason": "not admitted, queued, or draining — the "
                              "scheduler does not track this key"}
        ordered = self._ordered_pending(now)
        position = next(i + 1 for i, r in enumerate(ordered)
                        if r.key == key)
        cfg = self.config
        waited = max(0.0, now - req.submitted_at)
        shape = (req.accelerator.lower(), req.topology.lower())
        fits_now = self.ledger.fit(req.accelerator, req.topology,
                                   req.num_slices) is not None
        victims = (self._find_victims(req, now)
                   if cfg.enable_preemption and not fits_now else None)
        total = self.fleet.total_slices(req.accelerator, req.topology)
        starved = self._starved(req, now)
        # Is an EARLIER starved gang holding this shape's door shut?
        door_holder = None
        for i, r in enumerate(ordered):
            if i >= position - 1:
                break
            if (r.accelerator.lower(), r.topology.lower()) == shape \
                    and self._starved(r, now):
                door_holder = r.key
                break
        draining_same_shape = [
            list(a.key) for a in self.ledger.allocations.values()
            if a.draining and (a.accelerator.lower(),
                               a.topology.lower()) == shape]
        return {
            "state": "Queued",
            "position": position,
            "of": len(ordered),
            "reason": self._queue_reason(req),
            "blocking_shape": f"{req.accelerator}:{req.topology}",
            "chips": req.chips,
            "slices": req.num_slices,
            "rank": {
                "priority": req.priority,
                "aging_boost": (self._effective_priority(req, now)
                                - req.priority),
                "effective_priority": self._effective_priority(req, now),
                "namespace_share": round(self._share(req), 3),
                "arrival_seq": req.seq,
            },
            "waited_seconds": round(waited, 3),
            "fits_now": fits_now,
            "feasible_if_drained": victims is not None,
            "drain_candidates": [
                {"key": list(a.key), "reason": reason, "chips": a.chips}
                for a, reason in (victims or [])
            ],
            "already_draining": draining_same_shape,
            "fleet_ceiling_slices": total,
            "over_ceiling": total < req.num_slices,
            "starvation": {
                "reserve_seconds": cfg.starvation_reserve_seconds,
                "holds_door": starved,
                "blocked_by_starved": (list(door_holder)
                                       if door_holder else None),
            },
        }
