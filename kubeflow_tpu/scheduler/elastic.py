"""Elastic fleet policy: scale-up intents, flex placement, spot reclaim
and slice defragmentation (pure core).

The PR 5 scheduler arbitrates a *static* fleet: when the queue holds
gangs that fit no pool it can only age them. This module closes the
loop the ROADMAP calls out, as pure functions over the existing
:class:`~kubeflow_tpu.scheduler.policy.PolicyQueue` /
:class:`~kubeflow_tpu.scheduler.fleet.ChipLedger` state so tier-1 can
drive every decision without an event loop:

- **Scale-up intents** (:func:`compute_shortfalls` + :class:`IntentBook`)
  — gangs that fit no pool *even if the fleet fully drained* produce one
  ProvisioningRequest-shaped intent per slice shape (deduped, TTL'd,
  withdrawn when the need evaporates). The runtime materialises each as
  a ``ProvisioningRequest`` CR in the controller namespace — the same
  GKE queued-provisioning idiom the notebook capacity gate already
  speaks, aimed at the pool autoscaler instead of one workload. The
  moment the fleet source (ConfigMap / node inference) reflects granted
  capacity, the normal dynamic-fleet rebind admits the waiters.
- **Flex placement** (:func:`flex_plan` / :func:`overflow_pass`) — a
  single-host gang whose own shape has no (free) pool may *borrow* one
  host from a same-accelerator pool of a larger shape. Borrowed hosts
  break whole native slices (``ChipLedger.broken_slices``): that is the
  fragmentation of the classic wedge — four 4-chip notebooks squatting
  on a big-slice pool hold a 16-chip gang hostage.
- **Defragmentation** (:func:`plan_defrag`) — a periodic pass that finds
  *idle* borrowers straddling pack-breaking pools and migrates them
  (drain → checkpoint → park → re-queue onto a pack pool of their own
  shape) so whole multislice shapes come free. Rate-limited, and only
  ever plans moves whose migrant has a guaranteed native (pack) slice
  to land on. ``KFTPU_DEFRAG=off`` disables it.
- **Spot reclaim** (:func:`node_reclaim_signal` / :func:`reclaimable`) —
  pools marked ``spot`` get a reclaim-aware ledger entry: a revocation
  signal on their nodes routes every resident gang through the PR 6
  drain protocol (checkpoint → release → re-queue at original priority
  with aging credit preserved) instead of letting the node teardown
  kill work in flight; the drain-grace hard stop remains the fallback
  so chips are never held hostage.

Everything here is a function of (queue state, ledger state, ``now``) —
no Kubernetes imports, no clock reads. The async side (annotation
patches, Events, metrics, the ProvisioningRequest CRs) lives in
:mod:`kubeflow_tpu.scheduler.runtime`.

Kill switches: ``KFTPU_ELASTIC=off`` disables the whole subsystem (the
scheduler then behaves exactly as PR 5–7 shipped it, proven byte-for-
byte by tier-1); ``KFTPU_DEFRAG=off`` disables only the defragmenter.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field

from kubeflow_tpu.api import keys
from kubeflow_tpu.runtime.objects import deep_get
from kubeflow_tpu.scheduler.fleet import (
    GKE_NODEPOOL_LABEL,
    Allocation,
    Fleet,
    NodePool,
)
from kubeflow_tpu.scheduler.policy import Admitted, GangRequest, PolicyQueue
from kubeflow_tpu.tpu.topology import TpuSlice

# Drain reasons the elastic runtime stamps (migration protocol contract:
# the finalizer only acts on its own reasons — these are the scheduler's,
# next to its "preempt:*" family).
SPOT_RECLAIM_REASON = "spot-reclaim"
DEFRAG_REASON = "defrag"

# Node taints that mean "this capacity is being revoked". GKE graceful
# node termination stamps impending-node-termination ahead of both
# maintenance and spot/preemptible reclaim; the dedicated spot key is
# accepted for operators (and tests) that signal reclaim explicitly.
RECLAIM_TAINTS = (
    "cloud.google.com/gke-spot-termination",
    "cloud.google.com/impending-node-termination",
)

DEFAULT_SCALE_UP_TTL_SECONDS = 300.0
DEFAULT_DEFRAG_INTERVAL_SECONDS = 30.0
DEFAULT_DEFRAG_IDLE_SECONDS = 600.0
DEFAULT_DEFRAG_MAX_MOVES = 2

# Kill switches (docs/operations.md "Elastic fleet"):
ELASTIC_ENV = "KFTPU_ELASTIC"
DEFRAG_ENV = "KFTPU_DEFRAG"


def elastic_enabled(environ=os.environ) -> bool:
    """``KFTPU_ELASTIC`` master switch — anything but off/false/0/no
    leaves the elastic subsystem on. Off restores PR 5–7 scheduler
    behavior byte-for-byte (no borrows, no intents, no defrag, spot
    pools inert)."""
    return environ.get(ELASTIC_ENV, "on").strip().lower() not in (
        "off", "false", "0", "no", "disabled",
    )


def defrag_enabled(environ=os.environ) -> bool:
    """``KFTPU_DEFRAG`` — defragmenter-only kill switch layered under
    the master one."""
    return environ.get(DEFRAG_ENV, "on").strip().lower() not in (
        "off", "false", "0", "no", "disabled",
    )


@dataclass(frozen=True)
class ElasticConfig:
    """Pure-policy knobs (env contract in cmd/envconfig.py)."""

    scale_up_ttl_seconds: float = DEFAULT_SCALE_UP_TTL_SECONDS
    enable_defrag: bool = True
    defrag_interval_seconds: float = DEFAULT_DEFRAG_INTERVAL_SECONDS
    # A borrower must look idle this long (culling's last-activity
    # signal, floored at admission like the victim search) before the
    # defragmenter will migrate it — moving a busy notebook to satisfy a
    # waiter is preemption's job, with its own priority rules.
    defrag_idle_seconds: float = DEFAULT_DEFRAG_IDLE_SECONDS
    # Rate limit: at most this many migrations per defrag pass.
    defrag_max_moves: int = DEFAULT_DEFRAG_MAX_MOVES


# ---- flex (host-borrowing) placement -------------------------------------------


def _flexible(req: GangRequest) -> TpuSlice | None:
    """A gang is flex-placeable when it is one single-host slice — the
    unit a foreign pool can host without splitting an ICI mesh across
    pools. Returns the parsed slice, or None."""
    if req.num_slices != 1:
        return None
    try:
        shape = TpuSlice.parse(req.accelerator, req.topology)
    except Exception:
        return None
    return shape if shape.num_hosts == 1 else None


def flex_capable(fleet: Fleet, slice_shape: TpuSlice,
                 num_slices: int = 1) -> bool:
    """Could this gang EVER be flex-placed on this fleet (ignoring
    current occupancy)? The one capability predicate the shortfall
    computation and the webhook fast-fail share — a drifted copy would
    make admission reject gangs the scheduler could seat, or vice
    versa. Placement itself (occupancy-aware) is
    :meth:`~kubeflow_tpu.scheduler.fleet.ChipLedger.borrow_fit`."""
    if num_slices != 1 or slice_shape.num_hosts != 1:
        return False
    acc = slice_shape.accelerator.name.lower()
    return any(
        p.accelerator.lower() == acc
        and p.chips_per_host >= slice_shape.chips_per_host
        for p in fleet.pools
    )


def flex_plan(ledger, req: GangRequest,
              *, protected_shapes: frozenset = frozenset()) -> dict | None:
    """Borrow plan (``{pool: 1}``) for a single-host gang that fits no
    pool of its own shape, or None. Pools whose native shape is in
    ``protected_shapes`` (a same-shape gang is waiting for native
    slices) accept no *new* breakage — flex must not manufacture the
    very fragmentation defrag exists to undo while a native waiter is
    queued. Placement preference lives in
    :meth:`~kubeflow_tpu.scheduler.fleet.ChipLedger.borrow_fit`, which
    the restart/rebind re-seat path shares."""
    if _flexible(req) is None:
        return None
    return ledger.borrow_fit(req.accelerator, req.topology,
                             avoid_new_break_shapes=protected_shapes)


def overflow_pass(policy: PolicyQueue, now: float) -> list:
    """Seat queued flexible gangs on borrowed hosts. Gangs a native fit
    can place right now are skipped — native placement (and the fair
    ordering of :meth:`PolicyQueue.schedule`) always wins; the runtime
    runs this BEFORE the schedule pass too, so a free borrowable host is
    used ahead of planning a needless preemption drain for the same
    waiter. Returns the
    :class:`~kubeflow_tpu.scheduler.policy.Admitted` records; the
    runtime applies the same side effects as native admissions. Shapes
    with a pending native waiter are protected from new breakage."""
    protected = frozenset(
        (r.accelerator.lower(), r.topology.lower())
        for r in policy.pending.values()
    )
    admitted: list[Admitted] = []
    for req in list(policy._ordered_pending(now)):
        if policy.ledger.fit(req.accelerator, req.topology,
                             req.num_slices) is not None:
            continue  # the native schedule pass will seat it
        plan = flex_plan(policy.ledger, req, protected_shapes=protected)
        if plan is None:
            continue
        policy.ledger.admit(Allocation(
            key=req.key, namespace=req.namespace,
            accelerator=req.accelerator, topology=req.topology,
            num_slices=req.num_slices, chips=req.chips,
            placements={}, borrow=dict(plan), priority=req.priority,
            admitted_at=now, workload=req.workload,
        ))
        del policy.pending[req.key]
        policy.gen += 1
        admitted.append(Admitted(
            key=req.key, placements=dict(plan),
            waited=max(0.0, now - req.submitted_at)))
    return admitted


# ---- scale-up intents ----------------------------------------------------------


@dataclass
class Shortfall:
    """One shape's unsatisfiable demand: no pool could host the gang(s)
    even with the whole fleet drained."""

    accelerator: str
    topology: str
    slices: int            # pool slices that must be ADDED
    chips: int
    keys: tuple            # the starved gangs, sorted


@dataclass
class ScaleUpIntent:
    """One pending pool-scale-up ask, ProvisioningRequest-shaped. Lives
    in the :class:`IntentBook` keyed by shape; the runtime mirrors it to
    a ProvisioningRequest CR named :attr:`name` so cluster tooling (and
    the chaos harness's grant/deny actions) can see and answer it."""

    accelerator: str
    topology: str
    slices: int
    chips: int
    for_keys: tuple
    created_at: float
    expires_at: float
    ceiling_at_creation: int = 0   # fleet slices of this shape back then
    renewals: int = 0
    denied: bool = False

    @property
    def shape(self) -> tuple[str, str]:
        return (self.accelerator.lower(), self.topology.lower())

    @property
    def name(self) -> str:
        return f"pool-scale-up-{self.accelerator}-{self.topology}".lower()

    def pending_seconds(self, now: float) -> float:
        return max(0.0, now - self.created_at)

    def to_provisioning_request(self, namespace: str) -> dict:
        """The intent as a ProvisioningRequest CR (the reference's GKE
        queued-provisioning flow, aimed at pool capacity): podSets count
        the HOSTS the new slices need, labeled with the shape so an
        autoscaler — or an operator reading /debug/scheduler — knows
        which nodepool to grow."""
        shape = TpuSlice.parse(self.accelerator, self.topology)
        return {
            "apiVersion": "autoscaling.x-k8s.io/v1beta1",
            "kind": "ProvisioningRequest",
            "metadata": {
                "name": self.name,
                "namespace": namespace,
                "labels": {
                    keys.TPU_SCALE_UP_ACCELERATOR: self.accelerator,
                    keys.TPU_SCALE_UP_TOPOLOGY: self.topology,
                },
            },
            "spec": {
                "provisioningClassName": "queued-provisioning.gke.io",
                "parameters": {
                    "accelerator": self.accelerator,
                    "topology": self.topology,
                    "slices": str(self.slices),
                    "chips": str(self.chips),
                },
                "podSets": [{
                    "podTemplateRef": {"name": self.name},
                    "count": self.slices * shape.num_hosts,
                }],
            },
        }


def compute_shortfalls(policy: PolicyQueue, now: float,
                       *, flex: bool = True) -> dict:
    """Shapes whose queued gangs fit no pool even if the fleet fully
    drained — the scale-up trigger. A gang that could still land via
    flex borrowing (single-host, some same-accelerator pool exists) is
    NOT short: it is waiting on churn, not on hardware. Per shape, the
    deficit is sized for the largest starved gang (enough for any one of
    them to admit; the rest follow as earlier ones complete)."""
    fleet = policy.fleet
    out: dict[tuple, Shortfall] = {}
    for req in policy.pending.values():
        shape = (req.accelerator.lower(), req.topology.lower())
        ceiling = fleet.total_slices(req.accelerator, req.topology)
        if ceiling >= req.num_slices:
            continue
        if flex:
            slice_shape = _flexible(req)
            if slice_shape is not None and flex_capable(fleet,
                                                        slice_shape):
                continue
        deficit = req.num_slices - ceiling
        chips_per_slice = TpuSlice.parse(
            req.accelerator, req.topology).num_chips
        prior = out.get(shape)
        keys = (req.key,) if prior is None else \
            tuple(sorted(set(prior.keys) | {req.key}))
        out[shape] = Shortfall(
            accelerator=req.accelerator.lower(),
            topology=req.topology.lower(),
            slices=max(deficit, prior.slices if prior else 0),
            chips=max(deficit, prior.slices if prior else 0)
            * chips_per_slice,
            keys=keys,
        )
    return out


@dataclass
class IntentSync:
    """What one :meth:`IntentBook.sync` pass changed."""

    created: list = field(default_factory=list)
    renewed: list = field(default_factory=list)      # TTL expired, still needed
    updated: list = field(default_factory=list)      # ask size changed
    withdrawn: list = field(default_factory=list)    # (intent, reason)


class IntentBook:
    """The deduped, TTL'd set of pending scale-up intents, keyed by
    shape. Pure bookkeeping — the runtime owns the CR mirror and the
    metrics."""

    def __init__(self, ttl_seconds: float = DEFAULT_SCALE_UP_TTL_SECONDS):
        self.ttl = ttl_seconds
        self.intents: dict[tuple, ScaleUpIntent] = {}

    def sync(self, shortfalls: dict, fleet: Fleet, now: float) -> IntentSync:
        """Reconcile the book against the current shortfalls: create
        intents for new shortfall shapes, renew expired-but-still-needed
        ones (the TTL bounds how long an unanswered ask sits before it
        is re-asserted — and alerted on), withdraw intents whose need
        evaporated. Withdrawal reasons: ``granted`` when the fleet now
        holds more of the shape than at creation (the capacity arrived),
        ``moot`` when the starved gangs went away."""
        events = IntentSync()
        for shape, short in shortfalls.items():
            intent = self.intents.get(shape)
            if intent is None:
                intent = ScaleUpIntent(
                    accelerator=short.accelerator,
                    topology=short.topology,
                    slices=short.slices, chips=short.chips,
                    for_keys=short.keys, created_at=now,
                    expires_at=now + self.ttl,
                    ceiling_at_creation=fleet.total_slices(
                        short.accelerator, short.topology),
                )
                self.intents[shape] = intent
                events.created.append(intent)
                continue
            if (intent.slices, intent.chips) != (short.slices,
                                                 short.chips):
                # Track the CURRENT deficit, shrinking included — a
                # partial grant must shrink the mirrored ask, or an
                # autoscaler that fills it provisions slices nobody
                # needs anymore.
                intent.slices = short.slices
                intent.chips = short.chips
                events.updated.append(intent)
            intent.for_keys = short.keys
            if now >= intent.expires_at:
                intent.expires_at = now + self.ttl
                intent.renewals += 1
                events.renewed.append(intent)
        for shape in list(self.intents):
            if shape in shortfalls:
                continue
            intent = self.intents.pop(shape)
            ceiling = fleet.total_slices(intent.accelerator,
                                         intent.topology)
            reason = "granted" if ceiling > intent.ceiling_at_creation \
                else "moot"
            events.withdrawn.append((intent, reason))
        return events

    def for_shape(self, accelerator: str,
                  topology: str) -> ScaleUpIntent | None:
        return self.intents.get((accelerator.lower(), topology.lower()))

    def debug_rows(self, now: float) -> list:
        return [
            {
                "name": i.name,
                "accelerator": i.accelerator,
                "topology": i.topology,
                "slices": i.slices,
                "chips": i.chips,
                "for": [f"{k[0]}/{k[1]}" for k in i.for_keys],
                "pending_sec": round(i.pending_seconds(now), 3),
                "renewals": i.renewals,
                "denied": i.denied,
            }
            for _, i in sorted(self.intents.items())
        ]


# ---- defragmentation -----------------------------------------------------------


@dataclass(frozen=True)
class DefragMove:
    """Migrate one idle borrower off a pack-breaking pool: drain →
    checkpoint → park → re-queue; it re-admits onto a pack pool of its
    own shape (guaranteed free at planning time)."""

    key: tuple             # the borrower to migrate
    source_pool: str       # where its borrowed host sits
    for_key: tuple         # the waiter whose shape comes free
    chips: int


def plan_defrag(policy: PolicyQueue, config: ElasticConfig,
                now: float) -> list:
    """One defragmentation planning pass (pure). For the highest-ranked
    queued gang that native-fit cannot place, find the pools of its
    shape broken by borrowers and pick the idlest borrowers whose
    migration (a) frees enough whole slices for the waiter and (b) has a
    native pack slice to land on. Emits at most
    ``config.defrag_max_moves`` moves; emits none when a partial
    migration would not actually admit the waiter (draining a notebook
    for no benefit is strictly worse than waiting)."""
    ledger = policy.ledger
    fleet = policy.fleet
    moves: list[DefragMove] = []
    # Native free slices per shape, for pack-home guarantees: each
    # planned migrant consumes one.
    pack_free: dict[tuple, int] = {}
    for pool in fleet.pools:
        pack_free[pool.shape_key] = pack_free.get(pool.shape_key, 0) \
            + ledger.free_slices(pool)

    def idle_borrowers(pool_name: str) -> list:
        out = []
        for alloc in ledger.allocations.values():
            if not alloc.borrowed or alloc.draining \
                    or alloc.workload != "notebook":
                continue
            if pool_name not in alloc.borrow:
                continue
            last = (None if alloc.last_active_at is None
                    else max(alloc.last_active_at, alloc.admitted_at))
            if last is None or now - last < config.defrag_idle_seconds:
                continue
            out.append((-(now - last), alloc.key, alloc))
        out.sort()
        return [a for *_rank, a in out]

    for req in policy._ordered_pending(now):
        if moves:
            break  # one waiter per pass — rate-limited by design
        shape = (req.accelerator.lower(), req.topology.lower())
        matching = fleet.matching(req.accelerator, req.topology)
        if not matching:
            continue
        if ledger.fit(req.accelerator, req.topology,
                      req.num_slices) is not None:
            continue  # the normal pass will admit it
        free = sum(max(ledger.free_slices(p), 0) for p in matching)
        candidate_moves: list[DefragMove] = []
        freed = 0
        for pool in matching:
            borrowed = ledger.borrowed.get(pool.name, 0)
            if not borrowed:
                continue
            for alloc in idle_borrowers(pool.name):
                if len(candidate_moves) >= config.defrag_max_moves:
                    break
                mshape = (alloc.accelerator.lower(),
                          alloc.topology.lower())
                if pack_free.get(mshape, 0) < 1:
                    continue  # no pack home — migrating would just
                              # re-borrow somewhere else
                pack_free[mshape] -= 1
                hosts = alloc.borrow[pool.name]
                before = math.ceil(borrowed / pool.hosts_per_slice)
                borrowed -= hosts
                freed += before - math.ceil(
                    borrowed / pool.hosts_per_slice)
                candidate_moves.append(DefragMove(
                    key=alloc.key, source_pool=pool.name,
                    for_key=req.key, chips=alloc.chips))
                if free + freed >= req.num_slices:
                    break
            if free + freed >= req.num_slices:
                break
        if candidate_moves and free + freed >= req.num_slices:
            moves = candidate_moves
    return moves


def plan_idle_borrower_eviction(policy: PolicyQueue, req: GangRequest,
                                now: float, *,
                                idle_after: float) -> Allocation | None:
    """Host-granular idle preemption: a flexible waiter with no free
    host to borrow may evict ONE *idle* borrower (most idle first, same
    idle rule as the native victim search — never a busy holder, and
    never a probe-less one) whose host the waiter can use. Without this,
    idle borrowers are invisible to every reclamation mechanism for a
    same-shape waiter whose shape has no native pool: not preemptible
    (they hold no slices), not defrag targets (no native pool is
    broken), and no scale-up intent (flex capacity nominally exists).
    The victim parks like any idle-preemption victim — NO auto-requeue —
    so two idle borrowers cannot ping-pong a host between themselves."""
    shape = _flexible(req)
    if shape is None:
        return None
    if flex_plan(policy.ledger, req) is not None:
        return None  # a free host exists; no eviction needed
    candidates = []
    for alloc in policy.ledger.allocations.values():
        if not alloc.borrowed or alloc.workload != "notebook":
            # Serving replicas are never eviction victims (workload-class
            # guard, kubeflow_tpu/serving) — and they carry no activity
            # probe, so the idle rule below could never clear them anyway.
            continue
        if alloc.accelerator.lower() != req.accelerator.lower():
            continue
        pool = policy.fleet.by_name(next(iter(alloc.borrow)))
        if pool is None or pool.name in policy.ledger.unavailable \
                or pool.chips_per_host < shape.chips_per_host:
            continue
        if alloc.draining:
            # A usable host is already on its way out — evicting a
            # second borrower for the same one-host waiter would
            # double-kill.
            return None
        last = (None if alloc.last_active_at is None
                else max(alloc.last_active_at, alloc.admitted_at))
        if last is None or now - last < idle_after:
            continue
        candidates.append((-(now - last), alloc.key, alloc))
    if not candidates:
        return None
    candidates.sort()
    return candidates[0][2]


# ---- spot reclaim --------------------------------------------------------------


def node_reclaim_signal(node: dict) -> str | None:
    """The revocation signal on one Node: a reclaim taint key, or None.
    This is the same upstream signal podsim's DisruptionTarget models at
    the pod level — here it is read fleet-side so the drain starts while
    the grace window is still open."""
    for taint in deep_get(node, "spec", "taints", default=[]) or []:
        if taint.get("key") in RECLAIM_TAINTS:
            return taint.get("key")
    return None


def pool_of_node(fleet: Fleet, node: dict) -> NodePool | None:
    """Map a Node to its fleet pool: exact nodepool-label match first,
    then the shape-disambiguated ``<pool>-<acc>-<topo>`` names
    ``Fleet.from_nodes`` mints for mixed-label pools."""
    labels = ((node.get("metadata") or {}).get("labels")) or {}
    nodepool = labels.get(GKE_NODEPOOL_LABEL)
    if not nodepool:
        return None
    pool = fleet.by_name(nodepool)
    if pool is not None:
        return pool
    prefixed = [p for p in fleet.pools
                if p.name.startswith(nodepool + "-")]
    return prefixed[0] if len(prefixed) == 1 else None


def reclaimable(ledger, pool_name: str) -> list:
    """Allocations holding capacity on one (spot) pool — native slices
    or borrowed hosts — that a reclaim must drain. Draining gangs are
    already on their way out."""
    out = []
    for alloc in ledger.allocations.values():
        if alloc.draining:
            continue
        if alloc.placements.get(pool_name) or \
                (alloc.borrow or {}).get(pool_name):
            out.append(alloc)
    return sorted(out, key=lambda a: a.key)
